"""Serial (single-shard) tree learner — staged wave-growth, fully jitted.

TPU-native redesign of the reference ``SerialTreeLearner``
(`/root/reference/src/treelearner/serial_tree_learner.cpp:155-622`).  The
reference grows leaf-wise: a sequential best-first loop that, per split,
builds the smaller child's histograms (OpenMP over feature groups), derives
the sibling by subtraction, scans features for the best split, and
physically repartitions row indices (`data_partition.hpp`).

Here the tree is built by a sequence of *waves*, with the reference's
histogram-economy strategy kept intact
(`serial_tree_learner.cpp:358-372`, `feature_histogram.hpp:64-70`):

  1. histogram ONLY the smaller child of every split made in the previous
     wave (one MXU one-hot-matmul kernel pass over all rows,
     `ops/pallas_histogram.py`; XLA scatter fallback off-TPU),
  2. derive each sibling by parent-minus-child subtraction from the
     persistent per-leaf histogram state ``[L, F, B, 3]`` held in HBM
     (the HistogramPool analog — no LRU needed, it all fits),
  3. re-scan ONLY those changed leaves (vectorized two-direction prefix
     scan, `ops/split.py`) and cache their best splits,
  4. split every positive-gain leaf (up to the wave's slot count) in one
     go, routing rows with one Pallas pass (`ops/pallas_route.py`).

The wave loop is *staged*: the first ``ceil(log2(L))`` waves are unrolled
with active-slot counts growing 8, 8, 16, 32, ... so the histogram
kernel's MXU cost tracks the actual number of active leaves (a tree's
early waves are nearly free), then a ``lax.while_loop`` at a fixed slot
count finishes any leftover splits.  ``wave_size=1`` reproduces the
reference's leaf-wise growth decision-for-decision.

Everything is static-shape: leaf arrays are sized ``[num_leaves]``, tree
node arrays ``[num_leaves-1]``, and finished trees report a dynamic
``num_leaves`` scalar.  The same step runs unchanged under ``shard_map``
for the distributed learners (the active-leaf histograms gain a ``psum``).
"""
from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from ..io.device import DeviceData
from ..ops.pallas_histogram import (bin_stride, default_backend,
                                    fused_config_ok, hist_active_pallas,
                                    hist_active_scatter, hist_raw_layout,
                                    hist_route_pallas, is_quantized,
                                    pack_values, pack_values_q,
                                    pallas_config_ok, transpose_bins,
                                    unpack_hist_raw)
from ..ops.pallas_route import (route_rows_pallas, route_rows_values_pallas,
                                route_rows_xla)
from ..ops.split import SplitParams, SplitResult, find_best_splits

NEG_INF = -1e30


class GrowthParams(NamedTuple):
    """Static tree-growth parameters."""
    num_leaves: int = 31
    max_depth: int = -1
    wave_size: int = 0          # 0 => unlimited (full wave); 1 => leaf-wise
    split: SplitParams = SplitParams()


class BuiltTree(NamedTuple):
    """A finished tree as device arrays (fixed shapes, dynamic num_leaves).

    Node layout matches the reference Tree (`tree.h`): internal nodes
    ``[0, num_leaves-2]``, children ``>=0`` internal / ``~leaf`` for leaves.
    """
    feature: jnp.ndarray         # [L-1] i32 (used-column index)
    threshold_bin: jnp.ndarray   # [L-1] i32
    default_left: jnp.ndarray    # [L-1] bool
    is_categorical: jnp.ndarray  # [L-1] bool
    cat_mask: jnp.ndarray        # [L-1, B] bool  (bins going left)
    left_child: jnp.ndarray      # [L-1] i32
    right_child: jnp.ndarray     # [L-1] i32
    gain: jnp.ndarray            # [L-1] f32
    internal_value: jnp.ndarray  # [L-1] f32 (parent leaf output)
    internal_count: jnp.ndarray  # [L-1] i32
    leaf_value: jnp.ndarray      # [L] f32
    leaf_count: jnp.ndarray      # [L] i32
    leaf_depth: jnp.ndarray      # [L] i32
    num_leaves: jnp.ndarray      # scalar i32
    row_leaf: jnp.ndarray        # [n] i32 final leaf per row (ALL rows)
    row_value: jnp.ndarray       # [n] f32 leaf_value[row_leaf] (emitted by
    #   the final route kernel on the Pallas path; empty [0] otherwise —
    #   the score update falls back to a gather)


class _WaveState(NamedTuple):
    leaf2: jnp.ndarray           # [2, n_pad] (row_leaf; hist_leaf/-1 bagged)
    nl: jnp.ndarray              # scalar i32 current leaf count
    done: jnp.ndarray            # scalar bool
    leaf_sum_grad: jnp.ndarray   # [L]
    leaf_sum_hess: jnp.ndarray   # [L]
    leaf_count: jnp.ndarray      # [L] f32 (in-bag counts)
    leaf_depth: jnp.ndarray      # [L] i32
    leaf_value: jnp.ndarray      # [L] f32
    leaf_parent: jnp.ndarray     # [L] i32 node idx
    leaf_is_left: jnp.ndarray    # [L] bool
    hist_state: jnp.ndarray      # [L, F_local, B, 3] per-leaf histograms
    best: SplitResult            # [L] cached best split per leaf
    pend_sel: jnp.ndarray        # [L] bool: splits decided last wave,
    pend_new: jnp.ndarray        # [L] i32  not yet applied to the rows
    act_small: jnp.ndarray       # [A] leaf ids to histogram this wave (-1 pad)
    act_parent: jnp.ndarray      # [A] slot holding the parent hist (-1: none)
    act_sibling: jnp.ndarray     # [A] sibling leaf id (-1: none)
    tree: BuiltTree


def _round8(x: int) -> int:
    return -(-x // 8) * 8


def split_cache_enabled() -> bool:
    """Per-leaf best-split cache (ISSUE 9, the reference's
    ``best_split_per_leaf_`` economy, `serial_tree_learner.cpp`): each
    wave scans ONLY the newly-histogrammed child slots and merges them
    into the ``[L]`` cache the selection reads — O(A·F·B) per wave
    instead of O(L·F·B).  ``LGBM_TPU_SPLIT_CACHE=0`` restores the full
    per-wave rescan of every leaf's histogram (the A/B baseline the
    ``split_finder`` bench table measures); models are byte-identical
    either way (unchanged histograms ⇒ unchanged gains ⇒ identical
    argmax tie-breaks — gated by tests/test_split_cache.py)."""
    return _os_env.environ.get("LGBM_TPU_SPLIT_CACHE", "1") not in (
        "0", "false")


# datasets at or below this row count take the single-body compile-lean
# path (override for A/B: LGBM_TPU_COMPILE_LEAN_ROWS)
import os as _os_env
_COMPILE_LEAN_ROWS = int(_os_env.environ.get("LGBM_TPU_COMPILE_LEAN_ROWS",
                                             65536))

# canonical reduction chunk for the root statistics (ISSUE 14): a FIXED
# constant, not a knob — the streamed out-of-core trainer
# (boosting/streaming.py) reproduces the root sums from per-block chunk
# sums, and any run-time variation here would silently fork the
# reduction tree the byte-identity contract pins
STREAM_CHUNK = 8192


def _pairwise_halve(v: jnp.ndarray) -> jnp.ndarray:
    """Reduce the LAST axis (a power of two) to 1 by repeated pairwise
    adds.  Every step is an explicit elementwise ``a + b`` — defined
    IEEE semantics XLA cannot legally reassociate — so the reduction
    tree is identical in every fusion context and on every backend,
    unlike a ``reduce`` op whose internal order is implementation-
    defined (and empirically varies with the surrounding program)."""
    while v.shape[-1] > 1:
        half = v.shape[-1] // 2
        v = v[..., :half] + v[..., half:]
    return v[..., 0]


def root_chunk_sums(grad, hess, bag) -> jnp.ndarray:
    """Per-chunk partial sums of the root statistics ``(g, h, count)``
    over a row range: ``-> [3, m]`` with ``m = ceil(n / STREAM_CHUNK)``.

    The chunk grid is anchored at row 0 of the given range and padded
    with exact zeros, and each chunk reduces through an explicit
    pairwise-halving tree — so a caller that folds this function over
    row blocks whose sizes are multiples of ``STREAM_CHUNK`` (the
    streamed trainer, ``boosting/streaming.py``) produces the
    identical ``[3, m]`` vector as one call over the whole range.
    Partition-invariance is the contract
    (tests/test_streaming.py pins it end-to-end)."""
    n = grad.shape[0]
    m = -(-n // STREAM_CHUNK)
    pad = (0, m * STREAM_CHUNK - n)
    g = jnp.pad(jnp.where(bag, grad, 0.0).astype(jnp.float32), pad)
    h = jnp.pad(jnp.where(bag, hess, 0.0).astype(jnp.float32), pad)
    c = jnp.pad(bag.astype(jnp.float32), pad)
    stacked = jnp.stack([g, h, c])                   # [3, m*C]
    return _pairwise_halve(stacked.reshape(3, m, STREAM_CHUNK))


def reduce_chunk_sums(cs: jnp.ndarray):
    """Reduce ``[3, m]`` chunk sums to root ``(sum_g, sum_h, cnt)``
    with the same fixed pairwise-halving tree over the (zero-padded)
    power-of-two chunk axis.  The tree depends only on ``m`` — never
    on how the rows were partitioned into blocks — which is what makes
    the streamed trainer's root statistics bitwise equal to the
    resident path's."""
    m = cs.shape[1]
    P = 1 << max(0, (m - 1).bit_length())
    v = jnp.pad(cs, ((0, 0), (0, P - m)))
    v = _pairwise_halve(v)
    return v[0], v[1], v[2]


def _reassoc_fault_armed() -> bool:
    # resolved ONCE at import (host side, before any tracing): the
    # fault must not be consulted inside the traced reducer — jit
    # would cache the answer anyway, and a host call from traced
    # scope would drag the faults/telemetry machinery into
    # detcheck's traced closure.  Arm via env in a fresh process
    # (LGBM_TPU_FAULTS="num.reassoc:...").
    from ..utils.faults import fault_flag
    return fault_flag("num.reassoc")


_NUM_REASSOC_FAULT = _reassoc_fault_armed()


def root_stats(grad, hess, bag):
    """Root ``(sum_g, sum_h, cnt)`` via the canonical chunked pairwise
    reduction (replaces the old ``jnp.sum``, whose XLA ``reduce``
    order is implementation-defined, varies with the surrounding
    program, and cannot be reassembled from streamed per-block
    partials)."""
    if _NUM_REASSOC_FAULT:
        # the PR 14 bug, resurrected on demand: a raw reassociable
        # reduction whose order XLA picks per-program — the identity
        # harness (tools/identity_check.py) must name the partition
        # pair this diverges, and numcheck's NUM001 must flag the
        # sums below at file:line.
        b = bag.astype(grad.dtype)
        # numcheck: disable=NUM001 -- deliberate num.reassoc fault body
        sg = jnp.sum(grad * b)
        # numcheck: disable=NUM001 -- deliberate num.reassoc fault body
        sh = jnp.sum(hess * b)
        return sg, sh, jnp.sum(b)
    return reduce_chunk_sums(root_chunk_sums(grad, hess, bag))


def stage_plan(L: int, wave_size: int = 0):
    """Active-slot counts for the unrolled waves + the while-loop tail.

    Wave ``w`` can split at most ``min(leaves_w, slots)`` leaves, so slot
    counts track the doubling leaf count; the tail loop finishes whatever
    the unrolled waves didn't (uneven gain distributions).  The tail runs
    at full width so a balanced tree completes within the unrolled stages
    (a narrow tail forced extra waves on the hot path).  Leaf-wise mode
    (``wave_size=1``) splits one leaf per wave, so everything runs in a
    narrow while loop instead.
    """
    if wave_size == 1:
        return [], 8
    A_full = min(_round8(max(1, L // 2)), 128)
    plan = []
    leaves = 1
    while leaves < L and len(plan) < 32:
        A = min(_round8(leaves), A_full)
        plan.append(A)
        leaves += min(A, leaves)
    return plan, A_full


def _empty_best(L: int, B: int) -> SplitResult:
    z = jnp.zeros(L, jnp.float32)
    return SplitResult(
        gain=jnp.full(L, NEG_INF, jnp.float32),
        feature=jnp.zeros(L, jnp.int32),
        threshold=jnp.zeros(L, jnp.int32),
        default_left=jnp.zeros(L, bool),
        is_categorical=jnp.zeros(L, bool),
        cat_mask=jnp.zeros((L, B), bool),
        left_sum_grad=z, left_sum_hess=z, left_count=z,
        right_sum_grad=z, right_sum_hess=z, right_count=z,
        left_output=z, right_output=z)


# ---------------------------------------------------------------------------
# histogram-wave strategies (the learner-type seam, tree_learner.cpp:9-33)
# ---------------------------------------------------------------------------
def uses_pallas(backend: str) -> bool:
    """Whether this backend runs the Pallas kernel family ("compact" is
    the wide kernel + leaf-compacted deep waves, not a separate kernel
    stack — routing, fusion, and bins_t prep are shared)."""
    return backend in ("pallas", "compact")


def _pallas_interpret() -> bool:
    """Pallas kernels run in interpret mode off-TPU (CPU oracle tests /
    forced-backend runs); compiled on the real device."""
    return jax.default_backend() != "tpu"


def wave_uses_compact(backend: str, num_slots: int) -> bool:
    """THE per-wave dispatch predicate: a wave whose active-slot count
    exceeds the compaction threshold takes the leaf-compacted kernel on
    the "compact" backend.  Slot counts are static per wave (stage_plan
    unrolled stages + the fixed-width tail), so this resolves at trace
    time — shallow waves keep the wide (fused) kernel with zero runtime
    branching."""
    from ..ops.compact import compact_slot_threshold
    return backend == "compact" and num_slots > compact_slot_threshold()


def wave_backend_plan(L: int, wave_size: int = 0, backend: str = "compact",
                      fused_ok: bool = True):
    """Per-wave kernel choice for a stage plan: ``-> (choices, tail)``
    with entries "compact" / "fused" / "<backend>".  Pure mirror of the
    dispatch :func:`build_tree` applies (same ``wave_uses_compact``
    predicate), exposed so tests can pin the selection without tracing
    a tree build."""
    plan, A_tail = stage_plan(L, wave_size)

    def choice(A: int) -> str:
        if wave_uses_compact(backend, A):
            return "compact"
        if uses_pallas(backend) and fused_ok:
            return "fused"
        return backend

    return [choice(A) for A in plan], choice(A_tail)


def resolve_backend(data: DeviceData, num_leaf_slots: int,
                    backend: str = "auto", hist_mode: str = "hilo") -> str:
    if backend == "auto":
        backend = default_backend()
    if backend == "compact":
        from ..ops.compact import compact_config_ok, compact_slot_threshold
        _, A_tail = stage_plan(num_leaf_slots)
        if (A_tail <= compact_slot_threshold()
                or not compact_config_ok(data.group_max_bins, hist_mode)):
            # shallow trees never reach the slot threshold (and a
            # VMEM-infeasible group cell can't run): plain wide kernel
            backend = "pallas"
    if uses_pallas(backend) and not pallas_config_ok(
            data.group_max_bins, num_leaf_slots, hist_mode):
        backend = "scatter"     # >256 bins or VMEM-infeasible config
    return backend


# int8 histogram cells accumulate exactly in int32 only while n*127 <
# 2^31 (~16.9M rows into one cell worst-case); past that the quantized
# modes would silently wrap
_INT8_ROW_LIMIT = ((1 << 31) - 1) // 127


def effective_hist_mode(mode: str, n: int) -> str:
    """Downgrade quantized modes past the exact-int32 row bound (the
    root leaf can concentrate every row in one cell) to the closest
    float mode by the parity table: int8hh (hi/lo grad AND hessian)
    maps to hilo, the others to hhilo."""
    if is_quantized(mode) and n > _INT8_ROW_LIMIT:
        return "hilo" if mode == "int8hh" else "hhilo"
    return mode


def default_hist_mode() -> str:
    """int8h by default: quantized values on the MXU's int8 path (2.1x
    the bf16 throughput on v5e: 370 vs 178 Tops/s measured), with the
    hessian as a two-level int8 hi+lo pair (~14-bit absolute precision;
    gains and leaf outputs divide by hessian sums, so hessian precision
    is what drives full-depth quality).  Every histogram cell
    accumulates EXACTLY in int32 (the one-hot operand is 0/1) — the only
    error is per-row quantization, the reference 4.x quantized-training
    trade-off.

    Chosen from the recorded 500-iteration parity table
    (`tests/data/hist_parity.json`, `tools/hist_parity.py`,
    `tests/test_hist_parity.py`): int8h matches full hi/lo-bf16 ("hilo",
    ~f32 sums) to 0.0003 AUC at reference depth — inside the reference's
    own GPU-parity envelope (`docs/GPU-Performance.rst:135-161`) — at
    0.38x the wall-clock of hhilo, the previous default.  Plain "int8"
    (single-column hessian) drifts ~0.007 (absolute quantization
    truncates small hessians) and plain "bf16" drifts 0.0035-0.0048;
    both stay available for A/B.  "int8hh" (hi/lo pairs for BOTH grad
    and hessian, 5/4 the MXU work) tightens the 250k-row drift 5x
    (0.0003 vs 0.0016) for ~8% wall-clock — the accuracy-margin choice
    when the parity envelope matters more than peak throughput.
    Overrides: the ``hist_mode`` config parameter (or ``gpu_use_dp``,
    which maps to hilo) wins; the LGBM_TPU_HIST_MODE env var is the
    debug-level override below it."""
    import os
    return os.environ.get("LGBM_TPU_HIST_MODE", "int8h")


def make_hist_fn(data: DeviceData, grad, hess, num_leaf_slots: int,
                 backend: str = "auto", hist_mode: Optional[str] = None,
                 bins_t: Optional[jnp.ndarray] = None):
    """Build the per-wave active-leaf histogram closure
    ``(hist_leaf, active) -> [A, F, B, 3]``.

    backend "pallas" = the MXU one-hot-matmul kernel (TPU);
    "scatter" = XLA scatter-add (CPU tests / oracle).  The two are
    cross-checked by ``tests/test_pallas_hist.py`` the way the reference
    checks GPU vs CPU histograms (`gpu_tree_learner.cpp:1020-1043`).
    """
    if hist_mode is None:
        hist_mode = default_hist_mode()
    hist_mode = effective_hist_mode(hist_mode, data.num_data)
    backend = resolve_backend(data, num_leaf_slots, backend, hist_mode)
    if uses_pallas(backend):
        if bins_t is None:
            bins_t = transpose_bins(data.bins)
        if is_quantized(hist_mode):
            vals, scales = pack_values_q(grad, hess, hist_mode)
        else:
            vals, scales = pack_values(grad, hess, hist_mode), None
        n_pad = bins_t.shape[1]
        n = data.bins.shape[0]
        interp = _pallas_interpret()
        # resolved once: the per-wave choice below keys only on the
        # wave's static slot count
        from ..ops import compact as compact_mod

        def hist_fn(hist_leaf, active):
            leaf = hist_leaf
            if leaf.shape[0] != n_pad:
                leaf = jnp.pad(leaf[:n], (0, n_pad - n), constant_values=-1)
            if wave_uses_compact(backend, active.shape[0]):
                # deep wave: leaf-compacted regroup + grouped kernel
                # (ops/compact.py) — per-row MXU work independent of A
                return compact_mod.hist_active_compact(
                    bins_t, vals, leaf, active, scales,
                    num_features=data.num_groups,
                    max_bins=data.group_max_bins,
                    num_leaf_slots=num_leaf_slots, mode=hist_mode,
                    interpret=interp)
            return hist_active_pallas(
                bins_t, vals, leaf, active, scales,
                num_features=data.num_groups, max_bins=data.group_max_bins,
                mode=hist_mode, interpret=interp)
    else:
        n = data.bins.shape[0]

        def hist_fn(hist_leaf, active):
            return hist_active_scatter(
                data.bins, grad, hess, hist_leaf[:n], active,
                max_bins=data.group_max_bins,
                num_leaf_slots=num_leaf_slots)
    return hist_fn


class HistFold(NamedTuple):
    """The streamed kernel-fold seam built by :func:`make_hist_fold_fn`.

    ``fold(bins, grad, hess, hist_leaf, active, acc, scales=None)``
    folds one block's rows into the carried RAW kernel accumulator and
    returns the new carry; ``init_acc()`` allocates the zero carry;
    ``unpack(acc, scales=None)`` finalizes the chain to the
    ``[A, F, B, 3]`` f32 grid the split scan consumes.  ``backend`` is
    the RESOLVED kernel choice ("pallas"/"compact") after the fold
    seam's own degradations."""
    fold: Callable
    init_acc: Callable
    unpack: Callable
    backend: str
    hist_mode: str
    quantized: bool


def make_hist_fold_fn(data: DeviceData, num_leaf_slots: int,
                      num_active: int, block_rows: int,
                      backend: str = "auto",
                      hist_mode: Optional[str] = None,
                      num_data: Optional[int] = None
                      ) -> Optional[HistFold]:
    """Build the out-of-core histogram FOLD closure — the seeded-kernel
    twin of :func:`make_hist_fn` for streamed training
    (``boosting/streaming.py``).

    A streamed tree histograms each wave as a chain of per-block kernel
    calls that carry the RAW kernel accumulator (``acc`` /
    ``raw=True`` in the kernels) instead of summing unpacked f32 grids:
    on the quantized modes (the default) every cell accumulates exactly
    in int32, and the final :func:`unpack_hist_raw` dequantizes ONCE —
    bitwise what one monolithic in-memory kernel call produces.  This is
    what puts streamed training in the byte-identity domain on the
    kernel backends, not just scatter.

    SANCTIONED REASSOCIATION CONTEXT (tools/numcheck): splitting one
    kernel reduction into per-block seeded calls reorders nothing — the
    seeded kernel replays the monolithic kernel's adds in the monolithic
    order, block boundaries are just program re-entry.  Exactness holds
    per mode: quantized modes are order-free int32; the wide float modes
    reuse the identical per-tile add sequence (same row tile for every
    same-shaped block).  Float COMPACT folds are the one chain-INEXACT
    case (block-local group padding reorders f32 adds) and are degraded
    to the wide kernel below.

    Args:
      num_active: the streamed wave width (streamed trees run every
        wave at the fixed tail width — ``stage_plan(L)[1]``).
      block_rows: rows per streamed block (every block padded alike,
        which keeps the raw layout call-invariant).
      num_data: GLOBAL stream row count for the quantized-mode row
        bound (``effective_hist_mode`` must see the stream total, not
        the block size — a 1B-row stream can overflow an int32 cell
        even though each block is tiny).  Defaults to ``data.num_data``.

    Returns None when the resolved backend is scatter (caller keeps the
    carried-f32 scatter fold) or the SEEDED cell is VMEM-infeasible.
    """
    from ..ops import compact as compact_mod
    from ..ops.vmem import hist_fold_cell_ok, round_up

    if hist_mode is None:
        hist_mode = default_hist_mode()
    hist_mode = effective_hist_mode(
        hist_mode, data.num_data if num_data is None else num_data)
    backend = resolve_backend(data, num_leaf_slots, backend, hist_mode)
    if not uses_pallas(backend):
        return None
    quantized = is_quantized(hist_mode)
    mb = data.group_max_bins
    use_compact = wave_uses_compact(backend, num_active)
    if use_compact and not quantized:
        use_compact, backend = False, "pallas"
    if use_compact:
        extra = compact_mod.COMPACT_GROUP * 4 + 2 * 1024 * 4
        if not hist_fold_cell_ok(mb, compact_mod.COMPACT_GROUP, hist_mode,
                                 extra_bytes=extra):
            use_compact, backend = False, "pallas"
    if not use_compact and not hist_fold_cell_ok(mb, num_active, hist_mode):
        return None
    if not use_compact:
        backend = "pallas"

    from ..ops.pallas_histogram import DEFAULT_ROW_TILE
    n_pad = round_up(block_rows, DEFAULT_ROW_TILE)
    F_pad = data.num_groups     # per-block transpose_bins(feat_tile=None)
    if use_compact:
        shape, dtype = compact_mod.compact_raw_layout(
            n_pad, num_active, F_pad, mb, hist_mode)
    else:
        shape, dtype = hist_raw_layout(n_pad, num_active, F_pad, mb,
                                       hist_mode)
    interp = _pallas_interpret()

    def init_acc():
        return jnp.zeros(shape, dtype)

    @jax.jit
    def fold(bins, grad, hess, hist_leaf, active, acc, scales=None):
        bins_t = transpose_bins(bins)
        if quantized:
            vals, _ = pack_values_q(grad, hess, hist_mode, scales=scales)
        else:
            vals = pack_values(grad, hess, hist_mode)
        leaf = hist_leaf.astype(jnp.int32)
        if use_compact:
            return compact_mod.hist_active_compact(
                bins_t, vals, leaf, active, scales, acc,
                num_features=F_pad, max_bins=mb,
                num_leaf_slots=num_leaf_slots, mode=hist_mode,
                interpret=interp, raw=True)
        return hist_active_pallas(
            bins_t, vals, leaf, active, scales, acc,
            num_features=F_pad, max_bins=mb, mode=hist_mode,
            interpret=interp, raw=True)

    # the unpack MUST be its own jitted program (not eager): eager
    # elementwise dequant skips XLA's fma contraction and lands 1 ulp
    # off the in-memory kernels' fused in-call unpack — enough to break
    # byte identity.  Jitted, the same elementwise graph compiles to the
    # same contraction and matches bitwise (pinned by the identity
    # matrix in tests/test_streaming.py).
    @jax.jit
    def unpack(acc, scales=None):
        if use_compact:
            return compact_mod.unpack_hist_compact_raw(
                acc, num_active, data.num_groups, mb, hist_mode, scales)
        return unpack_hist_raw(acc, num_active, data.num_groups, mb,
                               hist_mode, scales)

    return HistFold(fold, init_acc, unpack, backend, hist_mode, quantized)


def make_route_fn(data: DeviceData, backend: str,
                  bins_t: Optional[jnp.ndarray] = None):
    """Per-wave split application closure ``(leaf2, best, sel, new_id)
    -> leaf2`` (the DataPartition::Split analog).  A ``lax.cond`` skips
    the full-data pass when no splits are pending (the root wave and
    drained tail waves)."""
    if uses_pallas(backend):
        if bins_t is None:
            bins_t = transpose_bins(data.bins)
        interp = _pallas_interpret()

        def route_impl(leaf2, best: SplitResult, sel, new_id):
            return route_rows_pallas(
                bins_t, leaf2, best.feature, best.threshold,
                best.default_left, best.is_categorical, best.cat_mask,
                sel, new_id, data.missing_types, data.nan_bins,
                data.default_bins, data.feat_group, data.feat_offset,
                data.num_bins, any_cat=data.has_categorical,
                interpret=interp)
    else:
        def route_impl(leaf2, best: SplitResult, sel, new_id):
            return route_rows_xla(
                data.bins, leaf2, best.feature, best.threshold,
                best.default_left, best.is_categorical, best.cat_mask,
                sel, new_id, data.missing_types, data.nan_bins,
                data.default_bins, data.feat_group, data.feat_offset,
                data.num_bins)

    def route_fn(leaf2, best: SplitResult, sel, new_id):
        return jax.lax.cond(
            jnp.any(sel),
            lambda l2: route_impl(l2, best, sel, new_id),
            lambda l2: l2,
            leaf2)
    return route_fn


def apply_hist_wave(hist_state, new_h, act_small, act_parent, act_sibling,
                    L: int):
    """Shared per-wave histogram bookkeeping for every learner strategy:
    derive each sibling by parent-minus-child subtraction
    (`feature_histogram.hpp:64-70`), persist both children into the
    per-leaf state, and hand back the changed-leaf ids + their grids.

    Returns ``(hist_state, ids [2A], grid [2A, F, B, 3])``.  The grid is
    exactly ``[new_h; sib_h]`` — no re-gather from state; padding slots
    (id -1) carry garbage and their scan results must be dropped by the
    caller (they are: the best-split scatter drops ids < 0).
    """
    parent_h = hist_state[jnp.clip(act_parent, 0, L - 1)]
    sib_h = parent_h - new_h                             # [A, F, B, 3]
    hist_state = hist_state.at[
        jnp.where(act_small >= 0, act_small, L)].set(new_h, mode="drop")
    hist_state = hist_state.at[
        jnp.where(act_sibling >= 0, act_sibling, L)].set(sib_h, mode="drop")
    ids = jnp.concatenate([act_small, act_sibling])      # [2A]
    grid = jnp.concatenate([new_h, sib_h], axis=0)       # [2A, F, B, 3]
    return hist_state, ids, grid


def make_fused_fn(data: DeviceData, grad, hess, hist_mode: str,
                  bins_t: jnp.ndarray):
    """Fused route+hist closure ``(leaf2, best, sel, new_id, active) ->
    (new_h, leaf2_new)`` — one bins stream per wave instead of two."""
    if is_quantized(hist_mode):
        vals, scales = pack_values_q(grad, hess, hist_mode)
    else:
        vals, scales = pack_values(grad, hess, hist_mode), None

    interp = _pallas_interpret()

    def fused(leaf2, best: SplitResult, sel, new_id, active):
        h, leaf2_new = hist_route_pallas(
            bins_t, vals, leaf2, active,
            best.feature, best.threshold, best.default_left,
            best.is_categorical, best.cat_mask, sel, new_id,
            data.missing_types, data.nan_bins, data.default_bins,
            data.feat_group, data.feat_offset, data.num_bins, scales,
            num_features=data.num_groups, max_bins=data.group_max_bins,
            mode=hist_mode, any_cat=data.has_categorical,
            interpret=interp)
        return h, leaf2_new
    return fused


def make_serial_strategy(data: DeviceData, grad, hess, params: GrowthParams,
                         feature_mask, psum_fn=None, backend: str = "auto",
                         hist_mode: Optional[str] = None,
                         bins_t: Optional[jnp.ndarray] = None,
                         psum_axis: Optional[str] = None):
    """The serial (and data-parallel, via `psum_fn`) wave strategy:
    histogram the active leaves, subtract siblings, rescan changed leaves.

    `psum_fn` injects the data-parallel histogram collective — the
    reference's ReduceScatter seam (`data_parallel_tree_learner.cpp:147-162`)
    collapses to one psum of the active-leaf histograms.  `psum_axis`
    switches that collective to the OVERLAPPED lowering
    (`ops/overlap.py`): the same logical reduction issued as column
    chunks whose sibling-subtract/state-scatter consumers double-buffer
    against the chunks still in flight — bit-identical values, identical
    logical schedule."""
    L = params.num_leaves
    hist_fn = make_hist_fn(data, grad, hess, L, backend, hist_mode, bins_t)

    def wave(hist_state, hist_leaf, act_small, act_parent, act_sibling,
             lsg, lsh, lc):
        new_h = hist_fn(hist_leaf, act_small)            # [A, G, Bg, 3]
        if psum_axis is not None:
            from ..ops.overlap import reduce_apply_overlapped
            hist_state, ids, grid = reduce_apply_overlapped(
                hist_state, new_h, act_small, act_parent, act_sibling, L,
                psum_axis)
            return scan_grid(data, params, feature_mask, hist_state, ids,
                             grid, lsg, lsh, lc)
        if psum_fn is not None:
            new_h = psum_fn(new_h)
        return rescan_changed(data, params, feature_mask, hist_state, new_h,
                              act_small, act_parent, act_sibling,
                              lsg, lsh, lc)
    return wave


def rescan_changed(data: DeviceData, params: GrowthParams, feature_mask,
                   hist_state, new_h, act_small, act_parent, act_sibling,
                   lsg, lsh, lc):
    """Shared post-histogram flow for every wave path (serial strategy and
    the fused kernel): sibling subtraction, EFB unbundle, rescan of the
    changed leaves."""
    L = hist_state.shape[0]
    hist_state, ids, grid = apply_hist_wave(
        hist_state, new_h, act_small, act_parent, act_sibling, L)
    return scan_grid(data, params, feature_mask, hist_state, ids, grid,
                     lsg, lsh, lc)


def scan_grid(data: DeviceData, params: GrowthParams, feature_mask,
              hist_state, ids, grid, lsg, lsh, lc):
    """EFB unbundle + best-split rescan of the changed-leaf grids — the
    tail of :func:`rescan_changed`, split out so the overlapped wave
    (`ops/overlap.py` reduce+apply) can share it verbatim.

    With the per-leaf split cache OFF (``LGBM_TPU_SPLIT_CACHE=0``) the
    changed-slot narrowing is discarded: every wave rescans the FULL
    ``[L, F, B]`` histogram state and rewrites the whole cache — the
    O(L·F·B) baseline.  Results are byte-identical (unchanged leaf
    histograms rescan to the identical floats), only the scanned width
    changes.  Either way the scan chunks its feature axis under the
    shared HBM model (`ops/vmem.py split_scan_chunk_features`) so the
    255-bin MSLR stack stays inside budget."""
    L = hist_state.shape[0]
    if not split_cache_enabled():
        ids = jnp.arange(L, dtype=jnp.int32)
        grid = hist_state
    safe = jnp.clip(ids, 0, L - 1)
    if data.is_bundled:
        from ..ops.histogram import unbundle_grid
        grid = unbundle_grid(grid, lsg[safe], lsh[safe], lc[safe],
                             data.feat_group, data.feat_offset,
                             data.num_bins, data.default_bins,
                             bin_stride(data.max_bins))
    B = grid.shape[2]
    from ..ops.pallas_split import find_best_splits_pallas, split_kernel_ok
    from ..ops.vmem import split_scan_chunk_features
    interp = _os_env.environ.get("LGBM_TPU_SPLIT_INTERPRET") == "1"
    if (split_kernel_ok(grid.shape[1], B, data.has_categorical,
                        num_rows=data.bins.shape[0])
            and (interp or jax.default_backend() == "tpu")):
        # fused split scan: one Pallas call replaces ~50 small XLA ops
        # per wave (the row-independent per-iteration tax, VERDICT r4 #4)
        res = find_best_splits_pallas(
            grid, lsg[safe], lsh[safe], lc[safe], data.num_bins,
            data.missing_types, data.default_bins, B=B,
            params=params.split, feature_mask=feature_mask,
            any_missing=data.has_missing, interpret=interp)
    else:
        fc = split_scan_chunk_features(grid.shape[0], grid.shape[1], B,
                                       any_missing=data.has_missing)
        res = find_best_splits(grid, lsg[safe], lsh[safe], lc[safe],
                               data.num_bins, data.missing_types,
                               data.default_bins, data.is_categorical,
                               params.split, feature_mask,
                               any_categorical=data.has_categorical,
                               any_missing=data.has_missing,
                               feature_chunk=fc)
    return hist_state, ids, res


def build_tree(data: DeviceData,
               grad: jnp.ndarray,
               hess: jnp.ndarray,
               params: GrowthParams,
               bag_mask: Optional[jnp.ndarray] = None,
               feature_mask: Optional[jnp.ndarray] = None,
               strategy=None,
               psum_fn=None,
               hist_backend: str = "auto",
               num_hist_features: Optional[int] = None,
               bins_t: Optional[jnp.ndarray] = None,
               hist_mode: Optional[str] = None,
               psum_axis: Optional[str] = None) -> BuiltTree:
    """Grow one tree.  Jittable; `psum_fn` lets the data-parallel learner
    inject a collective over active-leaf histograms; `strategy` replaces
    the whole wave procedure (feature/voting-parallel,
    `parallel/learners.py`).  `num_hist_features` overrides the width of
    the histogram state (feature-parallel shards keep only their slice);
    `bins_t` is the once-per-dataset transposed bins (computed here when
    absent); `psum_axis` routes the data-parallel wave reduction through
    the overlapped chunked lowering (`ops/overlap.py`) — `psum_fn` is
    still used for the root-statistics reduction either way."""
    n = data.bins.shape[0]
    L = params.num_leaves

    mode = effective_hist_mode(hist_mode or default_hist_mode(), n)
    backend = resolve_backend(data, L, hist_backend, mode)
    if uses_pallas(backend) and bins_t is None:
        bins_t = transpose_bins(data.bins)

    # staged waves only pay off on the Pallas path (MXU cost ∝ slots);
    # the scatter backend compiles one while-loop body instead (8 unrolled
    # stages × shard_map × 3 learners is minutes of XLA-CPU compile time)
    if uses_pallas(backend):
        plan, A_tail = stage_plan(L, params.wave_size)
        # compile-lean: on small datasets the staged unrolled waves buy
        # nothing (MXU cost ∝ slots×n is trivial) but multiply HLO size
        # ~7x — and XLA compile time, not FLOPs, dominates small-data
        # cold starts (~30 s vs ~1.5 s of device work for 100
        # iterations).  One full-width while-loop body compiles once and
        # runs the same wave sequence.
        if n <= _COMPILE_LEAN_ROWS and params.wave_size != 1:
            plan = []
    else:
        plan, A_tail = [], _round8(max(1, L // 2))
    wave_cap = params.wave_size if params.wave_size > 0 else L
    # the final route can emit per-row leaf values (gather-free score
    # update) on any serial Pallas path — captured BEFORE the serial
    # strategy closure is assigned below
    emit_values = (strategy is None and psum_fn is None
                   and uses_pallas(backend))
    # fused route+hist: one bins stream per wave (serial Pallas path with
    # every stored column in a single kernel tile);
    # LGBM_TPU_NO_FUSED=1 forces the unfused path (A/B debugging)
    import os as _os
    fused = (strategy is None and psum_fn is None and uses_pallas(backend)
             and not _os.environ.get("LGBM_TPU_NO_FUSED")
             and fused_config_ok(bins_t.shape[0], data.group_max_bins, L,
                                 mode))
    fused_fn = (make_fused_fn(data, grad, hess, mode, bins_t)
                if fused else None)
    # the "compact" backend needs the strategy (route + compacted hist)
    # for its deep waves even when the shallow waves run fused
    if strategy is None and (not fused or backend == "compact"):
        strategy = make_serial_strategy(data, grad, hess, params,
                                        feature_mask, psum_fn=psum_fn,
                                        backend=backend, bins_t=bins_t,
                                        hist_mode=hist_mode,
                                        psum_axis=psum_axis)
    route_fn = make_route_fn(data, backend, bins_t)

    def scan_changed(hist_state, new_h, s, lsg, lsh, lc):
        return rescan_changed(data, params, feature_mask, hist_state, new_h,
                              s.act_small, s.act_parent, s.act_sibling,
                              lsg, lsh, lc)

    A0 = plan[0] if plan else A_tail
    state = _init_state(data, grad, hess, params, bag_mask, psum_fn,
                        backend, bins_t, num_hist_features, A0)

    def body(s: _WaveState, A_out: int) -> _WaveState:
        # --- 0-3: apply last wave's pending splits to the rows, then
        # histogram the active leaves, subtract siblings, rescan.  The
        # fused kernel does the route inside the histogram's bins stream.
        # stage_plan-aware dispatch: the wave's slot count is static, so
        # deep waves (> compaction threshold on the "compact" backend)
        # trace the route + leaf-compacted grouped kernel while shallow
        # waves keep the wide fused kernel (wave_uses_compact — the same
        # predicate make_hist_fn applies inside the strategy)
        if fused and not wave_uses_compact(backend,
                                           s.act_small.shape[0]):
            new_h, leaf2 = fused_fn(s.leaf2, s.best, s.pend_sel,
                                    s.pend_new, s.act_small)
            hist_state, ids, res = scan_changed(
                s.hist_state, new_h, s, s.leaf_sum_grad, s.leaf_sum_hess,
                s.leaf_count)
        else:
            leaf2 = route_fn(s.leaf2, s.best, s.pend_sel, s.pend_new)
            hist_state, ids, res = strategy(
                s.hist_state, leaf2[1], s.act_small, s.act_parent,
                s.act_sibling, s.leaf_sum_grad, s.leaf_sum_hess,
                s.leaf_count)
        return _apply_wave(s, leaf2, hist_state, ids, res, A_out, params,
                           wave_cap)

    # --- staged unrolled waves (slot counts track the growing tree) -----
    for i, A_in in enumerate(plan):
        A_out = plan[i + 1] if i + 1 < len(plan) else A_tail
        state = body(state, A_out)

    # --- while-loop tail at fixed slot count -----------------------------
    def cond(s: _WaveState):
        return (~s.done) & (s.nl < L)

    final = jax.lax.while_loop(cond, lambda s: body(s, A_tail), state)
    # apply the last wave's pending splits before reading row_leaf; on the
    # Pallas path the same pass emits each row's leaf value (the score
    # update's lv[row_leaf] gather costs ~7 ms/iter at 1M rows on TPU)
    lv_final = jnp.where(final.nl > 1, final.leaf_value,
                         jnp.zeros_like(final.leaf_value))
    if emit_values:
        leaf2_final, row_value = route_rows_values_pallas(
            bins_t, final.leaf2, final.best.feature, final.best.threshold,
            final.best.default_left, final.best.is_categorical,
            final.best.cat_mask, final.pend_sel, final.pend_new,
            data.missing_types, data.nan_bins, data.default_bins,
            data.feat_group, data.feat_offset, data.num_bins, lv_final,
            any_cat=data.has_categorical, interpret=_pallas_interpret())
        row_value = row_value[:n]
    else:
        leaf2_final = route_fn(final.leaf2, final.best, final.pend_sel,
                               final.pend_new)
        row_value = jnp.zeros(0, jnp.float32)   # empty: caller gathers
    final = final._replace(leaf2=leaf2_final)
    return final.tree._replace(
        leaf_value=final.leaf_value,
        leaf_count=final.leaf_count.astype(jnp.int32),
        leaf_depth=final.leaf_depth,
        num_leaves=final.nl,
        row_leaf=final.leaf2[0, :n],
        row_value=row_value,
    )


def _init_state(data: DeviceData, grad, hess, params: GrowthParams,
                bag_mask, psum_fn, backend: str, bins_t,
                num_hist_features: Optional[int], A0: int) -> _WaveState:
    """Initial wave state: empty tree, root leaf stats, root wave active
    set.  Shared by :func:`build_tree` and :func:`build_tree_phases`."""
    n = data.bins.shape[0]
    L = params.num_leaves
    Lm = max(L - 1, 1)
    B = bin_stride(data.max_bins)                  # feature-space stride
    Bh = bin_stride(data.group_max_bins)           # stored-column stride
    Gh = (num_hist_features if num_hist_features is not None
          else data.num_groups)
    n_pad = bins_t.shape[1] if uses_pallas(backend) else n

    row_leaf0 = jnp.zeros(n, jnp.int32)
    hist_leaf0 = (jnp.where(bag_mask, 0, -1).astype(jnp.int32)
                  if bag_mask is not None else row_leaf0)
    leaf2 = jnp.full((2, n_pad), -1, jnp.int32)
    leaf2 = jax.lax.dynamic_update_slice(leaf2, row_leaf0[None, :], (0, 0))
    leaf2 = jax.lax.dynamic_update_slice(leaf2, hist_leaf0[None, :], (1, 0))

    tree = BuiltTree(
        feature=jnp.zeros(Lm, jnp.int32),
        threshold_bin=jnp.zeros(Lm, jnp.int32),
        default_left=jnp.zeros(Lm, bool),
        is_categorical=jnp.zeros(Lm, bool),
        cat_mask=jnp.zeros((Lm, B), bool),
        left_child=jnp.full(Lm, -1, jnp.int32),
        right_child=jnp.full(Lm, -1, jnp.int32),
        gain=jnp.zeros(Lm, jnp.float32),
        internal_value=jnp.zeros(Lm, jnp.float32),
        internal_count=jnp.zeros(Lm, jnp.int32),
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_count=jnp.zeros(L, jnp.int32),
        leaf_depth=jnp.zeros(L, jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32),
        row_leaf=row_leaf0,
        row_value=jnp.zeros(0, jnp.float32),
    )

    # root statistics (in-bag) via the canonical chunked reduction:
    # partition-invariant by construction, so the streamed out-of-core
    # trainer reproduces them bitwise from per-block chunk sums
    # (boosting/streaming.py; the old jnp.sum reduction tree could not
    # be reassembled from block partials)
    bag = (leaf2[1] == 0)
    sum_g, sum_h, cnt = root_stats(grad, hess, bag[:n])
    if psum_fn is not None:
        sum_g, sum_h, cnt = psum_fn((sum_g, sum_h, cnt))

    from ..ops.split import leaf_output as _leaf_out
    root_out = _leaf_out(sum_g, sum_h, params.split.lambda_l1,
                         params.split.lambda_l2)

    return _WaveState(
        leaf2=leaf2,
        nl=jnp.asarray(1, jnp.int32), done=jnp.asarray(False),
        leaf_sum_grad=jnp.zeros(L).at[0].set(sum_g),
        leaf_sum_hess=jnp.zeros(L).at[0].set(sum_h),
        leaf_count=jnp.zeros(L).at[0].set(cnt),
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(root_out),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_is_left=jnp.zeros(L, bool),
        hist_state=jnp.zeros((L, Gh, Bh, 3), jnp.float32),
        best=_empty_best(L, B),
        pend_sel=jnp.zeros(L, bool),
        pend_new=jnp.zeros(L, jnp.int32),
        act_small=jnp.full(A0, -1, jnp.int32).at[0].set(0),  # root wave
        act_parent=jnp.full(A0, -1, jnp.int32),
        act_sibling=jnp.full(A0, -1, jnp.int32),
        tree=tree,
    )


def make_phases_driver(data: DeviceData,
                       params: GrowthParams,
                       hist_backend: str = "auto",
                       bins_t: Optional[jnp.ndarray] = None,
                       hist_mode: Optional[str] = None):
    """Once-per-booster factory for the per-phase-timed UNFUSED wave
    driver (``LGBM_TPU_TIMETAG=phases``).

    Returns ``build(grad, hess, bag_mask=None, feature_mask=None) ->
    BuiltTree`` running the same wave algorithm as :func:`build_tree`
    but with route / hist / scan / update as SEPARATE device dispatches,
    each wrapped in a timetag — the analog of the reference's per-phase
    TIMETAG counters (`serial_tree_learner.cpp:12-39`), which a single
    fused jitted scan cannot attribute.  The jitted phase functions are
    built HERE, once, with grad/hess as traced arguments, so repeated
    trees reuse the compiled programs and the tags time kernels, not
    compiles.  Every dispatch still pays the host-device round trip
    (tens of ms through a remote-device tunnel), so read the REPORT'S
    RATIOS, not its sums, and never compare its totals to the fused
    path's wall clock.  Must be called OUTSIDE jit."""
    from ..utils.timetag import tag
    n = data.bins.shape[0]
    L = params.num_leaves
    mode = effective_hist_mode(hist_mode or default_hist_mode(), n)
    backend = resolve_backend(data, L, hist_backend, mode)
    if uses_pallas(backend) and bins_t is None:
        bins_t = jax.jit(transpose_bins)(data.bins)
    _, A_tail = stage_plan(L, params.wave_size)
    wave_cap = params.wave_size if params.wave_size > 0 else L

    route_fn = make_route_fn(data, backend, bins_t)

    @jax.jit
    def init_jit(grad, hess, bag_mask):
        return _init_state(data, grad, hess, params, bag_mask, None,
                           backend, bins_t, None, A_tail)

    @jax.jit
    def hist_jit(grad, hess, s):
        hist_fn = make_hist_fn(data, grad, hess, L, backend, mode, bins_t)
        return hist_fn(s.leaf2[1], s.act_small)

    @jax.jit
    def scan_jit(s, new_h, feature_mask):
        return rescan_changed(
            data, params, feature_mask, s.hist_state, new_h, s.act_small,
            s.act_parent, s.act_sibling, s.leaf_sum_grad, s.leaf_sum_hess,
            s.leaf_count)

    @jax.jit
    def route_jit(s):
        return route_fn(s.leaf2, s.best, s.pend_sel, s.pend_new)

    update_jit = jax.jit(functools.partial(
        _apply_wave, A_out=A_tail, params=params, wave_cap=wave_cap))

    # obs spans ride the same phase boundaries as the timetags: these
    # dispatches are host-blocked (each done() waits on its outputs),
    # so the span durations ARE device time for route (leaf routing) /
    # hist (histogram build) / scan (split find) / update
    from ..obs import span as obs_span

    def build(grad, hess, bag_mask=None, feature_mask=None) -> BuiltTree:
        with obs_span("tree.init"), tag("tree:init") as done:
            # root statistics + state zero-fill: previously the one
            # unattributed dispatch of the phase-timed path (the
            # device-time attribution parser joins XLA ops to named
            # spans — an unnamed dispatch is a coverage hole)
            state = init_jit(grad, hess, bag_mask)
            done(state.leaf_sum_grad)
        while True:
            with obs_span("tree.route"), tag("tree:route") as done:
                leaf2 = route_jit(state)
                done(leaf2)
            state = state._replace(leaf2=leaf2)
            with obs_span("tree.hist"), tag("tree:hist") as done:
                new_h = hist_jit(grad, hess, state)
                done(new_h)
            with obs_span("tree.split_find"), tag("tree:scan") as done:
                hist_state, ids, res = scan_jit(state, new_h, feature_mask)
                done(res.gain)
            with obs_span("tree.update"), tag("tree:update") as done:
                # memcheck: disable=MEM002 -- wave-loop carry on the
                # unfused profiling path; production training rides the
                # fused block whose score state IS donated (gated)
                state = update_jit(state, leaf2, hist_state, ids, res)
                done(state.nl)
            if bool(state.done) or int(state.nl) >= L:
                break
        with obs_span("tree.route"), tag("tree:route") as done:
            leaf2 = route_jit(state)
            done(leaf2)
        state = state._replace(leaf2=leaf2)
        return state.tree._replace(
            leaf_value=state.leaf_value,
            leaf_count=state.leaf_count.astype(jnp.int32),
            leaf_depth=state.leaf_depth,
            num_leaves=state.nl,
            row_leaf=state.leaf2[0, :n],
            row_value=jnp.zeros(0, jnp.float32),   # debug path: gather
        )

    return build


def _apply_wave(s: _WaveState, leaf2, hist_state, ids, res: SplitResult,
                A_out: int, params: GrowthParams,
                wave_cap: int) -> _WaveState:
    """Post-histogram wave bookkeeping: merge rescanned best splits,
    select this wave's splits by gain rank, record tree nodes, update
    leaf state, and stage the next wave's active sets.  Shared between
    the jitted wave body and the phase-timed debug driver
    (:func:`build_tree_phases`)."""
    L = s.leaf_sum_grad.shape[0]
    Lm = s.tree.feature.shape[0]
    best = jax.tree.map(
        lambda cur, new: cur.at[
            jnp.where(ids >= 0, ids, L)].set(new, mode="drop"),
        s.best, res)

    # --- 4: select this wave's splits -------------------------------
    lid = jnp.arange(L)
    gain = jnp.where(lid < s.nl, best.gain, NEG_INF)
    if params.max_depth > 0:
        gain = jnp.where(s.leaf_depth >= params.max_depth, NEG_INF, gain)
    can = gain > 0.0

    order = jnp.argsort(-gain)                      # leaves by gain desc
    rank = jnp.argsort(order)                       # rank[l]
    budget = L - s.nl
    k = jnp.minimum(jnp.minimum(jnp.sum(can), budget),
                    min(wave_cap, A_out))
    sel = can & (rank < k)

    new_id = jnp.where(sel, s.nl + rank, L)         # L => drop scatter
    node_idx = jnp.where(sel, s.nl - 1 + rank, Lm)  # Lm => drop scatter

    # --- 5: record tree nodes (scatter at node_idx; drop unselected)
    t = s.tree
    dl = jnp.where(best.is_categorical, False, best.default_left)
    t = t._replace(
        feature=t.feature.at[node_idx].set(best.feature, mode="drop"),
        threshold_bin=t.threshold_bin.at[node_idx].set(best.threshold,
                                                       mode="drop"),
        default_left=t.default_left.at[node_idx].set(dl, mode="drop"),
        is_categorical=t.is_categorical.at[node_idx].set(
            best.is_categorical, mode="drop"),
        cat_mask=t.cat_mask.at[node_idx].set(best.cat_mask, mode="drop"),
        gain=t.gain.at[node_idx].set(best.gain, mode="drop"),
        internal_value=t.internal_value.at[node_idx].set(
            s.leaf_value, mode="drop"),
        internal_count=t.internal_count.at[node_idx].set(
            s.leaf_count.astype(jnp.int32), mode="drop"),
        left_child=t.left_child.at[node_idx].set(~lid, mode="drop"),
        right_child=t.right_child.at[node_idx].set(
            ~new_id, mode="drop"),
    )
    # fix the parent's child pointer: leaf l was ~l, becomes node_idx
    parent = jnp.where(sel, s.leaf_parent, -1)
    fix_left = jnp.where(sel & s.leaf_is_left & (parent >= 0),
                         parent, Lm)
    fix_right = jnp.where(sel & ~s.leaf_is_left & (parent >= 0),
                          parent, Lm)
    t = t._replace(
        left_child=t.left_child.at[fix_left].set(node_idx, mode="drop"),
        right_child=t.right_child.at[fix_right].set(node_idx, mode="drop"),
    )

    # --- 6: update leaf state: left child keeps id l, right -> new_id
    depth1 = s.leaf_depth + 1
    lsg = jnp.where(sel, best.left_sum_grad, s.leaf_sum_grad)
    lsh = jnp.where(sel, best.left_sum_hess, s.leaf_sum_hess)
    lc = jnp.where(sel, best.left_count, s.leaf_count)
    lv = jnp.where(sel, best.left_output, s.leaf_value)
    ld = jnp.where(sel, depth1, s.leaf_depth)
    lp = jnp.where(sel, node_idx, s.leaf_parent)
    lil = jnp.where(sel, True, s.leaf_is_left)

    lsg = lsg.at[new_id].set(best.right_sum_grad, mode="drop")
    lsh = lsh.at[new_id].set(best.right_sum_hess, mode="drop")
    lc = lc.at[new_id].set(best.right_count, mode="drop")
    lv = lv.at[new_id].set(best.right_output, mode="drop")
    ld = ld.at[new_id].set(depth1, mode="drop")
    lp = lp.at[new_id].set(node_idx, mode="drop")
    lil = lil.at[new_id].set(False, mode="drop")

    # --- 7: this wave's splits become the pending route, applied at
    # the start of the next wave (or post-loop finalization)
    pend_sel = sel
    pend_new = jnp.where(sel, new_id, 0).astype(jnp.int32)

    # --- 8: next wave's active sets (smaller child + subtraction) ---
    # the smaller child gets histogrammed; the sibling is derived from
    # the parent histogram left in slot l (the left child's id)
    smaller_left = best.left_count <= best.right_count
    small_val = jnp.where(smaller_left, lid, new_id)
    sib_val = jnp.where(smaller_left, new_id, lid)
    slot = jnp.where(sel, rank, A_out)
    pad_out = jnp.full(A_out, -1, jnp.int32)
    act_small = pad_out.at[slot].set(small_val, mode="drop")
    act_parent = pad_out.at[slot].set(lid, mode="drop")
    act_sibling = pad_out.at[slot].set(sib_val, mode="drop")

    nl2 = s.nl + k
    return _WaveState(
        leaf2=leaf2, nl=nl2,
        done=(k == 0),
        leaf_sum_grad=lsg, leaf_sum_hess=lsh, leaf_count=lc,
        leaf_depth=ld, leaf_value=lv, leaf_parent=lp, leaf_is_left=lil,
        hist_state=hist_state, best=best,
        pend_sel=pend_sel, pend_new=pend_new,
        act_small=act_small, act_parent=act_parent,
        act_sibling=act_sibling,
        tree=t)


@jax.jit
def predict_built_tree(tree: BuiltTree, data: DeviceData,
                       bins: jnp.ndarray) -> jnp.ndarray:
    """Leaf value per row of `bins` for a just-built tree (validation score
    update path; train rows use ``tree.row_leaf`` directly)."""
    n = bins.shape[0]
    node = jnp.where(tree.num_leaves > 1, 0, ~0) * jnp.ones(n, jnp.int32)

    from ..ops.pallas_route import unbundle_bin

    def body(_, node):
        is_leaf = node < 0
        nidx = jnp.maximum(node, 0)
        f = tree.feature[nidx]
        c = jnp.take_along_axis(
            bins, data.feat_group[f][:, None], axis=1)[:, 0].astype(jnp.int32)
        b = unbundle_bin(c, data.feat_offset[f], data.num_bins[f],
                         data.default_bins[f])
        mt = data.missing_types[f]
        is_missing = (((mt == MISSING_NAN) & (b == data.nan_bins[f]))
                      | ((mt == MISSING_ZERO) & (b == data.default_bins[f])))
        num_left = jnp.where(is_missing, tree.default_left[nidx],
                             b <= tree.threshold_bin[nidx])
        cat_left = tree.cat_mask[nidx, jnp.minimum(b, tree.cat_mask.shape[-1] - 1)]
        go_left = jnp.where(tree.is_categorical[nidx], cat_left, num_left)
        nxt = jnp.where(go_left, tree.left_child[nidx], tree.right_child[nidx])
        return jnp.where(is_leaf, node, nxt)

    depth = tree.leaf_value.shape[0] - 1
    node = jax.lax.fori_loop(0, depth, body, node)
    leaf = jnp.where(node < 0, ~node, 0)
    return tree.leaf_value[leaf]


def built_tree_path_matrices(tree: BuiltTree):
    """Signed leaf-path matrices of a just-built DEVICE tree, traceably
    (the device analog of ``models/tree.py build_path_matrices``, which
    walks host trees with a Python stack).

    ``P[l, m]`` is +1 / -1 when internal node ``m`` lies on leaf ``l``'s
    root path going left / right, else 0; ``plen[l]`` is the leaf's
    depth (-1 for unused slots, so they can never be selected).  Node
    indices are creation-ordered — a child's index always exceeds its
    parent's — so ONE ascending ``fori_loop`` over the node axis
    propagates root paths with tiny ``[L, M]`` state per step; the
    per-ROW work is deferred to a single MXU contraction in
    ``predict_built_tree_matmul``.  Conditional scatters write to a
    trailing dummy slot, the scan-safe alternative to predication."""
    L = tree.leaf_value.shape[0]
    M = max(L - 1, 1)
    nodeP = jnp.zeros((M + 1, M), jnp.float32)
    node_len = jnp.zeros(M + 1, jnp.int32)
    leafP = jnp.zeros((L + 1, M), jnp.float32)
    # stump: leaf 0's zero-length path matches S == 0
    plen = jnp.where((jnp.arange(L + 1) == 0) & (tree.num_leaves <= 1),
                     0, -1).astype(jnp.int32)

    def body(m, carry):
        nodeP, node_len, leafP, plen = carry
        real = m < tree.num_leaves - 1
        blen = node_len[m] + 1
        for child_arr, sign in ((tree.left_child, 1.0),
                                (tree.right_child, -1.0)):
            c = child_arr[m]
            path = nodeP[m].at[m].set(sign)
            is_leaf = c < 0
            li = jnp.where(real & is_leaf, ~c, L)
            ni = jnp.where(real & ~is_leaf, c, M)
            leafP = leafP.at[li].set(path)
            plen = plen.at[li].set(blen)
            nodeP = nodeP.at[ni].set(path)
            node_len = node_len.at[ni].set(blen)
        return nodeP, node_len, leafP, plen

    _, _, leafP, plen = jax.lax.fori_loop(
        0, M, body, (nodeP, node_len, leafP, plen))
    return leafP[:L], plen[:L]


def _select_row_leaf(sel, leaf_value):
    """Per-row leaf value via single-nonzero selection.

    Each row lands in exactly one leaf, so the leaf-axis sum picks one
    value — exact in any order, and registered as a sanctioned numcheck
    context (tools/numcheck/reduction_registry.py)."""
    return jnp.sum(jnp.where(sel, leaf_value[:, None], 0.0), axis=0)


def predict_built_tree_matmul(tree: BuiltTree, data: DeviceData,
                              bins: jnp.ndarray) -> jnp.ndarray:
    """Leaf value per row of ``bins`` with NO per-row tree walk: every
    node decision at once + one path-agreement contraction (the in-scan
    valid-set scorer; same algorithm as ``predict_binned_matmul`` but
    for a single device-resident ``BuiltTree``).

    Steps (all exact): per-node bin values via a one-hot matmul against
    the stored columns (f32 operands — generalized gathers over
    ``[n, M]`` faulted the TPU worker at scale, r4), EFB unbundling +
    missing handling per node, ``d2 = ±1`` decisions, ``S = d2 @ P^T``
    and the leaf is the unique ``l`` with ``S[l] == plen[l]``.
    Numerical splits only — callers route categorical valid sets
    through ``predict_built_tree``."""
    from ..ops.pallas_route import unbundle_bin
    P, plen = built_tree_path_matrices(tree)
    f = tree.feature                              # [M] used-column ids
    G = bins.shape[1]
    # c[m, n]: node m's stored column value per row, as one matmul
    oh = jax.nn.one_hot(data.feat_group[f], G, dtype=jnp.float32)
    c = jax.lax.dot_general(
        oh, bins.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)       # [M, n]
    b = unbundle_bin(c.astype(jnp.int32), data.feat_offset[f][:, None],
                     data.num_bins[f][:, None], data.default_bins[f][:, None])
    mt = data.missing_types[f][:, None]
    is_missing = (((mt == MISSING_NAN) & (b == data.nan_bins[f][:, None]))
                  | ((mt == MISSING_ZERO)
                     & (b == data.default_bins[f][:, None])))
    go_left = jnp.where(is_missing, tree.default_left[:, None],
                        b <= tree.threshold_bin[:, None])
    d2 = (2.0 * go_left - 1.0).astype(jnp.bfloat16)          # [M, n] ±1
    # S[l, n] = sum_m P[l, m] * d2[m, n]; ±1 operands with f32
    # accumulation keep integer path sums exact up to |plen| <= M
    S = jax.lax.dot_general(
        P.astype(jnp.bfloat16), d2, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # [L, n]
    sel = (S == plen[:, None].astype(jnp.float32)) & (plen[:, None] >= 0)
    return _select_row_leaf(sel, tree.leaf_value)
