"""Serial (single-shard) tree learner — one jitted wave-growth loop.

TPU-native redesign of the reference ``SerialTreeLearner``
(`/root/reference/src/treelearner/serial_tree_learner.cpp:155-622`).  The
reference grows leaf-wise: a sequential best-first loop that, per split,
builds the smaller child's histograms (OpenMP over feature groups), derives
the sibling by subtraction, scans features for the best split, and
physically repartitions row indices (`data_partition.hpp`).

Here the whole tree is built by ONE ``lax.while_loop`` of *waves*:

  1. one histogram pass for ALL current leaves (``build_histograms`` —
     a single scatter keyed by the row→leaf vector; no data partition,
     no histogram pool, no ordered bins),
  2. one vectorized split search for all leaves × features
     (``find_best_splits``),
  3. split the top-``wave_size`` leaves by gain in the same wave.

``wave_size=1`` reproduces the reference's leaf-wise growth decision-for-
decision (one best-gain leaf per wave).  ``wave_size>=num_leaves`` splits
every positive-gain leaf per wave — ~log2(num_leaves) histogram passes per
tree instead of num_leaves−1, the TPU-friendly default (the histogram pass
costs O(n·F) regardless of how many leaves it serves, so batching splits
divides the dominant cost by the wave width).

Everything is static-shape: leaf arrays are sized ``[num_leaves]``, tree
node arrays ``[num_leaves-1]``, and finished trees report a dynamic
``num_leaves`` scalar.  The same step runs unchanged under ``shard_map``
for the distributed learners (histograms gain a ``psum``).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from ..io.device import DeviceData
from ..ops.histogram import build_histograms, pad_to_feature_grid
from ..ops.split import SplitParams, SplitResult, find_best_splits

NEG_INF = -1e30


class GrowthParams(NamedTuple):
    """Static tree-growth parameters."""
    num_leaves: int = 31
    max_depth: int = -1
    wave_size: int = 0          # 0 => unlimited (full wave); 1 => leaf-wise
    split: SplitParams = SplitParams()


class BuiltTree(NamedTuple):
    """A finished tree as device arrays (fixed shapes, dynamic num_leaves).

    Node layout matches the reference Tree (`tree.h`): internal nodes
    ``[0, num_leaves-2]``, children ``>=0`` internal / ``~leaf`` for leaves.
    """
    feature: jnp.ndarray         # [L-1] i32 (used-column index)
    threshold_bin: jnp.ndarray   # [L-1] i32
    default_left: jnp.ndarray    # [L-1] bool
    is_categorical: jnp.ndarray  # [L-1] bool
    cat_mask: jnp.ndarray        # [L-1, B] bool  (bins going left)
    left_child: jnp.ndarray      # [L-1] i32
    right_child: jnp.ndarray     # [L-1] i32
    gain: jnp.ndarray            # [L-1] f32
    internal_value: jnp.ndarray  # [L-1] f32 (parent leaf output)
    internal_count: jnp.ndarray  # [L-1] i32
    leaf_value: jnp.ndarray      # [L] f32
    leaf_count: jnp.ndarray      # [L] i32
    leaf_depth: jnp.ndarray      # [L] i32
    num_leaves: jnp.ndarray      # scalar i32
    row_leaf: jnp.ndarray        # [n] i32 final leaf per row (ALL rows)


class _WaveState(NamedTuple):
    row_leaf: jnp.ndarray        # [n] leaf per row (all rows, incl. out-of-bag)
    hist_leaf: jnp.ndarray       # [n] leaf per row or -1 (out-of-bag)
    nl: jnp.ndarray              # scalar i32 current leaf count
    done: jnp.ndarray            # scalar bool
    leaf_sum_grad: jnp.ndarray   # [L]
    leaf_sum_hess: jnp.ndarray   # [L]
    leaf_count: jnp.ndarray      # [L] f32 (in-bag counts)
    leaf_depth: jnp.ndarray      # [L] i32
    leaf_value: jnp.ndarray      # [L] f32
    leaf_parent: jnp.ndarray     # [L] i32 node idx
    leaf_is_left: jnp.ndarray    # [L] bool
    tree: BuiltTree


def _row_go_left(data: DeviceData, best: SplitResult, row_leaf, rows_feature,
                 rows_bin):
    """Per-row left/right decision for the leaf's chosen split."""
    l = row_leaf
    f = rows_feature                                     # [n] split feature per row
    b = rows_bin                                         # [n] bin at that feature
    mt = data.missing_types[f]
    is_missing = (((mt == MISSING_NAN) & (b == data.nan_bins[f]))
                  | ((mt == MISSING_ZERO) & (b == data.default_bins[f])))
    thr = best.threshold[l]
    num_left = jnp.where(is_missing, best.default_left[l], b <= thr)
    cat_left = best.cat_mask[l, jnp.minimum(b, best.cat_mask.shape[-1] - 1)]
    return jnp.where(best.is_categorical[l], cat_left, num_left)


def default_splitter(data: DeviceData, grad, hess, params: GrowthParams,
                     feature_mask, psum_fn=None, hist_fn=build_histograms):
    """The serial find-splits strategy: histograms for all leaves + one
    vectorized scan.  Distributed learners swap this closure out (the
    analog of the reference's learner-template matrix,
    `tree_learner.cpp:9-33`); `psum_fn` injects the data-parallel
    histogram collective (`data_parallel_tree_learner.cpp:147-162`)."""
    L = params.num_leaves
    B = data.max_bins

    def splitter(hist_leaf, leaf_sum_grad, leaf_sum_hess, leaf_count):
        hist_flat = hist_fn(data.bins, grad, hess, hist_leaf,
                            data.bin_offsets, L, data.total_bins)
        if psum_fn is not None:
            hist_flat = psum_fn(hist_flat)
        grid = pad_to_feature_grid(hist_flat, data.bin_offsets,
                                   data.num_bins, B)
        return find_best_splits(grid, leaf_sum_grad, leaf_sum_hess,
                                leaf_count, data.num_bins,
                                data.missing_types, data.default_bins,
                                data.is_categorical, params.split,
                                feature_mask,
                                any_categorical=data.has_categorical)
    return splitter


def build_tree(data: DeviceData,
               grad: jnp.ndarray,
               hess: jnp.ndarray,
               params: GrowthParams,
               bag_mask: Optional[jnp.ndarray] = None,
               feature_mask: Optional[jnp.ndarray] = None,
               hist_fn=build_histograms,
               psum_fn=None,
               splitter=None) -> BuiltTree:
    """Grow one tree.  Jittable; `psum_fn` lets distributed learners inject
    a collective over local histograms (the reference's ReduceScatter seam,
    `data_parallel_tree_learner.cpp:147-162`); `splitter` replaces the whole
    find-splits strategy (feature/voting-parallel)."""
    n, F = data.bins.shape
    L = params.num_leaves
    Lm = max(L - 1, 1)
    B = data.max_bins

    row_leaf = jnp.zeros(n, jnp.int32)
    hist_leaf = (jnp.where(bag_mask, 0, -1).astype(jnp.int32)
                 if bag_mask is not None else jnp.zeros(n, jnp.int32))

    tree = BuiltTree(
        feature=jnp.zeros(Lm, jnp.int32),
        threshold_bin=jnp.zeros(Lm, jnp.int32),
        default_left=jnp.zeros(Lm, bool),
        is_categorical=jnp.zeros(Lm, bool),
        cat_mask=jnp.zeros((Lm, B), bool),
        left_child=jnp.full(Lm, -1, jnp.int32),
        right_child=jnp.full(Lm, -1, jnp.int32),
        gain=jnp.zeros(Lm, jnp.float32),
        internal_value=jnp.zeros(Lm, jnp.float32),
        internal_count=jnp.zeros(Lm, jnp.int32),
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_count=jnp.zeros(L, jnp.int32),
        leaf_depth=jnp.zeros(L, jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32),
        row_leaf=row_leaf,
    )

    # root statistics (in-bag)
    bag = (hist_leaf == 0)
    sum_g = jnp.sum(jnp.where(bag, grad, 0.0))
    sum_h = jnp.sum(jnp.where(bag, hess, 0.0))
    cnt = jnp.sum(bag.astype(jnp.float32))
    if psum_fn is not None:
        sum_g, sum_h, cnt = psum_fn((sum_g, sum_h, cnt))

    from ..ops.split import leaf_output as _leaf_out
    root_out = _leaf_out(sum_g, sum_h, params.split.lambda_l1,
                         params.split.lambda_l2)

    state = _WaveState(
        row_leaf=row_leaf, hist_leaf=hist_leaf,
        nl=jnp.asarray(1, jnp.int32), done=jnp.asarray(False),
        leaf_sum_grad=jnp.zeros(L).at[0].set(sum_g),
        leaf_sum_hess=jnp.zeros(L).at[0].set(sum_h),
        leaf_count=jnp.zeros(L).at[0].set(cnt),
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(root_out),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_is_left=jnp.zeros(L, bool),
        tree=tree,
    )

    wave = params.wave_size if params.wave_size > 0 else L
    if splitter is None:
        splitter = default_splitter(data, grad, hess, params, feature_mask,
                                    psum_fn=psum_fn, hist_fn=hist_fn)

    def cond(s: _WaveState):
        return (~s.done) & (s.nl < L)

    def body(s: _WaveState) -> _WaveState:
        best = splitter(s.hist_leaf, s.leaf_sum_grad, s.leaf_sum_hess,
                        s.leaf_count)
        lid = jnp.arange(L)
        gain = jnp.where(lid < s.nl, best.gain, NEG_INF)
        if params.max_depth > 0:
            gain = jnp.where(s.leaf_depth >= params.max_depth, NEG_INF, gain)
        can = gain > 0.0

        order = jnp.argsort(-gain)                      # leaves by gain desc
        rank = jnp.argsort(order)                       # rank[l]
        budget = L - s.nl
        k = jnp.minimum(jnp.minimum(jnp.sum(can), budget), wave)
        sel = can & (rank < k)

        new_id = jnp.where(sel, s.nl + rank, L)         # L => drop scatter
        node_idx = jnp.where(sel, s.nl - 1 + rank, Lm)  # Lm => drop scatter

        # --- record tree nodes (scatter at node_idx; drop where unselected)
        t = s.tree
        dl = jnp.where(best.is_categorical, False, best.default_left)
        t = t._replace(
            feature=t.feature.at[node_idx].set(best.feature, mode="drop"),
            threshold_bin=t.threshold_bin.at[node_idx].set(best.threshold,
                                                           mode="drop"),
            default_left=t.default_left.at[node_idx].set(dl, mode="drop"),
            is_categorical=t.is_categorical.at[node_idx].set(
                best.is_categorical, mode="drop"),
            cat_mask=t.cat_mask.at[node_idx].set(best.cat_mask, mode="drop"),
            gain=t.gain.at[node_idx].set(best.gain, mode="drop"),
            internal_value=t.internal_value.at[node_idx].set(
                s.leaf_value, mode="drop"),
            internal_count=t.internal_count.at[node_idx].set(
                s.leaf_count.astype(jnp.int32), mode="drop"),
            left_child=t.left_child.at[node_idx].set(~lid, mode="drop"),
            right_child=t.right_child.at[node_idx].set(
                ~new_id, mode="drop"),
        )
        # fix the parent's child pointer: leaf l was ~l, becomes node_idx
        parent = jnp.where(sel, s.leaf_parent, -1)
        fix_left = jnp.where(sel & s.leaf_is_left & (parent >= 0),
                             parent, Lm)
        fix_right = jnp.where(sel & ~s.leaf_is_left & (parent >= 0),
                              parent, Lm)
        t = t._replace(
            left_child=t.left_child.at[fix_left].set(node_idx, mode="drop"),
            right_child=t.right_child.at[fix_right].set(node_idx, mode="drop"),
        )

        # --- update leaf state: left child keeps id l, right child -> new_id
        depth1 = s.leaf_depth + 1
        lsg = jnp.where(sel, best.left_sum_grad, s.leaf_sum_grad)
        lsh = jnp.where(sel, best.left_sum_hess, s.leaf_sum_hess)
        lc = jnp.where(sel, best.left_count, s.leaf_count)
        lv = jnp.where(sel, best.left_output, s.leaf_value)
        ld = jnp.where(sel, depth1, s.leaf_depth)
        lp = jnp.where(sel, node_idx, s.leaf_parent)
        lil = jnp.where(sel, True, s.leaf_is_left)

        lsg = lsg.at[new_id].set(best.right_sum_grad, mode="drop")
        lsh = lsh.at[new_id].set(best.right_sum_hess, mode="drop")
        lc = lc.at[new_id].set(best.right_count, mode="drop")
        lv = lv.at[new_id].set(best.right_output, mode="drop")
        ld = ld.at[new_id].set(depth1, mode="drop")
        lp = lp.at[new_id].set(node_idx, mode="drop")
        lil = lil.at[new_id].set(False, mode="drop")

        # --- route rows ------------------------------------------------
        def route(leaf_vec):
            safe = jnp.maximum(leaf_vec, 0)
            f = best.feature[safe]
            b = jnp.take_along_axis(
                data.bins, f[:, None], axis=1)[:, 0].astype(jnp.int32)
            go_left = _row_go_left(data, best, safe, f, b)
            moved = sel[safe] & ~go_left & (leaf_vec >= 0)
            return jnp.where(moved, new_id[safe], leaf_vec)

        row_leaf2 = route(s.row_leaf)
        hist_leaf2 = route(s.hist_leaf)

        nl2 = s.nl + k
        return _WaveState(
            row_leaf=row_leaf2, hist_leaf=hist_leaf2, nl=nl2,
            done=(k == 0),
            leaf_sum_grad=lsg, leaf_sum_hess=lsh, leaf_count=lc,
            leaf_depth=ld, leaf_value=lv, leaf_parent=lp, leaf_is_left=lil,
            tree=t)

    final = jax.lax.while_loop(cond, body, state)
    return final.tree._replace(
        leaf_value=final.leaf_value,
        leaf_count=final.leaf_count.astype(jnp.int32),
        leaf_depth=final.leaf_depth,
        num_leaves=final.nl,
        row_leaf=final.row_leaf,
    )


def predict_built_tree(tree: BuiltTree, data: DeviceData,
                       bins: jnp.ndarray) -> jnp.ndarray:
    """Leaf value per row of `bins` for a just-built tree (validation score
    update path; train rows use ``tree.row_leaf`` directly)."""
    n = bins.shape[0]
    node = jnp.where(tree.num_leaves > 1, 0, ~0) * jnp.ones(n, jnp.int32)

    def body(_, node):
        is_leaf = node < 0
        nidx = jnp.maximum(node, 0)
        f = tree.feature[nidx]
        b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0].astype(jnp.int32)
        mt = data.missing_types[f]
        is_missing = (((mt == MISSING_NAN) & (b == data.nan_bins[f]))
                      | ((mt == MISSING_ZERO) & (b == data.default_bins[f])))
        num_left = jnp.where(is_missing, tree.default_left[nidx],
                             b <= tree.threshold_bin[nidx])
        cat_left = tree.cat_mask[nidx, jnp.minimum(b, tree.cat_mask.shape[-1] - 1)]
        go_left = jnp.where(tree.is_categorical[nidx], cat_left, num_left)
        nxt = jnp.where(go_left, tree.left_child[nidx], tree.right_child[nidx])
        return jnp.where(is_leaf, node, nxt)

    depth = tree.leaf_value.shape[0] - 1
    node = jax.lax.fori_loop(0, depth, body, node)
    leaf = jnp.where(node < 0, ~node, 0)
    return tree.leaf_value[leaf]
