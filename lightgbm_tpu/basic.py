"""User-facing Dataset and Booster classes.

API parity with the reference Python package
(`/root/reference/python-package/lightgbm/basic.py`: ``Dataset``
`basic.py:572`, ``Booster`` `basic.py:1264`) — same constructor signatures
and core methods, so reference users can switch imports.  Unlike the
reference (ctypes over a C core), the data pipeline here is
numpy→binning→HBM and the booster drives the jitted JAX training step
directly; pandas input is handled the same way (categorical dtype columns
auto-detected, `basic.py:239-305`).
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config, canonicalize_params
from .io.dataset import BinnedDataset, Metadata
from .utils.log import log_info, log_warning


def _data_to_numpy(data):
    """Accept numpy / pandas / list-of-lists / scipy-CSR-like."""
    if hasattr(data, "toarray"):          # scipy sparse
        return np.asarray(data.toarray(), np.float64), None
    if hasattr(data, "dtypes") and hasattr(data, "columns"):   # pandas
        import pandas as pd               # local import; optional dep
        df = data
        cat_cols = [i for i, dt in enumerate(df.dtypes)
                    if str(dt) == "category"]
        out = np.empty((len(df), df.shape[1]), np.float64)
        for i, col in enumerate(df.columns):
            s = df[col]
            if str(s.dtype) == "category":
                out[:, i] = s.cat.codes.astype(np.float64)
            else:
                out[:, i] = pd.to_numeric(s, errors="coerce").astype(np.float64)
        names = [str(c) for c in df.columns]
        return out, {"categorical": cat_cols, "names": names}
    arr = np.asarray(data)
    if arr.dtype == np.object_:
        arr = arr.astype(np.float64)
    return arr, None


class Dataset:
    """Training data wrapper (reference basic.py:572-1262 API surface)."""

    def __init__(self, data, label=None, reference=None, weight=None,
                 group=None, init_score=None, feature_name="auto",
                 categorical_feature="auto", params=None,
                 free_raw_data=True, silent=False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._constructed: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None

    # -- construction ---------------------------------------------------
    def construct(self) -> "Dataset":
        if self._constructed is not None:
            return self
        if self.reference is not None:
            ref = self.reference.construct()._constructed
        else:
            ref = None
        if isinstance(self.data, str):
            from .io.loader import load_file
            cfg = Config.from_params(self.params)
            rank, world, ag = 0, 1, None
            if cfg.num_machines > 1 and ref is None:
                import jax
                if jax.process_count() > 1:
                    # distributed file load: mod-rank row sharding +
                    # feature-sharded bin-find allgather — ONLY for the
                    # row-sharding learners.  Feature-parallel keeps the
                    # full rows on every machine (reference semantics,
                    # feature_parallel_tree_learner.cpp), and serial
                    # must too (sharding it would silently train each
                    # rank on 1/world of the data)
                    if cfg.tree_learner in ("data", "voting"):
                        from .io.distributed import jax_process_allgather
                        rank = jax.process_index()
                        world = jax.process_count()
                        ag = jax_process_allgather
            ds = load_file(self.data, cfg, reference=ref,
                           rank=rank, num_machines=world, allgather=ag)
            if self.label is None and ds.metadata.label is not None:
                pass
            self._constructed = ds
            self._apply_fields()
            return self
        X, pd_info = _data_to_numpy(self.data)
        cat = []
        names = None
        if pd_info is not None:
            names = pd_info["names"]
            if self.categorical_feature == "auto":
                cat = pd_info["categorical"]
        if self.categorical_feature not in ("auto", None):
            cat = [names.index(c) if isinstance(c, str) and names else int(c)
                   for c in self.categorical_feature]
        if isinstance(self.feature_name, (list, tuple)):
            names = list(self.feature_name)
        cfg = Config.from_params(self.params)
        md = Metadata()
        self._constructed = BinnedDataset.from_raw(
            X, cfg, categorical_features=cat, feature_names=names,
            reference=ref, metadata=md)
        self._apply_fields()
        if self.free_raw_data:
            self.data = None
        return self

    def _apply_fields(self):
        md = self._constructed.metadata
        if self.label is not None:
            md.set_field("label", np.asarray(self.label).reshape(-1))
        if self.weight is not None:
            md.set_field("weight", self.weight)
        if self.group is not None:
            md.set_field("group", self.group)
        if self.init_score is not None:
            md.set_field("init_score", self.init_score)

    # -- reference API surface ------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None):
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def subset(self, used_indices, params=None):
        self.construct()
        sub = Dataset.__new__(Dataset)
        sub.__dict__.update({k: v for k, v in self.__dict__.items()})
        sub._constructed = self._constructed.subset(np.asarray(used_indices))
        sub.used_indices = np.asarray(used_indices)
        sub.reference = self
        return sub

    def set_field(self, name, data):
        self.construct()
        self._constructed.metadata.set_field(name, data)

    def get_field(self, name):
        self.construct()
        return self._constructed.metadata.get_field(name)

    def set_label(self, label):
        self.label = label
        if self._constructed is not None:
            self._constructed.metadata.set_field("label", label)

    def set_weight(self, weight):
        self.weight = weight
        if self._constructed is not None:
            self._constructed.metadata.set_field("weight", weight)

    def set_group(self, group):
        self.group = group
        if self._constructed is not None:
            self._constructed.metadata.set_field("group", group)

    def set_init_score(self, init_score):
        self.init_score = init_score
        if self._constructed is not None:
            self._constructed.metadata.set_field("init_score", init_score)

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        qb = self.get_field("group")
        return None if qb is None else np.diff(qb)

    def get_init_score(self):
        return self.get_field("init_score")

    def num_data(self) -> int:
        self.construct()
        return self._constructed.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._constructed.num_total_features

    def save_binary(self, filename: str):
        self.construct()
        self._constructed.save_binary(filename)

    @property
    def feature_names(self):
        self.construct()
        return self._constructed.feature_names


class Booster:
    """Trained model handle (reference basic.py:1264+ API surface)."""

    def __init__(self, params=None, train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent=False):
        params = dict(params or {})
        self.params = params
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_dataset = train_set
        if train_set is not None:
            train_set.construct()
            cfg = Config.from_params(params)
            from .boosting.variants import create_boosting
            self._gbdt = create_boosting(cfg, train_set._constructed,
                                         fobj=cfg.extra.get("fobj"))
            self._valid_sets: List[Dataset] = []
            self._name_valid_sets: List[str] = []
        elif model_file is not None:
            from .utils.file_io import open_read
            with open_read(model_file) as f:
                text = f.read()
            self._init_from_string(text)
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise ValueError(
                "need at least one of train_set, model_file, model_str")

    def _init_from_string(self, text):
        from .boosting.gbdt import GBDT
        cfg = Config.from_params(self.params)
        self._gbdt = GBDT(cfg, None)
        self._gbdt.load_model_from_string(text)
        self._valid_sets = []
        self._name_valid_sets = []

    # -- training -------------------------------------------------------
    def add_valid(self, data: Dataset, name: str):
        data.construct()
        self._gbdt.add_valid(data._constructed, name)
        self._valid_sets.append(data)
        self._name_valid_sets.append(name)
        return self

    def update(self, train_set=None, fobj=None):
        """One boosting iteration; returns True if fully trained
        (reference Booster.update, basic.py)."""
        if fobj is not None:
            score = self._gbdt.scores
            import jax.numpy as jnp
            K = self._gbdt.num_tree_per_iteration
            s = (np.asarray(score).reshape(-1, order="F") if K > 1
                 else np.asarray(score[:, 0]))
            grad, hess = fobj(s, self._train_dataset)
            grad = np.asarray(grad, np.float32).reshape(-1, K, order="F")
            hess = np.asarray(hess, np.float32).reshape(-1, K, order="F")
            return self._gbdt.train_one_iter(jnp.asarray(grad),
                                             jnp.asarray(hess))
        return self._gbdt.train_one_iter()

    def rollback_one_iter(self):
        self._gbdt.rollback_one_iter()
        return self

    @property
    def current_iteration(self):
        return self._gbdt.current_iteration

    def num_trees(self):
        return self._gbdt.num_trees()

    def digest(self, include_scores: bool = True) -> str:
        """Canonical model/score sha256 — the reproducibility contract's
        unit of comparison (``obs/determinism.py``): identical data +
        config + seeds must reproduce this digest bit-for-bit.  Pass
        ``include_scores=False`` to hash the model alone (e.g. after
        ``free_dataset()`` the score state is gone)."""
        return self._gbdt.digest(include_scores=include_scores)

    # -- evaluation -----------------------------------------------------
    def eval_train(self, feval=None):
        name = getattr(self, "_train_data_name", "training")
        results = [(name, m, v, h) for _, m, v, h in self._gbdt.eval_train()]
        return self._format_eval(results, feval, name, self._train_dataset)

    def eval_valid(self, feval=None):
        out = self._format_eval(self._gbdt.eval_valid(), feval, None, None)
        if feval is not None:
            for i, vs in enumerate(self._valid_sets):
                out.extend(self._custom_eval(
                    feval, self._name_valid_sets[i], vs,
                    np.asarray(self._gbdt._valid_scores[i])))
        return out

    def _format_eval(self, results, feval, train_name, train_set):
        out = [(name, metric, val, hib) for name, metric, val, hib in results]
        if feval is not None and train_name is not None:
            out.extend(self._custom_eval(feval, train_name, train_set,
                                         np.asarray(self._gbdt.scores)))
        return out

    def _custom_eval(self, feval, name, dataset, scores):
        s = scores if scores.shape[1] > 1 else scores[:, 0]
        res = feval(s, dataset)
        if isinstance(res, tuple):
            res = [res]
        return [(name, mn, mv, hib) for mn, mv, hib in res]

    # -- prediction -----------------------------------------------------
    def predict(self, data, num_iteration=-1, raw_score=False,
                pred_leaf=False, pred_contrib=False, device=None,
                **kwargs):
        """Predict (reference Booster.predict surface).

        ``num_iteration`` (``<= 0`` -> ``best_iteration`` when set)
        truncates EVERY mode identically — the slicing lives in one
        place per path (``GBDT.predict_raw`` / ``GBDT.predict_leaf`` /
        ``serve.compile_model``), multiclass included.

        ``device`` selects the serving path: ``True`` compiles the
        model once (cached per truncation) into the TPU-resident
        tensorized predictor (``lightgbm_tpu/serve/``) and scores the
        whole batch in one jitted dispatch; ``False`` forces the
        legacy path; ``None`` (default) follows the
        ``LGBM_TPU_PREDICT_DEVICE`` env var (off by default).
        ``pred_contrib`` always takes the host path.
        """
        X, _ = _data_to_numpy(data)
        if num_iteration is None or num_iteration <= 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        if device is None:
            import os
            device = os.environ.get("LGBM_TPU_PREDICT_DEVICE",
                                    "") not in ("", "0")
        if pred_contrib:
            from .boosting.contrib import predict_contrib
            return predict_contrib(self._gbdt, X, num_iteration)
        if device:
            cm = self._device_predictor(num_iteration)
            if pred_leaf:
                return cm.leaf_indices(X)
            return cm.predict(X, raw_score=raw_score)
        if pred_leaf:
            return self._gbdt.predict_leaf(X, num_iteration=num_iteration)
        return self._gbdt.predict(X, raw_score=raw_score,
                                  num_iteration=num_iteration)

    def _device_predictor(self, num_iteration=-1):
        """The serving-compiled form of this model, cached per
        (model length, truncation) — training another iteration or
        rolling back invalidates by key."""
        from .serve import compile_model
        key = (len(self._gbdt.models), int(num_iteration or -1))
        cache = getattr(self, "_serve_cache", None)
        if cache is None or key not in cache:
            # single-entry cache: stale packs from previous lengths
            # would otherwise pin device memory
            self._serve_cache = {key: compile_model(
                self._gbdt, num_iteration=num_iteration)}
        return self._serve_cache[key]

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Re-fit the existing tree structures' leaf values on new data
        (reference python-package ``Booster.refit`` over
        ``LGBM_BoosterRefit`` / RefitTree, gbdt.cpp:268-280):
        ``new_leaf = decay_rate * old + (1 - decay_rate) * refit``.
        Returns a NEW Booster; this one is untouched.  ``kwargs`` apply
        to BOTH the refit dataset and the new booster's config
        (lambda_l1/l2 etc. steer the refit leaf estimates)."""
        params = dict(self.params)
        params.update(kwargs)
        new = Booster(params=params, model_str=self.model_to_string())
        if kwargs:
            new._gbdt.reset_config(params)
        ds = Dataset(data, label=label, params=params)
        ds.construct()
        new._gbdt.refit_dataset(ds._constructed, decay_rate=decay_rate)
        return new

    # -- model IO -------------------------------------------------------
    def save_model(self, filename, num_iteration=-1):
        if num_iteration is None or num_iteration <= 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        self._gbdt.save_model(filename, num_iteration)
        return self

    def model_to_string(self, num_iteration=-1):
        return self._gbdt.save_model_to_string(num_iteration or -1)

    def model_from_string(self, model_str, verbose=True):
        self._init_from_string(model_str)
        return self

    def dump_model(self, num_iteration=-1):
        """JSON dump (reference DumpModel, gbdt_model_text.cpp:15-49)."""
        g = self._gbdt
        trees = []
        T = len(g.models)
        if num_iteration and num_iteration > 0:
            T = min(T, num_iteration * g.num_tree_per_iteration)
        for i, t in enumerate(g.models[:T]):
            trees.append({
                "tree_index": i,
                "num_leaves": t.num_leaves,
                "num_cat": t.num_cat,
                "shrinkage": t.shrinkage_rate,
                "tree_structure": _tree_to_json(t, 0),
            })
        return {
            "name": "tree",
            "version": "v2",
            "num_class": g.num_class,
            "num_tree_per_iteration": g.num_tree_per_iteration,
            "label_index": 0,
            "max_feature_idx": g.max_feature_idx,
            "feature_names": g.feature_names,
            "objective": (g.objective.to_string() if g.objective else ""),
            "average_output": g.average_output,
            "tree_info": trees,
        }

    def feature_importance(self, importance_type="split", iteration=-1):
        return self._gbdt.feature_importance(importance_type, iteration or -1)

    def feature_name(self):
        return list(self._gbdt.feature_names)

    def num_feature(self):
        return self._gbdt.max_feature_idx + 1

    def free_dataset(self):
        self._train_dataset = None
        return self

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        return Booster(params=self.params,
                       model_str=self.model_to_string())

    def __getstate__(self):
        state = {"params": self.params,
                 "model_str": self.model_to_string(),
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        self._init_from_string(state["model_str"])
        self._train_dataset = None


def _tree_to_json(t, node):
    if t.num_leaves == 1:
        return {"leaf_value": float(t.leaf_value[0])}
    if node < 0:
        leaf = ~node
        return {"leaf_index": int(leaf),
                "leaf_value": float(t.leaf_value[leaf]),
                "leaf_count": int(t.leaf_count[leaf])}
    is_cat = bool(t.decision_type[node] & 1)
    d = {
        "split_index": int(node),
        "split_feature": int(t.split_feature[node]),
        "split_gain": float(t.split_gain[node]),
        "threshold": float(t.threshold[node]),
        "decision_type": "==" if is_cat else "<=",
        "default_left": bool(t.decision_type[node] & 2),
        "missing_type": ["None", "Zero", "NaN"][(t.decision_type[node] >> 2) & 3],
        "internal_value": float(t.internal_value[node]),
        "internal_count": int(t.internal_count[node]),
        "left_child": _tree_to_json(t, int(t.left_child[node])),
        "right_child": _tree_to_json(t, int(t.right_child[node])),
    }
    return d
