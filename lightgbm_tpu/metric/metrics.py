"""Evaluation metrics — vectorized jnp/numpy implementations.

Counterparts of the reference metric classes (factory
`/root/reference/src/metric/metric.cpp:11-57`; regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp, map_metric.hpp,
xentropy_metric.hpp, dcg_calculator.cpp).  Each metric is
``eval(label, score, weight, query) -> list[(name, value, higher_better)]``
where ``score`` is the RAW model score; link inversion (sigmoid/softmax/
exp) is applied internally, matching the reference's convention of passing
the objective into ``Metric::Eval``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config

EvalResult = Tuple[str, float, bool]   # (name, value, higher_is_better)


def _wmean(values: np.ndarray, weight: Optional[np.ndarray]) -> float:
    if weight is None:
        return float(np.mean(values))
    return float(np.sum(values * weight) / np.sum(weight))


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class Metric:
    names: Sequence[str] = ()
    higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def eval(self, label, score, weight=None, query=None) -> List[EvalResult]:
        raise NotImplementedError


# --- regression metrics (regression_metric.hpp:16+) ------------------------
class L2Metric(Metric):
    names = ("l2",)

    def eval(self, label, score, weight=None, query=None):
        return [("l2", _wmean((score - label) ** 2, weight), False)]


class RMSEMetric(Metric):
    names = ("rmse",)

    def eval(self, label, score, weight=None, query=None):
        return [("rmse", float(np.sqrt(_wmean((score - label) ** 2, weight))),
                 False)]


class L1Metric(Metric):
    names = ("l1",)

    def eval(self, label, score, weight=None, query=None):
        return [("l1", _wmean(np.abs(score - label), weight), False)]


class QuantileMetric(Metric):
    names = ("quantile",)

    def eval(self, label, score, weight=None, query=None):
        a = self.config.alpha
        d = label - score
        loss = np.where(d >= 0, a * d, (a - 1.0) * d)
        return [("quantile", _wmean(loss, weight), False)]


class HuberMetric(Metric):
    names = ("huber",)

    def eval(self, label, score, weight=None, query=None):
        a = self.config.alpha
        d = np.abs(score - label)
        loss = np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
        return [("huber", _wmean(loss, weight), False)]


class FairMetric(Metric):
    names = ("fair",)

    def eval(self, label, score, weight=None, query=None):
        c = self.config.fair_c
        x = np.abs(score - label)
        loss = c * x - c * c * np.log1p(x / c)
        return [("fair", _wmean(loss, weight), False)]


class PoissonMetric(Metric):
    names = ("poisson",)

    def eval(self, label, score, weight=None, query=None):
        # score is raw (log link)
        loss = np.exp(score) - label * score
        return [("poisson", _wmean(loss, weight), False)]


class MapeMetric(Metric):
    names = ("mape",)

    def eval(self, label, score, weight=None, query=None):
        loss = np.abs((label - score) / np.maximum(1.0, np.abs(label)))
        return [("mape", _wmean(loss, weight), False)]


class GammaMetric(Metric):
    names = ("gamma",)

    def eval(self, label, score, weight=None, query=None):
        # negative log-likelihood of Gamma with log link (regression_metric.hpp)
        psi = 1.0
        theta = -1.0 / np.maximum(np.exp(score), 1e-15)
        a = psi
        b = -np.log(-theta)
        loss = -(label * theta - b) / a
        return [("gamma", _wmean(loss, weight), False)]


class GammaDevianceMetric(Metric):
    names = ("gamma_deviance", "gamma-deviance")

    def eval(self, label, score, weight=None, query=None):
        eps = 1e-9
        mu = np.maximum(np.exp(score), eps)
        frac = np.maximum(label, eps) / mu
        loss = 2.0 * (-np.log(frac) + frac - 1.0)
        return [("gamma-deviance", _wmean(loss, weight), False)]


class TweedieMetric(Metric):
    names = ("tweedie",)

    def eval(self, label, score, weight=None, query=None):
        rho = self.config.tweedie_variance_power
        mu = np.maximum(np.exp(score), 1e-15)
        a = label * np.power(mu, 1.0 - rho) / (1.0 - rho)
        b = np.power(mu, 2.0 - rho) / (2.0 - rho)
        return [("tweedie", _wmean(-a + b, weight), False)]


# --- binary metrics (binary_metric.hpp:20+) --------------------------------
class BinaryLoglossMetric(Metric):
    names = ("binary_logloss",)

    def eval(self, label, score, weight=None, query=None):
        p = np.clip(_sigmoid(self.config.sigmoid * score), 1e-15, 1 - 1e-15)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return [("binary_logloss", _wmean(loss, weight), False)]


class BinaryErrorMetric(Metric):
    names = ("binary_error",)

    def eval(self, label, score, weight=None, query=None):
        pred = (score > 0).astype(np.float64)
        return [("binary_error", _wmean((pred != label).astype(np.float64),
                                        weight), False)]


_WARNED_DEGENERATE_AUC: set = set()


def _warn_degenerate_auc(msg: str) -> None:
    """Warn ONCE per degenerate-AUC condition per process: eval runs
    every iteration, and the reference warns a single time at metric
    Init (binary_metric.hpp), not per evaluation."""
    if msg not in _WARNED_DEGENERATE_AUC:
        _WARNED_DEGENERATE_AUC.add(msg)
        from ..utils.log import log_warning
        log_warning(msg)


def binary_auc(label, score, weight=None):
    """Tie-aware rank-sum AUC with weights (binary_metric.hpp:157-234
    semantics, computed by sort + cumulative sums instead of bucket
    merge) — the shared helper behind AucMetric, the bench gate, and
    the parity tooling."""
    label = np.asarray(label)
    score = np.asarray(score)
    if len(label) == 0:
        # degenerate input (e.g. an empty valid set or a zero-row rank
        # shard): NaN, never a silent perfect score (ADVICE r4)
        _warn_degenerate_auc("AUC over an empty set is undefined; "
                             "returning NaN")
        return float("nan")
    order = np.argsort(score, kind="mergesort")
    s = score[order]
    y = label[order]
    # f64 throughout: the rank-sum area is O(n^2/4) — ~2.7e13 at 10.5M
    # rows, far past f32's 24-bit integer range (a f32 accumulation
    # returned AUC > 1 on the full-scale bench leg)
    w = (weight[order].astype(np.float64) if weight is not None
         else np.ones(len(y), np.float64))
    wp = w * (y > 0)
    wn = w * (y <= 0)
    # group ties: average rank treatment via per-tie-block trapezoid
    # cumulative negatives BEFORE each block + half within block —
    # vectorized with reduceat (continuous scores mean ~n blocks; a
    # Python block loop took minutes at 10.5M rows)
    boundaries = np.nonzero(np.diff(s))[0]
    starts = np.concatenate([[0], boundaries + 1])
    bp = np.add.reduceat(wp, starts)
    bn = np.add.reduceat(wn, starts)
    cum_before = np.concatenate([[0.0], np.cumsum(bn)[:-1]])
    area = float(np.sum(bp * (cum_before + 0.5 * bn)))
    total_pos = wp.sum()
    total_neg = wn.sum()
    if total_pos == 0 or total_neg == 0:
        # the reference warns and skips AUC when a class is absent
        # (binary_metric.hpp Init); keep the conventional 1.0 but say so
        _warn_degenerate_auc("AUC over a single-class set is degenerate; "
                             "reporting 1.0")
        return 1.0
    return float(area / (total_pos * total_neg))


class AucMetric(Metric):
    names = ("auc",)
    higher_better = True

    def eval(self, label, score, weight=None, query=None):
        return [("auc", binary_auc(label, score, weight), True)]


# --- multiclass (multiclass_metric.hpp:16+) --------------------------------
class MultiLoglossMetric(Metric):
    names = ("multi_logloss",)

    def eval(self, label, score, weight=None, query=None):
        # score [n, K] raw
        s = score - score.max(axis=1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(axis=1, keepdims=True)
        idx = label.astype(np.int64)
        loss = -np.log(np.clip(p[np.arange(len(label)), idx], 1e-15, None))
        return [("multi_logloss", _wmean(loss, weight), False)]


class MultiErrorMetric(Metric):
    names = ("multi_error",)

    def eval(self, label, score, weight=None, query=None):
        pred = np.argmax(score, axis=1)
        err = (pred != label.astype(np.int64)).astype(np.float64)
        return [("multi_error", _wmean(err, weight), False)]


# --- ranking (rank_metric.hpp, map_metric.hpp, dcg_calculator.cpp) ---------
class NDCGMetric(Metric):
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = tuple(config.ndcg_eval_at) or (1, 2, 3, 4, 5)
        gains = config.label_gain
        if not gains:
            gains = tuple(float((1 << i) - 1) for i in range(31))
        self.label_gain = np.asarray(gains)
        self.names = tuple(f"ndcg@{k}" for k in self.eval_at)

    def eval(self, label, score, weight=None, query=None):
        assert query is not None, "ndcg requires query boundaries"
        qb = np.asarray(query)
        results = {k: [] for k in self.eval_at}
        qw = np.ones(len(qb) - 1)
        for q in range(len(qb) - 1):
            l = label[qb[q]:qb[q + 1]].astype(np.int64)
            s = score[qb[q]:qb[q + 1]]
            order = np.argsort(-s, kind="mergesort")
            gains = self.label_gain[l[order]]
            ideal = np.sort(self.label_gain[l])[::-1]
            disc = 1.0 / np.log2(np.arange(len(l)) + 2.0)
            for k in self.eval_at:
                kk = min(k, len(l))
                idcg = np.sum(ideal[:kk] * disc[:kk])
                if idcg <= 0:
                    results[k].append(1.0)   # all-zero-gain query counts 1
                else:
                    results[k].append(np.sum(gains[:kk] * disc[:kk]) / idcg)
        return [(f"ndcg@{k}", float(np.average(results[k], weights=qw)), True)
                for k in self.eval_at]


class MapMetric(Metric):
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = tuple(config.ndcg_eval_at) or (1, 2, 3, 4, 5)
        self.names = tuple(f"map@{k}" for k in self.eval_at)

    def eval(self, label, score, weight=None, query=None):
        assert query is not None, "map requires query boundaries"
        qb = np.asarray(query)
        results = {k: [] for k in self.eval_at}
        for q in range(len(qb) - 1):
            l = (label[qb[q]:qb[q + 1]] > 0).astype(np.float64)
            s = score[qb[q]:qb[q + 1]]
            order = np.argsort(-s, kind="mergesort")
            rel = l[order]
            hits = np.cumsum(rel)
            prec = hits / (np.arange(len(rel)) + 1)
            for k in self.eval_at:
                kk = min(k, len(rel))
                npos = rel[:kk].sum()
                ap = (np.sum(prec[:kk] * rel[:kk]) / npos) if npos > 0 else 0.0
                results[k].append(ap)
        return [(f"map@{k}", float(np.mean(results[k])), True)
                for k in self.eval_at]


# --- cross-entropy family (xentropy_metric.hpp:68-300) ---------------------
class XentropyMetric(Metric):
    names = ("xentropy",)

    def eval(self, label, score, weight=None, query=None):
        p = np.clip(_sigmoid(score), 1e-15, 1 - 1e-15)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return [("xentropy", _wmean(loss, weight), False)]


class XentLambdaMetric(Metric):
    names = ("xentlambda",)

    def eval(self, label, score, weight=None, query=None):
        w = weight if weight is not None else 1.0
        p = np.clip(1.0 - np.exp(-w * np.exp(score)), 1e-15, 1 - 1e-15)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return [("xentlambda", float(np.mean(loss)), False)]


class KlDivMetric(Metric):
    names = ("kldiv",)

    def eval(self, label, score, weight=None, query=None):
        p = np.clip(_sigmoid(score), 1e-15, 1 - 1e-15)
        y = np.clip(label, 1e-15, 1 - 1e-15)
        kl = (y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p)))
        return [("kldiv", _wmean(kl, weight), False)]


METRICS = {
    "l2": L2Metric, "mse": L2Metric, "mean_squared_error": L2Metric,
    "regression": L2Metric,
    "l2_root": RMSEMetric, "rmse": RMSEMetric,
    "root_mean_squared_error": RMSEMetric,
    "l1": L1Metric, "mae": L1Metric, "mean_absolute_error": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MapeMetric, "mean_absolute_percentage_error": MapeMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "gamma-deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AucMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
    "xentropy": XentropyMetric, "cross_entropy": XentropyMetric,
    "xentlambda": XentLambdaMetric, "cross_entropy_lambda": XentLambdaMetric,
    "kldiv": KlDivMetric, "kullback_leibler": KlDivMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Factory (reference src/metric/metric.cpp:11-57)."""
    key = name.strip().lower()
    if key in ("", "none", "null", "na"):
        return None
    cls = METRICS.get(key)
    if cls is None:
        raise ValueError(f"unknown metric {name!r}")
    return cls(config)


def default_metric_for_objective(objective: str) -> str:
    return {
        "regression": "l2", "regression_l1": "l1", "huber": "huber",
        "fair": "fair", "poisson": "poisson", "quantile": "quantile",
        "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
        "binary": "binary_logloss", "multiclass": "multi_logloss",
        "multiclassova": "multi_logloss", "xentropy": "xentropy",
        "xentlambda": "xentlambda", "lambdarank": "ndcg",
    }.get(objective, "l2")
