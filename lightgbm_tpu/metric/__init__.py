from .metrics import METRICS, Metric, create_metric
