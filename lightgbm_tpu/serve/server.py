"""Micro-batching async serving harness over a :class:`CompiledModel`.

The north star is serving heavy traffic: many small concurrent
prediction requests, one accelerator.  Dispatching each request alone
wastes the device (a 1-row batch costs the same dispatch latency as a
4096-row one); batching naively recompiles per batch shape.  This
harness does the standard two things, instrumented end to end:

* **micro-batching** — requests land in a queue; a worker thread
  coalesces up to ``max_batch`` rows or ``max_wait_ms``, whichever
  comes first, into one device dispatch;
* **padding buckets** — every coalesced batch is padded to a
  power-of-two bucket from a fixed set, all compiled during warmup, so
  steady-state traffic NEVER re-enters XLA.  Under
  ``LGBM_TPU_TRACE_CONTRACT=1`` the server runs its whole life under a
  :class:`~lightgbm_tpu.obs.trace_contract.CompileTracker` and writes a
  ``serve_trace_contract`` section into the telemetry summary — the
  runtime proof of the zero-recompile property.

Delivery contract: every accepted request is resolved EXACTLY once —
with its scores, or (after the retry budget is exhausted, or on a
non-transient fault) with the scoring exception.  Scoring runs through
``utils/retry.retry_call`` under the ``serve.score`` fault point
(``utils/faults.py``), so a mid-batch transient re-scores the whole
batch (pure function — idempotent) without dropping or double-resolving
any request.  ``close()`` drains the queue: requests accepted before
shutdown are scored before the worker exits.

Telemetry: ``serve.compile`` (warmup, per bucket), ``serve.batch``
(one per coalesced dispatch, with rows/bucket/requests attrs),
``serve.score`` (inside the model, one per device dispatch), counters
``serve.requests/.rows/.batches/.padded_rows``.  Per-bucket request
latency accumulates into bounded ROLLING quantile sketches
(``obs/ops_plane.RollingQuantiles``): ``stats()`` reports windowed
p50/p99/p99.9 at constant memory under sustained traffic.

Live ops plane: with ``LGBM_TPU_OPS_PORT`` set the server mounts the
``/metrics`` + ``/healthz`` HTTP surface (``obs/ops_plane.py``) and
wires ``/drain`` to itself — stop accepting, flush every queued
request (exactly-once delivery holds through the drain), report final
stats.  ``LGBM_TPU_WATCHDOG_S`` arms the stall watchdog around each
coalesced batch dispatch (``obs/health.py``).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs import counter_add, event, span, set_section
from ..obs import health as obs_health
from ..obs import ops_plane as obs_ops
from ..obs import profiler as obs_profiler
from ..obs.ops_plane import RollingQuantiles
from ..obs.trace_contract import CompileTracker, contract_enabled
from ..utils.faults import fault_point
from ..utils.log import log_info, log_warning
from ..utils.retry import RetryPolicy, retry_call
from .compiler import CompiledModel, next_bucket

_SENTINEL = object()


class _Request:
    __slots__ = ("rows", "future", "t_enqueue")

    def __init__(self, rows: np.ndarray):
        self.rows = rows
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()


def _default_buckets(max_batch: int, min_bucket: int) -> List[int]:
    out = []
    b = min_bucket
    top = next_bucket(max_batch, min_bucket)
    while b < top:
        out.append(b)
        b *= 4
    out.append(top)
    return out


class PredictionServer:
    """Async micro-batching front end for a compiled model.

    ``submit(x)`` returns a ``concurrent.futures.Future`` resolving to
    the prediction for ``x`` (one row ``[F]`` or a block ``[k, F]``);
    ``predict(x)`` is the blocking convenience.  ``close()`` drains and
    stops the worker.
    """

    def __init__(self, model: CompiledModel, *, max_batch: int = 4096,
                 max_wait_ms: float = 2.0,
                 buckets: Optional[Sequence[int]] = None,
                 raw_score: bool = False, binned: bool = False,
                 min_bucket: int = 64, warmup: bool = True,
                 retry_policy: Optional[RetryPolicy] = None):
        self.model = model
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.raw_score = raw_score
        self.binned = binned
        self.min_bucket = int(min_bucket)
        self.buckets = sorted(set(int(b) for b in buckets)) if buckets \
            else _default_buckets(self.max_batch, self.min_bucket)
        self._retry = retry_policy
        self._q: "queue.Queue" = queue.Queue()
        from ..obs.lock_contract import named_lock
        self._lock = named_lock("serve")
        self._closed = False
        self._n_submitted = 0
        self._n_resolved = 0
        self._n_failed = 0
        self._n_batches = 0
        self._n_rows = 0
        self._n_padded = 0
        # per-bucket request latency: bounded ROLLING quantile sketches
        # (obs/ops_plane.py) — the old all-time lists grew without
        # bound under sustained traffic and froze the percentiles on
        # ancient history; the sketch window is LGBM_TPU_OPS_SKETCH
        self._latency: Dict[int, RollingQuantiles] = {}
        self._carry: List[_Request] = []    # worker-only: batch overflow
        # worker-only: previous batch dispatch's return time, for the
        # serve.dispatch_gap_s host-latency counter
        self._t_last_dispatch: Optional[float] = None
        # the runtime zero-recompile proof: a live tracker when the
        # trace contract is armed (track_threads=False — the worker
        # thread's compiles ARE the contract here, unlike training's
        # background AOT upgrades)
        self._tracker: Optional[CompileTracker] = None
        if contract_enabled():
            self._tracker = CompileTracker(track_threads=False).__enter__()
        # HBM watermark contract (obs/mem_contract.py): sampled once
        # per coalesced batch on the worker thread; the report lands as
        # the `serve_mem_contract` summary section on close().  Warmup
        # 2: the first batches still materialize bucket result buffers.
        from ..obs.mem_contract import maybe_watermark
        self._mem_wm = maybe_watermark("serve", "serve_mem_contract",
                                       warmup=2).__enter__()
        # live ops plane (obs/ops_plane.py, LGBM_TPU_OPS_PORT): the
        # /metrics + /healthz scrape surface, with /drain wired to
        # this server (stop accepting, flush the queue, report); the
        # stall watchdog (LGBM_TPU_WATCHDOG_S) arms around each
        # coalesced batch dispatch
        self._ops = obs_ops.mount("serve")
        if self._ops is not None:
            self._ops.register_drain(self._drain_report)
        self._wd = obs_health.Watchdog.maybe("serve")
        obs_health.mark_warming("serve")
        if warmup:
            self.warm()
        if self._tracker is not None:
            self._tracker.mark_steady()
        obs_health.mark_ready()
        self._thread = threading.Thread(
            target=self._run, name="lgbm-tpu-serve", daemon=True)
        self._thread.start()

    # -- lifecycle -------------------------------------------------------
    def warm(self) -> None:
        """Compile every bucket program (idempotent after the first)."""
        self.model.warm(self.buckets, binned=self.binned)

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain the queue, stop the worker, finalize the contract
        report.  Requests submitted before close are scored; submit
        afterwards raises."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        obs_health.mark_draining(plane="serve")
        self._q.put(_SENTINEL)
        self._thread.join(timeout)
        if self._wd is not None:
            self._wd.stop()
            self._wd = None
        if self._tracker is not None:
            self._tracker.__exit__(None, None, None)
            rep = self._tracker.report()
            set_section("serve_trace_contract", rep)
            if not rep["steady_ok"]:
                event("contract", "serve_recompile_after_warmup",
                      count=rep["compiles_steady"],
                      names=rep["steady_names"])
                log_warning(
                    f"serve trace contract violated: "
                    f"{rep['compiles_steady']} recompile(s) after warmup "
                    f"({', '.join(rep['steady_names'][:5])}) — a batch "
                    f"shape escaped the padding buckets")
            self._tracker = None
        if self._mem_wm is not None:
            rep = self._mem_wm.finalize("serve_mem_contract")
            self._mem_wm = None
            if not rep["steady_ok"]:
                log_warning(
                    f"serve mem contract violated: "
                    f"{rep['violation_count']} watermark crossing(s) — "
                    f"a per-batch live-buffer leak in the serving path")
        log_info(f"serve: drained ({self._n_resolved} resolved, "
                 f"{self._n_failed} failed, {self._n_batches} batches)")

    def _drain_report(self) -> Dict:
        """The ops plane's ``/drain`` hook: stop accepting, flush every
        in-flight request (``close`` drains the queue — the
        exactly-once delivery contract holds through the drain), and
        report the final stats."""
        self.close()
        rep = self.stats()
        rep["drained"] = True
        return rep

    def __enter__(self) -> "PredictionServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- request API -----------------------------------------------------
    def submit(self, x: np.ndarray) -> Future:
        rows = np.asarray(x)
        if rows.ndim == 1:
            rows = rows[None, :]
        if not self.binned:
            rows = np.ascontiguousarray(rows, np.float32)
        req = _Request(rows)
        with self._lock:
            if self._closed:
                raise RuntimeError("PredictionServer is closed")
            self._n_submitted += 1
            # queue under the admission lock: close() flips _closed
            # under the same lock before posting the drain sentinel, so
            # every admitted request is queued ahead of the drain and
            # its future always resolves (a put outside the lock can
            # land after the worker drained and exited, stranding the
            # future — tools/interleave.py seam "server")
            self._q.put(req)
        counter_add("serve.requests")
        return req.future

    def predict(self, x: np.ndarray, timeout: Optional[float] = 60.0):
        return self.submit(x).result(timeout)

    def stats(self) -> Dict:
        """Counts + per-bucket latency percentiles (ms) over the
        bounded rolling window (p50/p99/p99.9; ``count`` stays
        all-time)."""
        with self._lock:
            lat = {b: s.stats_ms() for b, s in self._latency.items()
                   if s.count}
            out = {
                "submitted": self._n_submitted,
                "resolved": self._n_resolved,
                "failed": self._n_failed,
                "batches": self._n_batches,
                "rows": self._n_rows,
                "padded_rows": self._n_padded,
                "pending": self._n_submitted - self._n_resolved
                           - self._n_failed,
            }
        out["latency_ms"] = lat
        return out

    # -- worker ----------------------------------------------------------
    def _collect(self, first: "_Request") -> List["_Request"]:
        """Coalesce queued requests behind ``first`` up to max_batch
        rows or the max-wait deadline.  A request that would overflow
        ``max_batch`` (and so escape the warmed bucket set) is carried
        into the NEXT batch instead — batches never outgrow the
        largest bucket unless a single request already does."""
        batch = [first]
        rows = first.rows.shape[0]
        deadline = time.perf_counter() + self.max_wait_s
        while rows < self.max_batch:
            wait = deadline - time.perf_counter()
            try:
                item = self._q.get(timeout=max(wait, 0.0)) if wait > 0 \
                    else self._q.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                # keep draining after this batch; re-post so the outer
                # loop sees the shutdown marker AFTER the queue empties
                self._q.put(_SENTINEL)
                break
            if rows + item.rows.shape[0] > self.max_batch:
                self._carry.append(item)
                break
            batch.append(item)
            rows += item.rows.shape[0]
        return batch

    def _bucket_for(self, n: int) -> int:
        """Smallest CONFIGURED bucket >= n.  Only a single request
        larger than every bucket escapes the warmed set (padded to its
        own power of two, compiled on first sight — and logged, since
        that is a contract violation waiting to be sized away)."""
        for b in self.buckets:
            if n <= b:
                return b
        counter_add("serve.oversize_batches")
        return next_bucket(n, self.min_bucket)

    def _score(self, X: np.ndarray) -> np.ndarray:
        fault_point("serve.score")
        return self.model.predict(X, raw_score=self.raw_score,
                                  binned=self.binned, pad=False)

    def _run_batch(self, batch: List["_Request"]) -> None:
        X = batch[0].rows if len(batch) == 1 else np.concatenate(
            [r.rows for r in batch])
        n = X.shape[0]
        bucket = self._bucket_for(n)
        if bucket != n:
            X = np.concatenate(
                [X, np.zeros((bucket - n,) + X.shape[1:], X.dtype)])
        # dispatch gap: device idle between consecutive batch
        # dispatches (queue wait + coalescing + padding on the host) —
        # the serving-side analog of the training loop's
        # gbdt.dispatch_gap_s host-latency counter
        t_prev = self._t_last_dispatch
        if t_prev is not None:
            counter_add("serve.dispatch_gap_s",
                        time.perf_counter() - t_prev)
            counter_add("serve.dispatch_gaps")
        # stall watchdog: armed per coalesced batch — a wedged device
        # dispatch mid-serve gets named (health:stall + forensics)
        # while the worker is still stuck on it
        if self._wd is not None:
            self._wd.arm("serve.batch", batch=self._n_batches,
                         bucket=bucket)
            obs_health.stall_fault(self._wd)
        # step marker: while a device-time capture is live each batch
        # is a profiler step, so per-batch device cost reads directly
        # off the trace (no-op otherwise)
        try:
            with span("serve.batch") as s, \
                    obs_profiler.step("serve.batch", self._n_batches):
                s["rows"] = n
                s["bucket"] = bucket
                s["requests"] = len(batch)
                try:
                    out = retry_call(self._score, X, policy=self._retry,
                                     what="serve.score")
                except Exception as exc:  # noqa: BLE001 - into futures
                    log_warning(f"serve: batch of {len(batch)} "
                                f"request(s) failed after retries: {exc}")
                    with self._lock:
                        self._n_failed += len(batch)
                    for r in batch:
                        r.future.set_exception(exc)
                    return
        finally:
            if self._wd is not None:
                self._wd.disarm()
        out = np.asarray(out)[:n]
        now = time.perf_counter()
        self._t_last_dispatch = now
        with self._lock:
            self._n_batches += 1
            self._n_rows += n
            self._n_padded += bucket - n
            lat = self._latency.setdefault(bucket, RollingQuantiles())
        counter_add("serve.batches")
        counter_add("serve.rows_batched", n)
        counter_add("serve.padded_rows", bucket - n)
        if self._mem_wm is not None:
            # per-batch watermark sample (worker thread — the Watermark
            # appends under no lock, but only this thread samples it)
            self._mem_wm.sample("serve.batch", bucket=bucket)
        off = 0
        for r in batch:
            k = r.rows.shape[0]
            res = out[off:off + k]
            off += k
            with self._lock:
                self._n_resolved += 1
                lat.observe(now - r.t_enqueue)
            # exactly-once: a Future can only be resolved once — a
            # retry re-scores the batch but delivery happens here, once
            r.future.set_result(res[0] if k == 1 else res)

    def _run(self) -> None:
        draining = False
        while True:
            if self._carry:
                item = self._carry.pop(0)
            elif draining:
                # drain anything still queued (pre-close requests are
                # FIFO-ahead of the sentinel; a racing submit that beat
                # the closed flag is also honored) then exit
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    return
            else:
                item = self._q.get()
            if item is _SENTINEL:
                draining = True
                continue
            self._run_batch(self._collect(item))
