"""TPU-resident tensorized prediction serving.

* :mod:`~lightgbm_tpu.serve.compiler` — pack a trained booster
  (including reference-format text models) into device-resident stacked
  tensors and score whole batches in one jitted dispatch, with an
  int8 binned fast path riding the training bin pipeline;
* :mod:`~lightgbm_tpu.serve.server` — micro-batching async harness
  (request queue, padding buckets, telemetry, retries, graceful drain).

Entry points::

    from lightgbm_tpu.serve import compile_model, PredictionServer
    cm = compile_model(booster)            # or Booster.predict(device=True)
    scores = cm.predict(X)                 # one dispatch, bucket-padded
    with PredictionServer(cm) as srv:
        fut = srv.submit(row)              # micro-batched async
"""
from .compiler import (CompiledModel, ServePack, build_pack, compile_model,
                       compile_trees, next_bucket)
from .server import PredictionServer

__all__ = [
    "CompiledModel", "ServePack", "build_pack", "compile_model",
    "compile_trees", "next_bucket", "PredictionServer",
]
