"""Model compiler: a trained booster packed for TPU-resident serving.

The reference serves predictions through a per-row pointer chase
(`src/application/predictor.hpp`, ``gbdt_prediction.cpp``: one thread
walks one tree for one row at a time).  ``Booster.predict`` here used to
bottom out in the same place — a host-side numpy traversal
(``models/tree.py predict_leaf_batch``) that never touches the device.
This module is the serving counterpart of the training redesign: the
whole forest becomes a handful of device-resident ``[T, M]`` node
tensors plus flattened categorical bitset tables, and ONE jitted
program routes a whole ``[batch, F]`` block through every tree with
per-depth gathers + ``where`` selects (the walk loop is padded to the
forest's max depth, a static program parameter).

Exactness contract (tested by ``tests/test_serve.py``):

* **Leaf routing is bit-exact** against the numpy oracle
  (``Tree.predict_leaf_batch`` / ``predict_row``) for float32 inputs.
  The device compares in f32 against thresholds pre-rounded TOWARD
  -inf to f32 (:func:`_f32_floor`): for any f32 ``x`` and f64 threshold
  ``t``, ``x <= t  <=>  x <= floor_f32(t)`` — so the f32 compare
  reproduces the reference's f64 ``NumericalDecision`` exactly.
  float64 inputs are cast to f32 first (documented narrowing).
* **Scores are within 1 ulp (f32)** of the f64 sequential
  tree-accumulation oracle: per-leaf f64 values are carried as hi/lo
  f32 pairs and accumulated with Neumaier compensated summation in
  tree order, so the device sum tracks the exact sum to ~2^-45
  relative before the single final rounding.

Two input paths share the walk:

* **raw** — ``[n, F]`` float rows, original feature indices,
  categorical membership via flattened value bitsets (the model file's
  ``cat_threshold`` words, one device table for the whole forest);
* **binned fast path** — ``[n, Fi]`` int8/int32 rows pre-binned
  through the TRAINING bin pipeline (``io/binning.py`` mappers): node
  compares become integer ``bin <= threshold_bin`` and categorical
  membership uses bin-space bitsets, skipping all float work.
"""
from __future__ import annotations

import functools
import os
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.binning import MISSING_NAN, MISSING_ZERO
from ..models.tree import (K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK,
                           _K_ZERO_THRESHOLD, Tree)
from ..obs import counter_add, span
from ..utils.log import log_info


def _f32_floor(values: np.ndarray) -> np.ndarray:
    """Largest float32 <= each (float64) value.

    For f32 ``x`` and f64 ``t``: ``x <= t`` iff ``x <= _f32_floor(t)``,
    which is what makes the device's f32 threshold compare bit-exact
    against the reference's f64 decision.  +-inf and NaN pass through.
    """
    v = np.asarray(values, np.float64)
    v32 = v.astype(np.float32)
    over = v32.astype(np.float64) > v
    return np.where(over, np.nextafter(v32, np.float32(-np.inf)), v32)


# floor-rounded f32 image of the reference kZeroThreshold: |x| <= 1e-35
# in f64 iff |x| <= this in f32, for f32 x
_ZERO_EPS_F32 = float(
    _f32_floor(np.array([_K_ZERO_THRESHOLD], np.float64))[0])


@jax.tree_util.register_pytree_node_class
class ServePack(NamedTuple):
    """The forest as device-resident stacked arrays (pytree).

    Node axes are ``[T, M]`` (M = max leaves - 1); ``max_depth`` is
    static aux data bounding the jitted walk loop.  Binned-path fields
    are 1-element placeholders when the pack was built without mappers.
    """

    split_feature: jnp.ndarray        # [T, M] int32, ORIGINAL feature idx
    threshold: jnp.ndarray            # [T, M] f32, floor-rounded
    default_left: jnp.ndarray         # [T, M] bool
    is_cat: jnp.ndarray               # [T, M] bool
    miss_zero: jnp.ndarray            # [T, M] bool (missing_type == Zero)
    miss_nan: jnp.ndarray             # [T, M] bool (missing_type == NaN)
    left_child: jnp.ndarray           # [T, M] int32 (>=0 node, ~leaf)
    right_child: jnp.ndarray          # [T, M] int32
    leaf_hi: jnp.ndarray              # [T, L] f32 = f32(leaf_value)
    leaf_lo: jnp.ndarray              # [T, L] f32 = f32(value - f64(hi))
    cat_offset: jnp.ndarray           # [T, M] int32 into cat_words
    cat_nwords: jnp.ndarray           # [T, M] int32
    cat_words: jnp.ndarray            # [W] uint32 flattened value bitsets
    split_feature_inner: jnp.ndarray  # [T, M] int32, used-column idx
    threshold_bin: jnp.ndarray        # [T, M] int32
    catbin_offset: jnp.ndarray        # [T, M] int32 into catbin_words
    catbin_nwords: jnp.ndarray        # [T, M] int32
    catbin_words: jnp.ndarray         # [Wb] uint32 bin-space bitsets
    feat_missing_type: jnp.ndarray    # [Fi] int32 (binned path)
    feat_nan_bin: jnp.ndarray         # [Fi] int32
    feat_zero_bin: jnp.ndarray        # [Fi] int32
    max_depth: int                    # static: walk loop bound

    def tree_flatten(self):
        return (tuple(self[:-1]), self.max_depth)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux)

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]


def build_pack(trees: Sequence[Tree], mappers=None,
               used_features: Optional[Sequence[int]] = None) -> ServePack:
    """Pack host trees into a :class:`ServePack`.

    ``mappers`` (per ORIGINAL feature, with ``used_features`` giving the
    inner-column order) additionally builds the binned fast path; trees
    must already be bin-aligned (trained in-process, or
    ``align_with_mappers`` called after a text load).
    """
    T = len(trees)
    L = max(max((t.num_leaves for t in trees), default=2), 2)
    M = L - 1
    sf = np.zeros((T, M), np.int32)
    thr = np.zeros((T, M), np.float32)
    dl = np.zeros((T, M), bool)
    ic = np.zeros((T, M), bool)
    mz = np.zeros((T, M), bool)
    mn = np.zeros((T, M), bool)
    lc = np.zeros((T, M), np.int32)
    rc = np.zeros((T, M), np.int32)
    hi = np.zeros((T, L), np.float32)
    lo = np.zeros((T, L), np.float32)
    co = np.zeros((T, M), np.int32)
    cn = np.zeros((T, M), np.int32)
    cat_words: List[int] = []
    sfi = np.zeros((T, M), np.int32)
    tb = np.zeros((T, M), np.int32)
    bo = np.zeros((T, M), np.int32)
    bn = np.zeros((T, M), np.int32)
    catbin_words: List[int] = []
    binned = mappers is not None
    for i, t in enumerate(trees):
        n = t.num_leaves
        m = n - 1
        v64 = np.asarray(t.leaf_value[:max(n, 1)], np.float64)
        h = v64.astype(np.float32)
        hi[i, :len(h)] = h
        lo[i, :len(h)] = (v64 - h.astype(np.float64)).astype(np.float32)
        if m == 0:
            # num_leaves == 1 stump: both children land on leaf 0
            lc[i, 0] = rc[i, 0] = ~0
            continue
        dt = np.asarray(t.decision_type[:m], np.int64)
        sf[i, :m] = t.split_feature[:m]
        sfi[i, :m] = t.split_feature_inner[:m]
        thr[i, :m] = _f32_floor(t.threshold[:m])
        dl[i, :m] = (dt & K_DEFAULT_LEFT_MASK) != 0
        ic[i, :m] = (dt & K_CATEGORICAL_MASK) != 0
        mt = (dt >> 2) & 3
        mz[i, :m] = mt == MISSING_ZERO
        mn[i, :m] = mt == MISSING_NAN
        lc[i, :m] = t.left_child[:m]
        rc[i, :m] = t.right_child[:m]
        tb[i, :m] = t.threshold_bin[:m]
        for node in range(m):
            if not ic[i, node]:
                continue
            ci = int(t.threshold_bin[node])
            words = [int(w) for w in
                     t.cat_threshold[t.cat_boundaries[ci]:
                                     t.cat_boundaries[ci + 1]]]
            co[i, node] = len(cat_words)
            cn[i, node] = len(words)
            cat_words.extend(words)
            if binned and ci < len(t.cat_left_bins):
                bins = np.asarray(t.cat_left_bins[ci], np.int64)
                nwords = int(bins.max()) // 32 + 1 if len(bins) else 1
                bwords = [0] * nwords
                for b in bins:
                    bwords[int(b) // 32] |= 1 << (int(b) % 32)
                bo[i, node] = len(catbin_words)
                bn[i, node] = nwords
                catbin_words.extend(bwords)
    if binned:
        inner = list(used_features if used_features is not None
                     else range(len(mappers)))
        fi_mt = np.zeros(max(len(inner), 1), np.int32)
        fi_nan = np.full(max(len(inner), 1), -1, np.int32)
        fi_zero = np.zeros(max(len(inner), 1), np.int32)
        for j, f in enumerate(inner):
            mp = mappers[f]
            fi_mt[j] = mp.missing_type
            fi_nan[j] = mp.num_bin - 1 if mp.missing_type == MISSING_NAN else -1
            fi_zero[j] = mp.default_bin
    else:
        fi_mt = np.zeros(1, np.int32)
        fi_nan = np.full(1, -1, np.int32)
        fi_zero = np.zeros(1, np.int32)
    depth = max(max((t.max_depth for t in trees), default=1), 1)
    # power-of-two walk bound: the loop length is a static program
    # parameter, so raw depths would recompile per forest shape
    depth = 1 << (depth - 1).bit_length()
    return place_pack(ServePack(
        jnp.asarray(sf), jnp.asarray(thr), jnp.asarray(dl), jnp.asarray(ic),
        jnp.asarray(mz), jnp.asarray(mn), jnp.asarray(lc), jnp.asarray(rc),
        jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(co), jnp.asarray(cn),
        jnp.asarray(np.asarray(cat_words or [0], np.uint32)),
        jnp.asarray(sfi), jnp.asarray(tb), jnp.asarray(bo), jnp.asarray(bn),
        jnp.asarray(np.asarray(catbin_words or [0], np.uint32)),
        jnp.asarray(fi_mt), jnp.asarray(fi_nan), jnp.asarray(fi_zero),
        depth))


def place_pack(pack: ServePack, mesh=None) -> ServePack:
    """Route the compiled forest through the partition-rule registry
    (``parallel/partition.py``): every ``serve/pack/<field>`` array
    must match a rule — an unregistered field is a hard error at
    compile time, exactly like a training array without a placement
    rule.  The serve rules are all REPLICATED for now, so without a
    ``mesh`` this is resolution-only (no behavior change: the
    single-chip server keeps its default placement byte-for-byte);
    with a mesh the pack is device_put replicated across it — the seam
    the trees-axis sharding of ROADMAP item 3a will refine."""
    from ..parallel.partition import (match_partition_rules, place_tree,
                                      serve_pack_names, serve_rules)
    names = serve_pack_names(pack)
    match_partition_rules(serve_rules(), names)    # completeness: raises
    if mesh is None:
        return pack
    placed = place_tree(serve_rules(), mesh, names)["serve"]["pack"]
    children, aux = pack.tree_flatten()
    fields = ServePack._fields
    return ServePack(*(placed[f] for f in fields[:len(children)]), aux)


# ---------------------------------------------------------------------------
# jitted scorers
# ---------------------------------------------------------------------------
def _bitset_member(words, offset, nwords, v):
    """``v in bitset`` per row — flattened-table lookup, no host work.
    ``v < 0`` or beyond the node's words is a miss (reference
    ``Common::FindInBitset``)."""
    w = jnp.right_shift(jnp.maximum(v, 0), 5)
    ok = (v >= 0) & (w < nwords)
    word = words[jnp.where(ok, offset + w, 0)]
    bit = jnp.right_shift(word, (v & 31).astype(jnp.uint32)) & jnp.uint32(1)
    return ok & (bit == jnp.uint32(1))


def _leaf_indices_block(pack: ServePack, Xb: jnp.ndarray, binned: bool):
    """Leaf index per (tree, row) for one row block -> [T, rc] int32.

    The per-depth step is the reference ``Tree::GetLeaf`` decision
    (`tree.h:112-119` / ``NumericalDecision`` / ``CategoricalDecision``)
    vectorized: one gather per node array, one ``where`` per select.
    """
    sf_arr = pack.split_feature_inner if binned else pack.split_feature

    def one_tree(sf, thr, tb, dl, ic, mz, mn, lc, rc, co, cn, bo, bn):
        node = jnp.zeros(Xb.shape[0], jnp.int32)

        def body(_, node):
            is_leaf = node < 0
            nidx = jnp.maximum(node, 0)
            f = sf[nidx]
            v = jnp.take_along_axis(Xb, f[:, None], axis=1)[:, 0]
            if binned:
                b = v.astype(jnp.int32)
                mt_f = pack.feat_missing_type[f]
                is_missing = (
                    ((mt_f == MISSING_NAN) & (b == pack.feat_nan_bin[f]))
                    | ((mt_f == MISSING_ZERO) & (b == pack.feat_zero_bin[f])))
                num_left = jnp.where(is_missing, dl[nidx], b <= tb[nidx])
                cat_left = _bitset_member(pack.catbin_words, bo[nidx],
                                          bn[nidx], b)
            else:
                v = v.astype(jnp.float32)
                nan = jnp.isnan(v)
                v0 = jnp.where(nan & ~mn[nidx], jnp.float32(0), v)
                is_missing = ((mz[nidx]
                               & (jnp.abs(v0) <= jnp.float32(_ZERO_EPS_F32)))
                              | (mn[nidx] & nan))
                num_left = jnp.where(is_missing, dl[nidx], v0 <= thr[nidx])
                # CategoricalDecision: NaN / negative / huge -> not in set
                cat = jnp.where(nan | (v < 0) | (v >= jnp.float32(2.0 ** 31)),
                                jnp.float32(-1), v).astype(jnp.int32)
                cat_left = _bitset_member(pack.cat_words, co[nidx],
                                          cn[nidx], cat)
            go_left = jnp.where(ic[nidx], cat_left, num_left)
            nxt = jnp.where(go_left, lc[nidx], rc[nidx])
            return jnp.where(is_leaf, node, nxt)

        node = jax.lax.fori_loop(0, pack.max_depth, body, node)
        return jnp.where(node < 0, ~node, 0)

    return jax.vmap(one_tree)(
        sf_arr, pack.threshold, pack.threshold_bin, pack.default_left,
        pack.is_cat, pack.miss_zero, pack.miss_nan, pack.left_child,
        pack.right_child, pack.cat_offset, pack.cat_nwords,
        pack.catbin_offset, pack.catbin_nwords)


def _accumulate(hi: jnp.ndarray, lo: jnp.ndarray, num_class: int):
    """Neumaier-compensated f32 sum over trees in TREE ORDER -> [rc, K].

    Tracks the exact f64 sequential accumulation (the oracle in
    ``GBDT._predict_loaded``) to ~2^-45 relative before the final f32
    rounding — the 1-ulp score contract."""
    T, rc = hi.shape
    s0 = jnp.zeros((num_class, rc), jnp.float32)
    c0 = jnp.zeros((num_class, rc), jnp.float32)

    def nadd(s_k, c_k, v):
        t = s_k + v
        err = jnp.where(jnp.abs(s_k) >= jnp.abs(v),
                        (s_k - t) + v, (v - t) + s_k)
        return t, c_k + err

    def body(t, carry):
        s, c = carry
        k = jnp.mod(t, num_class)
        s_k = jax.lax.dynamic_index_in_dim(s, k, 0, keepdims=False)
        c_k = jax.lax.dynamic_index_in_dim(c, k, 0, keepdims=False)
        s_k, c_k = nadd(s_k, c_k,
                        jax.lax.dynamic_index_in_dim(hi, t, 0, keepdims=False))
        s_k, c_k = nadd(s_k, c_k,
                        jax.lax.dynamic_index_in_dim(lo, t, 0, keepdims=False))
        s = jax.lax.dynamic_update_index_in_dim(s, s_k, k, 0)
        c = jax.lax.dynamic_update_index_in_dim(c, c_k, k, 0)
        return s, c

    s, c = jax.lax.fori_loop(0, T, body, (s0, c0))
    return (s + c).T


def _row_blocks(X: jnp.ndarray, rchunk: int):
    n = X.shape[0]
    rc_sz = min(rchunk, max(n, 1))
    RC = -(-n // rc_sz)
    n_pad = RC * rc_sz
    Xp = X if n_pad == n else jnp.concatenate(
        [X, jnp.zeros((n_pad - n,) + X.shape[1:], X.dtype)])
    return Xp.reshape((RC, rc_sz) + X.shape[1:]), n_pad


@functools.partial(jax.jit, static_argnames=("num_class", "rchunk", "binned"))
def _score_batch(pack: ServePack, X: jnp.ndarray, *, num_class: int,
                 rchunk: int, binned: bool) -> jnp.ndarray:
    """Raw scores for a whole batch in ONE dispatch -> [n, K] f32.
    ``lax.map`` over row blocks bounds the [T, rchunk] walk state."""
    n = X.shape[0]

    def row_block(Xb):
        leaves = _leaf_indices_block(pack, Xb, binned)
        hi = jnp.take_along_axis(pack.leaf_hi, leaves, axis=1)
        lo = jnp.take_along_axis(pack.leaf_lo, leaves, axis=1)
        return _accumulate(hi, lo, num_class)

    blocks, n_pad = _row_blocks(X, rchunk)
    out = jax.lax.map(row_block, blocks)
    return out.reshape(n_pad, num_class)[:n]


@functools.partial(jax.jit, static_argnames=("rchunk", "binned"))
def _leaf_batch(pack: ServePack, X: jnp.ndarray, *, rchunk: int,
                binned: bool) -> jnp.ndarray:
    """Per-tree leaf index per row (PredictLeafIndex) -> [n, T] int32."""
    n = X.shape[0]

    def row_block(Xb):
        return _leaf_indices_block(pack, Xb, binned).T

    blocks, n_pad = _row_blocks(X, rchunk)
    out = jax.lax.map(row_block, blocks)
    return out.reshape(n_pad, pack.num_trees)[:n]


# ---------------------------------------------------------------------------
# user-facing compiled model
# ---------------------------------------------------------------------------
def _default_rchunk() -> int:
    try:
        return int(os.environ.get("LGBM_TPU_SERVE_ROW_CHUNK", 16384))
    except ValueError:
        return 16384


def next_bucket(n: int, min_bucket: int = 256) -> int:
    """Smallest power-of-two bucket >= n (>= min_bucket): padding every
    batch to a bucket keeps the set of compiled programs finite, so
    steady-state serving never re-enters XLA."""
    return max(min_bucket, 1 << max(n - 1, 0).bit_length())


class CompiledModel:
    """A booster compiled for device-resident scoring.

    Construct via :func:`compile_model` (boosters) or
    :func:`compile_trees` (bare tree lists).  All entry points pad the
    batch to a power-of-two bucket by default (``pad=True``) so
    repeated mixed-size calls reuse a small set of compiled programs.
    """

    def __init__(self, pack: ServePack, *, num_class: int = 1,
                 objective=None, average_output: bool = False,
                 base_score: float = 0.0, mappers=None,
                 used_features: Optional[Sequence[int]] = None,
                 num_features: Optional[int] = None,
                 rchunk: Optional[int] = None, min_bucket: int = 256):
        self.pack = pack
        self.num_class = max(1, num_class)
        self.objective = objective
        self.average_output = average_output
        self.base_score = float(base_score)
        self.mappers = mappers
        self.used_features = (list(used_features)
                              if used_features is not None else None)
        sf_max = int(np.asarray(pack.split_feature).max(initial=0))
        self.num_features = int(num_features if num_features is not None
                                else sf_max + 1)
        self.rchunk = int(rchunk or _default_rchunk())
        self.min_bucket = int(min_bucket)

    # -- helpers ---------------------------------------------------------
    @property
    def num_trees(self) -> int:
        return self.pack.num_trees

    @property
    def has_binned(self) -> bool:
        return self.mappers is not None

    def bin_rows(self, X: np.ndarray) -> np.ndarray:
        """Bin raw rows through the TRAINING mappers (prediction-mode
        sentinels for unseen categories) -> [n, Fi] uint8/int32 for the
        binned fast path."""
        if self.mappers is None:
            raise ValueError("model was compiled without bin mappers; "
                             "the binned fast path is unavailable")
        X = np.asarray(X, np.float64)
        inner = (self.used_features if self.used_features is not None
                 else list(range(len(self.mappers))))
        out = np.zeros((X.shape[0], max(len(inner), 1)), np.int32)
        sentinel_max = 0
        for j, f in enumerate(inner):
            mp = self.mappers[f]
            out[:, j] = mp.value_to_bin(X[:, f], prediction_mode=True)
            sentinel_max = max(sentinel_max, mp.num_bin)
        if sentinel_max <= np.iinfo(np.uint8).max:
            return out.astype(np.uint8)     # the int8 fast-path payload
        return out

    def _prepare(self, X: np.ndarray, binned: bool, pad: bool):
        if binned and self.mappers is None:
            raise ValueError("model was compiled without bin mappers; "
                             "the binned fast path is unavailable")
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        want = (len(self.used_features) if binned and self.used_features
                is not None else self.num_features)
        if X.shape[1] < want:
            raise ValueError(f"expected >= {want} feature columns, "
                             f"got {X.shape[1]}")
        if not binned:
            X = np.ascontiguousarray(X, np.float32)
        n = X.shape[0]
        if pad:
            bucket = next_bucket(n, self.min_bucket)
            if bucket != n:
                X = np.concatenate(
                    [X, np.zeros((bucket - n,) + X.shape[1:], X.dtype)])
        return X, n

    # -- scoring ---------------------------------------------------------
    def predict_raw(self, X: np.ndarray, *, binned: bool = False,
                    pad: bool = True) -> np.ndarray:
        """Raw scores [n] (or [n, K] multiclass), one device dispatch."""
        Xp, n = self._prepare(X, binned, pad)
        if self.num_trees == 0:
            out = np.full((n, self.num_class), self.base_score, np.float64)
            return out if self.num_class > 1 else out[:, 0]
        with span("serve.score") as s:
            s["rows"] = n
            s["batch"] = int(Xp.shape[0])
            out = np.asarray(_score_batch(
                self.pack, jnp.asarray(Xp), num_class=self.num_class,
                rchunk=self.rchunk, binned=binned))[:n]
        counter_add("serve.rows", n)
        return out if self.num_class > 1 else out[:, 0]

    def predict(self, X: np.ndarray, raw_score: bool = False,
                *, binned: bool = False, pad: bool = True) -> np.ndarray:
        """Objective-transformed prediction (the ``Booster.predict``
        contract: sigmoid/softmax applied unless ``raw_score``)."""
        raw = self.predict_raw(X, binned=binned, pad=pad)
        if raw_score or self.objective is None:
            return raw
        if self.average_output:
            raw = raw / max(1, self.num_trees // self.num_class)
        return np.asarray(self.objective.convert_output(jnp.asarray(raw)))

    def leaf_indices(self, X: np.ndarray, *, binned: bool = False,
                     pad: bool = True) -> np.ndarray:
        """Per-tree leaf index per row -> [n, T] int32 (PredictLeafIndex)."""
        Xp, n = self._prepare(X, binned, pad)
        if self.num_trees == 0:
            return np.zeros((n, 0), np.int32)
        with span("serve.score") as s:
            s["rows"] = n
            s["leaf"] = True
            return np.asarray(_leaf_batch(
                self.pack, jnp.asarray(Xp), rchunk=self.rchunk,
                binned=binned))[:n]

    def warm(self, buckets: Sequence[int], *, binned: bool = False) -> None:
        """Compile the scorer for each bucket size up front (the
        serving warmup; afterwards mixed batch sizes hit the program
        cache only)."""
        F = (len(self.used_features) if binned and self.used_features
             is not None else self.num_features)
        dtype = np.uint8 if binned else np.float32
        for b in sorted(set(int(v) for v in buckets)):
            with span("serve.compile") as s:
                s["bucket"] = b
                self.predict_raw(np.zeros((b, F), dtype), binned=binned,
                                 pad=False)


def compile_trees(trees: Sequence[Tree], *, num_class: int = 1,
                  objective=None, average_output: bool = False,
                  base_score: float = 0.0, mappers=None,
                  used_features: Optional[Sequence[int]] = None,
                  num_features: Optional[int] = None,
                  rchunk: Optional[int] = None,
                  min_bucket: int = 256) -> CompiledModel:
    """Compile a bare tree list (see :func:`compile_model` for boosters)."""
    with span("serve.compile") as s:
        s["trees"] = len(trees)
        pack = build_pack(trees, mappers=mappers, used_features=used_features)
    counter_add("serve.compiled_trees", len(trees))
    return CompiledModel(pack, num_class=num_class, objective=objective,
                         average_output=average_output, base_score=base_score,
                         mappers=mappers, used_features=used_features,
                         num_features=num_features, rchunk=rchunk,
                         min_bucket=min_bucket)


def compile_model(model: Any, num_iteration: int = -1, *,
                  rchunk: Optional[int] = None,
                  min_bucket: int = 256) -> CompiledModel:
    """Compile a trained model for serving.

    ``model`` is a ``Booster`` (trained in-process or loaded from the
    reference text format) or a ``GBDT``.  ``num_iteration > 0``
    truncates to the first ``num_iteration * num_tree_per_iteration``
    trees — the single truncation seam shared by every predict surface.
    The binned fast path is built when the model still carries its
    training dataset (bin mappers); loaded models serve the raw path.
    """
    g = getattr(model, "_gbdt", model)
    K = max(1, getattr(g, "num_tree_per_iteration", 1))
    trees = list(g.models)
    if num_iteration is not None and num_iteration > 0:
        trees = trees[:num_iteration * K]
    mappers = None
    used = None
    if getattr(g, "train_set", None) is not None:
        mappers = g.train_set.mappers
        used = g.train_set.used_features
    num_features = getattr(g, "max_feature_idx", -1) + 1 or None
    cm = compile_trees(
        trees, num_class=K, objective=getattr(g, "objective", None),
        average_output=bool(getattr(g, "average_output", False)),
        base_score=float(getattr(g, "init_score_value", 0.0) or 0.0),
        mappers=mappers, used_features=used, num_features=num_features,
        rchunk=rchunk, min_bucket=min_bucket)
    log_info(f"serve: compiled {len(trees)} trees "
             f"(depth pad {cm.pack.max_depth}, "
             f"binned={'yes' if cm.has_binned else 'no'})")
    return cm
