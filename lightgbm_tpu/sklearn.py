"""scikit-learn estimator API.

Parity with the reference wrappers
(`/root/reference/python-package/lightgbm/sklearn.py`: ``LGBMModel``
`sklearn.py:127`, ``LGBMRegressor`` `:594`, ``LGBMClassifier`` `:624`,
``LGBMRanker`` `:734`) — same constructor parameters, ``fit`` keywords and
attributes (``best_iteration_``, ``feature_importances_``, ``classes_``),
so estimators drop into sklearn pipelines/grid-search unchanged.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train as train_fn


class LGBMModel:
    """Base sklearn-style estimator."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=100,
                 subsample_for_bin=200000, objective=None, class_weight=None,
                 min_split_gain=0.0, min_child_weight=1e-3,
                 min_child_samples=20, subsample=1.0, subsample_freq=0,
                 colsample_bytree=1.0, reg_alpha=0.0, reg_lambda=0.0,
                 random_state=None, n_jobs=-1, silent=True,
                 importance_type="split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._n_features = 0
        self._classes = None
        self._n_classes = 0
        self.set_params(**kwargs)

    # -- sklearn protocol ------------------------------------------------
    def get_params(self, deep=True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective, "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample, "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "silent": self.silent, "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if key not in self.get_params():
                self._other_params[key] = value
            self._other_params.setdefault(key, value) if key in self._other_params \
                else None
        return self

    def _process_params(self, default_objective: str) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("class_weight", None)
        params.pop("n_jobs", None)
        objective = params.pop("objective", None) or default_objective
        ren = {
            "boosting_type": "boosting_type",
            "num_leaves": "num_leaves", "max_depth": "max_depth",
            "learning_rate": "learning_rate",
            "subsample_for_bin": "bin_construct_sample_cnt",
            "min_split_gain": "min_gain_to_split",
            "min_child_weight": "min_sum_hessian_in_leaf",
            "min_child_samples": "min_data_in_leaf",
            "subsample": "bagging_fraction",
            "subsample_freq": "bagging_freq",
            "colsample_bytree": "feature_fraction",
            "reg_alpha": "lambda_l1", "reg_lambda": "lambda_l2",
        }
        out = {}
        for k, v in params.items():
            if k in ("n_estimators", "random_state"):
                continue
            out[ren.get(k, k)] = v
        if callable(objective):
            self._fobj = _ObjectiveFunctionWrapper(objective)
            out["objective"] = "none"
        else:
            self._fobj = None
            out["objective"] = objective
        if self.random_state is not None:
            out["seed"] = int(self.random_state) \
                if not hasattr(self.random_state, "randint") \
                else int(self.random_state.randint(1 << 30))
        if out.get("bagging_fraction", 1.0) < 1.0 and \
                not out.get("bagging_freq"):
            out["bagging_freq"] = 1
        return out

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            early_stopping_rounds=None, verbose=False, feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMModel":
        params = self._process_params(self._default_objective())
        if eval_metric:
            params["metric"] = eval_metric if isinstance(eval_metric, str) \
                else list(eval_metric)
        if self.class_weight is not None and isinstance(self.class_weight, dict):
            cw = np.asarray([self.class_weight.get(int(v), 1.0) for v in y])
            sample_weight = cw if sample_weight is None else sample_weight * cw

        y_t = self._transform_label(np.asarray(y))
        train_set = Dataset(X, label=y_t, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(train_set.create_valid(
                    vx, label=self._transform_label(np.asarray(vy)),
                    weight=vw, group=vg, init_score=vi))
                valid_names.append(eval_names[i] if eval_names else f"valid_{i}")

        self._evals_result = {}
        self._Booster = train_fn(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=valid_names,
            fobj=self._fobj, feval=_to_feval(eval_metric),
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = np.asarray(X).shape[1] if not isinstance(X, str) else 0
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _transform_label(self, y):
        return y.astype(np.float32)

    def predict(self, X, raw_score=False, num_iteration=None, device=None,
                **kwargs):
        """``device=True`` scores through the TPU-resident serving
        predictor (``lightgbm_tpu/serve/``); see ``Booster.predict``."""
        if self._Booster is None:
            raise RuntimeError("fit() must be called before predict()")
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration or -1,
                                     device=device, **kwargs)

    # -- attributes ------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self):
        return getattr(self._Booster, "best_score", {})

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self._Booster.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        return self._n_features


class LGBMRegressor(LGBMModel):
    def _default_objective(self):
        return "regression"


class LGBMClassifier(LGBMModel):
    def _default_objective(self):
        return "binary" if self._n_classes <= 2 else "multiclass"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._label_map = {c: i for i, c in enumerate(self._classes)}
        if self._n_classes > 2:
            self._other_params["num_class"] = self._n_classes
        return super().fit(X, y, **kwargs)

    def _transform_label(self, y):
        return np.asarray([self._label_map[v] for v in y], np.float32)

    def predict(self, X, raw_score=False, num_iteration=None, device=None,
                **kwargs):
        proba = self.predict_proba(X, raw_score=raw_score,
                                   num_iteration=num_iteration,
                                   device=device, **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return proba
        if proba.ndim > 1:
            return self._classes[np.argmax(proba, axis=1)]
        return self._classes[(proba > 0.5).astype(int)]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      device=None, **kwargs):
        out = super().predict(X, raw_score=raw_score,
                              num_iteration=num_iteration, device=device,
                              **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return out
        if out.ndim == 1:
            return np.stack([1.0 - out, out], axis=1)
        return out

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    def _default_objective(self):
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("LGBMRanker.fit requires group")
        return super().fit(X, y, group=group, **kwargs)


class _ObjectiveFunctionWrapper:
    """Adapts sklearn-style fobj(y_true, y_pred) -> (grad, hess) to the
    engine's fobj(score, dataset) (reference sklearn.py:28-96)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, score, dataset):
        label = np.asarray(dataset.get_label() if hasattr(dataset, "get_label")
                           else dataset.metadata.label)
        return self.func(label, score)


def _to_feval(eval_metric):
    if callable(eval_metric):
        def feval(score, dataset):
            label = np.asarray(dataset.get_label()
                               if hasattr(dataset, "get_label")
                               else dataset.metadata.label)
            res = eval_metric(label, score)
            return res
        return feval
    return None
