"""Configuration system for lightgbm_tpu.

TPU-native re-design of the reference config layer
(`/root/reference/include/LightGBM/config.h:47-525`, `src/io/config.cpp`):
the reference holds KV strings parsed into nested typed structs
(IOConfig/ObjectiveConfig/MetricConfig/TreeConfig/BoostingConfig/NetworkConfig
inside OverallConfig).  Here a single flat dataclass `Config` carries every
hyper-parameter; `ParameterAlias`-style canonicalisation
(`config.h:364-525`) is reproduced in `ALIAS_TABLE` / `canonicalize_params`.

TPU-specific additions (no reference counterpart): `mesh_shape`,
`data_axis_name`, `feature_axis_name`, `hist_dtype` — they configure the
jax.sharding.Mesh used by the distributed tree learners instead of the
reference's socket/MPI machine lists.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .utils.log import log_warning

# ---------------------------------------------------------------------------
# Alias table — parity with reference config.h:364-455 (plus sklearn-era extras)
# ---------------------------------------------------------------------------
ALIAS_TABLE: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "num_thread": "num_threads",
    "random_seed": "seed",
    "random_state": "seed",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "pre_partition": "is_pre_partition",
    "training_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "eval_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "n_estimators": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "machine_list_filename": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "predict_leaf_index": "is_predict_leaf_index",
    "contrib": "is_predict_contrib",
    "predict_contrib": "is_predict_contrib",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "unbalanced_sets": "is_unbalance",
    "bagging_fraction_seed": "bagging_seed",
    "workers": "machines",
    "nodes": "machines",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "metric_freq": "output_freq",
    "resume": "resume_from",
    "snapshot_keep_cnt": "snapshot_keep",
}

# Known canonical parameter names (reference config.h:456-492 parameter_set),
# plus TPU-native extensions.
PARAMETER_SET = frozenset({
    "config_file", "task", "device", "num_threads", "seed", "boosting_type",
    "objective", "data", "output_model", "input_model", "output_result",
    "valid_data", "is_enable_sparse", "is_pre_partition", "is_training_metric",
    "ndcg_eval_at", "min_data_in_leaf", "min_sum_hessian_in_leaf", "num_leaves",
    "feature_fraction", "num_iterations", "bagging_fraction", "bagging_freq",
    "learning_rate", "tree_learner", "num_machines", "local_listen_port",
    "use_two_round_loading", "machine_list_file", "is_save_binary_file",
    "early_stopping_round", "verbose", "has_header", "label_column",
    "weight_column", "group_column", "ignore_column", "categorical_column",
    "is_predict_raw_score", "is_predict_leaf_index", "is_predict_contrib",
    "min_gain_to_split", "top_k", "lambda_l1", "lambda_l2", "num_class",
    "is_unbalance", "max_depth", "max_bin", "bagging_seed", "drop_rate",
    "skip_drop", "max_drop", "uniform_drop", "xgboost_dart_mode", "drop_seed",
    "top_rate", "other_rate", "min_data_in_bin", "data_random_seed",
    "bin_construct_sample_cnt", "num_iteration_predict", "pred_early_stop",
    "pred_early_stop_freq", "pred_early_stop_margin", "use_missing", "sigmoid",
    "fair_c", "poisson_max_delta_step", "poission_max_delta_step",
    "scale_pos_weight", "boost_from_average", "max_position", "label_gain",
    "metric", "output_freq", "time_out", "gpu_platform_id", "gpu_device_id",
    "gpu_use_dp", "convert_model", "convert_model_language",
    "feature_fraction_seed", "enable_bundle", "data_filename",
    "valid_data_filenames", "snapshot_freq", "snapshot_keep",
    "resume_from", "sparse_threshold", "telemetry_output",
    "enable_load_from_binary_file", "max_conflict_rate", "histogram_pool_size",
    "is_provide_training_metric", "machines", "zero_as_missing",
    "init_score_file", "valid_init_score_file", "max_cat_threshold",
    "cat_smooth", "min_data_per_group", "cat_l2", "max_cat_to_onehot",
    "alpha", "reg_sqrt", "tweedie_variance_power",
    # fork additions (run_mode/yarn rendezvous, HDFS ingest - config.h:275-281)
    "run_mode", "application_master_address", "local_ip_prefix", "local_ip",
    "name_node", "username",
    # TPU-native extensions
    "mesh_shape", "data_axis_name", "feature_axis_name", "hist_dtype",
    "growth_mode", "deterministic", "hist_mode",
    # commonly passed by the python layer
    "categorical_feature", "feature_name", "objective_seed", "metric_seed",
})

_TRUE_SET = {"true", "+", "1", "yes", "y", "t", "on"}
_FALSE_SET = {"false", "-", "0", "no", "n", "f", "off"}


def canonicalize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve aliases to canonical names, mirroring
    ``ParameterAlias::KeyAliasTransform`` (reference ``config.h:364-525``).

    When both an alias and the canonical key appear, the canonical key wins;
    among multiple aliases the longest (then lexicographically larger) name
    wins, matching the reference's reproducible-priority rule.
    """
    out: Dict[str, Any] = {}
    alias_src: Dict[str, str] = {}
    for key in sorted(params.keys(), key=lambda k: (len(k), k)):
        value = params[key]
        canonical = ALIAS_TABLE.get(key, key)
        if canonical != key:
            if canonical in params:
                log_warning(
                    f"{canonical} is set, {key}={value!r} will be ignored.")
                continue
            if canonical in out:
                log_warning(
                    f"{canonical} is set with {alias_src[canonical]}, "
                    f"overridden by {key}={value!r}.")
            alias_src[canonical] = key
            out[canonical] = value
        else:
            if key not in PARAMETER_SET:
                log_warning(f"Unknown parameter: {key}")
            out[key] = value
    return out


def param_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    if isinstance(value, (int, float)):
        return bool(value)
    s = str(value).strip().lower()
    if s in _TRUE_SET:
        return True
    if s in _FALSE_SET:
        return False
    raise ValueError(f"cannot parse boolean parameter value {value!r}")


def _parse_int_list(value: Any) -> List[int]:
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return [int(v) for v in value]
    return [int(v) for v in str(value).replace(";", ",").split(",") if v != ""]


def _parse_float_list(value: Any) -> List[float]:
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return [float(v) for v in value]
    return [float(v) for v in str(value).replace(";", ",").split(",") if v != ""]


def _parse_str_list(value: Any) -> List[str]:
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return [str(v) for v in value]
    return [s for s in str(value).replace(";", ",").split(",") if s != ""]


@dataclass
class Config:
    """All hyper-parameters, flattened (reference: OverallConfig, config.h:286-306)."""

    # --- task / device ------------------------------------------------------
    task: str = "train"                      # train|predict|convert_model|refit
    device: str = "tpu"                      # cpu|gpu|tpu  (tpu == jax default backend)
    seed: int = 0
    num_threads: int = 0
    verbose: int = 1
    deterministic: bool = True

    # --- boosting -----------------------------------------------------------
    boosting_type: str = "gbdt"              # gbdt|dart|goss|rf
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    early_stopping_round: int = 0
    output_freq: int = 1
    is_training_metric: bool = False
    snapshot_freq: int = -1
    # fault tolerance: retain the newest K snapshots (current + a
    # fallback in case a crash tears the current one mid-write), and an
    # optional snapshot to resume a preempted run from ("auto" =
    # newest valid snapshot under the output_model prefix)
    snapshot_keep: int = 2
    resume_from: str = ""
    # observability: stream the telemetry JSONL trace to this path
    # (per-rank suffixed in multi-host runs; see obs/telemetry.py and
    # the LGBM_TPU_TRACE env equivalent)
    telemetry_output: str = ""

    # dart
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4

    # goss
    top_rate: float = 0.2
    other_rate: float = 0.1

    # --- objective ----------------------------------------------------------
    objective: str = "regression"
    alpha: float = 0.9                       # huber / quantile
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    reg_sqrt: bool = False
    label_gain: Tuple[float, ...] = ()
    max_position: int = 20
    num_iteration_predict: int = -1
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0

    # --- metric -------------------------------------------------------------
    metric: Tuple[str, ...] = ()
    ndcg_eval_at: Tuple[int, ...] = (1, 2, 3, 4, 5)

    # --- tree ---------------------------------------------------------------
    tree_learner: str = "serial"             # serial|feature|data|voting
    num_leaves: int = 31
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    top_k: int = 20                          # voting parallel
    max_cat_threshold: int = 32
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    histogram_pool_size: float = -1.0
    growth_mode: str = "wave"                # wave (TPU fast) | leafwise (reference-exact)
    # histogram accumulation precision on the Pallas path (the TPU analog
    # of the reference's gpu_use_dp, docs/GPU-Performance.rst:135-161):
    # "" = auto (bf16 products, f32 accumulation; see
    # learner/serial.py default_hist_mode + the recorded parity table),
    # "bf16" | "ghilo" (hi+lo gradients, plain hess) | "hilo" (hi+lo
    # pairs for both, ~f32 sums) | "scatter" is
    # accepted via hist_backend-style env override for debugging.
    hist_mode: str = ""

    # --- io / dataset -------------------------------------------------------
    max_bin: int = 255
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    use_missing: bool = True
    zero_as_missing: bool = False
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    enable_load_from_binary_file: bool = True
    is_save_binary_file: bool = False
    use_two_round_loading: bool = False
    is_pre_partition: bool = False
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_column: str = ""
    data: str = ""
    valid_data: Tuple[str, ...] = ()
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"
    output_result: str = "LightGBM_predict_result.txt"
    init_score_file: str = ""
    valid_init_score_file: Tuple[str, ...] = ()
    is_predict_raw_score: bool = False
    is_predict_leaf_index: bool = False
    is_predict_contrib: bool = False
    convert_model: str = "gbdt_prediction.cpp"
    convert_model_language: str = ""

    # --- network / distributed ---------------------------------------------
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""
    machines: str = ""
    run_mode: str = ""
    application_master_address: str = ""

    # --- TPU-native ---------------------------------------------------------
    mesh_shape: Tuple[int, ...] = ()          # () == all local devices on one axis
    data_axis_name: str = "data"
    feature_axis_name: str = "feature"
    hist_dtype: str = "float32"

    # free-form extras kept for round-tripping
    extra: Dict[str, Any] = field(default_factory=dict)

    # -----------------------------------------------------------------------
    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        params = canonicalize_params(dict(params or {}))
        cfg = cls()
        cfg.update(params)
        cfg.check()
        return cfg

    def update(self, params: Dict[str, Any]) -> None:
        fields = {f.name: f for f in dataclasses.fields(self)}
        for key, value in params.items():
            if key == "poission_max_delta_step":   # reference typo kept as alias
                key = "poisson_max_delta_step"
            if key == "objective" and callable(value):
                # custom objective function: trained via fobj, like the
                # reference's objective=None + custom gradients path
                self.extra["fobj"] = value
                self.objective = "none"
                continue
            if key not in fields:
                self.extra[key] = value
                continue
            f = fields[key]
            try:
                if f.type in ("bool", bool):
                    value = param_bool(value)
                elif f.type in ("int", int):
                    value = int(value)
                elif f.type in ("float", float):
                    value = float(value)
                elif key in ("metric", "valid_data", "valid_init_score_file"):
                    value = tuple(_parse_str_list(value))
                elif key == "ndcg_eval_at":
                    value = tuple(_parse_int_list(value))
                elif key == "label_gain":
                    value = tuple(_parse_float_list(value))
                elif key == "mesh_shape":
                    value = tuple(_parse_int_list(value))
                elif f.type in ("str", str):
                    value = str(value)
            except (TypeError, ValueError) as exc:
                raise ValueError(f"bad value for parameter {key}: {value!r}") from exc
            setattr(self, key, value)
        # objective aliases (reference objective factory names)
        self.objective = _canonical_objective(self.objective)
        self.boosting_type = _canonical_boosting(self.boosting_type)

    def check(self) -> None:
        """Parameter conflict checks (reference ``OverallConfig::CheckParamConflict``)."""
        if self.num_leaves < 2:
            raise ValueError("num_leaves must be >= 2")
        if self.max_bin < 2:
            raise ValueError("max_bin must be >= 2")
        if not (0.0 < self.feature_fraction <= 1.0):
            raise ValueError("feature_fraction must be in (0, 1]")
        if not (0.0 < self.bagging_fraction <= 1.0):
            raise ValueError("bagging_fraction must be in (0, 1]")
        if self.boosting_type == "goss" and self.top_rate + self.other_rate > 1.0:
            raise ValueError("goss requires top_rate + other_rate <= 1")
        if self.boosting_type == "rf":
            if not (self.bagging_freq > 0 and self.bagging_fraction < 1.0):
                raise ValueError(
                    "random forest needs bagging_freq > 0 and bagging_fraction < 1")
        if self.objective in ("multiclass", "multiclassova") and self.num_class < 2:
            raise ValueError("num_class must be >= 2 for multiclass objectives")
        if self.objective not in ("multiclass", "multiclassova") and self.num_class != 1:
            raise ValueError("num_class must be 1 for non-multiclass objectives")
        if self.tree_learner not in ("serial", "feature", "data", "voting"):
            raise ValueError(f"unknown tree_learner {self.tree_learner!r}")
        if self.growth_mode not in ("wave", "leafwise"):
            raise ValueError(f"unknown growth_mode {self.growth_mode!r}")
        if self.hist_mode not in ("", "bf16", "ghilo", "hhilo", "hilo",
                                  "int8", "int8h", "int8hh"):
            raise ValueError(f"unknown hist_mode {self.hist_mode!r}")
        # gpu_use_dp is the reference's GPU double-precision knob
        # (docs/GPU-Performance.rst): honor it as "use the high-precision
        # histogram mode" unless hist_mode was given explicitly
        if not self.hist_mode and self.extra.get("gpu_use_dp") in (
                True, "true", "1", 1):
            self.hist_mode = "hilo"
        # accepted-but-inert knobs must warn loudly, not silently no-op
        # (reference knobs that have no TPU counterpart)
        from .utils.log import log_warning
        if self.extra.get("gpu_platform_id") is not None or \
                self.extra.get("gpu_device_id") is not None:
            log_warning("gpu_platform_id/gpu_device_id have no effect: "
                        "device selection is JAX's (TPU kernels replace "
                        "the OpenCL learner)")

    @property
    def is_parallel(self) -> bool:
        return self.tree_learner != "serial" or self.num_machines > 1

    @property
    def num_tree_per_iteration(self) -> int:
        if self.objective in ("multiclass", "multiclassova"):
            return self.num_class
        return 1

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("extra", None)
        return d


_OBJECTIVE_ALIASES = {
    "regression": "regression",
    "regression_l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "l1": "regression_l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "lambdarank": "lambdarank",
    "xentropy": "xentropy",
    "cross_entropy": "xentropy",
    "xentlambda": "xentlambda",
    "cross_entropy_lambda": "xentlambda",
    "none": "none",
    "null": "none",
    "custom": "none",
    "": "none",
}

_BOOSTING_ALIASES = {
    "gbdt": "gbdt", "gbrt": "gbdt",
    "dart": "dart",
    "goss": "goss",
    "rf": "rf", "random_forest": "rf",
}


def _canonical_objective(name: str) -> str:
    key = str(name).strip().lower()
    if key.startswith("l2_root") or key == "rmse":
        return "regression"
    if key not in _OBJECTIVE_ALIASES:
        raise ValueError(f"unknown objective {name!r}")
    return _OBJECTIVE_ALIASES[key]


def _canonical_boosting(name: str) -> str:
    key = str(name).strip().lower()
    if key not in _BOOSTING_ALIASES:
        raise ValueError(f"unknown boosting type {name!r}")
    return _BOOSTING_ALIASES[key]
