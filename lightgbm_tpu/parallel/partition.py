"""Partition-rule sharding registry — ONE named placement mechanism.

Every persistent device array in the system is named in a flat
``/``-separated name tree and placed by matching that name against an
ordered table of ``(rule_name, regex, PartitionSpec)`` rules — the
``match_partition_rules`` + ``make_shard_and_gather_fns`` pattern of
the big-model trainers (SNIPPETS.md [1]/[2]: fmengine / EasyDeL place
params by regex once, then every step consumes them in place), applied
to the GBDT training store.  Before this module the same decisions
lived in five bespoke sites (``MeshContext.place_data`` for
bins/metadata, ad-hoc ``NamedSharding``/``with_sharding_constraint``
pairs in ``boosting/gbdt.py`` for grad/hess/bag, default-device
``device_put`` for scores/valid state, and the serve pack's implicit
``jnp.asarray`` placement) — five places a new array could silently
pick a wrong layout.

Contract (the registry-completeness gate, ``tools/partition_audit.py``
+ ``tests/test_partition.py``):

* every persistent name placed on a mesh matches **exactly one** rule
  — zero matches raise :class:`PartitionRuleError` at placement time
  (a hard error, never a silent default), and overlapping rules fail
  the audit;
* the rule table is TOTAL over the canonical persistent-name set
  (``persistent_names``): training store fields (from the real
  ``DeviceData`` fields, so a new field cannot drift out of coverage),
  scores, valid scores, grad/hess, bag/feature masks, early-stopping
  state, and the serve tree pack (from the real ``ServePack`` fields —
  registered replicated for now, proving the registry spans train AND
  serve with zero behavior change).

Name tree (flat, ``/``-joined):

==========================  =============================================
``data/<field>``            training ``DeviceData`` arrays (``data/bins``
                            row-sharded for data/voting, replicated for
                            feature-parallel; metadata replicated)
``scores``                  running train scores ``[n, K]`` (replicated:
                            host eval/feval/C-API read them per window,
                            and ``n`` is the UNPADDED row count — row
                            padding happens inside the jitted build)
``valid/<i>/scores``        running valid scores (replicated)
``valid/<i>/data/<field>``  valid ``DeviceData`` arrays (replicated)
``grad`` / ``hess``         per-iteration gradient slices (row-sharded
                            for data/voting; padded inside jit first)
``bag_mask``                row-sampling mask (row-sharded, padded
                            out-of-bag inside jit)
``feature_mask``            per-tree feature mask (replicated)
``es/<key>``                early-stopping score state (replicated)
``serve/pack/<field>``      compiled ``ServePack`` arrays (replicated)
==========================  =============================================
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, Iterable, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rule = Tuple[str, str, P]


class PartitionRuleError(ValueError):
    """A persistent array name did not match exactly one partition rule."""


# ---------------------------------------------------------------------------
# rule tables
# ---------------------------------------------------------------------------
def train_rules(data_axis: str = "data",
                row_sharded: bool = True) -> Tuple[Rule, ...]:
    """The training-side rule table for one mesh context.

    ``row_sharded`` is the learner-type switch: data/voting-parallel
    shard the row axis, feature-parallel replicates rows (the learner
    slices feature columns inside the shard instead).  The regexes are
    mutually exclusive by construction (``data/bins`` is carved out of
    the metadata catch-all with a lookahead) so the completeness gate
    can demand EXACTLY one match per name."""
    row = P(data_axis) if row_sharded else P()
    return (
        ("bins",         r"^data/bins$",            row),
        ("data_meta",    r"^data/(?!bins$)",        P()),
        ("scores",       r"^scores$",               P()),
        ("valid_scores", r"^valid/\d+/scores$",     P()),
        ("valid_data",   r"^valid/\d+/data/",       P()),
        ("grad_hess",    r"^(grad|hess)$",          row),
        ("bag_mask",     r"^bag_mask$",             row),
        ("feature_mask", r"^feature_mask$",         P()),
        ("es_state",     r"^es/",                   P()),
    ) + serve_rules()


def serve_rules() -> Tuple[Rule, ...]:
    """Serve-side rules: the compiled tree pack is replicated for now
    (every chip holds the whole forest; the trees-axis sharding of
    ROADMAP item 3a will refine exactly this one rule)."""
    return (("serve_pack", r"^serve/pack/", P()),)


# ---------------------------------------------------------------------------
# name trees
# ---------------------------------------------------------------------------
def device_data_names(dd) -> Dict[str, Any]:
    """``{field: array}`` for a ``DeviceData``'s ARRAY children, named
    by the real NamedTuple fields — a new persistent field shows up
    here automatically and must find a rule."""
    children, _ = dd.tree_flatten()
    return dict(zip(type(dd)._fields, children))


def serve_pack_names(pack) -> Dict[str, Any]:
    """``{field: array}`` for a ``ServePack``'s array children."""
    children, _ = pack.tree_flatten()
    return {"serve": {"pack": dict(zip(type(pack)._fields, children))}}


def flatten_names(tree: Any, sep: str = "/") -> List[Tuple[str, Any]]:
    """Flatten a dict name tree to ``[(joined_name, leaf), ...]``."""
    out: List[Tuple[str, Any]] = []

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{sep}{k}" if prefix else str(k), v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(f"{prefix}{sep}{i}" if prefix else str(i), v)
        else:
            out.append((prefix, node))

    walk("", tree)
    return out


def persistent_names(num_valid: int = 1) -> List[str]:
    """The canonical persistent-name set the audit must cover: derived
    from the REAL ``DeviceData`` / ``ServePack`` field lists (source of
    truth, not a copy) plus the booster-level state names."""
    from ..io.device import DeviceData
    names = [f"data/{f}" for f in DeviceData._fields[:9]]
    names += ["scores", "grad", "hess", "bag_mask", "feature_mask"]
    for i in range(num_valid):
        names += [f"valid/{i}/scores"]
        names += [f"valid/{i}/data/{f}" for f in DeviceData._fields[:9]]
    names += ["es/best_scores", "es/best_iter"]
    from ..serve.compiler import ServePack
    names += [f"serve/pack/{f}" for f in ServePack._fields[:-1]]
    return names


# ---------------------------------------------------------------------------
# matching
# ---------------------------------------------------------------------------
def matching_rules(rules: Sequence[Rule], name: str) -> List[str]:
    return [rn for rn, rx, _ in rules if re.search(rx, name) is not None]


def match_name(rules: Sequence[Rule], name: str) -> P:
    """The one rule's spec for ``name``; an unmatched name is a HARD
    error — a persistent array without a placement decision must fail
    loudly at placement time, not inherit a silent default layout."""
    for rule_name, rx, spec in rules:
        if re.search(rx, name) is not None:
            return spec
    raise PartitionRuleError(
        f"no partition rule matches persistent array {name!r}; add a "
        f"rule to lightgbm_tpu/parallel/partition.py (rules: "
        f"{[r[0] for r in rules]})")


def match_partition_rules(rules: Sequence[Rule], tree: Any,
                          sep: str = "/") -> Dict[str, P]:
    """``{flat_name: PartitionSpec}`` for a dict name tree.  Scalars /
    0-d leaves get ``P()`` (never partition a scalar — snippet [1]);
    every other leaf must match a rule or this raises."""
    specs: Dict[str, P] = {}
    for name, leaf in flatten_names(tree, sep):
        if np.ndim(leaf) == 0:
            specs[name] = P()
        else:
            specs[name] = match_name(rules, name)
    return specs


def audit_rules(rules: Sequence[Rule],
                names: Iterable[str]) -> List[str]:
    """The completeness gate: every name must match EXACTLY one rule.
    Returns human-readable findings (empty == clean)."""
    findings = []
    for name in names:
        hits = matching_rules(rules, name)
        if len(hits) == 0:
            findings.append(f"{name}: matches NO partition rule")
        elif len(hits) > 1:
            findings.append(
                f"{name}: matches {len(hits)} rules {hits} (must be 1)")
    return findings


# ---------------------------------------------------------------------------
# shard / gather
# ---------------------------------------------------------------------------
def make_shard_and_gather_fns(rules: Sequence[Rule], mesh: Mesh,
                              ) -> Tuple[Callable[[str, Any], Any],
                                         Callable[[Any], Any]]:
    """``(shard_fn, gather_fn)`` over a mesh: ``shard_fn(name, x)``
    places ``x`` under the matched rule's ``NamedSharding`` (host
    numpy or device arrays both accepted — one transfer, no eager
    relayout later); ``gather_fn(x)`` replicates back (the full-array
    view host readers expect)."""
    rep = NamedSharding(mesh, P())

    def shard_fn(name: str, x):
        if np.ndim(x) == 0:
            return jax.device_put(x, rep)
        return jax.device_put(x, NamedSharding(mesh, match_name(rules, name)))

    def gather_fn(x):
        return jax.device_put(x, rep)

    return shard_fn, gather_fn


def place_tree(rules: Sequence[Rule], mesh: Mesh, tree: Any,
               sep: str = "/") -> Any:
    """Place a whole dict name tree under the registry; returns a tree
    of the same structure with every array leaf device_put under its
    matched rule."""
    shard_fn, _ = make_shard_and_gather_fns(rules, mesh)

    def walk(prefix: str, node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(f"{prefix}{sep}{k}" if prefix else str(k), v)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(
                walk(f"{prefix}{sep}{i}" if prefix else str(i), v)
                for i, v in enumerate(node))
        return shard_fn(prefix, node)

    return walk("", tree)
