"""Device mesh management — the communication backend seam.

Replaces the reference's network stack (`/root/reference/src/network/`:
socket/MPI linkers, Bruck/recursive-halving/ring collectives,
`network.cpp:64-243`) with JAX device meshes and XLA collectives over
ICI/DCN.  The reference's pluggable-collective hook
(``LGBM_NetworkInitWithFunctions``, `c_api.h:760`) maps to this module:
every distributed learner takes a ``MeshContext`` and calls
``psum``-style collectives inside ``shard_map``; tests inject a virtual
8-device CPU mesh (`XLA_FLAGS=--xla_force_host_platform_device_count=8`).

Multi-host: ``init_distributed`` wraps ``jax.distributed.initialize`` —
the coordinator-address pattern is the TPU-native equivalent of the
fork's YARN application-master rendezvous (`linkers_socket.cpp:27-68`:
workers report to an AM address and receive the machine list; here the
coordinator does the same via the JAX distributed service).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..utils.log import log_info, log_warning


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host rendezvous (reference: YARN AM rendezvous + TCP mesh
    handshake, linkers_socket.cpp:27-68,225-274).  On TPU pods the
    environment usually auto-detects; explicit args mirror the
    ``application_master_address`` config of the fork."""
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id

    from ..obs import span
    from ..utils.faults import fault_point
    from ..utils.retry import RetryPolicy, retry_call

    def _connect():
        # injection seam for the rendezvous handshake (the fault the
        # fork's YARN workers see when the AM isn't up yet)
        fault_point("rendezvous.connect")
        jax.distributed.initialize(**kwargs)

    from ..obs.flight_recorder import record as fr_record
    fr_record("parallel.mesh.rendezvous", "distributed.initialize")
    from ..obs.telemetry import hold_trace, release_trace
    try:
        # retried with backoff: at pod startup the coordinator may come
        # up seconds after the workers (the reference's socket Connect
        # loops with time_out retries, linkers_socket.cpp:225-274).
        # Trace records buffer until the rendezvous resolves this
        # process's rank — the per-rank trace file must not open as
        # rank 0 on every worker.
        hold_trace()
        try:
            with span("mesh.rendezvous"):
                retry_call(_connect, policy=RetryPolicy.from_env(),
                           what="rendezvous.connect")
        finally:
            release_trace()
    except RuntimeError as exc:
        # idempotent entry: the CLI's already-meshed probe reads private
        # jax state and may miss on a future jax — double-initialize
        # must then degrade to a no-op, not a crash (ADVICE r4).
        # jax 0.9 phrases it "distributed.initialize should only be
        # called once."; older builds say "already initialized"
        msg = str(exc).lower()
        if ("already initialized" not in msg
                and "only be called once" not in msg):
            raise


def init_distributed_from_machines(machines: str, local_listen_port: int,
                                   num_machines: int) -> None:
    """LGBM_NetworkInit semantics (c_api.h:749-756): a comma-separated
    ``ip:port`` machine list.  The reference resolves its own rank by
    matching a local endpoint against the list and TCP-meshes everyone
    (`linkers_socket.cpp:97-107,225-274`); here the first machine is the
    ``jax.distributed`` coordinator and rank = list position, matched by
    the local listen port (all-loopback lists work for tests)."""
    entries = [m.strip() for m in machines.replace("\n", ",").split(",")
               if m.strip()]
    if num_machines > len(entries):
        raise ValueError(
            f"num_machines={num_machines} but machine list has "
            f"{len(entries)} entries")
    entries = entries[:num_machines]
    import socket

    def _is_local_ip(host: str) -> bool:
        """Bindability test — the reference resolves its local endpoint by
        actually binding a socket (`linkers_socket.cpp:20-78`), which works
        where hostname DNS lies (Debian's 127.0.1.1 /etc/hosts entry)."""
        if host in ("127.0.0.1", "localhost", "0.0.0.0"):
            return True
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.bind((host, 0))
                return True
            finally:
                s.close()
        except OSError:
            return False

    # rank = the local entry; when several entries are local (all-loopback
    # test lists), the listen port disambiguates — port matching only
    # applies AMONG local entries, else the shared-port multi-host setup
    # (every machine listening on the same port) would resolve rank 0
    # everywhere
    local = [i for i, e in enumerate(entries)
             if _is_local_ip(e.rsplit(":", 1)[0])]
    if len(local) == 1:
        rank = local[0]
    else:
        cands = local if local else range(len(entries))
        matches = [i for i in cands
                   if ":" in entries[i]
                   and int(entries[i].rsplit(":", 1)[1]) == local_listen_port]
        if len(matches) != 1:
            raise ValueError(
                "cannot resolve local rank from machine list "
                f"{entries!r} with local_listen_port={local_listen_port}")
        rank = matches[0]
    init_distributed(coordinator_address=entries[0],
                     num_processes=num_machines, process_id=rank)


class ProcessRows:
    """Block layout of mod-rank-sharded local rows inside global
    row-sharded arrays (multi-process data/voting-parallel training).

    Each process contributes ONE padded block of the global row axis:
    ``[rank*per, rank*per + n_local)`` are its real rows, the rest of
    the block is padding (masked out-of-bag).  The reference's
    equivalent is each machine's local row range after mod-rank
    sharding (`dataset_loader.cpp:639-742`)."""

    def __init__(self, mesh_ctx: "MeshContext", n_local: int):
        from ..io.distributed import jax_process_allgather
        self.mesh_ctx = mesh_ctx
        self.world = jax.process_count()
        self.counts = [int(x) for x in jax_process_allgather(int(n_local))]
        self.n_local = int(n_local)
        self.n_global = sum(self.counts)
        ld = jax.local_device_count()
        # per-process block: covers the largest local shard, divisible
        # by the local device count so every device shard is equal
        self.per = -(-max(self.counts) // ld) * ld
        self.n_pad = self.per * self.world

    def globalize(self, local: np.ndarray, fill=0) -> jax.Array:
        """``[n_local, ...] -> global [n_pad, ...]`` row-sharded array."""
        local = np.asarray(local)
        block = np.full((self.per,) + local.shape[1:], fill, local.dtype)
        block[:len(local)] = local
        return jax.make_array_from_process_local_data(
            self.mesh_ctx.row_sharding(), block)

    def replicate(self, x) -> jax.Array:
        return jax.device_put(np.asarray(x), self.mesh_ctx.replicated())

    def valid_mask_local(self) -> np.ndarray:
        m = np.zeros(self.per, bool)
        m[:self.n_local] = True
        return m

    def local_np(self, global_arr) -> np.ndarray:
        """This process's REAL rows of a global row-sharded array.
        Shards are DEDUPED by row offset: on a 2-D (data x feature)
        mesh the feature-axis devices hold row replicas."""
        by_start = {}
        for s in global_arr.addressable_shards:
            by_start.setdefault(s.index[0].start or 0, s.data)
        block = np.concatenate(
            [np.asarray(by_start[k]) for k in sorted(by_start)])
        return block[:self.n_local]


class MeshContext:
    """A 1-D (data) or 2-D (data × feature) device mesh + shard helpers.

    All placement decisions flow through the partition-rule registry
    (``parallel/partition.py``): ``partition_rules()`` is the rule
    table for this mesh's learner type, ``sharding_for(name)`` resolves
    one persistent name, and ``place_data``/``place_scores``/
    ``place_valid`` place whole state groups — an array name without a
    rule raises instead of inheriting a default layout."""

    def __init__(self, config: Config, devices: Optional[Sequence] = None):
        self.config = config
        devices = list(devices if devices is not None else jax.devices())
        shape = tuple(config.mesh_shape) or (len(devices),)
        n_mesh = int(np.prod(shape))
        if n_mesh > len(devices):
            raise ValueError(
                f"mesh_shape {shape} needs {n_mesh} devices, have "
                f"{len(devices)}")
        devices = devices[:n_mesh]
        self.data_axis = config.data_axis_name
        self.feature_axis = config.feature_axis_name
        if len(shape) == 1:
            self.mesh = Mesh(np.asarray(devices).reshape(shape),
                             (self.data_axis,))
            self.axis_names: Tuple[str, ...] = (self.data_axis,)
        elif len(shape) == 2:
            self.mesh = Mesh(np.asarray(devices).reshape(shape),
                             (self.data_axis, self.feature_axis))
            self.axis_names = (self.data_axis, self.feature_axis)
        else:
            raise ValueError("mesh_shape must have 1 or 2 axes")

    @property
    def num_data_shards(self) -> int:
        return self.mesh.shape[self.data_axis]

    @property
    def num_feature_shards(self) -> int:
        return (self.mesh.shape[self.feature_axis]
                if self.feature_axis in self.mesh.shape else 1)

    @property
    def row_sharded(self) -> bool:
        """Whether this mesh's learner type shards the row axis
        (data/voting) or replicates rows (feature-parallel)."""
        return self.config.tree_learner in ("data", "voting")

    def partition_rules(self):
        """The partition-rule table governing every persistent array
        placed on THIS mesh (see ``parallel/partition.py``)."""
        from .partition import train_rules
        return train_rules(self.data_axis, self.row_sharded)

    def sharding_for(self, name: str) -> NamedSharding:
        """Resolve one persistent array name through the registry —
        an unmatched name raises ``PartitionRuleError``."""
        from .partition import match_name
        return NamedSharding(self.mesh,
                             match_name(self.partition_rules(), name))

    def row_sharding(self) -> NamedSharding:
        """[n, ...] arrays sharded over rows."""
        return NamedSharding(self.mesh, P(self.data_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def place_data(self, dd, row_sharded: Optional[bool] = None):
        """Place a DeviceData ONCE under the partition-rule registry:
        ``data/bins`` sharded over the data axis rows (replicated for
        feature-parallel, which replicates rows), every ``data/<meta>``
        array replicated.  Without this, each jitted distributed build
        re-lays-out the single-device store to the mesh per dispatch —
        at the 10.5M-row HIGGS shape that is a ~294 MB reshard of the
        biggest buffer EVERY iteration.  The pjit shard-rule pattern of
        SNIPPETS.md [1]/[2] (fmengine / EasyDeL trainers place params
        once, then every step consumes them in place) applied to the
        GBDT training store."""
        from ..io.device import DeviceData
        from .partition import device_data_names, place_tree, train_rules
        children, aux = dd.tree_flatten()
        rules = (self.partition_rules() if row_sharded is None
                 else train_rules(self.data_axis, row_sharded))
        placed = place_tree(rules, self.mesh,
                            {"data": device_data_names(dd)})["data"]
        fields = type(dd)._fields
        return DeviceData(*(placed[f] for f in fields[:len(children)]), *aux)

    def place_scores(self, scores) -> jax.Array:
        """Place a running score state (``scores`` / ``valid/i/scores``)
        under its registry rule (replicated: host eval reads it per
        window, and the row count is the unpadded n)."""
        return jax.device_put(scores, self.sharding_for("scores"))

    def place_valid(self, i: int, dd, scores):
        """Place valid set ``i``'s DeviceData + running scores under
        the ``valid/<i>/...`` rules (all replicated)."""
        from ..io.device import DeviceData
        from .partition import device_data_names, place_tree
        tree = {"valid": {str(i): {"data": device_data_names(dd),
                                   "scores": scores}}}
        placed = place_tree(self.partition_rules(), self.mesh,
                            tree)["valid"][str(i)]
        children, aux = dd.tree_flatten()
        fields = type(dd)._fields
        dd_placed = DeviceData(
            *(placed["data"][f] for f in fields[:len(children)]), *aux)
        return dd_placed, placed["scores"]

    def pad_rows(self, n: int) -> int:
        """Rows padded to a multiple of the data-shard count."""
        d = self.num_data_shards
        return (n + d - 1) // d * d


def shard_row_ranges(n: int, num_shards: int):
    """The mesh row partition as explicit ``[(lo, hi), ...]`` global
    ranges — the SAME contiguous equal-length layout ``pad_rows`` +
    row sharding produce (shard ``d`` owns rows ``[d*per, (d+1)*per)``
    of the padded space).  The streamed out-of-core trainer
    (``boosting/streaming.py``) assigns blocks to shards through this,
    which is what makes per-rank shard ownership compose with mesh row
    sharding: streamed shard folds cover exactly the rows the
    in-memory data-parallel mesh places on each device."""
    d = max(1, num_shards)
    per = (n + d - 1) // d
    return [(i * per, (i + 1) * per) for i in range(d)]


def make_mesh(num_devices: int, axis: str = "data",
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices[:num_devices]), (axis,))
