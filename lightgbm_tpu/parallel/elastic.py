"""Elastic rendezvous: generation-numbered membership + host collectives.

The reference's YARN application master hands every worker the live
machine list once (``linkers_socket.cpp:27-68``) and never updates it —
a dead rank hangs the first collective forever and the job dies with
its snapshots unused.  This module redoes that machine-list loop as a
restartable *epoch protocol*:

* **Generations** — the coordinator numbers every membership view.
  Each (re)join returns ``(world_size, rank, generation)``; ANY
  membership change (join, clean leave, heartbeat eviction) bumps the
  generation and fails every in-flight and future collective of the
  old generation with :class:`GenerationChanged` — survivors unwind to
  the recovery loop (``boosting/streaming.train_elastic``) instead of
  deadlocking against a member that no longer exists.
* **Rank-failure detection** — two complementary signals.  Peer
  heartbeats (interval ``LGBM_TPU_HEARTBEAT_S``) carry the rank's live
  health state from the PR 13 plane (``obs/health.py``); the
  coordinator evicts a member only when its heartbeats STOP — a rank
  whose watchdog reports ``stalled`` but whose heartbeat thread is
  alive is wedged-but-alive and is deliberately NOT evicted (killing a
  wedged XLA dispatch's process is the operator's call, not the
  protocol's).  Independently, every client collective is bounded by
  ``LGBM_TPU_COLLECTIVE_DEADLINE_S`` and raises the typed
  :class:`~lightgbm_tpu.io.distributed.RankLostError` instead of
  blocking forever — the backstop for a dead *coordinator* or an
  eviction that lands slower than the deadline.
* **Rank-ordered collectives** — ``allgather`` is the only primitive
  (barriers are allgathers of a tag).  Contributions are keyed
  ``(generation, seq)``; payloads return in rank order, so the
  streamed trainer can combine per-shard partials in *shard* order —
  the partition-invariant fold that makes recovery byte-identical.

Transport is one JSON line per request over loopback/DCN TCP (the
reference's own linker transport class); numpy payloads ride base64
``.npy`` bytes (:func:`encode_array`).  The module is deliberately
jax-free: protocol tests run without a device runtime.

Fault points (``utils/faults.py``): ``rendezvous.drop_rank`` makes the
coordinator's monitor evict the newest member (a lost rank without
killing a process), ``heartbeat.miss`` makes a client skip beats,
``collective.hang`` (in ``io/distributed.deadline_call``) stalls a
collective past the deadline, ``collective.slow`` delays one rank's
contribution SUB-deadline (``LGBM_TPU_COLLECTIVE_SLOW`` seconds) — the
injected straggler that the fleet report must localize.
"""
from __future__ import annotations

import base64
import io
import json
import os
import socket
import socketserver
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..io.distributed import RankLostError, collective_deadline_s
from ..obs import counter_add, event, gauge_set, span
from ..obs import fleet as obs_fleet
from ..utils.log import log_info, log_warning

__all__ = [
    "ElasticCoordinator", "ElasticClient", "ElasticRun",
    "GenerationChanged", "RankLostError", "ELASTIC_INTERRUPTS",
    "heartbeat_s", "elastic_address", "encode_array", "decode_array",
]


class GenerationChanged(RuntimeError):
    """The membership changed under an in-flight collective: the old
    generation's world no longer exists.  Survivors re-rendezvous and
    resume from the last committed barrier snapshot."""

    def __init__(self, generation: int, detail: str = ""):
        self.generation = int(generation)
        msg = (f"elastic membership changed (now generation "
               f"{generation}); in-flight collectives are invalid")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class EvictedError(RuntimeError):
    """This member was evicted (missed heartbeats); it must re-join as
    a fresh member to participate again."""


# what the recovery loop catches: lost peers, lost epochs.  (Evicted
# members also recover — by re-joining as a new member.)
ELASTIC_INTERRUPTS = (RankLostError, GenerationChanged, EvictedError)


def heartbeat_s() -> float:
    """Heartbeat interval from ``LGBM_TPU_HEARTBEAT_S`` (default 0.5 s;
    eviction timeout defaults to 5 intervals, coordinator-side)."""
    try:
        s = float(os.environ.get("LGBM_TPU_HEARTBEAT_S", "0.5"))
    except ValueError:
        return 0.5
    return s if s > 0 else 0.5


def elastic_address() -> Optional[str]:
    """``LGBM_TPU_ELASTIC`` — the coordinator's ``host:port``.  Doubles
    as the elastic on/off switch: unset means classic fixed-world
    training."""
    return os.environ.get("LGBM_TPU_ELASTIC") or None


def encode_array(arr: np.ndarray) -> str:
    """numpy array -> base64 ``.npy`` bytes (dtype+shape travel with
    the payload; bitwise round-trip)."""
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_array(text: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(text.encode("ascii"))),
                   allow_pickle=False)


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------
class _Member:
    __slots__ = ("member", "joined_seq", "last", "state", "detail")

    def __init__(self, member: str, joined_seq: int):
        self.member = member
        self.joined_seq = joined_seq
        self.last = time.monotonic()
        self.state = ""
        self.detail: Dict[str, Any] = {}


class ElasticCoordinator:
    """The rendezvous + collective server (the YARN-AM analog, run
    in-process by the launcher — ``tools/chaos.py`` — or standalone).

    One instance serves one training job.  Thread-per-connection; all
    state under one condition variable.  ``start()`` returns the bound
    ``host:port`` for ``LGBM_TPU_ELASTIC``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout_s: Optional[float] = None,
                 ledger_path: Optional[str] = None):
        self.heartbeat_timeout_s = (heartbeat_timeout_s
                                    if heartbeat_timeout_s is not None
                                    else heartbeat_s() * 5)
        # the SIGKILL-survivable fleet history (obs/fleet.FleetLedger):
        # every membership change and completed collective round,
        # fsync'd line-at-a-time.  Off unless a path is given
        # (LGBM_TPU_FLEET_LEDGER or the constructor)
        path = ledger_path or obs_fleet.ledger_path_env()
        self._ledger = obs_fleet.FleetLedger(path) if path else None
        from ..obs.lock_contract import named_condition
        self._cv = named_condition("elastic_coord")
        self._members: Dict[str, _Member] = {}   # member id -> _Member
        self._generation = 0
        self._join_seq = 0
        # (generation, seq) -> {rank: payload}; results cached until the
        # last member of the round has read them.  _touch records each
        # round's last contribution: a legitimate round completes and
        # drains within one client deadline of it, so a round idle for
        # several deadlines was abandoned (its members timed out
        # client-side and retry under fresh keys after resync) and the
        # monitor ages it out to keep coordinator memory bounded.
        self._rounds: Dict[Tuple[int, int], Dict[int, Any]] = {}
        self._reads: Dict[Tuple[int, int], int] = {}
        self._touch: Dict[Tuple[int, int], float] = {}
        # per-round arrival wall-clocks {key: {rank: ts}} — ONE clock
        # (the coordinator's), so the returned per-rank arrival list is
        # directly comparable and each client derives its wait_s from
        # it without any cross-rank clock agreement
        self._arrivals: Dict[Tuple[int, int], Dict[int, float]] = {}
        self._round_sites: Dict[Tuple[int, int], str] = {}
        self._gauge_ranks = 0        # high-water of per-rank age gauges
        self._deadline_hint = 0.0    # max client deadline seen on the wire
        self._stop = False
        coord = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline()
                    if not line:
                        return
                    req = json.loads(line.decode())
                    resp = coord._dispatch(req)
                # tpulint: disable=TPL006 -- not swallowed: the error is
                # serialized onto the wire and raised client-side by
                # ElasticClient._check
                except Exception as exc:    # noqa: BLE001
                    resp = {"ok": False, "error": f"{type(exc).__name__}: "
                                                  f"{exc}"}
                try:
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                except OSError:
                    pass                    # client gave up (deadline)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._threads: List[threading.Thread] = []

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> str:
        # the coordinator is the fleet's authoritative observer: give
        # it its own scrapeable /metrics (gated on LGBM_TPU_OPS_PORT,
        # same as every other owner; idempotent if the launcher
        # already mounted one)
        from ..obs import ops_plane
        ops_plane.mount("elastic-coordinator")
        t = threading.Thread(target=self._server.serve_forever,
                             name="lgbm-tpu-elastic-coord", daemon=True)
        t.start()
        m = threading.Thread(target=self._monitor,
                             name="lgbm-tpu-elastic-monitor", daemon=True)
        m.start()
        self._threads = [t, m]
        self._ledger_put("coordinator_start", address=self.address,
                         heartbeat_timeout_s=self.heartbeat_timeout_s)
        log_info(f"elastic coordinator listening on {self.address}")
        return self.address

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._server.shutdown()
        self._server.server_close()
        # bounded-shutdown contract: every spawned thread gets a
        # join(timeout) — the server thread exits with shutdown(), the
        # monitor wakes on the notify above and sees _stop
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        self._ledger_put("coordinator_stop")
        if self._ledger is not None:
            self._ledger.close()

    def _ledger_put(self, kind: str, **fields: Any) -> None:
        if self._ledger is not None:
            self._ledger.put_line(kind, **fields)

    # -- introspection (tests, the chaos launcher's kill scheduler) ----
    def membership(self) -> Dict[str, Any]:
        with self._cv:
            ranks = self._ranks()
            return {
                "generation": self._generation,
                "world": len(ranks),
                "members": [
                    {"member": m.member, "rank": ranks[m.member],
                     "state": m.state, "detail": dict(m.detail),
                     "age_s": time.monotonic() - m.last}
                    for m in sorted(self._members.values(),
                                    key=lambda x: x.joined_seq)],
            }

    # -- internals -----------------------------------------------------
    def _ranks(self) -> Dict[str, int]:
        """member id -> rank: contiguous 0..W-1 in sorted member-id
        order — a pure function of the membership SET, so concurrent
        joiners racing into the same generation get the same rank map
        no matter which socket thread lands first (the join-order
        scheme this replaces handed out ranks by arrival, which two
        deflaked tests had to poll around).  A shrink re-ranks
        survivors — every rank map is per-generation and clients
        re-learn theirs on resync.  Caller holds ``_cv``."""
        return {m: r for r, m in enumerate(sorted(self._members))}

    def _bump(self, why: str, **attrs) -> None:
        """Membership changed: new generation, fail the old one's
        rounds.  Caller holds ``_cv``."""
        self._generation += 1
        self._rounds = {k: v for k, v in self._rounds.items()
                        if k[0] >= self._generation}
        self._reads = {k: v for k, v in self._reads.items()
                       if k[0] >= self._generation}
        self._touch = {k: v for k, v in self._touch.items()
                       if k[0] >= self._generation}
        self._arrivals = {k: v for k, v in self._arrivals.items()
                          if k[0] >= self._generation}
        self._round_sites = {k: v for k, v in self._round_sites.items()
                             if k[0] >= self._generation}
        counter_add("elastic.generation_bumps")
        event("elastic", why, generation=self._generation,
              world=len(self._members), **attrs)
        self._ledger_put(why, generation=self._generation,
                         world=len(self._members), **attrs)
        self._cv.notify_all()

    def _monitor(self) -> None:
        from ..utils.faults import fault_flag
        tick = max(self.heartbeat_timeout_s / 4.0, 0.02)
        while True:
            with self._cv:
                if self._stop:
                    return
                now = time.monotonic()
                dead = [m for m in self._members.values()
                        if now - m.last > self.heartbeat_timeout_s]
                if not dead and fault_flag("rendezvous.drop_rank"):
                    # the injected lost-rank: drop the newest member
                    live = sorted(self._members.values(),
                                  key=lambda m: m.joined_seq)
                    if live:
                        dead = [live[-1]]
                # age out abandoned rounds: every contributor gives up
                # at most one client deadline after its contribution,
                # so a round idle for several deadlines has no live
                # client left (survivors retry under fresh keys)
                stale_after = max(self._deadline_hint * 3,
                                  self.heartbeat_timeout_s * 4, 2.0)
                for key in [k for k, ts in self._touch.items()
                            if now - ts > stale_after]:
                    self._rounds.pop(key, None)
                    self._reads.pop(key, None)
                    self._touch.pop(key, None)
                    self._arrivals.pop(key, None)
                    self._round_sites.pop(key, None)
                    counter_add("elastic.rounds_aged_out")
                # ops-plane gauges: the coordinator's own state, every
                # tick (world size, generation, open rounds, per-rank
                # heartbeat age; ranks beyond the current world read -1
                # so a shrink is visible, not a stale flatline)
                ranks = self._ranks()
                gauge_set("elastic.world_size", len(ranks))
                gauge_set("elastic.generation", self._generation)
                gauge_set("elastic.open_rounds", len(self._rounds))
                for m in self._members.values():
                    gauge_set(f"elastic.heartbeat_age_s.rank{ranks[m.member]}",
                              round(now - m.last, 3))
                for r in range(len(ranks), self._gauge_ranks):
                    gauge_set(f"elastic.heartbeat_age_s.rank{r}", -1)
                self._gauge_ranks = max(self._gauge_ranks, len(ranks))
                for m in dead:
                    ranks = self._ranks()
                    lost_rank = ranks.get(m.member, -1)
                    del self._members[m.member]
                    counter_add("elastic.evictions")
                    log_warning(
                        f"elastic: rank {lost_rank} ({m.member}) lost "
                        f"(no heartbeat for {now - m.last:.2f}s); "
                        f"world {len(self._members) + 1} -> "
                        f"{len(self._members)}")
                    self._bump("rank_lost", rank=lost_rank,
                               member=m.member,
                               last_state=m.state or "unknown",
                               age_s=round(now - m.last, 3))
                self._cv.wait(tick)

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "join":
            return self._op_join(req)
        if op == "sync":
            return self._op_sync(req)
        if op == "allgather":
            return self._op_allgather(req)
        if op == "heartbeat":
            return self._op_heartbeat(req)
        if op == "leave":
            return self._op_leave(req)
        if op == "info":
            return {"ok": True, **self.membership()}
        if op == "clock":
            # the clock-alignment probe: no membership check (a joiner
            # syncs before it has a rank), no state touched — just the
            # coordinator's wall clock for midpoint-of-RTT estimation
            return {"ok": True, "server_ts": time.time()}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _view(self, member: str) -> Dict[str, Any]:
        ranks = self._ranks()
        return {"ok": True, "world": len(ranks),
                "rank": ranks.get(member, -1),
                "generation": self._generation}

    def _op_join(self, req) -> Dict[str, Any]:
        member = req["member"]
        min_world = int(req.get("min_world", 1))
        with self._cv:
            if member not in self._members:
                self._join_seq += 1
                self._members[member] = _Member(member, self._join_seq)
                counter_add("elastic.joins")
                self._bump("join", member=member)
                rank = self._ranks()[member]
                log_info(f"elastic: member {member} joined as rank "
                         f"{rank} (world {len(self._members)}, "
                         f"generation {self._generation})")
            # hold until the world is big enough (initial formation)
            while len(self._members) < min_world \
                    and member in self._members and not self._stop:
                self._cv.wait(0.2)
            if member not in self._members:
                return {"ok": False, "error": "evicted"}
            return self._view(member)

    def _op_sync(self, req) -> Dict[str, Any]:
        with self._cv:
            if req["member"] not in self._members:
                return {"ok": False, "error": "evicted"}
            return self._view(req["member"])

    def _op_heartbeat(self, req) -> Dict[str, Any]:
        with self._cv:
            m = self._members.get(req["member"])
            if m is None:
                return {"ok": False, "error": "evicted"}
            m.last = time.monotonic()
            m.state = str(req.get("state", ""))
            m.detail = dict(req.get("detail") or {})
            return self._view(req["member"])

    def _op_leave(self, req) -> Dict[str, Any]:
        with self._cv:
            m = self._members.pop(req["member"], None)
            if m is not None:
                counter_add("elastic.leaves")
                self._bump("member_left", member=req["member"])
                log_info(f"elastic: member {req['member']} left "
                         f"(world {len(self._members)}, generation "
                         f"{self._generation})")
            return {"ok": True, "generation": self._generation}

    def _op_allgather(self, req) -> Dict[str, Any]:
        member = req["member"]
        gen = int(req["generation"])
        seq = int(req["seq"])
        key = (gen, seq)
        with self._cv:
            if member not in self._members:
                return {"ok": False, "error": "evicted"}
            if gen != self._generation:
                return {"ok": False, "error": "generation_changed",
                        "generation": self._generation}
            ranks = self._ranks()
            world = len(ranks)
            try:
                self._deadline_hint = max(self._deadline_hint,
                                          float(req.get("deadline_s") or 0))
            except (TypeError, ValueError):
                pass
            rank = ranks[member]
            parts = self._rounds.setdefault(key, {})
            arr = self._arrivals.setdefault(key, {})
            if rank not in parts:
                # coordinator-clock arrival stamp: one clock for every
                # rank, so the returned list is directly comparable
                arr[rank] = time.time()
            parts[rank] = req.get("payload")
            if req.get("site"):
                self._round_sites[key] = str(req["site"])
            self._touch[key] = time.monotonic()
            if len(parts) >= world:
                # this contribution completed the round: one ledger
                # line with the arrival spread (emitted once — by the
                # last arriver, i.e. the straggler itself)
                vals = sorted(arr.values())
                self._ledger_put(
                    "round", site=self._round_sites.get(key, ""),
                    generation=gen, seq=seq, world=world,
                    skew_s=round(vals[-1] - vals[0], 6) if vals else 0.0,
                    straggler_rank=(max(arr, key=arr.get)
                                    if arr else -1))
                counter_add("elastic.rounds")
            self._cv.notify_all()
            while True:
                if self._stop:
                    return {"ok": False, "error": "coordinator stopped"}
                if gen != self._generation:
                    return {"ok": False, "error": "generation_changed",
                            "generation": self._generation}
                if len(self._rounds.get(key, ())) >= world:
                    break
                self._cv.wait(0.5)
            payloads = [self._rounds[key][r] for r in range(world)]
            arrivals = [self._arrivals.get(key, {}).get(r)
                        for r in range(world)]
            # drop the round once every member has read it
            self._reads[key] = self._reads.get(key, 0) + 1
            if self._reads[key] >= world:
                self._rounds.pop(key, None)
                self._reads.pop(key, None)
                self._touch.pop(key, None)
                self._arrivals.pop(key, None)
                self._round_sites.pop(key, None)
            return {"ok": True, "payloads": payloads,
                    "arrivals": arrivals}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class ElasticClient:
    """One training process's handle on the elastic world.

    ``join`` -> ``(world, rank, generation)``; ``allgather``/``barrier``
    are the generation-scoped collectives; a daemon heartbeat thread
    keeps membership alive and carries the live health state (the
    wedged-vs-dead signal).  All blocking calls are bounded by
    ``deadline_s`` and raise :class:`RankLostError` on expiry."""

    def __init__(self, address: Optional[str] = None,
                 member: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 heartbeat_interval_s: Optional[float] = None):
        addr = address or elastic_address()
        if not addr:
            raise ValueError("no elastic coordinator address (pass one "
                             "or set LGBM_TPU_ELASTIC=host:port)")
        host, _, port = addr.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.member = member or (os.environ.get("LGBM_TPU_ELASTIC_MEMBER")
                                 or f"m-{uuid.uuid4().hex[:12]}")
        self.deadline_s = (deadline_s if deadline_s is not None
                           else (collective_deadline_s() or 300.0))
        self.heartbeat_interval_s = (heartbeat_interval_s
                                     if heartbeat_interval_s is not None
                                     else heartbeat_s())
        self.world = 0
        self.rank = -1
        self.generation = -1
        # churn the heartbeat thread has SEEN but this client has not
        # yet adopted; only _adopt mutates (generation, seq) — the pair
        # keys collective rounds and must move together on every member.
        # _seen_generation is written by BOTH the heartbeat thread and
        # the main thread, so it gets its own leaf lock
        from ..obs.lock_contract import named_lock
        self._state_lock = named_lock("elastic_client")
        self._seen_generation = -1
        self.seq = 0
        self._status: Dict[str, Any] = {}
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_pause = threading.Event()
        # coordinator-clock alignment (refreshed per generation): the
        # offset every trace record is stamped with (clk_off_s) and its
        # rtt/2 error bound
        self.clock_offset_s: Optional[float] = None
        self.clock_err_s: Optional[float] = None
        self._clock_synced_gen = -2
        # monotonic start of the in-flight collective, if any: when a
        # deadline fires, the recovery loop reads this to charge the
        # whole stall to the `detect` phase of the MTTR breakdown
        self.op_started: Optional[float] = None

    # -- transport -----------------------------------------------------
    def _rpc(self, msg: Dict[str, Any],
             timeout: Optional[float] = None) -> Dict[str, Any]:
        timeout = self.deadline_s if timeout is None else timeout
        site = f"elastic.{msg.get('op')}"
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=timeout) as sock:
                sock.settimeout(timeout)
                f = sock.makefile("rwb")
                f.write(json.dumps(msg).encode() + b"\n")
                f.flush()
                line = f.readline()
            if not line:
                raise RankLostError(site, timeout,
                                    "coordinator closed the connection")
            return json.loads(line.decode())
        except socket.timeout:
            counter_add("collective.deadline_exceeded")
            event("elastic", "rank_lost", site=site, deadline_s=timeout)
            raise RankLostError(site, timeout) from None
        except (OSError, ValueError) as exc:
            # reset/refused/broken-pipe from a coordinator hiccup, or a
            # truncated JSON line: every transport failure funnels into
            # the typed recovery path (train_elastic catches
            # ELASTIC_INTERRUPTS, not raw socket errors)
            counter_add("elastic.transport_errors")
            event("elastic", "rank_lost", site=site, deadline_s=timeout,
                  error=type(exc).__name__)
            raise RankLostError(
                site, timeout,
                f"transport failure {type(exc).__name__}: {exc}") from None

    def _check(self, resp: Dict[str, Any]) -> Dict[str, Any]:
        if resp.get("ok"):
            return resp
        err = resp.get("error", "")
        if err == "generation_changed":
            counter_add("elastic.generation_changed")
            raise GenerationChanged(resp.get("generation", -1))
        if err == "evicted":
            raise EvictedError(f"member {self.member} was evicted "
                               "(missed heartbeats); re-join required")
        raise RuntimeError(f"elastic coordinator error: {err}")

    # -- membership ----------------------------------------------------
    def join_world(self, min_world: int = 1) -> Tuple[int, int, int]:
        """(Re)join the world; blocks until ``min_world`` members are
        present.  Returns ``(world, rank, generation)`` and starts the
        heartbeat.  Retried through the shared policy with the
        ``rendezvous.connect`` fault point in front (the same seam
        ``mesh.init_distributed`` exposes)."""
        from ..utils.faults import fault_point
        from ..utils.retry import retry_call

        def _join():
            fault_point("rendezvous.connect")
            return self._check(self._rpc(
                {"op": "join", "member": self.member,
                 "min_world": int(min_world)}))

        with span("elastic.rendezvous", member=self.member,
                  min_world=int(min_world)):
            resp = retry_call(_join, what="elastic.join")
        self._adopt(resp)
        self._maybe_sync_clock()
        event("elastic", "joined", rank=self.rank, world=self.world,
              generation=self.generation)
        self._start_heartbeat()
        return self.world, self.rank, self.generation

    def resync(self) -> Tuple[int, int, int]:
        """Adopt the current membership view (after a
        :class:`GenerationChanged`); in-flight sequence numbers reset —
        collectives are scoped per generation."""
        with span("elastic.rendezvous", member=self.member, resync=1):
            resp = self._check(self._rpc({"op": "sync",
                                          "member": self.member}))
        self._adopt(resp)
        self._maybe_sync_clock()
        return self.world, self.rank, self.generation

    def _adopt(self, resp: Dict[str, Any]) -> None:
        self.world = int(resp["world"])
        self.rank = int(resp["rank"])
        self.generation = int(resp["generation"])
        with self._state_lock:
            self._seen_generation = self.generation
        # unconditional: every member re-adopts after an interrupt, so
        # resetting only on a generation change would leave a member
        # whose view was already current (e.g. the heartbeat saw the
        # bump first) keyed off its peers' (generation, seq) forever
        self.seq = 0

    def _maybe_sync_clock(self) -> None:
        """Refresh the coordinator-clock offset once per adopted
        generation (``LGBM_TPU_CLOCK_SYNC=0`` disables): midpoint-of-RTT
        against the ``clock`` op, minimum-RTT sample, error bound
        ``rtt/2``.  Best-effort — a sync failure leaves the previous
        offset in place rather than interrupting training."""
        if not obs_fleet.clock_sync_enabled():
            return
        if self._clock_synced_gen == self.generation:
            return

        def _fetch() -> float:
            resp = self._rpc({"op": "clock", "member": self.member},
                             timeout=max(self.heartbeat_interval_s * 4,
                                         2.0))
            if not resp.get("ok"):
                raise RankLostError("elastic.clock", 0.0,
                                    "clock probe refused")
            return float(resp["server_ts"])

        try:
            off, err = obs_fleet.estimate_clock_offset(_fetch)
        except (RankLostError, OSError, ValueError):
            return
        self.clock_offset_s, self.clock_err_s = off, err
        self._clock_synced_gen = self.generation
        obs_fleet.set_clock(off, err)
        event("fleet", "clock_sync", offset_s=round(off, 6),
              err_s=round(err, 6), generation=self.generation)

    @property
    def observed_generation(self) -> int:
        """The newest generation this process has any evidence of —
        adopted (collectives run under it) or merely seen by the
        heartbeat thread (collectives of the adopted generation are
        doomed; :class:`ElasticRun` fails them eagerly)."""
        with self._state_lock:
            return max(self.generation, self._seen_generation)

    def leave(self) -> None:
        self._hb_stop.set()
        try:
            self._rpc({"op": "leave", "member": self.member}, timeout=5.0)
        except (RankLostError, OSError):
            pass

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)

    # -- collectives ---------------------------------------------------
    def allgather(self, obj: Any,
                  site: str = "elastic.allgather") -> List[Any]:
        """Rank-ordered allgather of a JSON-serializable object within
        the current generation.  Raises :class:`GenerationChanged` when
        the membership moved, :class:`RankLostError` past the deadline
        (the ``collective.hang`` fault stalls this call to prove the
        deadline detects it; ``collective.slow`` delays it
        SUB-deadline — the injected straggler for skew attribution).

        ``site`` names the call point; together with
        ``(generation, seq)`` it joins per-rank trace records of the
        same collective.  The span splits wall time into ``wait_s``
        (blocked on later-arriving peers, from the coordinator's
        single-clock arrival stamps) vs ``xfer_s`` (everything else:
        transport + coordinator turnaround)."""
        from ..obs import enabled as obs_enabled
        from ..utils.faults import fault_flag
        if fault_flag("collective.slow"):
            time.sleep(obs_fleet.collective_slow_s(self.deadline_s))
        self.seq += 1
        if fault_flag("collective.hang"):
            time.sleep(self.deadline_s * 1.5 + 0.05)
        nbytes = -1
        if obs_enabled():
            try:
                nbytes = len(json.dumps(obj).encode())
            except (TypeError, ValueError):
                nbytes = -1
        # cleared on SUCCESS only: after a failure the recovery loop
        # reads (and consumes) it as the stall start of the `detect`
        # phase — the deadline wait is part of the MTTR, not overhead
        # that vanishes with the exception
        self.op_started = time.monotonic()
        with span("collective.elastic", site=site,
                  generation=self.generation, seq=self.seq) as sp:
            t0 = time.perf_counter()
            resp = self._check(self._rpc(
                {"op": "allgather", "member": self.member,
                 "generation": self.generation, "seq": self.seq,
                 "deadline_s": self.deadline_s, "site": site,
                 "payload": obj}))
            dur = time.perf_counter() - t0
            arrivals = resp.get("arrivals")
            if arrivals and 0 <= self.rank < len(arrivals) \
                    and all(a is not None for a in arrivals):
                last = max(arrivals)
                wait = max(last - arrivals[self.rank], 0.0)
                straggler = arrivals.index(last)
                sp["wait_s"] = round(wait, 6)
                sp["xfer_s"] = round(max(dur - wait, 0.0), 6)
                sp["arrive_ts"] = arrivals[self.rank]
                sp["straggler_rank"] = straggler
                if nbytes >= 0:
                    sp["bytes"] = nbytes
                if obs_enabled():
                    obs_fleet.note_collective(
                        site, self.generation, self.seq, wait,
                        max(dur - wait, 0.0), nbytes,
                        straggler == self.rank)
        self.op_started = None
        return resp["payloads"]

    def barrier(self, tag: str, site: str = "elastic.barrier") -> None:
        """All current members reach ``tag`` (an allgather of the tag;
        mismatched tags are a protocol desync and raise loudly)."""
        tags = self.allgather({"barrier": tag}, site=site)
        if any(t != {"barrier": tag} for t in tags):
            raise RuntimeError(f"elastic barrier desync at {tag!r}: "
                               f"{tags}")

    # -- heartbeats ----------------------------------------------------
    def set_status(self, **detail: Any) -> None:
        """Attach status to this member's heartbeats (the chaos
        launcher schedules kills off it; operators see it in
        ``info()``)."""
        self._status.update(detail)

    def pause_heartbeats(self, pause: bool = True) -> None:
        """Test hook: a paused heartbeat thread is a dead rank as far
        as the coordinator can tell."""
        if pause:
            self._hb_pause.set()
        else:
            self._hb_pause.clear()

    def _start_heartbeat(self) -> None:
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._hb_stop.clear()
        self._hb_thread = threading.Thread(
            target=self._hb_run, name=f"lgbm-tpu-heartbeat-{self.member}",
            daemon=True)
        self._hb_thread.start()

    def _hb_run(self) -> None:
        from ..obs import health
        from ..utils.faults import fault_flag
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            if self._hb_pause.is_set():
                continue
            if fault_flag("heartbeat.miss"):
                continue            # the injected dropped beat
            try:
                resp = self._rpc(
                    {"op": "heartbeat", "member": self.member,
                     "state": health.state()["state"],
                     "detail": dict(self._status)},
                    timeout=max(self.heartbeat_interval_s * 2, 1.0))
            except (RankLostError, OSError, ValueError):
                continue            # next beat retries; eviction is the
                #                     coordinator's judgement, not ours
            if resp.get("ok"):
                # observe membership churn between collectives; the
                # client ADOPTS it only via resync/_adopt (which also
                # resets seq — the two must never move separately)
                with self._state_lock:
                    self._seen_generation = max(
                        self._seen_generation,
                        int(resp.get("generation", -1)))


class ElasticRun:
    """One generation's frozen view, handed to the streamed trainer:
    the client plus the (world, rank, generation) it will train under
    and the run-lifetime protocol shard count ``num_shards`` — FIXED
    across membership changes, so per-shard partials combine in shard
    order and any world size reproduces the same bytes."""

    def __init__(self, client: ElasticClient, num_shards: int):
        self.client = client
        self.world = client.world
        self.rank = client.rank
        self.generation = client.generation
        self.num_shards = int(num_shards)
        if self.num_shards < 1:
            raise ValueError("num_shards must be >= 1")

    def owned_shards(self) -> Tuple[int, ...]:
        """The mod-world shard slice (the out-of-core store's
        ``sources[r::S]`` rule, applied to protocol shards)."""
        return tuple(s for s in range(self.num_shards)
                     if s % self.world == self.rank)

    def allgather(self, obj: Any,
                  site: str = "elastic.allgather") -> List[Any]:
        g = self.client.observed_generation
        if g != self.generation:
            raise GenerationChanged(g, "membership moved under this run")
        return self.client.allgather(obj, site=site)

    def barrier(self, tag: str, site: str = "elastic.barrier") -> None:
        g = self.client.observed_generation
        if g != self.generation:
            raise GenerationChanged(g, "membership moved under this run")
        self.client.barrier(tag, site=site)
