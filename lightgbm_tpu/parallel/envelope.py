"""Multi-chip divergence envelope — gating the near-tie flip budget.

The reference's distributed contract is bit-identical trees on every
machine (`application.cpp:249-254`; the split sequence of
`data_parallel_tree_learner.cpp:147-162` is identical by construction).
The JAX port's data-parallel psum reassociates f32 adds per shard
layout, so gain ties can flip split winners — MULTICHIP_r05 measured a
1.63% row-leaf mismatch vs serial at bench shape with mse equal to 5
decimals.  Documenting that envelope is not the same as GATING it
(VERDICT r5 Weak #4): nothing previously asserted that mismatched rows
diverge only at NEAR-TIES, so a real histogram-merge corruption could
hide inside the 1.63%.

This module is that gate.  For every row whose serial and distributed
leaf differ, it walks both trees down the row's bin vector to the
first node where the two trees' split content diverges.  Up to that
node the two paths applied identical predicates, so both nodes cover
the SAME row region — their recorded split gains are the winning gains
of two candidate splits over (modulo psum rounding) the same
histogram.  A reassociation flip therefore requires the two gains to
be nearly equal; a corrupted merge produces O(gain)-sized gaps.  The
gate asserts:

* the row-leaf mismatch fraction is under a hard ceiling
  (``mismatch_ceiling``; r05 measured 0.0163 at bench shape), and
* every divergence point's winning-vs-losing gain gap is inside the
  near-tie margin (``rel_margin`` relative to the larger gain, plus an
  absolute ``abs_margin`` floor for near-zero gains).

Two divergence kinds carry no comparable gain pair and are classified
separately (both ceiling-bounded with the rest):

* **budget flips** — one tree split a region the other left as a leaf
  (the leaf budget was spent elsewhere; a frontier-ordering tie), and
* **renumberings** — both paths applied IDENTICAL predicates end to
  end, so the regions are the same and only the leaf *ids* differ
  (leaf numbering follows split order, which ties reorder); the gate
  instead asserts the two leaf VALUES agree within the measured f32
  envelope.

Margin calibration (measured on the 8-way CPU mesh at bench shape,
131072 x 28 x 255 leaves, where the row-leaf mismatch reproduces r05's
0.0163 exactly):

* leaf values of verified-identical row sets differ from the exact f64
  value by up to **0.0104** on the SERIAL path (the histogram
  parent-sibling subtraction chain's f32 noise; the distributed psum
  path measured 1.4e-4) -> ``value_margin`` default 0.05;
* recorded gains of the SAME split differ serial-vs-distributed by up
  to rel ~1.1e-2 at deep nodes -> a flipped pair's gain gap must clear
  ``rel_margin`` 0.05 AND ``abs_margin`` 0.5 before it counts as
  corruption rather than reassociation noise.

On violation, :func:`assert_envelope` raises with the report AND the
collective flight recorder's last-K schedule
(``lightgbm_tpu/obs/flight_recorder.py``) so the failure attributes to
a recorded collective site instead of a bare number.

Scope: numerical (non-categorical), fully-observed features — the
shapes the multi-chip dry run and the CPU-mesh tier-1 test train.  The
walker self-validates against ``row_leaf`` before trusting its own
routing, so a semantics drift fails loudly rather than silently
passing the gate.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def _tree_arrays(tree) -> Dict[str, np.ndarray]:
    return {
        "feature": np.asarray(tree.feature),
        "threshold": np.asarray(tree.threshold_bin),
        "left": np.asarray(tree.left_child),
        "right": np.asarray(tree.right_child),
        "gain": np.asarray(tree.gain, dtype=np.float64),
        "num_leaves": int(tree.num_leaves),
    }


def _walk(t: Dict[str, np.ndarray], bins_row: np.ndarray):
    """Yield the (node, feature, threshold, gain) path of one row; the
    walk ends when a child is a leaf (``~leaf`` encoding)."""
    node = 0
    if t["num_leaves"] <= 1:
        return
    while True:
        f = int(t["feature"][node])
        thr = int(t["threshold"][node])
        yield node, f, thr, float(t["gain"][node])
        child = (t["left"][node] if int(bins_row[f]) <= thr
                 else t["right"][node])
        if child < 0:
            return
        node = int(child)


def _walk_leaf(t: Dict[str, np.ndarray], bins_row: np.ndarray) -> int:
    node = 0
    if t["num_leaves"] <= 1:
        return 0
    while True:
        f = int(t["feature"][node])
        child = (t["left"][node]
                 if int(bins_row[f]) <= int(t["threshold"][node])
                 else t["right"][node])
        if child < 0:
            return ~int(child)
        node = int(child)


def near_tie_report(serial, dist, bins: np.ndarray,
                    max_rows: int = 20_000) -> Dict[str, Any]:
    """Measure the divergence envelope between a serial and a
    distributed :class:`BuiltTree` over the binned matrix ``bins``.

    Returns a report dict: mismatch fraction, the measured near-tie
    gain gaps at every divergence point (max/mean, relative), budget
    flips, and the first divergence example for debugging."""
    ts, td = _tree_arrays(serial), _tree_arrays(dist)
    lv_s = np.asarray(serial.leaf_value, dtype=np.float64)
    lv_d = np.asarray(dist.leaf_value, dtype=np.float64)
    rl_s = np.asarray(serial.row_leaf)
    rl_d = np.asarray(dist.row_leaf)
    n = min(len(rl_s), len(rl_d), len(bins))
    mism = np.nonzero(rl_s[:n] != rl_d[:n])[0]
    report: Dict[str, Any] = {
        "rows": int(n),
        "mismatched_rows": int(len(mism)),
        "mismatch_fraction": float(len(mism) / max(n, 1)),
        "divergence_points": 0,
        "budget_flips": 0,
        "renumbered_rows": 0,
        "max_rel_gain_gap": 0.0,
        "mean_rel_gain_gap": 0.0,
        "max_renumbered_value_gap": 0.0,
        "walker_validated_rows": 0,
        "first_divergence": None,
        "gaps": [],
    }
    if not len(mism):
        return report
    rows = mism[:max_rows]
    # self-validate routing semantics on the rows we are about to judge
    # (plus they ARE the interesting rows): the numpy walker must agree
    # with the device row_leaf of BOTH trees, or the gate's geometry is
    # wrong and its verdict meaningless
    bad = 0
    for r in rows[:256]:
        if (_walk_leaf(ts, bins[r]) != int(rl_s[r])
                or _walk_leaf(td, bins[r]) != int(rl_d[r])):
            bad += 1
    if bad:
        raise AssertionError(
            f"envelope walker disagrees with device routing on "
            f"{bad}/256 sampled rows — missing/categorical semantics "
            f"in play; the near-tie gate only covers numerical "
            f"fully-observed features")
    report["walker_validated_rows"] = int(min(len(rows), 256))

    gaps = []
    seen_points = set()
    for r in rows:
        it_s = _walk(ts, bins[r])
        it_d = _walk(td, bins[r])
        while True:
            s = next(it_s, None)
            d = next(it_d, None)
            if s is None and d is None:
                # identical predicates end to end: the leaf ID differs
                # only because split ORDER numbered it differently —
                # the regions match, so the VALUES must too
                report["renumbered_rows"] += 1
                vgap = abs(lv_s[int(rl_s[r])] - lv_d[int(rl_d[r])])
                if vgap > report["max_renumbered_value_gap"]:
                    report["max_renumbered_value_gap"] = float(vgap)
                break
            if s is None or d is None:
                # one tree split this region further: the leaf budget
                # went elsewhere (frontier-ordering tie) — no gain pair
                report["budget_flips"] += 1
                break
            (ns, fs, th_s, g_s) = s
            (nd, fd, th_d, g_d) = d
            if fs == fd and th_s == th_d:
                continue
            key = (ns, nd)
            if key not in seen_points:
                seen_points.add(key)
                denom = max(abs(g_s), abs(g_d), 1e-12)
                gap = abs(g_s - g_d)
                gaps.append([gap / denom, gap, g_s, g_d, int(ns),
                             int(nd)])
                if report["first_divergence"] is None:
                    report["first_divergence"] = {
                        "row": int(r), "serial_node": int(ns),
                        "dist_node": int(nd),
                        "serial_split": (int(fs), int(th_s)),
                        "dist_split": (int(fd), int(th_d)),
                        "serial_gain": g_s, "dist_gain": g_d,
                    }
            break
    report["divergence_points"] = len(gaps)
    report["gaps"] = gaps
    if gaps:
        rels = [g[0] for g in gaps]
        report["max_rel_gain_gap"] = float(max(rels))
        report["mean_rel_gain_gap"] = float(np.mean(rels))
    return report


def assert_envelope(serial, dist, bins: np.ndarray,
                    mismatch_ceiling: float = 0.03,
                    rel_margin: float = 0.05,
                    abs_margin: float = 0.5,
                    value_margin: float = 0.05,
                    label: str = "data-parallel",
                    report: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Gate the divergence envelope; raises AssertionError (with the
    report and the flight recorder's last-K collective schedule) on a
    ceiling or near-tie violation.  Returns the report when clean."""
    rep = report if report is not None else near_tie_report(
        serial, dist, bins)
    problems = []
    if rep["mismatch_fraction"] > mismatch_ceiling:
        problems.append(
            f"row-leaf mismatch {rep['mismatch_fraction']:.4f} exceeds "
            f"the hard ceiling {mismatch_ceiling} (r05 measured 0.0163)")
    # a gain gap is a violation only if it clears BOTH margins:
    # relative for real gains, absolute for the ~zero-gain noise floor
    bad_gaps = [g for g in rep["gaps"]
                if g[0] > rel_margin and g[1] > abs_margin]
    if bad_gaps:
        worst = max(bad_gaps)
        problems.append(
            f"{len(bad_gaps)} divergence point(s) outside the "
            f"near-tie margin (rel {rel_margin}, abs {abs_margin}); "
            f"worst: rel_gap={worst[0]:.3e} abs_gap={worst[1]:.3e} "
            f"gains=({worst[2]:.6f}, {worst[3]:.6f}) at serial node "
            f"{worst[4]} / dist node {worst[5]} — this is NOT f32 "
            f"reassociation noise; suspect a histogram-merge or "
            f"collective-layout bug")
    if rep["max_renumbered_value_gap"] > value_margin:
        problems.append(
            f"a 'renumbered' leaf pair (identical split path) has "
            f"leaf-value gap {rep['max_renumbered_value_gap']:.3e} > "
            f"{value_margin}: same region, different value — the "
            f"histogram sums themselves diverged")
    if problems:
        from ..obs.flight_recorder import dump_to_summary, snapshot
        dump_to_summary(f"envelope.{label}")
        sched = snapshot()["last"][-12:]
        lines = [f"  {e['seq']}: {e['site']} {e['op']} axis={e['axis']} "
                 f"shape={e['shape']}" for e in sched]
        brief = {k: v for k, v in rep.items() if k != "gaps"}
        raise AssertionError(
            f"multi-chip divergence envelope violated ({label}):\n- "
            + "\n- ".join(problems)
            + f"\nreport: {brief}"
            + "\nlast recorded collective schedule (flight recorder):\n"
            + ("\n".join(lines) if lines else "  <empty>"))
    return rep


# ----------------------------------------------------------------------
# Model-level flip envelope: block-vs-eager training paths.
#
# The fused lax.scan block and the eager per-iteration path run the same
# math through DIFFERENT XLA programs, so f32 scatter-add reassociation
# makes histogram sums (and therefore recorded gains and leaf values)
# drift in the last ulp from the very first tree.  Most of the time that
# drift is invisible; occasionally it flips a near-tie split winner or a
# missing-direction choice, after which the two models fit different
# residuals and every later tree legitimately diverges.  The tree-level
# near_tie_report above can't gate this axis (it needs row_leaf vectors
# of a single tree pair); this section classifies the divergence at the
# MODEL-TEXT level instead: the structural prefix must match exactly,
# the first flip must be a genuine near-tie, and nothing past the flip
# is compared (incomparable by construction).

def _parse_model_trees(model_str: str):
    """Parse the reference text format into per-tree numpy arrays."""
    trees, cur = [], None
    for line in model_str.splitlines():
        if line.startswith("Tree="):
            cur = {}
            trees.append(cur)
        elif line.startswith("end of trees"):
            cur = None
        elif cur is not None and "=" in line:
            k, v = line.split("=", 1)
            cur[k] = v
    out = []
    for t in trees:
        d: Dict[str, Any] = {"num_leaves": int(t.get("num_leaves", "1"))}
        for k, dt in (("split_feature", np.int64),
                      ("decision_type", np.int64),
                      ("left_child", np.int64), ("right_child", np.int64),
                      ("split_gain", np.float64), ("threshold", np.float64),
                      ("leaf_value", np.float64)):
            v = t.get(k, "").split()
            d[k] = (np.asarray(v, dtype=dt) if v
                    else np.zeros(0, dtype=dt))
        out.append(d)
    return out


def model_flip_report(model_a: str, model_b: str,
                      rel_margin: float = 0.05,
                      abs_margin: float = 0.5) -> Dict[str, Any]:
    """Compare two trained models (text format) tree by tree in boosting
    order and classify the FIRST structural divergence.

    Node numbering follows split order, so two trees that made the same
    choices have identical (feature, threshold, decision_type, children)
    arrays; thresholds come from the shared f64 bin uppers and compare
    exactly.  The first differing node is the flip point — its two
    recorded gains are the winning gains of two candidates over (modulo
    f32 reassociation) the same histogram, so a legitimate flip requires
    them to be nearly equal, exactly the near-tie argument
    :func:`near_tie_report` makes per row.  Kinds:

    * ``near_tie_flip`` — different split content at the flip node;
      near-tie iff the gain gap is inside ``rel_margin`` OR
      ``abs_margin`` (violating BOTH = corruption, same calibration as
      :func:`assert_envelope`);
    * ``missing_direction`` — same feature+threshold, only the
      default-direction bit differs (the missing-side allocation was the
      tie); gains are the same split's and must agree within margins;
    * ``budget_flip`` — equal common prefix but one tree recorded more
      splits (min_data/min_gain boundary); near-tie iff the extra gain
      is small vs the tree's max gain or under ``abs_margin``.

    Identical-prefix trees also contribute ``max_leaf_value_gap`` (the
    f32 value envelope; the tree-level gate measured 0.0104 serial-side).
    """
    ta, tb = _parse_model_trees(model_a), _parse_model_trees(model_b)
    report: Dict[str, Any] = {
        "trees": int(min(len(ta), len(tb))),
        "prefix_trees": 0, "flip_tree": None, "flip_node": None,
        "flip_kind": None, "gain_a": None, "gain_b": None,
        "rel_gain_gap": None, "abs_gain_gap": None, "near_tie": True,
        "max_leaf_value_gap": 0.0,
    }

    def _near(ga: float, gb: float) -> bool:
        gap = abs(ga - gb)
        return (gap / max(abs(ga), abs(gb), 1e-12) <= rel_margin
                or gap <= abs_margin)

    for i, (x, y) in enumerate(zip(ta, tb)):
        m = min(len(x["split_feature"]), len(y["split_feature"]))
        neq = np.zeros(m, dtype=bool)
        for k in ("split_feature", "threshold", "decision_type",
                  "left_child", "right_child"):
            neq |= x[k][:m] != y[k][:m]
        diff = np.nonzero(neq)[0]
        if not len(diff) and (len(x["split_feature"])
                              == len(y["split_feature"])):
            if len(x["leaf_value"]) == len(y["leaf_value"]) and m >= 0:
                gap = (float(np.max(np.abs(x["leaf_value"]
                                           - y["leaf_value"])))
                       if len(x["leaf_value"]) else 0.0)
                report["max_leaf_value_gap"] = max(
                    report["max_leaf_value_gap"], gap)
            report["prefix_trees"] = i + 1
            continue
        report["flip_tree"] = i
        if len(diff):
            j = int(diff[0])
            ga = float(x["split_gain"][j])
            gb = float(y["split_gain"][j])
            same_split = (x["split_feature"][j] == y["split_feature"][j]
                          and x["threshold"][j] == y["threshold"][j])
            report["flip_kind"] = ("missing_direction" if same_split
                                   else "near_tie_flip")
        else:
            # equal prefix, one tree kept splitting: judge the first
            # extra split's gain against the tree's own scale
            j = m
            longer = x if len(x["split_feature"]) > m else y
            ga = float(longer["split_gain"][m])
            gb = 0.0
            scale = float(np.max(longer["split_gain"])) if m else ga
            report["flip_kind"] = "budget_flip"
            report.update(flip_node=j, gain_a=ga, gain_b=gb,
                          abs_gain_gap=ga,
                          rel_gain_gap=ga / max(scale, 1e-12),
                          near_tie=(ga <= abs_margin
                                    or ga / max(scale, 1e-12)
                                    <= rel_margin))
            break
        gap = abs(ga - gb)
        report.update(flip_node=j, gain_a=ga, gain_b=gb,
                      abs_gain_gap=gap,
                      rel_gain_gap=gap / max(abs(ga), abs(gb), 1e-12),
                      near_tie=_near(ga, gb))
        break
    return report


def assert_model_flip_envelope(model_a: str, model_b: str,
                               rel_margin: float = 0.05,
                               abs_margin: float = 0.5,
                               value_margin: float = 0.05,
                               label: str = "block-vs-eager"
                               ) -> Dict[str, Any]:
    """Gate the model-level flip envelope; raises on a non-near-tie flip
    or a prefix leaf-value gap outside the f32 envelope.  Returns the
    report (``flip_tree`` None when the models match structurally)."""
    rep = model_flip_report(model_a, model_b,
                            rel_margin=rel_margin, abs_margin=abs_margin)
    problems = []
    if rep["flip_tree"] is not None and not rep["near_tie"]:
        problems.append(
            f"first structural divergence (tree {rep['flip_tree']}, node "
            f"{rep['flip_node']}, kind {rep['flip_kind']}) is NOT a "
            f"near-tie: gains=({rep['gain_a']:.6f}, {rep['gain_b']:.6f}) "
            f"rel_gap={rep['rel_gain_gap']:.3e} "
            f"abs_gap={rep['abs_gain_gap']:.3e} — this is not f32 "
            f"reassociation noise; suspect a mask or histogram bug")
    if rep["max_leaf_value_gap"] > value_margin:
        problems.append(
            f"identical-structure trees have leaf-value gap "
            f"{rep['max_leaf_value_gap']:.3e} > {value_margin}: same "
            f"regions, different values — the histogram sums diverged")
    if problems:
        raise AssertionError(
            f"model flip envelope violated ({label}):\n- "
            + "\n- ".join(problems) + f"\nreport: {rep}")
    return rep
