"""Multi-chip divergence envelope — gating the near-tie flip budget.

The reference's distributed contract is bit-identical trees on every
machine (`application.cpp:249-254`; the split sequence of
`data_parallel_tree_learner.cpp:147-162` is identical by construction).
The JAX port's data-parallel psum reassociates f32 adds per shard
layout, so gain ties can flip split winners — MULTICHIP_r05 measured a
1.63% row-leaf mismatch vs serial at bench shape with mse equal to 5
decimals.  Documenting that envelope is not the same as GATING it
(VERDICT r5 Weak #4): nothing previously asserted that mismatched rows
diverge only at NEAR-TIES, so a real histogram-merge corruption could
hide inside the 1.63%.

This module is that gate.  For every row whose serial and distributed
leaf differ, it walks both trees down the row's bin vector to the
first node where the two trees' split content diverges.  Up to that
node the two paths applied identical predicates, so both nodes cover
the SAME row region — their recorded split gains are the winning gains
of two candidate splits over (modulo psum rounding) the same
histogram.  A reassociation flip therefore requires the two gains to
be nearly equal; a corrupted merge produces O(gain)-sized gaps.  The
gate asserts:

* the row-leaf mismatch fraction is under a hard ceiling
  (``mismatch_ceiling``; r05 measured 0.0163 at bench shape), and
* every divergence point's winning-vs-losing gain gap is inside the
  near-tie margin (``rel_margin`` relative to the larger gain, plus an
  absolute ``abs_margin`` floor for near-zero gains).

Two divergence kinds carry no comparable gain pair and are classified
separately (both ceiling-bounded with the rest):

* **budget flips** — one tree split a region the other left as a leaf
  (the leaf budget was spent elsewhere; a frontier-ordering tie), and
* **renumberings** — both paths applied IDENTICAL predicates end to
  end, so the regions are the same and only the leaf *ids* differ
  (leaf numbering follows split order, which ties reorder); the gate
  instead asserts the two leaf VALUES agree within the measured f32
  envelope.

Margin calibration (measured on the 8-way CPU mesh at bench shape,
131072 x 28 x 255 leaves, where the row-leaf mismatch reproduces r05's
0.0163 exactly):

* leaf values of verified-identical row sets differ from the exact f64
  value by up to **0.0104** on the SERIAL path (the histogram
  parent-sibling subtraction chain's f32 noise; the distributed psum
  path measured 1.4e-4) -> ``value_margin`` default 0.05;
* recorded gains of the SAME split differ serial-vs-distributed by up
  to rel ~1.1e-2 at deep nodes -> a flipped pair's gain gap must clear
  ``rel_margin`` 0.05 AND ``abs_margin`` 0.5 before it counts as
  corruption rather than reassociation noise.

On violation, :func:`assert_envelope` raises with the report AND the
collective flight recorder's last-K schedule
(``lightgbm_tpu/obs/flight_recorder.py``) so the failure attributes to
a recorded collective site instead of a bare number.

Scope: numerical (non-categorical), fully-observed features — the
shapes the multi-chip dry run and the CPU-mesh tier-1 test train.  The
walker self-validates against ``row_leaf`` before trusting its own
routing, so a semantics drift fails loudly rather than silently
passing the gate.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def _tree_arrays(tree) -> Dict[str, np.ndarray]:
    return {
        "feature": np.asarray(tree.feature),
        "threshold": np.asarray(tree.threshold_bin),
        "left": np.asarray(tree.left_child),
        "right": np.asarray(tree.right_child),
        "gain": np.asarray(tree.gain, dtype=np.float64),
        "num_leaves": int(tree.num_leaves),
    }


def _walk(t: Dict[str, np.ndarray], bins_row: np.ndarray):
    """Yield the (node, feature, threshold, gain) path of one row; the
    walk ends when a child is a leaf (``~leaf`` encoding)."""
    node = 0
    if t["num_leaves"] <= 1:
        return
    while True:
        f = int(t["feature"][node])
        thr = int(t["threshold"][node])
        yield node, f, thr, float(t["gain"][node])
        child = (t["left"][node] if int(bins_row[f]) <= thr
                 else t["right"][node])
        if child < 0:
            return
        node = int(child)


def _walk_leaf(t: Dict[str, np.ndarray], bins_row: np.ndarray) -> int:
    node = 0
    if t["num_leaves"] <= 1:
        return 0
    while True:
        f = int(t["feature"][node])
        child = (t["left"][node]
                 if int(bins_row[f]) <= int(t["threshold"][node])
                 else t["right"][node])
        if child < 0:
            return ~int(child)
        node = int(child)


def near_tie_report(serial, dist, bins: np.ndarray,
                    max_rows: int = 20_000) -> Dict[str, Any]:
    """Measure the divergence envelope between a serial and a
    distributed :class:`BuiltTree` over the binned matrix ``bins``.

    Returns a report dict: mismatch fraction, the measured near-tie
    gain gaps at every divergence point (max/mean, relative), budget
    flips, and the first divergence example for debugging."""
    ts, td = _tree_arrays(serial), _tree_arrays(dist)
    lv_s = np.asarray(serial.leaf_value, dtype=np.float64)
    lv_d = np.asarray(dist.leaf_value, dtype=np.float64)
    rl_s = np.asarray(serial.row_leaf)
    rl_d = np.asarray(dist.row_leaf)
    n = min(len(rl_s), len(rl_d), len(bins))
    mism = np.nonzero(rl_s[:n] != rl_d[:n])[0]
    report: Dict[str, Any] = {
        "rows": int(n),
        "mismatched_rows": int(len(mism)),
        "mismatch_fraction": float(len(mism) / max(n, 1)),
        "divergence_points": 0,
        "budget_flips": 0,
        "renumbered_rows": 0,
        "max_rel_gain_gap": 0.0,
        "mean_rel_gain_gap": 0.0,
        "max_renumbered_value_gap": 0.0,
        "walker_validated_rows": 0,
        "first_divergence": None,
        "gaps": [],
    }
    if not len(mism):
        return report
    rows = mism[:max_rows]
    # self-validate routing semantics on the rows we are about to judge
    # (plus they ARE the interesting rows): the numpy walker must agree
    # with the device row_leaf of BOTH trees, or the gate's geometry is
    # wrong and its verdict meaningless
    bad = 0
    for r in rows[:256]:
        if (_walk_leaf(ts, bins[r]) != int(rl_s[r])
                or _walk_leaf(td, bins[r]) != int(rl_d[r])):
            bad += 1
    if bad:
        raise AssertionError(
            f"envelope walker disagrees with device routing on "
            f"{bad}/256 sampled rows — missing/categorical semantics "
            f"in play; the near-tie gate only covers numerical "
            f"fully-observed features")
    report["walker_validated_rows"] = int(min(len(rows), 256))

    gaps = []
    seen_points = set()
    for r in rows:
        it_s = _walk(ts, bins[r])
        it_d = _walk(td, bins[r])
        while True:
            s = next(it_s, None)
            d = next(it_d, None)
            if s is None and d is None:
                # identical predicates end to end: the leaf ID differs
                # only because split ORDER numbered it differently —
                # the regions match, so the VALUES must too
                report["renumbered_rows"] += 1
                vgap = abs(lv_s[int(rl_s[r])] - lv_d[int(rl_d[r])])
                if vgap > report["max_renumbered_value_gap"]:
                    report["max_renumbered_value_gap"] = float(vgap)
                break
            if s is None or d is None:
                # one tree split this region further: the leaf budget
                # went elsewhere (frontier-ordering tie) — no gain pair
                report["budget_flips"] += 1
                break
            (ns, fs, th_s, g_s) = s
            (nd, fd, th_d, g_d) = d
            if fs == fd and th_s == th_d:
                continue
            key = (ns, nd)
            if key not in seen_points:
                seen_points.add(key)
                denom = max(abs(g_s), abs(g_d), 1e-12)
                gap = abs(g_s - g_d)
                gaps.append([gap / denom, gap, g_s, g_d, int(ns),
                             int(nd)])
                if report["first_divergence"] is None:
                    report["first_divergence"] = {
                        "row": int(r), "serial_node": int(ns),
                        "dist_node": int(nd),
                        "serial_split": (int(fs), int(th_s)),
                        "dist_split": (int(fd), int(th_d)),
                        "serial_gain": g_s, "dist_gain": g_d,
                    }
            break
    report["divergence_points"] = len(gaps)
    report["gaps"] = gaps
    if gaps:
        rels = [g[0] for g in gaps]
        report["max_rel_gain_gap"] = float(max(rels))
        report["mean_rel_gain_gap"] = float(np.mean(rels))
    return report


def assert_envelope(serial, dist, bins: np.ndarray,
                    mismatch_ceiling: float = 0.03,
                    rel_margin: float = 0.05,
                    abs_margin: float = 0.5,
                    value_margin: float = 0.05,
                    label: str = "data-parallel",
                    report: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Gate the divergence envelope; raises AssertionError (with the
    report and the flight recorder's last-K collective schedule) on a
    ceiling or near-tie violation.  Returns the report when clean."""
    rep = report if report is not None else near_tie_report(
        serial, dist, bins)
    problems = []
    if rep["mismatch_fraction"] > mismatch_ceiling:
        problems.append(
            f"row-leaf mismatch {rep['mismatch_fraction']:.4f} exceeds "
            f"the hard ceiling {mismatch_ceiling} (r05 measured 0.0163)")
    # a gain gap is a violation only if it clears BOTH margins:
    # relative for real gains, absolute for the ~zero-gain noise floor
    bad_gaps = [g for g in rep["gaps"]
                if g[0] > rel_margin and g[1] > abs_margin]
    if bad_gaps:
        worst = max(bad_gaps)
        problems.append(
            f"{len(bad_gaps)} divergence point(s) outside the "
            f"near-tie margin (rel {rel_margin}, abs {abs_margin}); "
            f"worst: rel_gap={worst[0]:.3e} abs_gap={worst[1]:.3e} "
            f"gains=({worst[2]:.6f}, {worst[3]:.6f}) at serial node "
            f"{worst[4]} / dist node {worst[5]} — this is NOT f32 "
            f"reassociation noise; suspect a histogram-merge or "
            f"collective-layout bug")
    if rep["max_renumbered_value_gap"] > value_margin:
        problems.append(
            f"a 'renumbered' leaf pair (identical split path) has "
            f"leaf-value gap {rep['max_renumbered_value_gap']:.3e} > "
            f"{value_margin}: same region, different value — the "
            f"histogram sums themselves diverged")
    if problems:
        from ..obs.flight_recorder import dump_to_summary, snapshot
        dump_to_summary(f"envelope.{label}")
        sched = snapshot()["last"][-12:]
        lines = [f"  {e['seq']}: {e['site']} {e['op']} axis={e['axis']} "
                 f"shape={e['shape']}" for e in sched]
        brief = {k: v for k, v in rep.items() if k != "gaps"}
        raise AssertionError(
            f"multi-chip divergence envelope violated ({label}):\n- "
            + "\n- ".join(problems)
            + f"\nreport: {brief}"
            + "\nlast recorded collective schedule (flight recorder):\n"
            + ("\n".join(lines) if lines else "  <empty>"))
    return rep
