"""Distributed tree learners: data- / feature- / voting-parallel.

TPU-native redesign of the reference parallel learners
(`/root/reference/src/treelearner/feature_parallel_tree_learner.cpp`,
`data_parallel_tree_learner.cpp`, `voting_parallel_tree_learner.cpp`,
shared sync helpers `parallel_tree_learner.h:184-207`).  The reference
couples each strategy to socket/MPI collectives; here each strategy is a
*wave closure* (histogram the active leaves → subtract siblings → rescan)
run inside one ``shard_map`` over a ``jax.sharding.Mesh``, with XLA
collectives on ICI/DCN:

* **data-parallel** — rows sharded; local active-leaf histograms merged
  with ``lax.psum`` (the ReduceScatter+owner-scan of
  `data_parallel_tree_learner.cpp:147-162` collapses to one collective of
  the wave's ``[A, F, B, 3]`` block — the smaller-child scheduling halves
  the reference's wire bytes the same way it halves its FLOPs).
* **feature-parallel** — rows replicated, feature columns statically
  sliced per shard (`feature_parallel_tree_learner.cpp:31-50`'s
  load-balance partition becomes an equal static slice); each shard keeps
  histogram state only for its own columns; local best splits are
  ``all_gather``-ed and the global argmax-by-gain picked everywhere (the
  ``SyncUpGlobalBestSplit`` max-by-gain reducer,
  `parallel_tree_learner.h:184-207`).
* **voting-parallel (PV-Tree)** — rows sharded; histogram state stays
  local; each shard votes its top-k features per changed leaf by local
  gain; votes are ``psum``-ed and the 2k global winners selected by
  summed local gains (`voting_parallel_tree_learner.cpp:164-193`
  GlobalVoting); only the winners' histogram columns are ``psum``-ed
  (comm O(2A·2k·B) instead of O(2A·F·B)), then the final scan runs on
  the merged columns.

All three return bit-identical trees on every shard (the reference's
distributed-determinism requirement, `application.cpp:249-254`).

Deep-wave compaction threads through all three learners via the shared
``make_hist_fn`` seam: on the "compact" backend (the TPU default for
deep trees) each shard regroups ITS OWN rows leaf-contiguously and runs
the grouped kernel (`ops/compact.py`) for waves above the slot
threshold.  The collective schedule is untouched — the data-parallel
``psum`` still reduces the same ``[A, F, B, 3]`` active-leaf block (the
compacted kernel has the identical output contract), feature-parallel
shards compact their own column slice, and voting-parallel compacts its
local histograms before the vote — so spmdcheck's static schedule and
the runtime flight-recorder fingerprints are identical to the wide
kernel's (shape/dtype/op/axis all unchanged; `tests/test_compact.py::
test_compact_psum_data_parallel` pins the psum'd parity).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs.flight_recorder import record as _fr_record

# jax >= 0.6 exposes shard_map at top level (replication checking via
# `check_vma`); 0.4.x ships it under experimental with `check_rep`.
# The alias keeps the bare name `shard_map` so the static analyzers'
# name-based root detection (tpulint callgraph, spmdcheck) still sees
# the wrapped function as a traced entry point.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
    _SM_CHECK_KW = "check_vma"
else:                               # jax 0.4.x fallback
    from jax.experimental.shard_map import shard_map
    _SM_CHECK_KW = "check_rep"

from ..io.device import DeviceData
from ..learner.serial import (BuiltTree, GrowthParams, apply_hist_wave,
                              build_tree, make_hist_fn,
                              split_cache_enabled)
from ..ops.pallas_histogram import bin_stride
from ..ops.split import (K_MIN_SCORE, SplitParams, SplitResult,
                         find_best_splits)


def _psum(axis):
    def psum_fn(x):
        # trace-time fingerprint: each process traces its own program,
        # so THIS is where a rank-divergent schedule would be born.
        # The named_scope stamps the flight-recorder site name into the
        # HLO op metadata, so profiler captures and HLO dumps name the
        # collective by the same site the runtime digest uses
        _fr_record("parallel.learners.hist_psum", "psum", axis, x)
        with jax.named_scope("collective.hist_psum"):
            return jax.lax.psum(x, axis)
    return psum_fn


def _sync_global_best(best: SplitResult, axis: str) -> SplitResult:
    """All-gather per-leaf SplitResults and keep the max-gain one — the
    ``SyncUpGlobalBestSplit`` reducer (`parallel_tree_learner.h:184-207`)."""
    _fr_record("parallel.learners.sync_global_best", "all_gather", axis,
               best.gain)
    with jax.named_scope("collective.sync_global_best"):
        gathered = jax.tree.map(
            lambda a: jax.lax.all_gather(a, axis), best)  # [S, 2A, ...]
    win = jnp.argmax(gathered.gain, axis=0)               # [2A]

    def pick(a):
        l = jnp.arange(a.shape[1])
        return a[win, l]

    return jax.tree.map(pick, gathered)


# ---------------------------------------------------------------------------
# feature-parallel
# ---------------------------------------------------------------------------
def make_feature_parallel_strategy(data: DeviceData, grad, hess,
                                   params: GrowthParams, feature_mask,
                                   axis: str, num_shards: int,
                                   hist_backend: str = "auto",
                                   hist_mode=None):
    """Features statically sliced per shard; per-shard histogram state
    covers only the local columns; global best via all_gather + argmax.

    EFB composes (VERDICT r3 #7): features are sliced in LOGICAL order
    and each shard gathers its features' group columns from the bundle
    store — a feature whose group is shared simply histograms its own
    copy of the group column, then unbundles its slice, exactly like the
    serial path (reference bundles identically on every rank for all
    learner types, dataset.cpp:138-210)."""
    F = data.num_features
    f_local = -(-F // num_shards)          # ceil
    L = params.num_leaves

    idx = jax.lax.axis_index(axis)
    start = jnp.minimum(idx * f_local, F - f_local)
    nb_loc = jax.lax.dynamic_slice_in_dim(data.num_bins, start, f_local)
    db_loc = jax.lax.dynamic_slice_in_dim(data.default_bins, start, f_local)
    mt_loc = jax.lax.dynamic_slice_in_dim(data.missing_types, start, f_local)
    ic_loc = jax.lax.dynamic_slice_in_dim(data.is_categorical, start, f_local)
    nanb_loc = jax.lax.dynamic_slice_in_dim(data.nan_bins, start, f_local)
    if data.is_bundled:
        fg_loc = jax.lax.dynamic_slice_in_dim(data.feat_group, start,
                                              f_local)
        off_loc = jax.lax.dynamic_slice_in_dim(data.feat_offset, start,
                                               f_local)
        bins_loc = jnp.take(data.bins, fg_loc, axis=1)   # group copies
    else:
        off_loc = jnp.full(f_local, -1, jnp.int32)
        bins_loc = jax.lax.dynamic_slice_in_dim(data.bins, start,
                                                f_local, 1)
    zero_off = jnp.zeros(f_local, jnp.int32)  # unused by the padded grid
    data_loc = DeviceData(bins_loc, zero_off, nb_loc, db_loc, mt_loc, ic_loc,
                          nanb_loc, jnp.arange(f_local, dtype=jnp.int32),
                          off_loc,
                          data.total_bins, data.max_bins,
                          data.has_categorical,
                          max_group_bins=data.max_group_bins)
    hist_fn = make_hist_fn(data_loc, grad, hess, L, hist_backend,
                           hist_mode)

    # mask features overlapping a previous shard (end-clamp duplicates)
    fid_global = start + jnp.arange(f_local)
    owned = fid_global >= idx * f_local
    fmask = owned
    if feature_mask is not None:
        fmask = fmask & jax.lax.dynamic_slice_in_dim(
            feature_mask, start, f_local)

    def wave(hist_state, hist_leaf, act_small, act_parent, act_sibling,
             lsg, lsh, lc):
        new_h = hist_fn(hist_leaf, act_small)            # [A, f_local, B, 3]
        hist_state, ids, grid = apply_hist_wave(
            hist_state, new_h, act_small, act_parent, act_sibling, L)
        if not split_cache_enabled():
            # split-cache escape hatch (ISSUE 9): full per-wave rescan
            # of the local-column histogram state — the post-allgather
            # global best is cached identically either way
            ids = jnp.arange(L, dtype=jnp.int32)
            grid = hist_state
        safe = jnp.clip(ids, 0, L - 1)
        if data.is_bundled:
            from ..ops.histogram import unbundle_grid
            grid = unbundle_grid(grid, lsg[safe], lsh[safe], lc[safe],
                                 jnp.arange(f_local, dtype=jnp.int32),
                                 off_loc, nb_loc, db_loc,
                                 bin_stride(data.max_bins))
        best = find_best_splits(grid, lsg[safe], lsh[safe], lc[safe],
                                nb_loc, mt_loc, db_loc, ic_loc,
                                params.split, fmask,
                                any_categorical=data.has_categorical,
                                any_missing=data.has_missing)
        best = best._replace(feature=(best.feature + start).astype(jnp.int32))
        return hist_state, ids, _sync_global_best(best, axis)

    return wave, f_local


# ---------------------------------------------------------------------------
# voting-parallel (PV-Tree)
# ---------------------------------------------------------------------------
def make_voting_parallel_strategy(data: DeviceData, grad, hess,
                                  params: GrowthParams, feature_mask,
                                  axis: str, num_shards: int, top_k: int,
                                  hist_backend: str = "auto",
                                  hist_mode=None):
    """PV-Tree: local active-leaf hists -> local vote -> global top-2k
    features -> psum only their histogram columns -> final scan."""
    F = data.num_features
    L = params.num_leaves
    k2 = min(2 * top_k, F)
    hist_fn = make_hist_fn(data, grad, hess, L, hist_backend, hist_mode)
    # local constraints scaled 1/S like the reference
    # (voting_parallel_tree_learner.cpp:55-56)
    local_params = params.split._replace(
        min_data_in_leaf=max(1, params.split.min_data_in_leaf // num_shards),
        min_sum_hessian_in_leaf=params.split.min_sum_hessian_in_leaf
        / num_shards)

    def wave(hist_state, hist_leaf, act_small, act_parent, act_sibling,
             lsg, lsh, lc):
        new_h = hist_fn(hist_leaf, act_small)            # local histograms
        hist_state, ids, grid = apply_hist_wave(
            hist_state, new_h, act_small, act_parent, act_sibling, L)
        if not split_cache_enabled():
            # escape hatch: vote + winner-column psum over every leaf
            # slot (per-slot results are independent, so the selected
            # splits — and the model — are byte-identical)
            ids = jnp.arange(L, dtype=jnp.int32)
            grid = hist_state
        safe = jnp.clip(ids, 0, L - 1)
        # local leaf totals from the local histogram (column 0's bins
        # contain every in-bag local row exactly once)
        loc_sum_g = jnp.sum(grid[:, 0, :, 0], axis=-1)
        loc_sum_h = jnp.sum(grid[:, 0, :, 1], axis=-1)
        loc_cnt = jnp.sum(grid[:, 0, :, 2], axis=-1)
        if data.is_bundled:
            from ..ops.histogram import unbundle_grid
            from ..ops.pallas_histogram import bin_stride
            grid = unbundle_grid(grid, loc_sum_g, loc_sum_h, loc_cnt,
                                 data.feat_group, data.feat_offset,
                                 data.num_bins, data.default_bins,
                                 bin_stride(data.max_bins))
        local_gain = _per_feature_gains(grid, loc_sum_g, loc_sum_h, loc_cnt,
                                        data, local_params, feature_mask)
        # top-k features per changed leaf locally; exchange ONLY the
        # (feature id, gain) pairs — O(k) wire bytes like the
        # reference's 2x k LightSplitInfo allgather
        # (voting_parallel_tree_learner.cpp:164-193), NOT a dense
        # [2A, F] votes psum whose volume rivals the histogram psum it
        # exists to avoid on wide data (VERDICT r3 #6)
        kk = min(top_k, F)
        _, local_top = jax.lax.top_k(local_gain, kk)
        local_vals = jnp.take_along_axis(local_gain, local_top, axis=1)
        local_vals = jnp.where(
            jnp.isfinite(local_vals) & (local_vals > K_MIN_SCORE / 2),
            local_vals, 0.0)
        _fr_record("parallel.learners.voting.vote_gather", "all_gather",
                   axis, local_top)
        with jax.named_scope("collective.vote_gather"):
            g_top = jax.lax.all_gather(local_top, axis)  # [S, 2A, k] i32
        _fr_record("parallel.learners.voting.vote_gather", "all_gather",
                   axis, local_vals)
        with jax.named_scope("collective.vote_gather"):
            g_val = jax.lax.all_gather(local_vals, axis)  # [S, 2A, k] f32
        # GlobalVoting: weighted-gain vote tally, scattered LOCALLY
        rows = jnp.arange(local_gain.shape[0])[None, :, None]
        votes = jnp.zeros(local_gain.shape).at[rows, g_top].add(g_val)
        _, sel_feats = jax.lax.top_k(votes, k2)          # [2A, k2]
        # psum ONLY the selected features' histogram columns
        sel_grid = jnp.take_along_axis(
            grid, sel_feats[:, :, None, None], axis=1)   # [2A, k2, B, 3]
        _fr_record("parallel.learners.voting.sel_psum", "psum", axis,
                   sel_grid)
        with jax.named_scope("collective.sel_psum"):
            sel_grid = jax.lax.psum(sel_grid, axis)
        nb = data.num_bins[sel_feats]
        mt = data.missing_types[sel_feats]
        db = data.default_bins[sel_feats]
        ic = data.is_categorical[sel_feats]
        best = _find_best_per_leaf_features(
            sel_grid, lsg[safe], lsh[safe], lc[safe], nb, mt, db, ic,
            params.split, data.has_categorical, data.has_missing)
        gfeat = jnp.take_along_axis(sel_feats, best.feature[:, None],
                                    axis=1)[:, 0]
        return hist_state, ids, best._replace(
            feature=gfeat.astype(jnp.int32))

    return wave


def _per_feature_gains(grid, lsg, lsh, lc, data: DeviceData,
                       sp: SplitParams, feature_mask):
    """Best gain per (changed-leaf, feature) — the voting criterion.  A
    simplified (numerical, missing-right) scan: votes only need a ranking,
    the exact scan runs later on the merged winners."""
    from ..ops.split import _split_gain, leaf_split_gain
    g = grid[..., 0]; h = grid[..., 1]; c = grid[..., 2]
    clg = jnp.cumsum(g, axis=-1)
    clh = jnp.cumsum(h, axis=-1)
    clc = jnp.cumsum(c, axis=-1)
    tg = lsg[:, None, None]; th = lsh[:, None, None]; tc = lc[:, None, None]
    gains = _split_gain(clg, clh, tg - clg, th - clh,
                        sp.lambda_l1, sp.lambda_l2)
    ok = ((clc >= sp.min_data_in_leaf) & (tc - clc >= sp.min_data_in_leaf)
          & (clh >= sp.min_sum_hessian_in_leaf)
          & (th - clh >= sp.min_sum_hessian_in_leaf))
    bin_ids = jnp.arange(grid.shape[2])
    ok &= (bin_ids[None, None, :] < (data.num_bins - 1)[None, :, None])
    gains = jnp.where(ok, gains, K_MIN_SCORE)
    per_feat = jnp.max(gains, axis=-1)
    parent = leaf_split_gain(lsg, lsh, sp.lambda_l1, sp.lambda_l2)
    per_feat = per_feat - parent[:, None]
    if feature_mask is not None:
        per_feat = jnp.where(feature_mask[None, :], per_feat, K_MIN_SCORE)
    return per_feat


def _find_best_per_leaf_features(sel_grid, lsg, lsh, lc, nb, mt, db, ic,
                                 sp: SplitParams, any_cat: bool,
                                 any_missing: bool = True):
    """find_best_splits variant where each leaf has its OWN feature set
    (per-leaf gathered columns): vmap the single-leaf scan over leaves."""
    def one_leaf(grid_l, sg, sh, cc, nb_l, mt_l, db_l, ic_l):
        r = find_best_splits(grid_l[None], sg[None], sh[None], cc[None],
                             nb_l, mt_l, db_l, ic_l, sp, None,
                             any_categorical=any_cat,
                             any_missing=any_missing)
        return jax.tree.map(lambda a: a[0], r)
    return jax.vmap(one_leaf)(sel_grid, lsg, lsh, lc, nb, mt, db, ic)


# ---------------------------------------------------------------------------
# shard_map driver
# ---------------------------------------------------------------------------
def build_tree_distributed(mesh: Mesh, axis: str, learner_type: str,
                           data: DeviceData, grad, hess,
                           params: GrowthParams,
                           bag_mask=None, feature_mask=None,
                           top_k: int = 20,
                           hist_backend: str = "auto",
                           hist_mode=None,
                           overlap: Optional[bool] = None) -> BuiltTree:
    """Run one tree build as an SPMD program over `mesh`.

    Row-sharded inputs (data/voting): ``bins``, ``grad``, ``hess``,
    ``bag_mask`` are sharded on the leading axis; tree outputs are
    replicated; ``row_leaf`` stays sharded.  Feature-parallel replicates
    rows and slices features inside the shard.

    ``overlap`` (data-parallel only; default = ``LGBM_TPU_OVERLAP``,
    on): lower the per-wave histogram psum through the double-buffered
    chunked reduction (`ops/overlap.py`) — bit-identical trees, the
    identical logical collective schedule (same flight-recorder
    fingerprints), with the reduction tail hidden behind the per-chunk
    sibling-subtract/state-scatter.  The root-statistics psum and the
    feature/voting collectives are untouched either way.
    """
    from ..ops.overlap import overlap_enabled
    if overlap is None:
        overlap = overlap_enabled()
    num_shards = mesh.shape[axis]
    row_shard = learner_type in ("data", "voting")
    n = data.num_data
    vec = P(axis) if row_shard else P()

    if bag_mask is None:
        bag_mask = jnp.ones(n, bool)
    if feature_mask is None:
        feature_mask = jnp.ones(data.num_features, bool)

    # static fields are closed over; only arrays cross the shard_map
    # boundary.  Derived from the pytree aux so new static fields can't
    # silently drift out of sync with DeviceData
    statics = data.tree_flatten()[1]

    def step(bins, offs, nb, db, mt, ic, nanb, fg, fo, grad_l, hess_l,
             bag_l, fmask_l):
        data_l = DeviceData(bins, offs, nb, db, mt, ic, nanb, fg, fo,
                            *statics)
        nhf = None
        psum_axis = None
        if learner_type == "data":
            strategy = None        # serial strategy + histogram psum
            psum_fn = _psum(axis)
            if overlap:
                psum_axis = axis   # overlapped wave reduction
        elif learner_type == "feature":
            strategy, nhf = make_feature_parallel_strategy(
                data_l, grad_l, hess_l, params, fmask_l, axis, num_shards,
                hist_backend, hist_mode)
            psum_fn = None
        elif learner_type == "voting":
            strategy = make_voting_parallel_strategy(
                data_l, grad_l, hess_l, params, fmask_l, axis, num_shards,
                top_k, hist_backend, hist_mode)
            psum_fn = _psum(axis)
        else:
            raise ValueError(learner_type)
        return build_tree(data_l, grad_l, hess_l, params, bag_mask=bag_l,
                          feature_mask=fmask_l, strategy=strategy,
                          psum_fn=psum_fn, hist_backend=hist_backend,
                          num_hist_features=nhf, hist_mode=hist_mode,
                          psum_axis=psum_axis)

    out_spec = BuiltTree(
        feature=P(), threshold_bin=P(), default_left=P(), is_categorical=P(),
        cat_mask=P(), left_child=P(), right_child=P(), gain=P(),
        internal_value=P(), internal_count=P(), leaf_value=P(),
        leaf_count=P(), leaf_depth=P(), num_leaves=P(), row_leaf=vec,
        row_value=P())   # distributed path scores via gather (empty [0])

    in_specs = (vec, P(), P(), P(), P(), P(), P(), P(), P(),
                vec, vec, vec, P())

    fn = shard_map(step, mesh=mesh, in_specs=in_specs,
                   out_specs=out_spec, **{_SM_CHECK_KW: False})
    return fn(data.bins, data.bin_offsets, data.num_bins, data.default_bins,
              data.missing_types, data.is_categorical, data.nan_bins,
              data.feat_group, data.feat_offset,
              grad, hess, bag_mask, feature_mask)
