"""DART, GOSS, and Random Forest boosting variants.

TPU-native counterparts of the reference subclasses
(`/root/reference/src/boosting/dart.hpp`, `goss.hpp`, `rf.hpp`; factory
`boosting.cpp:30-63`).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..learner.serial import build_tree
from ..utils.log import log_info
from .gbdt import GBDT


def _dart_host_rng() -> bool:
    """``LGBM_TPU_DART_HOST_RNG=1`` restores the legacy STATEFUL
    ``np.random.RandomState`` drop stream (pre-PR 12).  The default is
    the pure ``(drop_seed, iteration)``-keyed derivation below: replay-
    stable across resume-from-snapshot (the RandomState stream depended
    on how many draws the dead run had consumed) and rank-identical by
    construction — the DET001 fix that unblocks multi-process DART
    (ROADMAP item 5).  The hatch exists for A/B against the legacy
    stream; parity is pinned by tests/test_determinism.py (registered
    as the `dart-keyed-vs-host-rng` seam in the detcheck parity
    registry)."""
    return os.environ.get("LGBM_TPU_DART_HOST_RNG", "0") == "1"


def _drop_uniforms(drop_seed: int, it: int) -> Tuple[float, np.ndarray]:
    """The keyed drop draws for iteration ``it``: one skip-drop uniform
    plus ``it`` per-past-iteration uniforms, a pure function of
    ``(drop_seed, it)`` via ``jax.random.fold_in`` — the same sanctioned
    idiom as the bagging/feature masks (gbdt.py).  The vector draw is
    padded to the next power of two so the eager uniform program
    compiles O(log iterations) times, not per iteration (trace-contract
    hygiene); the pad values are never read."""
    import jax
    key = jax.random.fold_in(jax.random.PRNGKey(drop_seed), it)
    u_skip = float(jax.random.uniform(jax.random.fold_in(key, 0)))
    pad = 1
    while pad < it:
        pad *= 2
    u = np.asarray(jax.random.uniform(jax.random.fold_in(key, 1), (pad,)))
    return u_skip, u[:it]


class DART(GBDT):
    """Dropout trees (reference dart.hpp:23-199).

    Per iteration (exact reference flow, ``DroppingTrees``/``Normalize``):
    a random subset of past *iterations* is dropped from the training
    score; the new tree is trained with shrinkage ``lr/(1+k)`` (or
    ``lr/(lr+k)`` in xgboost mode); afterwards each dropped tree is
    rescaled to ``k/(k+1)`` (resp. ``k/(k+lr)``) of its old weight and the
    train/valid scores are patched accordingly."""

    boosting_name = "dart"

    def __init__(self, config: Config, train_set, objective=None, fobj=None):
        super().__init__(config, train_set, objective, fobj)
        self._rng_drop = None
        if _dart_host_rng():
            # detcheck: disable=DET001 -- legacy escape hatch
            # (LGBM_TPU_DART_HOST_RNG=1): the stateful pre-PR 12 stream,
            # kept for A/B against the keyed derivation; NOT replay- or
            # rank-stable, documented as such in README "Determinism"
            self._rng_drop = np.random.RandomState(config.drop_seed)
        self._tree_weights: list = []   # per-iteration DART weight
        self._sum_weight = 0.0

    def train_one_iter(self, grad=None, hess=None) -> bool:
        c = self.config
        K = self.num_tree_per_iteration
        lr = c.learning_rate
        drop_iters = self._select_drop()
        k = float(len(drop_iters))
        # The WHOLE drop set's contribution in ONE stacked-predict
        # dispatch per class / valid set (stacked trees sum outputs),
        # reused for both the drop and the renormalize patch — the
        # reference patches scores in one pass the same way
        # (dart.hpp:146-186); the r4 per-tree loop was O(drops) host
        # dispatches per iteration, a 38-s-class cliff over the device
        # tunnel at 500 iterations (VERDICT r5 #9).  All dropped trees
        # share one ``factor``, so only the summed prediction is needed.
        drop_tp = [None] * K
        drop_vp = [[None] * len(self._valid_device) for _ in range(K)]
        if k:
            for cls in range(K):
                trees = [self.models[di * K + cls] for di in drop_iters]
                tp = self._predict_host_trees_binned(trees,
                                                     self.device_data)
                drop_tp[cls] = tp
                self.scores = self.scores.at[:, cls].add(-tp)
                for vi, vd in enumerate(self._valid_device):
                    drop_vp[cls][vi] = self._predict_host_trees_binned(
                        trees, vd)
        # new-tree shrinkage (dart.hpp:127-134)
        if not c.xgboost_dart_mode:
            self.shrinkage_rate = lr / (1.0 + k)
        else:
            self.shrinkage_rate = lr if k == 0 else lr / (lr + k)
        finished = super().train_one_iter(grad, hess)
        if finished:
            return True
        # Normalize (dart.hpp:146-186): dropped tree weight *= factor;
        # train score had it fully removed -> add back factor * pred;
        # valid score still holds it fully -> add (factor - 1) * pred.
        factor = (k / (k + 1.0)) if not c.xgboost_dart_mode else (
            k / (k + lr) if k > 0 else 1.0)
        if k:
            for cls in range(K):
                self.scores = self.scores.at[:, cls].add(
                    factor * drop_tp[cls])
                for vi in range(len(self._valid_device)):
                    self._valid_scores[vi] = self._valid_scores[vi].at[
                        :, cls].add((factor - 1.0) * drop_vp[cls][vi])
        for di in drop_iters:
            for cls in range(K):
                self.models[di * K + cls].shrinkage(factor)
            if not c.uniform_drop:
                self._sum_weight -= self._tree_weights[di] * (
                    1.0 / (k + 1.0) if not c.xgboost_dart_mode
                    else 1.0 / (k + lr))
                self._tree_weights[di] *= factor
        if not c.uniform_drop:
            self._tree_weights.append(self.shrinkage_rate)
            self._sum_weight += self.shrinkage_rate
        self._stacked_cache = None
        return False

    def snapshot_extra_state(self) -> dict:
        # per-tree DART weights: with the keyed drop RNG these are the
        # ONLY bookkeeping a resume needs beyond trees+scores for a
        # weighted-drop run to continue bit-for-bit
        return {"dart_tree_weights": [float(w) for w in self._tree_weights],
                "dart_sum_weight": float(self._sum_weight)}

    def load_snapshot_extra_state(self, extra: dict) -> None:
        if "dart_tree_weights" in extra:
            self._tree_weights = [float(w)
                                  for w in extra["dart_tree_weights"]]
            self._sum_weight = float(extra.get("dart_sum_weight", 0.0))

    def _select_drop(self) -> np.ndarray:
        """Reference DroppingTrees (dart.hpp:85-125): per-iteration Bernoulli
        with rate drop_rate (weight-scaled unless uniform_drop).

        Default path: draws come from :func:`_drop_uniforms`, pure in
        ``(drop_seed, self.iter)`` — identical expected drop-count
        semantics (same Bernoulli rates, same in-order ``max_drop``
        cap), but byte-stable across resume-from-snapshot and across
        ranks.  ``LGBM_TPU_DART_HOST_RNG=1`` keeps the legacy stream."""
        c = self.config
        iters = self.iter
        if self._rng_drop is not None:
            return self._select_drop_host(iters)
        if iters == 0:
            return np.zeros(0, np.int64)
        from ..obs import determinism
        determinism.rng_site("dart.drop", "drop_seed/iteration")
        u_skip, u = _drop_uniforms(c.drop_seed, iters)
        from ..utils.faults import fault_flag
        if fault_flag("det.rng_drift"):
            # injected RNG drift: consume the NEXT iteration's draws in
            # place of this one's — the silent divergence class the
            # determinism contract (window digests) must localize
            u_skip, u = _drop_uniforms(c.drop_seed, iters + 1)
            u = u[:iters]
        if u_skip < c.skip_drop:
            return np.zeros(0, np.int64)
        return self._drop_from_uniforms(u, iters)

    def _drop_from_uniforms(self, u: np.ndarray, iters: int) -> np.ndarray:
        c = self.config
        out = []
        if not c.uniform_drop and self._sum_weight > 0:
            inv_avg = len(self._tree_weights) / self._sum_weight
            rate = c.drop_rate
            if c.max_drop > 0:
                rate = min(rate, c.max_drop * inv_avg / self._sum_weight)
            for i in range(iters):
                if u[i] < rate * self._tree_weights[i] * inv_avg:
                    out.append(i)
                    if c.max_drop > 0 and len(out) >= c.max_drop:
                        break
        else:
            rate = c.drop_rate
            if c.max_drop > 0:
                rate = min(rate, c.max_drop / max(1.0, float(iters)))
            for i in range(iters):
                if u[i] < rate:
                    out.append(i)
                    if c.max_drop > 0 and len(out) >= c.max_drop:
                        break
        return np.asarray(out, np.int64)

    def _select_drop_host(self, iters: int) -> np.ndarray:
        """The pre-PR 12 stream, VERBATIM (escape hatch): sequential
        ``RandomState`` draws, including the early ``max_drop`` break
        that stops consuming draws — byte-compatible with models
        trained before the migration."""
        c = self.config
        if iters == 0 or self._rng_drop.rand() < c.skip_drop:
            return np.zeros(0, np.int64)
        out = []
        if not c.uniform_drop and self._sum_weight > 0:
            inv_avg = len(self._tree_weights) / self._sum_weight
            rate = c.drop_rate
            if c.max_drop > 0:
                rate = min(rate, c.max_drop * inv_avg / self._sum_weight)
            for i in range(iters):
                if self._rng_drop.rand() < rate * self._tree_weights[i] * inv_avg:
                    out.append(i)
                    if c.max_drop > 0 and len(out) >= c.max_drop:
                        break
        else:
            rate = c.drop_rate
            if c.max_drop > 0:
                rate = min(rate, c.max_drop / max(1.0, float(iters)))
            for i in range(iters):
                if self._rng_drop.rand() < rate:
                    out.append(i)
                    if c.max_drop > 0 and len(out) >= c.max_drop:
                        break
        return np.asarray(out, np.int64)


def _abs_grad_importance(G, H):
    """GOSS per-row importance: sum over classes of ``|g*h|``.

    The class axis K is never partitioned (rows shard, classes
    replicate) and the importance only RANKS rows, so the operand order
    is partition-independent — registered as a sanctioned numcheck
    context (tools/numcheck/reduction_registry.py)."""
    return jnp.sum(jnp.abs(G * H), axis=1)


class GOSS(GBDT):
    """Gradient-based One-Side Sampling (reference goss.hpp:36-214): keep
    the top `top_rate` rows by |grad·hess|, sample `other_rate` of the rest
    and amplify their gradients by (1-a)/b.

    The sampling is a pure jnp transform of (gradients, iteration), so
    it runs INSIDE the fused ``lax.scan`` block (`_block_sample`) —
    GOSS configs keep the single-dispatch fast path; the per-iteration
    override below uses the identical derivation (same
    (seed, iteration)-keyed Bernoulli draw), so both paths build the
    same trees."""

    boosting_name = "goss"
    _goss_mp_sample = None

    def _block_sample(self, G, H, it, valid=None, orig_idx=None):
        import jax
        c = self.config
        a, b = c.top_rate, c.other_rate
        # top_k counts REAL rows: under multi-process sharding the
        # global row axis carries per-block padding whose (0, 0)
        # gradients must not dilute the threshold
        n_real = (self._pr.n_global if self._pr is not None
                  else self.num_data)
        top_k = max(1, int(n_real * a))
        # importance = sum over classes of |g*h| (goss.hpp BaggingHelper)
        imp = _abs_grad_importance(G, H)
        if valid is not None:
            imp = jnp.where(valid, imp, -1.0)
        threshold = jnp.sort(imp)[-top_k]
        is_top = imp >= threshold
        key = jax.random.fold_in(jax.random.PRNGKey(c.bagging_seed), it)
        if orig_idx is None:
            rnd = jax.random.uniform(key, imp.shape)
        else:
            # the mod-rank layout PERMUTES rows: draw in ORIGINAL row
            # order and gather through the layout map, so a distributed
            # run samples the identical row set as a serial run on the
            # same data (padding slots hit the trailing 1.0, never
            # selected)
            rnd = jnp.concatenate(
                [jax.random.uniform(key, (n_real,)),
                 jnp.ones(1)])[orig_idx]
        is_other = (~is_top) & (rnd < b / max(1e-12, 1.0 - a))
        if valid is not None:
            is_top = is_top & valid
            is_other = is_other & valid
        multiplier = (1.0 - a) / max(b, 1e-12)
        scale = jnp.where(is_other, multiplier, 1.0)[:, None]
        return G * scale, H * scale, is_top | is_other

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if grad is None or hess is None:
            grad, hess = self._gradients()
        from ..obs import determinism
        determinism.rng_site("goss.sample", "bagging_seed/iteration")
        if self._pr is not None:
            # multi-process: gradients are global row-sharded arrays;
            # the sampling runs as ONE jitted SPMD program (eagerly
            # mixing replicated PRNG draws with sharded operands would
            # fail device placement), with padding rows masked out
            import jax
            if self._goss_mp_sample is None:
                pr = self._pr
                rank = jax.process_index()
                orig = np.arange(pr.per, dtype=np.int64) * pr.world + rank
                orig[pr.n_local:] = pr.n_global     # pads -> dummy slot
                self._goss_orig = pr.globalize(orig.astype(np.int32),
                                               fill=pr.n_global)
                self._goss_valid = pr.globalize(
                    pr.valid_mask_local(), fill=False)
                self._goss_mp_sample = jax.jit(
                    lambda G, H, it, valid, orig_idx: self._block_sample(
                        G, H, it, valid, orig_idx))
            # memcheck: disable=MEM002 -- per-iteration [n] f32 pair, not
            # persistent state; this path runs in tier-1 on the CPU
            # backend where donation is gated off (zero-copy host reads)
            grad, hess, bag = self._goss_mp_sample(
                grad, hess, jnp.int32(self.iter), self._goss_valid,
                self._goss_orig)
        else:
            grad, hess, bag = self._block_sample(grad, hess, self.iter)
        return self._train_with_bag(grad, hess, bag)

    def _train_with_bag(self, grad, hess, bag) -> bool:
        finished = True
        K = self.num_tree_per_iteration
        for k in range(K):
            fmask = self._feature_mask(self.iter * K + k)
            bt = self._build_tree(grad[:, k], hess[:, k], bag, fmask)
            if int(bt.num_leaves) > 1:
                finished = False
            bt = self._renew_leaves(bt, k)
            # stump => zero contribution (gbdt.cpp:435-460), matching the
            # stump-masked row_value the Pallas path emits
            bt = bt._replace(leaf_value=jnp.where(
                bt.num_leaves > 1, bt.leaf_value,
                jnp.zeros_like(bt.leaf_value)))
            self._update_scores(bt, k)
            host = self._to_host_tree(bt)
            host.shrinkage(self.shrinkage_rate)
            if len(self.models) < K and abs(self.init_score_value) > 1e-15:
                host.add_bias(self.init_score_value)
            self.models.append(host)
        self.iter += 1
        self._stacked_cache = None
        return finished


class RF(GBDT):
    """Random forest mode (reference rf.hpp:15-207): mandatory bagging, no
    shrinkage, gradients always computed from the 0-score baseline, outputs
    averaged over trees."""

    boosting_name = "rf"
    average_output = True

    def __init__(self, config: Config, train_set, objective=None, fobj=None):
        super().__init__(config, train_set, objective, fobj)
        self.shrinkage_rate = 1.0
        # RF gradients are w.r.t. the constant init score only (rf.hpp:80+)
        if train_set is not None:
            K = self.num_tree_per_iteration
            if self._pr is not None:
                # global row-sharded like the live scores: the objective
                # computes gradients over the global row axis
                self._base_score = self._pr.globalize(np.full(
                    (train_set.num_data, K), self.init_score_value,
                    np.float32))
            else:
                self._base_score = jnp.full((self.num_data, K),
                                            self.init_score_value,
                                            jnp.float32)

    def _gradients(self):
        saved = self.scores
        self.scores = self._base_score
        try:
            return super()._gradients()
        finally:
            self.scores = saved

    def _update_scores(self, bt, k):
        # accumulate raw sums; averaging happens at predict time
        self.scores = self.scores.at[:, k].add(bt.leaf_value[bt.row_leaf])
        from ..learner.serial import predict_built_tree
        for i, vd in enumerate(self._valid_device):
            pred = predict_built_tree(bt, vd, vd.bins)
            self._valid_scores[i] = self._valid_scores[i].at[:, k].add(pred)

    def eval_train(self):
        return self._eval_avg(super().eval_train)

    def eval_valid(self):
        return self._eval_avg(super().eval_valid)

    def _eval_avg(self, fn):
        # temporarily average scores for metric evaluation
        T = max(1, len(self.models) // max(1, self.num_tree_per_iteration))
        ss, vs = self.scores, list(self._valid_scores)
        self.scores = self.scores / T
        self._valid_scores = [v / T for v in self._valid_scores]
        try:
            return fn()
        finally:
            self.scores, self._valid_scores = ss, vs


def create_boosting(config: Config, train_set=None, objective=None, fobj=None):
    """Factory (reference Boosting::CreateBoosting, boosting.cpp:30-63)."""
    cls = {"gbdt": GBDT, "dart": DART, "goss": GOSS, "rf": RF}[
        config.boosting_type]
    booster = cls(config, train_set, objective, fobj)
    if config.input_model:
        from ..utils.file_io import open_read
        with open_read(config.input_model) as f:
            booster.load_model_from_string(f.read())
    return booster
