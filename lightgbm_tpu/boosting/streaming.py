"""Streaming block trainer — out-of-core training (ROADMAP item 4).

Rows live in the mmap-able binned shard cache (``io/outofcore.py``),
NOT in HBM: per tree, row blocks of ``LGBM_TPU_STREAM_ROWS`` stream
host→device one wave at a time, each block is routed through the
partial tree and its per-wave histograms accumulate into the resident
``[L, F, B, 3]`` state — the histogram trick is what makes GBDT
uniquely streamable (one pass over the data per wave, no resident
rows).  Per-device HBM scales with the block size, never with dataset
rows (memcheck MEM003 models it; the bench ``stream_ingest`` leg's
watermark proves it).  Scores, gradients and hessians are host-
resident and updated per block as the blocks stream.

**Byte-identity contract** (the DET005 seam ``LGBM_TPU_STREAM_ROWS``,
pinned by tests/test_streaming.py): streamed training is
BYTE-IDENTICAL — model text and score digests via ``Booster.digest()``
— to in-memory ``lgb.train`` on the same data, serial AND 2-shard
data-parallel, on ALL THREE histogram backends.  Three mechanisms:

1. **Carried-accumulator folds.**  On the scatter backend, XLA applies
   same-location scatter-add updates in row order, so folding per-block
   scatters into a carried f32 ``[A, F, B, 3]`` accumulator reproduces
   the monolithic ``hist_active_scatter`` bitwise.  On the
   Pallas/compact kernels the fold carries the RAW kernel accumulator
   instead (``learner.serial.make_hist_fold_fn``): each block's kernel
   call SEEDS its output from the carry via ``input_output_aliases``
   (the ``@pl.when`` zero-init becomes a seed-load), so a chain of
   per-block calls replays the monolithic kernel's adds in the
   monolithic order — exactly int32 on the quantized default modes
   (per-tree global quantization scales are host-derived over every
   block, :func:`_fold_scales`), same-order f32 on the wide float
   modes.  The raw carry is dequantized/unpacked ONCE per wave, by the
   same jitted graph the in-memory kernels fuse in-call.  Float
   COMPACT folds are the one chain-inexact case and degrade to the
   wide kernel inside the fold seam.
2. **Canonical chunked root statistics** (``learner/serial.py
   root_stats``): the resident ``_init_state`` derives the root sums
   from fixed ``STREAM_CHUNK``-sized chunk sums reduced by a fixed
   pairwise tree — partition-invariant, so this trainer reassembles
   the identical scalars from per-block chunk sums.
3. **The fenced block body** (``gbdt._make_block_fn``): the serial
   scan body barriers gradients and the built tree and updates scores
   with the contraction-proof scale-then-gather shape (the PR 11 mesh
   discipline), so this module's standalone per-block programs compile
   to the same last-ulp rounding as the fused in-memory body.

**The upload/compute pipeline** (``LGBM_TPU_STREAM_PIPELINE``, default
on): the wave loop runs a bounded-depth-2 prefetch+staging pipeline —
a single host staging thread reads block k+1 from the ShardStore mmap
and pads it while block k's fold computes on device, and block k+1's
``device_put`` is issued BEFORE block k's fold is awaited, so the
host->device copy rides under kernel time instead of serializing with
it.  Fold order never changes — the pipeline reorders only host
staging work — so ``LGBM_TPU_STREAM_PIPELINE=0`` (the serial escape
hatch) is byte-identical by construction; ``stream.prefetch`` /
``stream.upload`` / ``stream.fold`` spans plus the
``stream.pipeline.overlap_s`` counter prove the overlap instead of
claiming it.  Uploads sit behind the shared retry policy with the
``stream.upload`` fault point: a transient device fault is retried
BEFORE the fold is dispatched, so a retried upload can never tear a
fold.

2-shard data-parallel composes by mirroring the mesh row partition
(``parallel/mesh.py shard_row_ranges``): each shard's blocks fold into
a per-shard accumulator and the shard partials combine in device order
— elementwise adds, exactly what the wave ``psum`` lowers to — so the
streamed model equals the in-memory 2-shard mesh model bitwise.  The
in-memory data-parallel psum schedule itself is untouched.

Supported: gbdt boosting, row-wise objectives (regression / binary /
multiclass / xentropy families), ``feature_fraction``, weights, serial
and data-parallel layouts.  Documented descopes (they raise):
bagging/GOSS (the [n]-shaped device mask breaks the memory contract),
DART (host score patching), ranking (row blocks would split queries),
custom ``fobj``, leaf-renewal objectives, valid sets / early stopping.

**Elastic training** (:func:`train_elastic`) rides this trainer because
ALL of its cross-shard communication is explicit host-side combination
of per-shard partials — unlike the in-memory mesh path, whose psum
lives inside an XLA dispatch that cannot be cancelled when a peer
dies.  The protocol fixes a shard count ``S`` for the run's lifetime
(``LGBM_TPU_ELASTIC_SHARDS``; default = the initial world size); each
rank owns shards ``s % world == rank``, folds their blocks exactly as
the local ``S``-shard path would, and the per-shard partials are
allgathered (``parallel/elastic.py``) and combined in SHARD order —
the identical elementwise adds regardless of which rank computed which
shard.  Training is therefore a pure function of ``(data, config, S)``:
any world size, any membership history, and any recovery from a
committed barrier snapshot produce byte-identical models (the chaos
gate ``tools/chaos.py`` proves it with real SIGKILLs).  On a
``RankLostError`` / ``GenerationChanged`` survivors re-rendezvous,
re-own shards at the new world size, and resume from the last
committed barrier (``boosting/snapshot.py`` barrier functions).
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import BinnedDataset, Metadata
from ..io.device import DeviceData, feature_meta_np
from ..learner.serial import (STREAM_CHUNK, BuiltTree, _WaveState,
                              _apply_wave, _empty_best, apply_hist_wave,
                              make_hist_fold_fn, reduce_chunk_sums,
                              root_chunk_sums, scan_grid, stage_plan)
from ..obs import counter_add, event, span as obs_span
from ..objective.objectives import create_objective
from ..ops.pallas_histogram import bin_stride
from ..ops.pallas_route import route_rows_xla
from ..ops.split import leaf_output as _leaf_output
from ..utils.log import log_info, log_warning
from .gbdt import GBDT, _device_feature_mask, growth_params_from_config

# past this row count the objective's device-label init is skipped (it
# would pin an [n] f32 in HBM) and boost-from-average binds the host
# label vector directly
_RESIDENT_SIDE_ROWS = 1 << 27


def stream_rows() -> int:
    """The configured streaming block size (``LGBM_TPU_STREAM_ROWS``),
    rounded UP to a multiple of ``STREAM_CHUNK`` — block boundaries
    must land on root-statistic chunk boundaries or the partition-
    invariant reduction contract breaks.  0 = streaming off."""
    r = int(os.environ.get("LGBM_TPU_STREAM_ROWS", "0"))
    if r <= 0:
        return 0
    return -(-r // STREAM_CHUNK) * STREAM_CHUNK


_SCALE_CHUNK = 1 << 24


def _fold_scales(grad: np.ndarray, hess: np.ndarray) -> np.ndarray:
    """Per-(tree, shard) global quantization scales for the seeded
    kernel folds: ``[|g|max, |h|max]`` clamped to 1e-30, f32.

    Every block of a shard must quantize against ONE scale pair or the
    int8 codes (and therefore the int32 accumulator) stop being a pure
    function of the data partition.  The in-memory kernels derive the
    same scalars on device as ``max(|x|)`` over the shard's rows —
    f32 absmax is exact and order-independent (no rounding, commutative
    idempotent max), so this chunked host reduction lands the identical
    bit pattern without paginating the full vector through HBM.
    SANCTIONED REASSOCIATION CONTEXT (tools/numcheck): chunking reorders
    only ``max``, never an add."""
    out = np.empty(2, np.float32)
    for i, arr in enumerate((grad, hess)):
        m = np.float32(0.0)
        for lo in range(0, arr.shape[0], _SCALE_CHUNK):
            c = np.max(np.abs(arr[lo:lo + _SCALE_CHUNK]))
            m = np.maximum(m, np.float32(c))
        out[i] = np.maximum(m, np.float32(1e-30))
    return out


class _Source:
    """Uniform block reader over a ShardStore or a resident
    BinnedDataset (the resident form exists so source independence —
    mmap cache vs RAM — is testable, and so the parity harness can
    stream the exact arrays the in-memory path trains on)."""

    def __init__(self, obj, config: Config):
        from ..io.outofcore import ShardStore
        self._store = obj if isinstance(obj, ShardStore) else None
        self._ds = obj if isinstance(obj, BinnedDataset) else None
        if self._store is None and self._ds is None:
            raise TypeError(f"unsupported stream source {type(obj)!r}")
        if self._ds is not None and self._ds.bundle is not None \
                and self._ds.bundle.is_bundled:
            raise ValueError("streaming does not support EFB-bundled "
                             "resident sources (the shard store ingests "
                             "unbundled)")
        self.config = config

    @property
    def n(self) -> int:
        return (self._store.n if self._store is not None
                else self._ds.num_data)

    @property
    def num_features(self) -> int:
        return (self._store.num_features if self._store is not None
                else self._ds.num_features)

    def read_rows(self, start: int, stop: int):
        if self._store is not None:
            return self._store.read_rows(start, stop)
        md = self._ds.metadata
        return (self._ds.bins[start:stop],
                md.label[start:stop] if md.label is not None else
                np.zeros(stop - start, np.float32),
                md.weight[start:stop] if md.weight is not None else None)

    def labels(self) -> np.ndarray:
        return (self._store.labels_array() if self._store is not None
                else self._ds.metadata.label)

    def weights(self) -> Optional[np.ndarray]:
        return (self._store.weights_array() if self._store is not None
                else self._ds.metadata.weight)

    def query_boundaries(self):
        return (None if self._store is not None
                else self._ds.metadata.query_boundaries)

    def light_dataset(self) -> BinnedDataset:
        """A bins-free BinnedDataset shell carrying mappers/feature
        metadata — enough for model IO (``GBDT._to_host_tree`` reads
        mappers and ``used_features``, never the rows)."""
        if self._ds is not None:
            return self._ds
        st = self._store
        ds = BinnedDataset()
        ds.config = self.config
        ds.num_total_features = st.num_total_features
        ds.feature_names = list(st.feature_names)
        ds.mappers = st.mappers
        ds.used_features = list(st.used_features)
        ds.feature_info = st.feature_info
        ds.bins = np.zeros((0, st.num_features), st.dtype)
        return ds


def _check_streamable(config: Config, objective, src: _Source) -> None:
    bad = None
    if config.boosting_type not in ("gbdt",):
        bad = f"boosting={config.boosting_type} (host score patching)"
    elif config.bagging_freq > 0 and config.bagging_fraction < 1.0:
        bad = "bagging (the [n]-shaped device mask breaks the " \
              "block-memory contract)"
    elif config.tree_learner not in ("serial", "data"):
        bad = f"tree_learner={config.tree_learner} (streamed v1 " \
              "composes with data-parallel row sharding only)"
    elif objective is None:
        bad = "objective=none / custom fobj"
    elif objective.need_renew_tree_output:
        bad = f"objective={objective.name} (leaf renewal rewrites " \
              "outputs from per-row scores)"
    elif "rank" in objective.name or src.query_boundaries() is not None:
        bad = "ranking objectives (row blocks would split queries)"
    if bad:
        raise ValueError(
            f"streaming training does not support {bad}; train "
            "in-memory, or see README \"Out-of-core training\" for the "
            "supported envelope")


def _num_shards(config: Config) -> int:
    if config.tree_learner != "data":
        return 1
    shape = tuple(config.mesh_shape) or (len(jax.devices()),)
    return max(1, int(shape[0]))


class StreamTrainer:
    """Host-driven streamed boosting over a block source.

    Produces a regular :class:`~lightgbm_tpu.boosting.gbdt.GBDT` (model
    IO, ``digest()``, prediction through the mapper shell) whose train
    scores are the streamed host-resident score state."""

    def __init__(self, config: Config, source, block_rows: int = 0,
                 num_shards: int = 0, elastic=None):
        self.config = config
        self.src = _Source(source, config)
        self.R = block_rows or stream_rows() or STREAM_CHUNK
        self.R = -(-self.R // STREAM_CHUNK) * STREAM_CHUNK
        # the protocol shard count: explicit > elastic run > mesh shape.
        # Under elastic training S is FIXED for the run's lifetime (it
        # is the identity domain — see the module docstring) while the
        # world size is not.
        self.elastic = elastic
        self.S = (int(num_shards)
                  or (int(elastic.num_shards) if elastic is not None else 0)
                  or _num_shards(config))
        self.owned = (elastic.owned_shards() if elastic is not None
                      else tuple(range(self.S)))
        self._owned_set = frozenset(self.owned)
        n = self.src.n
        if n <= 0:
            raise ValueError("empty stream source")
        self.n = n
        from ..parallel.mesh import shard_row_ranges
        self.ranges = shard_row_ranges(n, self.S)
        self.per = self.ranges[0][1] - self.ranges[0][0]

        booster = GBDT(config, None)
        booster.train_set = self.src.light_dataset()
        booster.growth = growth_params_from_config(config)
        booster.feature_names = booster.train_set.feature_names
        booster.max_feature_idx = booster.train_set.num_total_features - 1
        self.booster = booster
        self.growth = booster.growth

        self.objective = create_objective(config)
        _check_streamable(config, self.objective, self.src)
        self.K = self.objective.num_model_per_iteration
        booster.num_tree_per_iteration = self.K
        # the saved model text must carry the objective header (predict
        # conversion + continued training on reload)
        booster.objective = self.objective

        light = self.src.light_dataset()
        meta = feature_meta_np(light)
        arrays = {k: jnp.asarray(meta[k]) for k in (
            "bin_offsets", "num_bins", "default_bins", "missing_types",
            "is_categorical", "nan_bins", "feat_group", "feat_offset")}
        self._dtype = light.bins.dtype
        # template DeviceData: per-block `bins` swap in, metadata fixed
        self.dd_meta = DeviceData(
            bins=jnp.zeros((self.R, self.src.num_features), self._dtype),
            total_bins=meta["total_bins"], max_bins=meta["max_bins"],
            has_categorical=meta["has_categorical"],
            max_group_bins=meta["max_group_bins"],
            is_bundled=meta["is_bundled"],
            has_missing=meta["has_missing"], **arrays)
        L = self.growth.num_leaves
        self.L = L
        _, self.A_tail = stage_plan(L, self.growth.wave_size)
        self.Bh = bin_stride(self.dd_meta.group_max_bins)
        from ..learner.serial import default_hist_mode, effective_hist_mode
        # the hist mode keys on the GLOBAL row count, not the block
        # size: quantized int32 accumulators bound on the total rows
        # folded through them, and the in-memory model this trainer
        # must equal bitwise keys its mode on n too
        self.hist_mode = effective_hist_mode(
            config.hist_mode or default_hist_mode(), n)
        # kernel-exact folds: on the Pallas/compact backends every block
        # call SEEDS the kernel accumulator from the carried raw grid
        # (learner.serial.make_hist_fold_fn), so the streamed chain IS
        # the monolithic kernel bitwise; None -> the exact scatter fold
        self._fold = make_hist_fold_fn(
            self.dd_meta, L, self.A_tail, self.R,
            hist_mode=self.hist_mode, num_data=n)
        self.backend = self._fold.backend if self._fold else "scatter"
        self._kernel_hist = self._fold is not None
        # bounded-depth-2 upload/compute pipeline (module docstring);
        # "0"/"off" is the byte-identical serial escape hatch
        self._pipeline_on = os.environ.get(
            "LGBM_TPU_STREAM_PIPELINE", "1").strip().lower() not in (
                "0", "off", "false")
        self._stager = None

        # host score state [n, K] f32 — the training state that would
        # not fit in HBM; every update happens on device per block and
        # lands back here bitwise
        self.scores = np.zeros((n, self.K), np.float32)
        self._init_scores()
        self._jits = {}
        # open MTTR episode handed over by train_elastic after a
        # recovery: train() closes it (phase `retrain`) once boosting
        # re-reaches the iteration the failure interrupted
        self.recovery_episode = None

    # -- init ------------------------------------------------------------
    def _init_scores(self) -> None:
        obj = self.objective
        if not self.config.boost_from_average:
            return
        y = np.ascontiguousarray(self.src.labels(), np.float32)
        w = self.src.weights()
        if self.n <= _RESIDENT_SIDE_ROWS:
            # the in-memory init path verbatim (device label freed right
            # after): bitwise-identical init score at fittable sizes
            md = Metadata()
            md.set_field("label", y)
            if w is not None:
                md.set_field("weight", np.ascontiguousarray(w))
            obj.init(md, self.n)
            obj.label = None
            obj.weight = None
        else:
            if getattr(self.config, "reg_sqrt", False):
                raise ValueError("reg_sqrt streaming past "
                                 f"{_RESIDENT_SIDE_ROWS} rows is not "
                                 "supported")
            obj._label_np = y
            obj._weight_np = (np.ascontiguousarray(w, np.float32)
                              if w is not None else None)
            obj._check_label()
        v = obj.boost_from_score()
        if v != 0.0:
            self.booster.init_score_value = v
            self.scores[:] = np.float32(v)
            log_info(f"boost from average: init score = {v:.6f}")

    # -- jitted per-step programs ---------------------------------------
    def _jit(self, name, fn, **kw):
        if name not in self._jits:
            self._jits[name] = jax.jit(fn, **kw)
        return self._jits[name]

    def _grad_fn(self):
        obj = self.objective
        K = self.K

        def grads(scores_b, label_b, weight_b):
            # bind the block's label/weight for the trace; row-wise
            # objectives make the block slice exact vs the full call
            obj.label = label_b
            obj.weight = weight_b
            try:
                if K == 1:
                    g, h = obj.get_gradients(scores_b[:, 0])
                    return g[:, None], h[:, None]
                return obj.get_gradients(scores_b)
            finally:
                obj.label = None
                obj.weight = None
        return self._jit("grads", grads)

    def _hist_into(self, acc, bins, grad, hess, hist_leaf, active):
        """Scatter one block's rows INTO the carried accumulator —
        the ``hist_active_scatter`` index arithmetic seeded with the
        fold carry, so the per-location add order equals the monolithic
        scatter's row order (the exactness contract)."""
        A = active.shape[0]
        F = bins.shape[1]
        B = self.Bh
        L = self.L
        safe_act = jnp.where(active >= 0, active, L)
        inv = jnp.full((L + 1,), A, jnp.int32).at[safe_act].set(
            jnp.arange(A, dtype=jnp.int32), mode="drop")
        slot = jnp.where(hist_leaf >= 0,
                         inv[jnp.clip(hist_leaf, 0, L)], A)
        idx = (slot[:, None] * (F * B)
               + jnp.arange(F, dtype=jnp.int32)[None, :] * B
               + bins.astype(jnp.int32))
        vals = jnp.stack([grad, hess, jnp.ones_like(grad)], -1)
        flat = acc.reshape(A * F * B, 3).at[idx].add(
            vals[:, None, :].astype(jnp.float32), mode="drop")
        return flat.reshape(A, F, B, 3)

    def _route(self, data: DeviceData, leaf2, best, pend_sel, pend_new):
        def do_route(l2):
            return route_rows_xla(
                data.bins, l2, best.feature, best.threshold,
                best.default_left, best.is_categorical, best.cat_mask,
                pend_sel, pend_new, data.missing_types, data.nan_bins,
                data.default_bins, data.feat_group, data.feat_offset,
                data.num_bins)
        return jax.lax.cond(jnp.any(pend_sel), do_route,
                            lambda l2: l2, leaf2)

    def _wave_block_fn(self):
        """(bins, leaf2, best, pend_sel, pend_new, acc, grad, hess,
        act_small, scales) -> (leaf2', acc'): route the pending splits
        over this block, then fold its active-leaf histograms into the
        carry — a SEEDED kernel call on the Pallas/compact backends
        (raw carry; ``scales`` is the shard's fixed quantization pair),
        the row-order scatter on the exact f32 path (``scales`` None)."""
        dd = self.dd_meta
        fold = self._fold

        def wave_block(bins, leaf2, best, pend_sel, pend_new, acc,
                       grad, hess, act_small, scales):
            data = dd._replace(bins=bins)
            leaf2 = self._route(data, leaf2, best, pend_sel, pend_new)
            if fold is not None:
                acc = fold.fold(bins, grad, hess, leaf2[1], act_small,
                                acc, scales)
            else:
                acc = self._hist_into(acc, data.bins, grad, hess,
                                      leaf2[1], act_small)
            return leaf2, acc
        return self._jit("wave_block", wave_block)

    def _final_route_fn(self):
        dd = self.dd_meta

        def final_route(bins, leaf2, best, pend_sel, pend_new):
            return self._route(dd._replace(bins=bins), leaf2, best,
                               pend_sel, pend_new)
        return self._jit("final_route", final_route)

    def _init_state_fn(self):
        """Chunk-sum-fed analog of ``learner.serial._init_state``: the
        root statistics arrive as the assembled ``[3, m]`` chunk-sum
        vector (folded over blocks on host) and reduce through the
        same fixed pairwise tree the resident path uses."""
        growth = self.growth
        L = self.L
        dd = self.dd_meta
        A0 = self.A_tail
        Bh = self.Bh
        B = bin_stride(dd.max_bins)

        def init(cs):
            sum_g, sum_h, cnt = reduce_chunk_sums(cs)
            root_out = _leaf_output(sum_g, sum_h, growth.split.lambda_l1,
                                    growth.split.lambda_l2)
            Lm = max(L - 1, 1)
            tree = BuiltTree(
                feature=jnp.zeros(Lm, jnp.int32),
                threshold_bin=jnp.zeros(Lm, jnp.int32),
                default_left=jnp.zeros(Lm, bool),
                is_categorical=jnp.zeros(Lm, bool),
                cat_mask=jnp.zeros((Lm, B), bool),
                left_child=jnp.full(Lm, -1, jnp.int32),
                right_child=jnp.full(Lm, -1, jnp.int32),
                gain=jnp.zeros(Lm, jnp.float32),
                internal_value=jnp.zeros(Lm, jnp.float32),
                internal_count=jnp.zeros(Lm, jnp.int32),
                leaf_value=jnp.zeros(L, jnp.float32),
                leaf_count=jnp.zeros(L, jnp.int32),
                leaf_depth=jnp.zeros(L, jnp.int32),
                num_leaves=jnp.asarray(1, jnp.int32),
                row_leaf=jnp.zeros(0, jnp.int32),
                row_value=jnp.zeros(0, jnp.float32))
            return _WaveState(
                leaf2=jnp.zeros((2, 1), jnp.int32),   # lives per block
                nl=jnp.asarray(1, jnp.int32), done=jnp.asarray(False),
                leaf_sum_grad=jnp.zeros(L).at[0].set(sum_g),
                leaf_sum_hess=jnp.zeros(L).at[0].set(sum_h),
                leaf_count=jnp.zeros(L).at[0].set(cnt),
                leaf_depth=jnp.zeros(L, jnp.int32),
                leaf_value=jnp.zeros(L, jnp.float32).at[0].set(root_out),
                leaf_parent=jnp.full(L, -1, jnp.int32),
                leaf_is_left=jnp.zeros(L, bool),
                hist_state=jnp.zeros((L, dd.num_groups, Bh, 3),
                                     jnp.float32),
                best=_empty_best(L, B),
                pend_sel=jnp.zeros(L, bool),
                pend_new=jnp.zeros(L, jnp.int32),
                act_small=jnp.full(A0, -1, jnp.int32).at[0].set(0),
                act_parent=jnp.full(A0, -1, jnp.int32),
                act_sibling=jnp.full(A0, -1, jnp.int32),
                tree=tree)
        return self._jit("init_state", init)

    def _wave_scan_fn(self):
        """(state, new_h, fmask) -> (hist_state, ids, res): sibling
        subtraction + split rescan on the folded accumulator — the
        same program grouping as the phase driver's ``scan_jit``
        (``rescan_changed``), which is pinned bitwise against the
        fused build."""
        dd = self.dd_meta
        growth = self.growth

        def wave_scan(s, new_h, fmask):
            L = s.hist_state.shape[0]
            hist_state, ids, grid = apply_hist_wave(
                s.hist_state, new_h, s.act_small, s.act_parent,
                s.act_sibling, L)
            return scan_grid(dd, growth, fmask, hist_state, ids, grid,
                             s.leaf_sum_grad, s.leaf_sum_hess,
                             s.leaf_count)
        return self._jit("wave_scan", wave_scan)

    def _wave_apply_fn(self):
        """Wave bookkeeping (``_apply_wave``) as its own program —
        the phase driver's ``update_jit`` grouping."""
        growth = self.growth
        A_tail = self.A_tail
        wave_cap = (growth.wave_size if growth.wave_size > 0
                    else growth.num_leaves)

        def wave_apply(s, hist_state, ids, res):
            return _apply_wave(s, s.leaf2, hist_state, ids, res,
                               A_tail, growth, wave_cap)
        return self._jit("wave_apply", wave_apply)

    def _root_cs_fn(self):
        def root_cs(grad, hess, mask):
            return root_chunk_sums(grad, hess, mask)
        return self._jit("root_cs", root_cs)

    def _score_update_fn(self):
        def update(scores_b, leaf_value, nl, row_leaf, lr, k):
            # the fenced body's update shape: stump-masked leaf values,
            # scale-then-gather — contraction-proof, so this standalone
            # program rounds like the in-memory fused body
            lv = jnp.where(nl > 1, leaf_value, jnp.zeros_like(leaf_value))
            lv_s = lr * lv
            return scores_b.at[:, k].add(lv_s[row_leaf])
        return self._jit("score_update", update, static_argnames=("k",))

    def _combine_fn(self, nparts: int):
        def combine(parts):
            # shard partials combine in device order — the elementwise
            # adds the wave psum lowers to on a D-shard mesh
            out = parts[0]
            for p in parts[1:]:
                out = out + p
            return out
        return self._jit(f"combine{nparts}", combine)

    # -- block geometry ---------------------------------------------------
    def _blocks(self) -> List[Tuple[int, int, int, int]]:
        """-> [(shard, start, stop, valid_rows)]: blocks subdivide each
        shard's row range (never straddling a shard boundary; padded to
        the uniform R on upload so one compiled program serves all)."""
        out = []
        for s, (lo, hi) in enumerate(self.ranges):
            hi = min(hi, self.n)
            pos = lo
            while pos < hi:
                stop = min(pos + self.R, hi)
                out.append((s, pos, stop, stop - pos))
                pos = stop
        return out

    def _my_blocks(self) -> List[Tuple[int, int, int, int]]:
        """This rank's blocks: under elastic training only the owned
        shards' blocks are read, folded and score-updated here — every
        shard has exactly one owner per generation (``s % world``), so
        the union over ranks is the full block list."""
        blocks = self._blocks()
        if self.elastic is None:
            return blocks
        return [b for b in blocks if b[0] in self._owned_set]

    def _pad_block(self, arr: Optional[np.ndarray], m: int,
                   fill=0) -> Optional[np.ndarray]:
        if arr is None:
            return None
        if m == self.R:
            return np.ascontiguousarray(arr)
        pad = np.full((self.R - m,) + arr.shape[1:], fill, arr.dtype)
        return np.concatenate([np.ascontiguousarray(arr), pad])

    # -- training ---------------------------------------------------------
    def train(self, num_iterations: Optional[int] = None) -> GBDT:
        iters = num_iterations or self.config.num_iterations
        # a restored barrier leaves booster.iter mid-run; continuing
        # from it keeps the per-iteration seeds (feature_fraction keys
        # on the TRUE iteration index) on the uninterrupted schedule
        start = self.booster.iter
        try:
            with obs_span("stream.train", rows=self.n, block=self.R,
                          shards=self.S):
                self._finish_recovery()
                for it in range(start, iters):
                    stopped = self._train_one_iter(it)
                    self._finish_recovery()
                    self._window_contracts(it + 1)
                    if stopped:
                        break
                    if self.elastic is not None:
                        # progress rides the heartbeats: operators (and
                        # the chaos launcher's kill scheduler) see it
                        # in info()
                        self.elastic.client.set_status(iteration=it + 1)
                        self._maybe_barrier(it + 1)
        finally:
            self._close_stager()
        ep = self.recovery_episode
        if ep is not None:
            # early stop before the failure iteration came back around:
            # close the episode at the point training actually ended
            self.recovery_episode = None
            ep.finish(iteration=int(self.booster.iter), truncated=True)
        if self.elastic is not None and self.elastic.world > 1:
            self._sync_scores()
        self.booster.scores = self.scores     # host state IS the digest
        self.booster.trim_trailing_stumps()
        return self.booster

    def _window_contracts(self, it: int) -> None:
        """Window-boundary sampling for the reproducibility contracts
        (``LGBM_TPU_DETERMINISM=1`` digest ledger, ``LGBM_TPU_NUM_
        CONTRACT=1`` ulp ledger) — the streamed analog of the in-memory
        trainer's window hook, over the SAME host score state the
        digest law is defined on.  Zero cost when neither contract is
        armed; skipped mid-run under elastic world > 1 where non-owned
        blocks hold stale scores until the final ``_sync_scores``."""
        from ..obs import determinism as _det
        from ..obs import num_contract as _num
        if not (_det.enabled() or _num.enabled()):
            return
        if self.elastic is not None and self.elastic.world > 1:
            return
        self.booster.scores = self.scores     # host state IS the digest
        if _det.enabled():
            _det.window_digest(self.booster, int(it))
        if _num.enabled():
            _num.window_check(self.scores, it=int(it))

    def _finish_recovery(self) -> None:
        """Close the open recovery episode once boosting has re-reached
        the iteration the failure interrupted — `retrain` ends at full
        recovery, not at re-rendezvous."""
        ep = self.recovery_episode
        if ep is not None and self.booster.iter >= ep.target_iter:
            self.recovery_episode = None
            ep.finish(iteration=int(self.booster.iter))

    def _train_one_iter(self, it: int) -> bool:
        c = self.config
        K = self.K
        grad_fn = self._grad_fn()
        blocks = self._my_blocks()
        n = self.n
        # gradients per block, stored host-side for the tree's waves
        G = np.empty((n, K), np.float32)
        H = np.empty((n, K), np.float32)
        with obs_span("stream.gradients", it=it):
            for _, start, stop, m in blocks:
                _, label, weight = self.src.read_rows(start, stop)
                sc = self._pad_block(self.scores[start:stop], m)
                lb = self._pad_block(
                    np.asarray(label, np.float32), m)
                wb = self._pad_block(
                    np.asarray(weight, np.float32) if weight is not None
                    else None, m)
                g, h = grad_fn(jnp.asarray(sc), jnp.asarray(lb),
                               jnp.asarray(wb) if wb is not None else None)
                G[start:stop] = np.asarray(g)[:m]
                H[start:stop] = np.asarray(h)[:m]

        F = self.src.num_features
        ff_on = c.feature_fraction < 1.0
        kf = max(1, int(c.feature_fraction * F))
        stumps = 0
        for k in range(K):
            # None (not all-ones) when feature_fraction is off — the
            # resident build traces the no-mask program shape
            fmask = (_device_feature_mask(c.feature_fraction_seed,
                                          it * K + k, F, kf)
                     if ff_on else None)
            nl = self._build_streamed_tree(it, k, G[:, k], H[:, k], fmask)
            if nl <= 1:
                stumps += 1
        self.booster.iter += 1
        if stumps == K:
            # mirror the in-memory stop: drop the all-stump iteration
            self.booster._pending = self.booster._pending[:-K]
            self.booster.iter -= 1
            log_warning("stopped streamed training: no more leaves meet "
                        f"the split requirements (iteration {it + 1})")
            return True
        return False

    # -- the upload/compute pipeline --------------------------------------
    def _ensure_stager(self):
        """The single host staging thread.  Depth is bounded at 2 by
        construction: at most one block is staged ahead of the block
        computing, so device residency is one extra block's uploads —
        the footprint model (tools/memcheck shapes.json) charges it."""
        if self._stager is None:
            from concurrent.futures import ThreadPoolExecutor
            self._stager = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="stream-stage")
        return self._stager

    def _close_stager(self) -> None:
        if self._stager is not None:
            self._stager.shutdown(wait=True)
            self._stager = None

    def _stage_block(self, start: int, stop: int, m: int,
                     grad: np.ndarray, hess: np.ndarray):
        """Host staging of one block (ShardStore mmap read + pad): the
        part of a block's turnaround that the pipeline moves onto the
        prefetch thread while the previous block's fold computes."""
        with obs_span("stream.prefetch", rows=m):
            bins, _, _ = self.src.read_rows(start, stop)
            return (self._pad_block(np.asarray(bins), m),
                    self._pad_block(grad[start:stop], m),
                    self._pad_block(hess[start:stop], m))

    def _upload_block(self, staged):
        """Device upload of a staged block behind the shared retry
        policy (``stream.upload`` fault point): a transient device
        fault retries the whole put BEFORE any fold is dispatched
        against these arrays, so a retried upload can never tear a
        fold."""
        from ..utils.faults import fault_point
        from ..utils.retry import retry_call
        bins_h, gb, hb = staged

        def put():
            fault_point("stream.upload")
            return (jnp.asarray(bins_h), jnp.asarray(gb),
                    jnp.asarray(hb))
        with obs_span("stream.upload", rows=int(bins_h.shape[0])):
            return retry_call(put, what="stream.upload")

    def _build_streamed_tree(self, it: int, k: int, grad: np.ndarray,
                             hess: np.ndarray, fmask) -> int:
        L = self.L
        blocks = self._my_blocks()
        wave_block = self._wave_block_fn()
        wave_scan = self._wave_scan_fn()
        wave_apply = self._wave_apply_fn()
        root_cs = self._root_cs_fn()
        combine = self._combine_fn(self.S)
        init_state = self._init_state_fn()
        update = self._score_update_fn()
        A = self.A_tail

        # leaf2 carries on host between waves (the streaming traffic);
        # root statistics fold per shard, reduce through the fixed
        # pairwise tree, and shard scalars combine in device order
        leaf2_host: List[np.ndarray] = []
        shard_cs = [[] for _ in range(self.S)]
        for (s, start, stop, m) in blocks:
            mask = np.zeros(self.R, bool)
            mask[:m] = True
            gb = self._pad_block(grad[start:stop], m)
            hb = self._pad_block(hess[start:stop], m)
            cs = np.asarray(root_cs(jnp.asarray(gb), jnp.asarray(hb),
                                    jnp.asarray(mask)))
            shard_cs[s].append(cs)
            l2 = np.full((2, self.R), -1, np.int32)
            l2[0, :] = 0
            l2[1, :m] = 0
            leaf2_host.append(l2)

        # in-memory chunk grids: serial = ceil(n/C); data-parallel =
        # ceil(per/C) per shard (mesh padding rows are zero chunks)
        exchange = (self.elastic is not None and self.elastic.world > 1)
        if exchange:
            # per-shard scalars reduce locally (the same fixed pairwise
            # tree any owner would run), travel as [3] f32 arrays, and
            # combine in SHARD order — bitwise what the single-process
            # S-shard branch below computes
            m_chunks = -(-self.per // STREAM_CHUNK)
            payload = {}
            for s in self.owned:
                cs = np.concatenate(shard_cs[s], axis=1)
                if cs.shape[1] < m_chunks:   # trailing mesh-pad chunks
                    cs = np.concatenate(
                        [cs, np.zeros((3, m_chunks - cs.shape[1]),
                                      np.float32)], axis=1)
                part = jnp.stack(reduce_chunk_sums(
                    jnp.asarray(cs[:, :m_chunks])))
                payload[str(s)] = np.asarray(part)
            merged = self._exchange_arrays(payload,
                                           site="elastic.root_stats")
            parts = [jnp.asarray(merged[s]) for s in range(self.S)]
            tot = parts[0] if self.S == 1 else combine(parts)
            state = init_state(tot[:, None])   # [3, 1]: identity reduce
        elif self.S == 1:
            m_chunks = -(-self.n // STREAM_CHUNK)
            cs_all = np.concatenate(shard_cs[0], axis=1)[:, :m_chunks]
            state = init_state(jnp.asarray(cs_all))
        else:
            m_chunks = -(-self.per // STREAM_CHUNK)
            parts = []
            for cs_list in shard_cs:
                cs = (np.concatenate(cs_list, axis=1) if cs_list
                      else np.zeros((3, 0), np.float32))
                if cs.shape[1] < m_chunks:   # trailing mesh-pad chunks
                    cs = np.concatenate(
                        [cs, np.zeros((3, m_chunks - cs.shape[1]),
                                      np.float32)], axis=1)
                parts.append(jnp.stack(reduce_chunk_sums(
                    jnp.asarray(cs[:, :m_chunks]))))
            tot = combine(parts)
            state = init_state(tot[:, None])   # [3, 1]: identity reduce

        # per-(tree, shard) quantization scales for the kernel folds —
        # fixed across blocks AND waves, host-derived over the shard's
        # full row range (bitwise the device absmax the in-memory
        # kernels compute; an empty shard range clamps to 1e-30 on both
        # sides).  None on the float modes and the scatter path.
        fold = self._fold
        scales_dev = {}
        if fold is not None and fold.quantized:
            for s in self.owned:
                lo, hi = self.ranges[s]
                hi = min(hi, self.n)
                scales_dev[s] = jnp.asarray(
                    _fold_scales(grad[lo:hi], hess[lo:hi]))

        pipelined = self._pipeline_on and len(blocks) > 1
        stager = self._ensure_stager() if pipelined else None

        def _staged(idx: int):
            _, b_start, b_stop, b_m = blocks[idx]
            return self._stage_block(b_start, b_stop, b_m, grad, hess)

        while True:
            if bool(state.done) or int(state.nl) >= L:
                break
            # the wave carry: RAW kernel accumulators on the fold
            # backends (seeded per block, unpacked once below), the f32
            # grid on the exact scatter path
            accs = [fold.init_acc() if fold is not None else
                    jnp.zeros((A, self.dd_meta.num_groups, self.Bh, 3),
                              jnp.float32) for _ in range(self.S)]
            dev = self._upload_block(_staged(0)) if blocks else None
            for bi, (s, start, stop, m) in enumerate(blocks):
                bins_d, gd, hd = dev
                dev = None
                # depth-2 pipeline: hand block k+1 to the staging
                # thread before dispatching block k's fold
                fut = (stager.submit(_staged, bi + 1)
                       if pipelined and bi + 1 < len(blocks) else None)
                with obs_span("stream.fold", block=bi):
                    l2, acc = wave_block(
                        bins_d, jnp.asarray(leaf2_host[bi]), state.best,
                        state.pend_sel, state.pend_new, accs[s], gd, hd,
                        state.act_small, scales_dev.get(s))
                accs[s] = acc
                if fut is not None:
                    # block k+1's staging wait + upload land here —
                    # after block k's fold DISPATCH, before its await —
                    # so the host->device copy rides under kernel time.
                    # The counter is the proof of overlap, not a claim.
                    t0 = time.perf_counter()
                    dev = self._upload_block(fut.result())
                    counter_add("stream.pipeline.overlap_s",
                                time.perf_counter() - t0)
                leaf2_host[bi] = np.asarray(l2)     # the fold await
                if dev is None and bi + 1 < len(blocks):
                    # serial escape hatch: stage + upload only after
                    # the fold is awaited (the reference schedule)
                    dev = self._upload_block(_staged(bi + 1))
            if fold is not None:
                # finalize each owned chain ONCE per wave — BEFORE the
                # shard exchange/combine, so the elastic protocol moves
                # the same f32 [A, F, B, 3] partials on every backend
                for s in self.owned:
                    accs[s] = fold.unpack(accs[s], scales_dev.get(s))
            if exchange:
                # per-shard wave partials are rank-independent (each
                # shard's carried fold is the same program any owner
                # runs); combining the gathered partials in shard order
                # IS the single-process combine below, bitwise
                merged = self._exchange_arrays(
                    {str(s): np.asarray(accs[s]) for s in self.owned},
                    site="elastic.wave_hist")
                parts = [jnp.asarray(merged[s]) for s in range(self.S)]
                new_h = parts[0] if self.S == 1 else combine(parts)
            else:
                new_h = accs[0] if self.S == 1 else combine(accs)
            hist_state, ids, res = wave_scan(state, new_h, fmask)
            state = wave_apply(state, hist_state, ids, res)
            counter_add("stream.waves")

        # final route + per-block score updates
        final_route = self._final_route_fn()
        lr = jnp.float32(self.booster.shrinkage_rate)
        nl = state.nl
        for bi, (s, start, stop, m) in enumerate(blocks):
            bins, _, _ = self.src.read_rows(start, stop)
            bins_d = jnp.asarray(self._pad_block(np.asarray(bins), m))
            l2 = final_route(bins_d, jnp.asarray(leaf2_host[bi]),
                             state.best, state.pend_sel, state.pend_new)
            row_leaf = l2[0]
            sc = self._pad_block(self.scores[start:stop], m)
            out = update(jnp.asarray(sc), state.leaf_value, nl,
                         row_leaf, lr, k=k)
            self.scores[start:stop] = np.asarray(out)[:m]

        # host tree (reuses the GBDT conversion machinery via _pending)
        lv_final = jnp.where(nl > 1, state.leaf_value,
                             jnp.zeros_like(state.leaf_value))
        bt = state.tree._replace(
            leaf_value=lv_final,
            leaf_count=state.leaf_count.astype(jnp.int32),
            leaf_depth=state.leaf_depth,
            num_leaves=nl,
            row_leaf=jnp.zeros(0, jnp.int32),
            row_value=jnp.zeros(0, jnp.float32))
        bias = (self.booster.init_score_value
                if (self.booster._num_models() < self.K
                    and abs(self.booster.init_score_value) > 1e-15)
                else 0.0)
        self.booster._pending.append(
            (bt, self.booster.shrinkage_rate, bias, 1))
        counter_add("stream.trees")
        return int(nl)

    # -- elastic protocol -------------------------------------------------
    def _exchange_arrays(self, payload,
                         site: str = "elastic.exchange") -> dict:
        """Allgather ``{shard: array}`` contributions and return the
        full ``{shard: array}`` map — every protocol shard must be
        covered (the mod-world ownership rule guarantees it; a hole
        means a protocol desync, not a recoverable fault).  ``site``
        names the call point on the collective's trace span — the
        straggler table is per-site, so root-stat, wave-histogram and
        score-sync skew attribute separately."""
        from ..parallel.elastic import decode_array, encode_array
        gathered = self.elastic.allgather(
            {s: encode_array(a) for s, a in payload.items()}, site=site)
        merged = {}
        for part in gathered:
            merged.update(part or {})
        out = {}
        for s in range(self.S):
            enc = merged.get(str(s))
            if enc is None:
                raise RuntimeError(
                    f"elastic exchange is missing shard {s} of {self.S} "
                    f"(world {self.elastic.world}): ranks disagree on "
                    "the shard protocol")
            out[s] = decode_array(enc)
        return out

    def _maybe_barrier(self, iteration: int) -> None:
        freq = int(self.config.snapshot_freq or 0)
        if freq <= 0 or iteration % freq != 0:
            return
        self._barrier_snapshot(iteration)

    def _barrier_snapshot(self, iteration: int) -> None:
        """The coordinated snapshot commit: shard states first, then a
        commit allgather of ``(iteration, model digest, shard shas)``
        that every rank must match, then rank 0 publishes model text +
        manifest (manifest LAST — its appearance is the global commit
        marker).  A SIGKILL anywhere in this sequence leaves either a
        complete barrier or a torn one that validation skips."""
        from .snapshot import commit_barrier, config_hash, \
            write_barrier_shard
        run = self.elastic
        prefix = self.config.output_model
        shard_shas = {}
        for s in self.owned:
            lo, hi = self.ranges[s]
            hi = min(hi, self.n)
            shard_shas[s] = write_barrier_shard(
                prefix, iteration, s, self.scores[lo:hi])
        model_text = self.booster.save_model_to_string(-1)
        digest = hashlib.sha256(model_text.encode()).hexdigest()
        acks = run.allgather({
            "iteration": int(iteration), "digest": digest,
            "shards": {str(s): sha for s, sha in shard_shas.items()}},
            site="elastic.barrier_commit")
        head = (acks[0]["iteration"], acks[0]["digest"])
        for a in acks[1:]:
            if (a["iteration"], a["digest"]) != head:
                event("elastic", "barrier_mismatch",
                      iteration=int(iteration))
                raise RuntimeError(
                    f"barrier commit mismatch at iteration {iteration}: "
                    f"ranks disagree on (iteration, model digest) "
                    f"{[(a['iteration'], a['digest'][:12]) for a in acks]}"
                    " — refusing to publish a snapshot that is not "
                    "globally valid")
        if run.rank == 0:
            merged = {}
            for a in acks:
                merged.update({int(s): sha
                               for s, sha in a["shards"].items()})
            meta = {
                "num_shards": int(self.S),
                "world_size": int(run.world),
                "generation": int(run.generation),
                "config_hash": config_hash(self.config),
                "init_score_value": float(self.booster.init_score_value),
                "num_tree_per_iteration": int(self.K),
            }
            commit_barrier(prefix, iteration, model_text, merged, meta,
                           keep=max(int(self.config.snapshot_keep), 1))
        # all ranks outlive the publish: a rank that raced ahead into
        # the next window could otherwise observe a half-written commit
        run.barrier(f"barrier-committed-{iteration}")
        counter_add("elastic.barriers")

    def restore_barrier(self, prefix: Optional[str] = None,
                        iteration: Optional[int] = None,
                        model_sha: Optional[str] = None) -> int:
        """Adopt the newest COMMITTED barrier under ``prefix`` (trees
        from the model text, scores from the shard state files); returns
        the restored iteration, 0 when there is nothing to restore.
        Rank-oblivious by construction: every rank reads the same
        manifest, and shard states are keyed by protocol shard, not by
        the rank that wrote them.

        ``iteration``/``model_sha`` pin the exact barrier the elastic
        world AGREED on (the restore allgather in ``train_elastic``) —
        a rank that cannot validate that barrier anymore fails fast
        here instead of resuming a different iteration and desyncing
        barrier tags mid-train."""
        from .snapshot import (barrier_paths, config_hash,
                               latest_valid_barrier, validate_barrier)
        prefix = prefix or self.config.output_model
        if iteration is None:
            man = latest_valid_barrier(prefix, num_shards=self.S)
            if man is None:
                return 0
        else:
            man = validate_barrier(barrier_paths(prefix,
                                                 int(iteration))[1])
            if man is None \
                    or int(man.get("num_shards", -1)) != self.S \
                    or (model_sha is not None
                        and man.get("model_sha256") != model_sha):
                raise RuntimeError(
                    f"agreed barrier snapshot (iteration {iteration}) "
                    "is no longer restorable on this rank — it "
                    "validated during the restore allgather but is now "
                    "missing, torn, or a different model; refusing to "
                    "resume from a different iteration than the rest "
                    "of the world")
        if man.get("config_hash") and \
                man["config_hash"] != config_hash(self.config):
            raise ValueError(
                "cannot resume from barrier snapshot: the training "
                "config changed (it would train a different model under "
                "the same prefix); clear the barrier files or keep the "
                "config")
        if int(man.get("num_tree_per_iteration", self.K)) != self.K:
            raise ValueError("barrier snapshot objective shape does not "
                             "match this run")
        with open(man["model_path"]) as f:
            donor = GBDT(self.config, None)
            donor.load_model_from_string(f.read())
        light = self.booster.train_set
        fmap = {f: i for i, f in enumerate(light.used_features)}
        for t in donor.models:
            t.align_with_mappers(light.mappers, fmap)
        self.booster.models = list(donor.models)
        self.booster._pending = []
        self.booster._stacked_cache = None
        self.booster.iter = int(man["iteration"])
        self.booster.init_score_value = float(
            man.get("init_score_value", self.booster.init_score_value))
        for s, path in man["shard_paths"].items():
            lo, hi = self.ranges[int(s)]
            hi = min(hi, self.n)
            arr = np.load(path)["scores"]
            if arr.shape != (hi - lo, self.K):
                raise ValueError(
                    f"barrier shard {s} carries scores of shape "
                    f"{arr.shape}, expected {(hi - lo, self.K)} — the "
                    "data or shard protocol changed under the prefix")
            self.scores[lo:hi] = arr
        counter_add("snapshot.barrier_resumes")
        log_info(f"restored barrier snapshot: iteration "
                 f"{self.booster.iter}, {len(man['shard_paths'])} shard "
                 f"states ({prefix})")
        return self.booster.iter

    def _sync_scores(self) -> None:
        """Train-end score replication: every rank gathers the shards
        it does not own, so the returned booster's ``digest()`` is the
        full-dataset digest on every rank (the identity the chaos gate
        compares)."""
        payload = {}
        for s in self.owned:
            lo, hi = self.ranges[s]
            hi = min(hi, self.n)
            payload[str(s)] = self.scores[lo:hi]
        merged = self._exchange_arrays(payload, site="elastic.score_sync")
        for s in range(self.S):
            lo, hi = self.ranges[s]
            hi = min(hi, self.n)
            self.scores[lo:hi] = merged[s]


def train_streaming(params, source, num_boost_round: Optional[int] = None,
                    cache_dir: Optional[str] = None,
                    block_rows: int = 0) -> GBDT:
    """Train out-of-core: ``source`` is a ShardStore, a list of data
    files (ingested into ``cache_dir`` first), or a resident
    BinnedDataset (streamed from RAM — the source-independence anchor).
    Returns a regular GBDT booster (save/predict/digest)."""
    from ..config import canonicalize_params
    from ..io.outofcore import default_cache_dir, ingest
    config = Config.from_params(canonicalize_params(dict(params)))
    config.check()
    if isinstance(source, (list, tuple)):
        cdir = cache_dir or default_cache_dir(list(source))
        source = ingest(list(source), config, cdir)
    trainer = StreamTrainer(config, source, block_rows=block_rows)
    return trainer.train(num_boost_round)


def elastic_shards(world: int, explicit: int = 0) -> int:
    """The run-lifetime protocol shard count: explicit argument >
    ``LGBM_TPU_ELASTIC_SHARDS`` > the initial world size.  Fixing S
    while the world varies is what makes every membership history land
    on the same bytes (the model is a function of ``(data, config, S)``,
    never of who computed which shard)."""
    s = int(explicit) or int(os.environ.get("LGBM_TPU_ELASTIC_SHARDS",
                                            "0") or 0)
    return s if s > 0 else max(int(world), 1)


def _write_elastic_summary(run) -> None:
    """Train-end merged telemetry summary over the ELASTIC allgather
    (elastic workers are not a jax multi-process world, so the
    ``cli.py`` ``jax_process_allgather`` route never fires for them):
    rank 0 writes ``<trace>.summary.json`` next to its trace file.

    The merge collective is gated only on shared state (``run.world``)
    — every rank participates or none does; whether a rank traces is a
    local decision applied AFTER the gather.  A peer lost between
    train end and here must not restart recovery over a summary, so
    elastic interrupts are swallowed (the trained model already
    returned on every rank's success path)."""
    import re
    from ..obs import merged_summary, write_summary
    from ..obs import telemetry
    from ..parallel.elastic import ELASTIC_INTERRUPTS
    try:
        merged = (merged_summary(
                      lambda obj: run.allgather(obj,
                                                site="elastic.summary"))
                  if run.world > 1 else None)
    except ELASTIC_INTERRUPTS:
        return
    path = telemetry.trace_path()
    if not path or (run.world > 1 and run.rank != 0):
        return
    base = re.sub(r"\.rank\d+$", "", path)
    try:
        write_summary(base + ".summary.json", merged)
    except OSError:
        log_warning("elastic: failed to write merged summary "
                    f"({base}.summary.json)")


def train_elastic(params, source, num_boost_round: Optional[int] = None,
                  coordinator: Optional[str] = None,
                  cache_dir: Optional[str] = None, block_rows: int = 0,
                  num_shards: int = 0, min_world: int = 1,
                  client=None, max_recoveries: int = 64) -> GBDT:
    """Train under the elastic protocol (``parallel/elastic.py``):
    rendezvous with the coordinator, stream-train the owned shard
    slice, commit cross-rank barrier snapshots every ``snapshot_freq``
    iterations, and on ANY elastic interrupt (lost rank, membership
    change, eviction) re-rendezvous at the new world size, re-shard,
    and resume from the last committed barrier.  The recovered model is
    byte-identical to the uninterrupted run at any world size
    (``tools/chaos.py`` is the gate).

    ``source`` follows :func:`train_streaming` (every member must see
    the same data and params — the protocol-agreement allgather checks
    the config hash).  ``coordinator`` defaults to ``LGBM_TPU_ELASTIC``.
    """
    from ..config import canonicalize_params
    from ..io.outofcore import default_cache_dir, ingest
    from ..obs import health
    from ..parallel.elastic import (ELASTIC_INTERRUPTS, ElasticClient,
                                    ElasticRun, EvictedError,
                                    elastic_address)
    from .snapshot import barrier_candidates, config_hash
    config = Config.from_params(canonicalize_params(dict(params)))
    config.check()
    if isinstance(source, (list, tuple)):
        cdir = cache_dir or default_cache_dir(list(source))
        source = ingest(list(source), config, cdir)
    own_client = client is None
    if client is None:
        addr = coordinator or elastic_address()
        if addr is None:
            raise ValueError(
                "elastic training needs a coordinator: pass "
                "coordinator='host:port' or set LGBM_TPU_ELASTIC")
        client = ElasticClient(addr)
    episode = None           # open MTTR episode (obs/fleet.py)
    trainer = None
    try:
        # records emitted during the rendezvous must not open the trace
        # file before this process knows its ELASTIC rank (same
        # discipline as mesh.init_distributed); set_rank makes the
        # coordinator's rank/world the trace identity — each elastic
        # worker is a world-1 jax process
        from ..obs.telemetry import hold_trace, release_trace, set_rank
        hold_trace()
        try:
            world, _, _ = client.join_world(min_world=min_world)
            set_rank(client.rank, client.world)
        finally:
            release_trace()
        S = elastic_shards(world, num_shards)
        chash = config_hash(config)
        recoveries = 0
        while True:
            try:
                run = ElasticRun(client, S)
                # protocol agreement before any work: every member of
                # this generation must train the same config with the
                # same shard count, or the partials are meaningless.
                # The same allgather carries each rank's view of the
                # committed barriers, so the world agrees on ONE
                # restore point up front — a lagging filesystem or a
                # concurrent prune must not let ranks resume different
                # iterations (that desync would only surface later as
                # a mid-train barrier-tag RuntimeError).
                cands = barrier_candidates(config.output_model,
                                           num_shards=S)
                views = run.allgather({
                    "shards": S, "config": chash,
                    "barriers": {str(i): sha
                                 for i, sha in cands.items()}},
                    site="elastic.protocol")
                proto = [{k: v for k, v in view.items()
                          if k != "barriers"} for view in views]
                for v in proto[1:]:
                    if v != proto[0]:
                        raise RuntimeError(
                            "elastic members disagree on the protocol "
                            f"({proto}); every member must train the "
                            "same params with the same shard count")
                common = set(views[0].get("barriers", {}).items())
                for v in views[1:]:
                    common &= set(v.get("barriers", {}).items())
                agreed = (max(common, key=lambda kv: int(kv[0]))
                          if common else None)
                with obs_span("elastic.reshard", world=run.world,
                              generation=run.generation, shards=S):
                    trainer = StreamTrainer(config, source,
                                            block_rows=block_rows,
                                            num_shards=S, elastic=run)
                    if episode is not None:
                        episode.mark("reshard")
                    it0 = (trainer.restore_barrier(
                               iteration=int(agreed[0]),
                               model_sha=agreed[1])
                           if agreed else 0)
                    if episode is not None:
                        episode.mark("restore")
                if it0:
                    log_info(f"elastic: resuming from barrier iteration "
                             f"{it0} as rank {run.rank}/{run.world} "
                             f"(generation {run.generation})")
                if episode is not None:
                    # the trainer closes it (phase `retrain`) when
                    # boosting re-reaches the interrupted iteration
                    trainer.recovery_episode = episode
                    episode = None
                health.mark_ready()
                booster = trainer.train(num_boost_round)
                _write_elastic_summary(run)
                return booster
            except ELASTIC_INTERRUPTS as exc:
                recoveries += 1
                if recoveries > max_recoveries:
                    raise
                counter_add("elastic.recoveries")
                # MTTR accounting: a new episode opens at the moment
                # the failed collective STARTED stalling (the consumed
                # client.op_started) — the deadline wait is the
                # `detect` phase.  A repeat interrupt subsumes any
                # episode still open from the previous attempt.
                from ..obs import fleet
                stall = client.op_started
                client.op_started = None
                if episode is not None:
                    episode.abandon()
                if trainer is not None \
                        and trainer.recovery_episode is not None:
                    trainer.recovery_episode.abandon()
                    trainer.recovery_episode = None
                episode = fleet.RecoveryEpisode(
                    error=type(exc).__name__,
                    generation=int(client.generation),
                    target_iter=(trainer.booster.iter
                                 if trainer is not None else 0),
                    stall_started=stall)
                episode.mark("detect")
                health.mark_recovering(reason=type(exc).__name__)
                with obs_span("elastic.recover",
                              error=type(exc).__name__):
                    event("elastic", "recover", error=type(exc).__name__,
                          generation=int(client.generation))
                    if isinstance(exc, EvictedError):
                        # evicted members come back as fresh members
                        client.join_world(min_world=1)
                    else:
                        try:
                            client.resync()
                        except ELASTIC_INTERRUPTS:
                            client.join_world(min_world=1)
                set_rank(client.rank, client.world)
                episode.mark("resync")
                continue
    finally:
        if own_client:
            try:
                client.leave()
            finally:
                client.close()
