"""GBDT — the boosting engine.

TPU-native counterpart of the reference GBDT
(`/root/reference/src/boosting/gbdt.cpp`, `gbdt.h`; model text IO
`gbdt_model_text.cpp`).  The per-iteration step mirrors ``TrainOneIter``
(`gbdt.cpp:377-472`): gradients from the objective (`gbdt.cpp:194-202`),
bagging, one tree per class via the tree learner, objective-specific leaf
renewal, shrinkage, score update (`ScoreUpdater`, `score_updater.hpp`),
eval + early stopping (`gbdt.cpp:492+`), periodic snapshots
(`gbdt.cpp:309-327`, the fork's snapshot_freq feature).

TPU design: scores/gradients live on device; the tree build is a single
jitted program; the host loop only sequences iterations and handles
serialization.  Trees exist in two forms — the device ``BuiltTree`` right
after training (score updates are pure gathers via ``row_leaf``) and the
host ``Tree`` (numpy) for the model file.
"""
from __future__ import annotations

import math
import os as _os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.binning import MISSING_NAN
from ..io.dataset import BinnedDataset
from ..io.device import DeviceData, to_device
from ..learner.serial import BuiltTree, GrowthParams, build_tree, predict_built_tree
from ..metric.metrics import Metric, create_metric, default_metric_for_objective
from ..models.tree import Tree, stack_trees, predict_binned
from ..obs import counter_add, event as obs_event, span as obs_span
from ..objective.objectives import ObjectiveFunction, create_objective
from ..ops.split import SplitParams
from ..utils.log import log_info, log_warning

K_MODEL_VERSION = "v2"     # reference gbdt_model_text.cpp:13

# mem.leak fault sink (tests/test_mem_contract.py): while the fault
# point is armed, _train appends one fresh device array per window
# here — a module-lifetime live-buffer leak the HBM watermark contract
# (obs/mem_contract.py, LGBM_TPU_MEM_CONTRACT=1) must catch and name.
_MEM_LEAK_SINK: List[jnp.ndarray] = []
# bytes leaked per window ~= 4 * this (f32); > the contract's default
# 1 MiB tolerance so a single armed window is visible above it
_MEM_LEAK_ELEMS = int(_os.environ.get("LGBM_TPU_MEM_LEAK_ELEMS", 1 << 19))


def _donation_enabled() -> bool:
    """Buffer donation through the jitted training programs (default
    ON on accelerators): the fused block donates the running score
    state (train + valid) so XLA writes the updated scores in place
    instead of allocating a second [n, K] f32 set per dispatch, and
    the mesh build donates grad/hess.  At the 10.5M-row HIGGS shape
    that is ~120 MB of HBM churn per block removed — headroom the
    wave histograms and the serve pack share.  ``LGBM_TPU_DONATE=0``
    disables for A/B (and restores full mid-execution retryability of
    the dispatch retry).

    CPU is excluded unconditionally: on the CPU backend ``np.asarray``
    of a device array is a ZERO-COPY view into the XLA buffer, and
    jaxlib 0.4.x donation reuses/frees that same memory — host reads
    of the score state (eval metrics, feval, the C API) then race the
    donated dispatch and flakily SIGSEGV (reproduced in this image:
    ``binary_auc`` reading a just-returned valid-score view crashed
    in 3/4 tier-1 runs with donation on, 0/4 with it off).  On
    TPU/GPU every host read is a device→host copy, so donation is
    safe there — and that is where the HBM win lives."""
    if jax.default_backend() == "cpu":
        return False
    return _os.environ.get("LGBM_TPU_DONATE", "1") != "0"


_EFFORT_OPT_OK: Optional[bool] = None


def _effort_opt_supported() -> bool:
    """Probe-compile once per process: a jax new enough to ACCEPT the
    ``compiler_options`` kwarg can still sit on an XLA/libtpu that
    rejects ``exec_time_optimization_effort`` — and that surfaces at
    the first compile, not at jit-wrap (review r4)."""
    global _EFFORT_OPT_OK
    if _EFFORT_OPT_OK is None:
        try:
            jax.jit(lambda x: x + 1, compiler_options={
                "exec_time_optimization_effort": -1.0})(
                    jnp.zeros(1)).block_until_ready()
            _EFFORT_OPT_OK = True
        except Exception:               # noqa: BLE001 - any failure:
            _EFFORT_OPT_OK = False      # fall back to default effort
            from ..utils.log import log_once
            log_once("effort_opt_unsupported",
                     "compiler exec_time_optimization_effort not "
                     "supported by this jax/XLA; using default effort",
                     level="info")
    return _EFFORT_OPT_OK


def _device_bag_mask(seed: int, epoch, n: int, fraction: float):
    """Bernoulli row mask, pure in (seed, bagging epoch).  Traceable:
    ``epoch`` may be a scan carry, so the fused block derives per-epoch
    masks on device with no host RNG in the loop (reference Bagging,
    gbdt.cpp:225-286, re-bags every bagging_freq iterations)."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), epoch)
    return jax.random.uniform(key, (n,)) < fraction


def _device_feature_mask(seed: int, tree_idx, F: int, k: int):
    """Exactly-k feature mask, pure in (seed, global tree index)
    (serial_tree_learner.cpp:240-266 samples k features per tree).
    Top-k over uniforms instead of choice-without-replacement: one sort,
    no sequential draws — and traceable inside ``lax.scan``."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), tree_idx)
    r = jax.random.uniform(key, (F,))
    # scatter the top-k INDICES into a boolean mask: a `r >= kth`
    # threshold admits every tied draw (2^-24 uniform granularity) and
    # breaks the exactly-k contract over hundreds of trees (ADVICE r4)
    idx = jax.lax.top_k(r, k)[1]
    return jnp.zeros(F, bool).at[idx].set(True)


def split_params_from_config(c: Config) -> SplitParams:
    return SplitParams(
        lambda_l1=c.lambda_l1, lambda_l2=c.lambda_l2,
        min_data_in_leaf=c.min_data_in_leaf,
        min_sum_hessian_in_leaf=c.min_sum_hessian_in_leaf,
        min_gain_to_split=c.min_gain_to_split,
        max_cat_threshold=c.max_cat_threshold,
        cat_smooth=c.cat_smooth, cat_l2=c.cat_l2,
        max_cat_to_onehot=c.max_cat_to_onehot)


import functools


@functools.partial(jax.jit,
                   static_argnames=("num_leaves", "max_depth", "wave_size",
                                    "hist_mode", "split_kernel"))
def _shared_serial_build(dd, grad, hess, bag, fmask, bins_t, split,
                         *, num_leaves, max_depth, wave_size, hist_mode,
                         split_kernel=True):
    """Module-level jitted serial tree build: shared across all GBDT
    instances, with SplitParams TRACED (only the shape-determining
    num_leaves/max_depth/wave_size are static) — so boosters differing
    only in regularization / min-data knobs reuse one compiled program
    instead of recompiling (the dominant cost of the CPU test suite).

    ``split_kernel`` is a pure CACHE KEY: when the fused split kernel is
    disabled after a Mosaic compile failure (``ops/pallas_split``
    global), the trace must re-run so the gate re-evaluates — without a
    distinct static arg the old jaxpr (with the failing kernel baked in)
    would be served from this shared cache forever."""
    growth = GrowthParams(num_leaves=num_leaves, max_depth=max_depth,
                          wave_size=wave_size, split=split)
    return build_tree(dd, grad, hess, growth, bag_mask=bag,
                      feature_mask=fmask, bins_t=bins_t,
                      hist_mode=hist_mode)


def _mesh_score_update_impl(scores, lv, row_leaf, lr, *, k):
    """Per-iteration mesh score update as ONE jitted program (one
    dispatch instead of three): gather the shrunk leaf values and add.
    The arithmetic region compiles exactly like the fused mesh block's
    update region, which is what keeps the ``LGBM_TPU_MESH_BLOCK=0``
    escape hatch byte-identical (tests/test_mesh_block.py pins it)."""
    return scores.at[:, k].add((lr * lv)[row_leaf[:scores.shape[0]]])


def _mesh_valid_update_impl(vscore, bt, vd, lr, *, k, matmul):
    """Per-iteration mesh valid-score update, one program — the same
    predictor selection and scale-then-predict arithmetic as the fused
    block (the predictors only gather/select leaf values)."""
    from ..learner.serial import predict_built_tree_matmul
    bts = bt._replace(leaf_value=lr * bt.leaf_value)
    pred = (predict_built_tree_matmul(bts, vd, vd.bins) if matmul
            else predict_built_tree(bts, vd, vd.bins))
    return vscore.at[:, k].add(pred)


# donated + plain lowerings of the mesh update programs: the gated
# dispatchers below pick per call (the gbdt block-fn idiom) — on
# TPU/GPU the running state updates in place, on CPU the zero-copy
# host-read hazard keeps donation off (see _donation_enabled)
_mesh_score_update_donated = functools.partial(
    jax.jit, static_argnames=("k",), donate_argnums=(0,))(
        _mesh_score_update_impl)
_mesh_score_update_plain = functools.partial(
    jax.jit, static_argnames=("k",))(_mesh_score_update_impl)
_mesh_valid_update_donated = functools.partial(
    jax.jit, static_argnames=("k", "matmul"), donate_argnums=(0,))(
        _mesh_valid_update_impl)
_mesh_valid_update_plain = functools.partial(
    jax.jit, static_argnames=("k", "matmul"))(_mesh_valid_update_impl)


def _mesh_score_update(scores, lv, row_leaf, lr, *, k):
    if _donation_enabled():
        return _mesh_score_update_donated(scores, lv, row_leaf, lr, k=k)
    return _mesh_score_update_plain(scores, lv, row_leaf, lr, k=k)


def _mesh_valid_update(vscore, bt, vd, lr, *, k, matmul):
    if _donation_enabled():
        return _mesh_valid_update_donated(vscore, bt, vd, lr, k=k,
                                          matmul=matmul)
    return _mesh_valid_update_plain(vscore, bt, vd, lr, k=k, matmul=matmul)


def growth_params_from_config(c: Config) -> GrowthParams:
    return GrowthParams(
        num_leaves=c.num_leaves, max_depth=c.max_depth,
        wave_size=1 if c.growth_mode == "leafwise" else 0,
        split=split_params_from_config(c))


class GBDT:
    """Gradient Boosting Decision Tree booster."""

    boosting_name = "gbdt"
    average_output = False

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction] = None,
                 fobj=None):
        self.config = config
        self.train_set = train_set
        self.fobj = fobj or config.extra.get("fobj")
        self.objective = objective
        # host trees are materialized lazily: device BuiltTrees accumulate
        # in _pending and convert in ONE batched device_get (each host
        # round-trip through a remote-device tunnel costs ~100ms, so the
        # training loop must not fetch per iteration)
        self._host_models: List[Tree] = []
        # pending entries: (device tree pytree, lr, bias, n_models);
        # n_models > 1 marks a scan-stacked block with leading axis [NB(, K)]
        self._pending: List[Tuple[BuiltTree, float, float, int]] = []
        self.iter = 0
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self.init_score_value = 0.0
        self.shrinkage_rate = config.learning_rate
        self.valid_sets: List[BinnedDataset] = []
        self.valid_names: List[str] = []
        self._valid_device: List[DeviceData] = []
        self._valid_scores: List[jnp.ndarray] = []
        self.metrics: List[Metric] = []
        self.feature_names: List[str] = []
        self.max_feature_idx = 0
        self._stacked_cache = None
        self._eval_history: Dict[str, Dict[str, List[float]]] = {}

        self.num_class = max(1, config.num_class)
        self.num_tree_per_iteration = config.num_tree_per_iteration
        self.mesh_ctx = None
        self._row_pad = 0
        # early-stopping bookkeeping lives on the INSTANCE (not train()
        # locals) so snapshots capture it and a resumed run keeps
        # counting stall rounds from where the dead run stood
        self._es_state: Dict[str, Dict] = {
            "best_scores": {}, "best_iter": {}, "key_order": []}
        # resume flag: train(num_iterations) treats the count as the
        # TOTAL target after resume_from_snapshot (the dead run's
        # target), vs "additional rounds" for continued training
        self._resumed = False
        # device-time attribution session (obs/profiler.py) while
        # train() runs under LGBM_TPU_PROFILE; dispatch-gap timestamp
        # for the ROADMAP item-1 host-latency counters
        self._profiler = None
        self._t_dispatch_ret: Optional[float] = None
        # stall watchdog (obs/health.py), live only inside train()
        self._watchdog = None

        if train_set is not None:
            self._init_train(train_set)

    # ------------------------------------------------------------------
    def _init_train(self, train_set: BinnedDataset) -> None:
        c = self.config
        n = train_set.num_data
        self.num_data = n
        # distributed setup: mesh + row padding to a shard multiple
        # (reference: Network::Init + mod-rank row sharding; here one SPMD
        # program over a jax Mesh, rows padded & masked out-of-bag)
        self.mesh_ctx = None
        self._row_pad = 0
        self._pr = None      # ProcessRows: multi-process row-block layout
        if c.tree_learner != "serial":
            from ..parallel.mesh import MeshContext, ProcessRows
            if len(jax.devices()) > 1 or c.mesh_shape:
                self.mesh_ctx = MeshContext(c)
                if c.tree_learner in ("data", "voting"):
                    if jax.process_count() > 1:
                        # cross-process training: this process's local
                        # rows become one padded block of the global
                        # row-sharded arrays (reference mod-rank
                        # sharding, dataset_loader.cpp:639-742).
                        # gbdt/goss/rf compose with it (GOSS samples on
                        # device from global gradients; RF's baseline
                        # scores globalize like the live scores); DART
                        # is the documented descope — its drop
                        # bookkeeping replays per-tree predictions
                        # through host-addressable scores (README
                        # "Multi-process training")
                        if self.boosting_name == "dart":
                            raise NotImplementedError(
                                "boosting=dart is not supported with "
                                "multi-process training (documented "
                                "descope: per-tree drop/renormalize "
                                "score patching assumes addressable "
                                "scores); use gbdt/goss/rf, or "
                                "single-process multi-device meshes")
                        self._pr = ProcessRows(self.mesh_ctx, n)
                        n = self.num_data = self._pr.n_pad
                    else:
                        n_pad = self.mesh_ctx.pad_rows(n)
                        self._row_pad = n_pad - n
            else:
                log_warning(f"tree_learner={c.tree_learner} requested but "
                            f"only one device is visible; running serial")
        if self._pr is not None:
            self.device_data = self._to_device_multiproc(train_set)
        elif self._row_pad:
            padded = BinnedDataset.__new__(BinnedDataset)
            padded.__dict__.update(train_set.__dict__)
            padded.bins = np.concatenate(
                [train_set.bins,
                 np.zeros((self._row_pad, train_set.bins.shape[1]),
                          train_set.bins.dtype)])
            self.device_data = to_device(padded)
        else:
            self.device_data = to_device(train_set)
        self.feature_names = train_set.feature_names
        self.max_feature_idx = train_set.num_total_features - 1
        if self.objective is None and c.objective != "none":
            self.objective = create_objective(c)
        if self.objective is not None:
            self.objective.init(train_set.metadata, train_set.num_data)
            self.num_tree_per_iteration = self.objective.num_model_per_iteration
            if self._pr is not None:
                # gradients compute over the GLOBAL row axis: every
                # per-row objective array becomes row-sharded (pad rows
                # 0), dataset-level statistics recompute globally
                from ..io.distributed import jax_process_allgather
                self.objective.globalize_rows(self._pr.globalize,
                                              jax_process_allgather)

        K = self.num_tree_per_iteration
        # scores built host-side and device_put in one transfer: eager
        # jnp.zeros/full each compile a mini-program over the tunnel
        n_local = train_set.num_data
        scores_np = np.zeros((n_local if self._pr is not None else n, K),
                             np.float32)
        # init score from metadata (continued training / custom init)
        ms = train_set.metadata.init_score
        if ms is not None:
            # numcheck: disable=NUM002 -- ingest cast of user-supplied
            # init_score to the f32 score dtype: a data conversion at
            # the model boundary, not an accumulation losing precision
            scores_np = np.asarray(ms, np.float64).reshape(
                -1, K, order="F").astype(np.float32)
        elif c.boost_from_average and self.objective is not None:
            if self._pr is not None:
                # the init score must come from GLOBAL statistics, not
                # this shard's (ranks would diverge otherwise)
                from ..io.distributed import jax_process_allgather
                v = self.objective.boost_from_score_global(
                    jax_process_allgather)
            else:
                v = self.objective.boost_from_score()
            if v != 0.0:
                self.init_score_value = v
                scores_np = np.full_like(scores_np, v)
                log_info(f"boost from average: init score = {v:.6f}")
        if self._pr is not None:
            self.scores = self._pr.globalize(scores_np)
        elif self.mesh_ctx is not None:
            # partition-rule placement (parallel/partition.py): the
            # running scores live under the registry's `scores` rule so
            # the fused mesh block consumes them in place — an
            # unregistered name would raise here, not silently default
            self.scores = self.mesh_ctx.place_scores(scores_np)
        else:
            self.scores = jax.device_put(scores_np)

        self.growth = growth_params_from_config(c)
        self._label = train_set.metadata.label
        self._weight = train_set.metadata.weight
        self._query = train_set.metadata.query_boundaries
        self._setup_metrics()

        self._setup_build_program()

    def _to_device_multiproc(self, train_set: BinnedDataset) -> DeviceData:
        """Cross-process DeviceData: the bins rows are a global
        row-sharded array assembled from every process's local block;
        per-feature metadata is identical everywhere -> replicated.
        (feature_meta_np keeps this from uploading a throwaway local
        copy of the bins matrix.)"""
        from ..io.device import feature_meta_np
        pr = self._pr
        meta = feature_meta_np(train_set)
        rep = {k: pr.replicate(meta[k]) for k in (
            "bin_offsets", "num_bins", "default_bins", "missing_types",
            "is_categorical", "nan_bins", "feat_group", "feat_offset")}
        return DeviceData(
            bins=pr.globalize(train_set.bins),
            total_bins=meta["total_bins"], max_bins=meta["max_bins"],
            has_categorical=meta["has_categorical"],
            max_group_bins=meta["max_group_bins"],
            is_bundled=meta["is_bundled"],
            has_missing=meta["has_missing"], **rep)

    def _setup_build_program(self) -> None:
        """(Re)build the jitted tree-build closure from the CURRENT config
        and growth params; called at init and after ``reset_config`` (a
        stale closure would silently keep the old hyperparameters)."""
        counter_add("gbdt.program_rebuilds")
        c = self.config
        # one jitted tree-build program, traced once per (shapes, params)
        growth = self.growth
        if self.mesh_ctx is None:
            # once-per-dataset transposed bins for the Pallas kernels
            from ..learner.serial import default_hist_mode, resolve_backend
            from ..ops.pallas_histogram import transpose_bins
            # config hist_mode wins; env var / bf16 default otherwise
            # (the gpu_use_dp analog — ADVICE r2)
            from ..learner.serial import effective_hist_mode
            hist_mode = effective_hist_mode(
                c.hist_mode or default_hist_mode(), self.num_data)
            self._bins_t = None
            backend = resolve_backend(self.device_data, growth.num_leaves,
                                      hist_mode=hist_mode)
            # the fused 32-iteration block is only safe on the Pallas
            # backends ("pallas"/"compact"): 32 chained SCATTER tree
            # builds in one program exceeded the device watchdog and
            # killed the worker at >256 bins x 300k rows (r4); scatter
            # configs dispatch per-iteration instead
            from ..learner.serial import uses_pallas
            self._block_backend_ok = (jax.default_backend() != "tpu"
                                      or uses_pallas(backend))
            if uses_pallas(backend):
                bins_host = (self.train_set.bins
                             if self.train_set is not None else None)
                if (bins_host is not None
                        and bins_host.shape[0] <= 1 << 20):
                    # small data: transpose on host — the jitted
                    # transpose's one-time compile over the tunnel
                    # dwarfs the duplicate copy
                    from ..ops.pallas_histogram import transpose_bins_host
                    self._bins_t = jax.device_put(
                        transpose_bins_host(bins_host))
                else:
                    self._bins_t = jax.jit(transpose_bins)(
                        self.device_data.bins)
            from ..utils.timetag import phases_enabled
            if phases_enabled():
                # LGBM_TPU_TIMETAG=phases: unfused per-phase-timed waves
                # (VERDICT r2 #8; reference serial_tree_learner.cpp:12-39).
                # The driver is built ONCE so its jitted phase programs
                # are reused across trees (tags time kernels, not
                # compiles).
                from ..learner.serial import make_phases_driver
                phases_build = make_phases_driver(
                    self.device_data, growth, bins_t=self._bins_t,
                    hist_mode=hist_mode)

                def _raw_build(dd, grad, hess, bag, fmask, bins_t=None):
                    return phases_build(grad, hess, bag_mask=bag,
                                        feature_mask=fmask)
            else:
                def _raw_build(dd, grad, hess, bag, fmask, bins_t=None):
                    from ..ops.pallas_split import split_kernel_disabled
                    return _shared_serial_build(
                        dd, grad, hess, bag, fmask, bins_t, growth.split,
                        num_leaves=growth.num_leaves,
                        max_depth=growth.max_depth,
                        wave_size=growth.wave_size,
                        hist_mode=hist_mode,
                        split_kernel=not split_kernel_disabled())
        else:
            from ..ops.overlap import overlap_enabled
            from ..parallel.learners import build_tree_distributed
            mesh = self.mesh_ctx.mesh
            axis = self.mesh_ctx.data_axis
            lt, tk = c.tree_learner, c.top_k
            dist_hist_mode = c.hist_mode or None
            self._bins_t = None
            # overlap resolved ONCE per program build (not at trace
            # time): an env flip mid-run must not serve a stale trace
            # from the per-instance jit cache
            overlap = overlap_enabled()
            if self._pr is None:
                # place the dataset ONCE under the partition-rule
                # registry (bins row-sharded / replicated per learner
                # type, metadata replicated): every dispatch then
                # consumes it in place instead of re-laying-out the
                # store to the mesh (the multi-process path is already
                # placed via make_array_from_process_local_data)
                self.device_data = self.mesh_ctx.place_data(
                    self.device_data)
            pad = self._row_pad
            # in-program placement constraints come from the SAME
            # registry rules (grad/hess/bag row-sharded for data/
            # voting, replicated for feature) — the registry is the
            # only placement mechanism, eager and traced alike
            grad_ns = self.mesh_ctx.sharding_for("grad")
            hess_ns = self.mesh_ctx.sharding_for("hess")
            bag_ns = self.mesh_ctx.sharding_for("bag_mask")

            def _raw_build(dd, grad, hess, bag, fmask, bins_t=None):
                # row padding + placement INSIDE the jitted program:
                # the old eager per-iteration jnp.concatenate calls
                # were 3 extra host-driven dispatches per tree, each
                # re-placing its output from the default device
                if bag is None:
                    bag = jnp.ones(grad.shape[0], bool)
                if pad:
                    grad = jnp.concatenate(
                        [grad, jnp.zeros(pad, grad.dtype)])
                    hess = jnp.concatenate(
                        [hess, jnp.zeros(pad, hess.dtype)])
                    bag = jnp.concatenate([bag, jnp.zeros(pad, bool)])
                grad = jax.lax.with_sharding_constraint(grad, grad_ns)
                hess = jax.lax.with_sharding_constraint(hess, hess_ns)
                bag = jax.lax.with_sharding_constraint(bag, bag_ns)
                return build_tree_distributed(
                    mesh, axis, lt, dd, grad, hess, growth,
                    bag_mask=bag, feature_mask=fmask, top_k=tk,
                    hist_mode=dist_hist_mode, overlap=overlap)

            # the fused mesh scan block (see _make_block_fn) runs this
            # same build per scan-body iteration; watchdog-wise the
            # mesh follows the serial rule — long chained-scatter
            # blocks only on Pallas-capable configs
            from ..learner.serial import (default_hist_mode,
                                          effective_hist_mode,
                                          resolve_backend, uses_pallas)
            mesh_hist_mode = effective_hist_mode(
                dist_hist_mode or default_hist_mode(), self.num_data)
            mesh_backend = resolve_backend(
                self.device_data, growth.num_leaves, hist_mode=mesh_hist_mode)
            self._block_backend_ok = (jax.default_backend() != "tpu"
                                      or uses_pallas(mesh_backend))
        # serial path: already jitted at module level (shared cache);
        # mesh path: per-instance jit (mesh/axis closed over), with
        # grad/hess donated — they die with the build (every caller
        # hands in per-iteration slices), freeing 2 x [n_pad] f32 of
        # HBM for the wave histograms.  Donation is safe with the
        # dispatch retry: the transient class it covers surfaces at
        # compile/enqueue time, before execution consumes the buffers
        # (LGBM_TPU_DONATE=0 restores undonated dispatches for A/B;
        # CPU never donates — see _donation_enabled).
        # the un-jitted build closure: the fused scan block's body
        # traces it inline (one dispatch per block instead of per
        # iteration — the mesh path included since the partition-rule
        # refactor)
        self._raw_build = _raw_build
        if self.mesh_ctx is None:
            self._jit_build = _raw_build
        elif _donation_enabled():
            self._jit_build = jax.jit(_raw_build, donate_argnums=(1, 2))
        else:
            self._jit_build = jax.jit(_raw_build)
        # recorded for the HBM watermark contract's donation-
        # effectiveness probe (obs/mem_contract.py): only meaningful on
        # backends where the score-state donation is actually armed
        self._donate_active = _donation_enabled()
        self._mem_watermark = None
        self._block_fns: Dict[int, object] = {}
        self._block_len_uses: Dict[int, int] = {}
        self._block_compiling: set = set()
        # live background-compile threads (bounded-shutdown contract:
        # join_background reaps them; non-daemon by design, see
        # _spawn_block_compile)
        self._bg_threads: list = []
        # how often the host checks trees for the no-more-splits stop
        # (reference checks every iteration, gbdt.cpp:435-470; through a
        # remote tunnel each check is a ~100ms round-trip)
        default_sync = 1 if jax.default_backend() == "cpu" else 16
        import os as _os
        self._sync_freq = int(_os.environ.get("LGBM_TPU_SYNC_FREQ",
                                              default_sync))
        # iterations per fused scan dispatch: one dispatch must finish
        # inside the device watchdog, and at big shapes (255 bins x 136
        # features x 2.3M rows) 32 chained iterations exceed it — set
        # LGBM_TPU_BLOCK_CAP=8 to keep each dispatch under ~10 s there
        self._block_cap = max(1, int(_os.environ.get("LGBM_TPU_BLOCK_CAP",
                                                     self._BLOCK_CAP)))

    def _setup_metrics(self) -> None:
        c = self.config
        names = list(c.metric)
        if not names and c.objective != "none":
            names = [default_metric_for_objective(c.objective)]
        self.metrics = []
        seen = set()
        for nm in names:
            m = create_metric(nm, c)
            if m is not None and m.names[0] not in seen:
                self.metrics.append(m)
                seen.add(m.names[0])

    def add_valid(self, valid_set: BinnedDataset, name: str) -> None:
        """Reference GBDT::AddValidDataset (gbdt.cpp:124+)."""
        if getattr(self, "_block_fns", None):
            # block programs take the valid DeviceData/score pytrees as
            # arguments; a new valid set changes their structure, so
            # cached compiles are for the wrong signature
            self._block_fns = {}
            self._block_len_uses = {}
            self._block_compiling = set()
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        self._valid_device.append(to_device(valid_set))
        K = self.num_tree_per_iteration
        n = valid_set.num_data
        # when trees already exist, tree 0 carries the init bias (AddBias)
        init = 0.0 if self.models else self.init_score_value
        score = jnp.full((n, K), init, jnp.float32)
        ms = valid_set.metadata.init_score
        if ms is not None:
            score = jnp.asarray(
                np.asarray(ms, np.float64).reshape(-1, K, order="F"), jnp.float32)
        if self.mesh_ctx is not None and self._pr is None:
            # valid state rides the fused mesh block as scan carries:
            # place the valid store + running scores ONCE under their
            # `valid/<i>/...` partition rules (replicated)
            vd, score = self.mesh_ctx.place_valid(
                len(self._valid_device) - 1, self._valid_device[-1], score)
            self._valid_device[-1] = vd
        # replay existing trees (continued training)
        if self.models:
            for it in range(len(self.models) // K):
                for k in range(K):
                    t = self.models[it * K + k]
                    pred = self._predict_host_tree_binned(t, self._valid_device[-1])
                    score = score.at[:, k].add(pred)
        self._valid_scores.append(score)

    # ------------------------------------------------------------------
    def _bagging_mask(self, it: int) -> Optional[jnp.ndarray]:
        """Row subsampling mask (reference Bagging, gbdt.cpp:225-286 —
        PRNG masks instead of index compaction: TPU-idiomatic).

        Stateless in (seed, iteration): the mask is a pure function of
        ``bagging_seed`` and ``it // bagging_freq``, so the fused block
        path derives the *identical* mask on device inside its
        ``lax.scan`` and block/non-block training produce the same
        models."""
        c = self.config
        if c.bagging_freq <= 0 or c.bagging_fraction >= 1.0:
            return None
        counter_add("gbdt.bagging_masks")
        from ..obs import determinism
        determinism.rng_site("gbdt.bag_mask", "bagging_seed/epoch")
        return _device_bag_mask(c.bagging_seed, it // c.bagging_freq,
                                self.num_data, c.bagging_fraction)

    def _block_sample(self, G, H, it):
        """Per-iteration row sampling inside the fused block: ``(G, H,
        it) -> (G, H, bag_mask_or_None)``.  Plain GBDT applies the
        bagging mask; GOSS overrides with gradient-based one-side
        sampling.  Both are pure in (seed, iteration), so the block and
        per-iteration paths build identical trees."""
        c = self.config
        if c.bagging_freq > 0 and c.bagging_fraction < 1.0:
            return G, H, _device_bag_mask(
                c.bagging_seed, it // c.bagging_freq, self.num_data,
                c.bagging_fraction)
        return G, H, None

    def _feature_mask(self, tree_idx: int) -> Optional[jnp.ndarray]:
        """Per-tree feature subsampling (serial_tree_learner.cpp:240-266),
        stateless in (seed, global tree index) — see _bagging_mask."""
        c = self.config
        F = self.device_data.num_features
        if c.feature_fraction >= 1.0:
            return None
        k = max(1, int(c.feature_fraction * F))
        from ..obs import determinism
        determinism.rng_site("gbdt.feature_mask",
                             "feature_fraction_seed/tree_idx")
        return _device_feature_mask(c.feature_fraction_seed, tree_idx, F, k)

    def _gradients(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(grad, hess) each [n, K] (reference Boosting(), gbdt.cpp:194-202).

        ``health.nan_grad`` fault seam: while armed, one gradient
        element is poisoned to NaN — the numerics-divergence class the
        window-boundary sentinels (``obs/health.py``) must catch and
        attribute to the right window (the NaN folds into the score
        state through this iteration's tree)."""
        g, h = self._gradients_impl()
        from ..utils.faults import fault_flag
        if fault_flag("health.nan_grad"):
            g = g.at[0, 0].set(jnp.nan)
        return g, h

    def _gradients_impl(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        if self.fobj is not None:
            g, h = self.fobj(np.asarray(self.scores).reshape(-1, order="F")
                             if self.num_tree_per_iteration > 1
                             else np.asarray(self.scores[:, 0]),
                             self.train_set)
            g = jnp.asarray(np.asarray(g, np.float32))
            h = jnp.asarray(np.asarray(h, np.float32))
            K = self.num_tree_per_iteration
            return (g.reshape(-1, K, order="F") if g.ndim == 1 and K > 1 else
                    g.reshape(-1, K)), \
                   (h.reshape(-1, K, order="F") if h.ndim == 1 and K > 1 else
                    h.reshape(-1, K))
        K = self.num_tree_per_iteration
        if K > 1:
            g, h = self.objective.get_gradients(self.scores)
            return g, h
        g, h = self.objective.get_gradients(self.scores[:, 0])
        return g[:, None], h[:, None]

    # -- lazy host-tree materialization --------------------------------
    @property
    def models(self) -> List[Tree]:
        """Host Tree list; materializes pending device trees on access."""
        self._flush_pending()
        return self._host_models

    @models.setter
    def models(self, value: List[Tree]) -> None:
        self._pending = []
        self._host_models = list(value)

    def _num_models(self) -> int:
        return len(self._host_models) + sum(p[3] for p in self._pending)

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        from ..utils.timetag import tag
        with obs_span("gbdt.to_host_trees"), tag("to_host_tree"):
            # ONE device->host transfer for all pending trees/blocks
            fetched = jax.device_get([p[0] for p in self._pending])
            K = max(1, self.num_tree_per_iteration)
            for f, (_, lr, bias, count) in zip(fetched, self._pending):
                # blocks carry a leading scan axis even at length 1; the
                # fixed-length block may hold masked residue iterations
                # past `count` trees — never materialized
                if np.ndim(f.num_leaves) == 0:
                    parts = [f]
                elif K == 1:
                    NB = min(f.num_leaves.shape[0], count)
                    parts = [jax.tree.map(lambda a, i=i: a[i], f)
                             for i in range(NB)]
                else:
                    NB = min(f.num_leaves.shape[0], count // K)
                    parts = [jax.tree.map(lambda a, i=i, k=k: a[i, k], f)
                             for i in range(NB) for k in range(K)]
                for pi, bt_np in enumerate(parts):
                    host = self._to_host_tree(bt_np)
                    host.shrinkage(lr)
                    if bias and pi < K:
                        # init score lives in the first tree per class
                        host.add_bias(bias)
                    self._host_models.append(host)
            self._pending = []

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[jnp.ndarray] = None,
                       hess: Optional[jnp.ndarray] = None) -> bool:
        """One boosting iteration (reference TrainOneIter gbdt.cpp:377-472).
        Returns True if training should stop (no further splits possible).

        Stays on device: no host sync per iteration.  The stump check
        (reference's should_continue) runs every `_sync_freq` iterations;
        stump trees contribute zero score either way (their leaf value is
        zeroed device-side, matching the reference's skipped UpdateScore)."""
        with obs_span("gbdt.iteration", it=self.iter):
            return self._train_one_iter(grad, hess)

    def _train_one_iter(self, grad: Optional[jnp.ndarray],
                        hess: Optional[jnp.ndarray]) -> bool:
        from ..utils.timetag import tag
        c = self.config
        with tag("boosting(grad)") as done:
            if grad is None or hess is None:
                grad, hess = self._gradients()
            done((grad, hess))
        bag = self._bagging_mask(self.iter)

        K = self.num_tree_per_iteration
        iter_trees = []
        raw_leaf_values = []    # pre-zeroing, for the numerics sentinel
        for k in range(K):
            fmask = self._feature_mask(self.iter * K + k)
            self._gap_dispatch_start()
            with tag("tree") as done:
                bt = self._build_tree(grad[:, k], hess[:, k], bag, fmask)
                self._gap_dispatch_done()
                done(bt.num_leaves)
            bt = self._renew_leaves(bt, k)
            # stump => zero contribution (reference skips UpdateScore and
            # Shrinkage for num_leaves<=1 trees, gbdt.cpp:435-460).  The
            # UN-zeroed leaf values are kept (a device reference, no
            # dispatch): a non-finite gradient always yields a stump
            # whose root value is non-finite, and the zeroing below is
            # exactly what used to hide that from every later check —
            # the stump-stop fetch inspects them (obs/health.py).
            raw_leaf_values.append(bt.leaf_value)
            bt = bt._replace(leaf_value=jnp.where(
                bt.num_leaves > 1, bt.leaf_value,
                jnp.zeros_like(bt.leaf_value)))
            iter_trees.append(bt)
            with tag("score") as done:
                self._update_scores(bt, k)
                done(self.scores)
            bias = (self.init_score_value
                    if (self._num_models() < K
                        and abs(self.init_score_value) > 1e-15) else 0.0)
            # row_leaf ([n]) is only needed for the score update above —
            # drop it so pending trees don't pin O(iters x n) HBM or ship
            # dead bytes through the batched device_get
            self._pending.append((bt._replace(row_leaf=bt.row_leaf[:0],
                                              row_value=bt.row_value[:0]),
                                  self.shrinkage_rate, bias, 1))
        self.iter += 1
        self._stacked_cache = None

        finished = False
        if self._sync_freq > 0 and (self.iter % self._sync_freq == 0):
            with tag("stump_check"):
                nls = jax.device_get([bt.num_leaves for bt in iter_trees])
            if all(int(nl) <= 1 for nl in nls):
                finished = True
                # drop this iteration's stump models (gbdt.cpp:462-468)
                self._pending = self._pending[:-K]
                self.iter -= 1
                from ..obs import health as _health
                if _health.sentinels_enabled():
                    # an all-stump stop is EITHER convergence or a
                    # poisoned gradient (every non-finite grad/hess
                    # NaNs the split gains into a stump whose root
                    # value is non-finite): inspect the pre-zeroing
                    # leaf values — one tiny [K, L] fetch on the rare
                    # stop path, zero extra dispatches
                    _health.check_leaf_values(
                        jax.device_get(raw_leaf_values),
                        window=self.iter)
                log_warning(
                    "stopped training because there are no more leaves "
                    f"that meet the split requirements (iteration "
                    f"{self.iter + 1})")
        return finished

    def _build_tree(self, grad: jnp.ndarray, hess: jnp.ndarray,
                    bag: Optional[jnp.ndarray],
                    fmask: Optional[jnp.ndarray]) -> BuiltTree:
        """Run the jitted tree build (serial or distributed)."""
        if self.mesh_ctx is not None:
            n = self.num_data
            if self._pr is not None:
                pr = self._pr
                if isinstance(bag, jnp.ndarray) and not getattr(
                        bag, "is_fully_addressable", True):
                    # the mask is ALREADY a global row-sharded device
                    # array (multi-process GOSS derives it from global
                    # gradients on device; padding rows pre-masked)
                    if fmask is not None:
                        fmask = pr.replicate(np.asarray(fmask))
                    return self._jit_build(self.device_data, grad, hess,
                                           bag, fmask)
                # cross-process: the bagging mask is a pure function of
                # (seed, iteration) so every rank computes the identical
                # full [n_pad] mask; each contributes its block, with
                # its per-block padding rows masked out-of-bag
                mask = pr.valid_mask_local()
                if bag is not None:
                    full = np.asarray(bag)
                    r = jax.process_index()
                    mask = mask & full[r * pr.per:(r + 1) * pr.per]
                bag = pr.globalize(mask, fill=False)
                if fmask is not None:
                    fmask = pr.replicate(np.asarray(fmask))
                return self._jit_build(self.device_data, grad, hess, bag,
                                       fmask)
            # padding + mesh placement of grad/hess/bag happen INSIDE
            # the jitted program (_raw_build) — one dispatch, no eager
            # per-iteration concat round-trips
            bt = self._jit_build(self.device_data, grad, hess, bag, fmask)
            if self._row_pad:
                bt = bt._replace(row_leaf=bt.row_leaf[:n])
            return bt
        try:
            return self._jit_build(self.device_data, grad, hess, bag,
                                   fmask, self._bins_t)
        except Exception as exc:        # noqa: BLE001 - classified below
            # a fused-split-kernel compile failure (Mosaic/VMEM) demotes
            # to the XLA scan path and re-dispatches once; anything else
            # propagates
            if not self._maybe_split_kernel_fallback(exc):
                raise
            return self._jit_build(self.device_data, grad, hess, bag,
                                   fmask, self._bins_t)

    def _renew_leaves(self, bt: BuiltTree, k: int) -> BuiltTree:
        """Objective-specific leaf re-fit (RenewTreeOutput,
        serial_tree_learner.cpp:592-622 + regression_objective.hpp)."""
        if (self.objective is not None
                and self.objective.need_renew_tree_output):
            new_vals = self.objective.renew_tree_output(
                self.scores[:, k], bt.row_leaf, self.growth.num_leaves)
            if new_vals is not None:
                bt = bt._replace(leaf_value=jnp.where(
                    jnp.arange(self.growth.num_leaves) < bt.num_leaves,
                    new_vals.astype(jnp.float32), bt.leaf_value))
        return bt

    def _update_scores(self, bt: BuiltTree, k: int) -> None:
        lr = self.shrinkage_rate
        if self.mesh_ctx is not None and self._pr is None:
            # one jitted program per update (see _mesh_score_update):
            # byte-identical arithmetic to the fused mesh block AND
            # fewer per-iteration dispatches on the escape-hatch path
            # (multi-process keeps the eager update: its valid stores
            # are process-local while bt/scores span the global mesh)
            self.scores = _mesh_score_update(
                self.scores, bt.leaf_value, bt.row_leaf,
                jnp.float32(lr), k=k)
            for i, vd in enumerate(self._valid_device):
                self._valid_scores[i] = _mesh_valid_update(
                    self._valid_scores[i], bt, vd, jnp.float32(lr), k=k,
                    matmul=not vd.has_categorical)
            return
        if bt.row_value.shape[0] and not (
                self.objective is not None
                and self.objective.need_renew_tree_output):
            # kernel-emitted per-row values (no gather); renewal rewrites
            # leaf_value after emission, so it must take the gather path
            self.scores = self.scores.at[:, k].add(lr * bt.row_value)
        else:
            self.scores = self.scores.at[:, k].add(
                lr * bt.leaf_value[bt.row_leaf])
        for i, vd in enumerate(self._valid_device):
            pred = predict_built_tree(bt, vd, vd.bins)
            self._valid_scores[i] = self._valid_scores[i].at[:, k].add(lr * pred)

    def _to_host_tree(self, bt) -> Tree:
        """Host-side BuiltTree (numpy pytree from ONE device_get) -> Tree
        with real-valued thresholds."""
        ds = self.train_set
        nl = int(bt.num_leaves)
        t = Tree(max(self.growth.num_leaves, 2))
        t.num_leaves = nl
        m = nl - 1
        if m == 0:
            t.leaf_value[0] = float(bt.leaf_value[0])
            t.leaf_count[0] = int(bt.leaf_count[0])
            return t
        feat_inner = np.asarray(bt.feature)[:m]
        thr_bin = np.asarray(bt.threshold_bin)[:m]
        dl = np.asarray(bt.default_left)[:m]
        is_cat = np.asarray(bt.is_categorical)[:m]
        cat_mask = np.asarray(bt.cat_mask)[:m]
        t.split_feature_inner[:m] = feat_inner
        t.left_child[:m] = np.asarray(bt.left_child)[:m]
        t.right_child[:m] = np.asarray(bt.right_child)[:m]
        t.split_gain[:m] = np.asarray(bt.gain)[:m]
        t.internal_value[:m] = np.asarray(bt.internal_value)[:m]
        t.internal_count[:m] = np.asarray(bt.internal_count)[:m]
        t.leaf_value[:nl] = np.asarray(bt.leaf_value)[:nl]
        t.leaf_count[:nl] = np.asarray(bt.leaf_count)[:nl]
        t.leaf_depth[:nl] = np.asarray(bt.leaf_depth)[:nl]
        for node in range(m):
            inner = int(feat_inner[node])
            orig = ds.used_features[inner]
            mapper = ds.mappers[orig]
            t.split_feature[node] = orig
            mt = mapper.missing_type
            if is_cat[node]:
                bins = np.nonzero(cat_mask[node])[0]
                bins = bins[bins < mapper.num_bin]
                values = sorted(int(mapper.bin_2_categorical[b]) for b in bins)
                from ..models.tree import _construct_bitset
                ci = t.num_cat
                t.decision_type[node] = np.int8(1 | ((mt & 3) << 2))
                t.threshold[node] = float(ci)
                t.threshold_bin[node] = ci
                bitset = _construct_bitset(values)
                t.cat_threshold.extend(bitset)
                t.cat_boundaries.append(len(t.cat_threshold))
                t.cat_left_bins.append(np.asarray(sorted(bins), np.int32))
                t.num_cat += 1
            else:
                dt = np.int8((mt & 3) << 2)
                if dl[node]:
                    dt |= np.int8(2)
                t.decision_type[node] = dt
                t.threshold_bin[node] = int(thr_bin[node])
                t.threshold[node] = mapper.threshold_value(int(thr_bin[node]))
        return t

    @staticmethod
    def _bundle_kw(dd: DeviceData) -> Dict[str, jnp.ndarray]:
        if not dd.is_bundled:
            return {}
        return {"feat_group": dd.feat_group, "feat_offset": dd.feat_offset,
                "num_bins": dd.num_bins}

    def _predict_host_tree_binned(self, tree: Tree, dd: DeviceData) -> jnp.ndarray:
        return self._predict_host_trees_binned([tree], dd)

    def _predict_host_trees_binned(self, trees: List[Tree],
                                   dd: DeviceData) -> jnp.ndarray:
        """SUMMED per-row output of ``trees`` in one stacked dispatch
        (predict_binned accumulates over the stacked tree axis) — the
        batched form DART's drop/renormalize pass relies on.  The tree
        axis pads to a power of two with zero stumps: DART's drop count
        varies every iteration and an unpadded stack would compile one
        program per distinct count."""
        if len(trees) > 1:
            pad = (1 << (len(trees) - 1).bit_length()) - len(trees)
            trees = list(trees) + [Tree(2)] * pad   # stumps: 0 output
        st = stack_trees(trees, max_bins=dd.max_bins,
                         pad_leaves=self.growth.num_leaves
                         if self.train_set is not None else 0)
        pred = predict_binned(st, dd.bins, dd.nan_bins, dd.default_bins,
                              dd.missing_types, **self._bundle_kw(dd))
        if dd is self.device_data and self._row_pad:
            pred = pred[:self.num_data]     # drop distributed padding rows
        return pred

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """Reference RollbackOneIter (gbdt.cpp:474-490)."""
        if self.iter <= 0:
            return
        K = self.num_tree_per_iteration
        for k in range(K):
            tree = self.models.pop()
            kk = K - 1 - k
            pred = self._predict_host_tree_binned(tree, self.device_data)
            self.scores = self.scores.at[:, kk].add(-pred)
            for i, vd in enumerate(self._valid_device):
                vpred = self._predict_host_tree_binned(tree, vd)
                self._valid_scores[i] = self._valid_scores[i].at[:, kk].add(-vpred)
        self.iter -= 1
        self._stacked_cache = None

    def merge_from(self, other: "GBDT") -> None:
        """Merge the other booster's trees in FRONT of this booster's, as
        deep copies (reference GBDT::MergeFrom, gbdt.h:50-67: other's
        trees are pushed first, then the original models, every tree
        copy-constructed — so iteration-limited predict/save and
        tree-indexed leaf access order like the reference, and mutating
        either booster afterwards never aliases the other).  Scores are
        refreshed from the merged trees when a train set is attached."""
        import copy
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            raise ValueError("cannot merge boosters with different "
                             "num_tree_per_iteration")
        new = [copy.deepcopy(t) for t in other.models]
        self.models = new + list(self.models)
        K = max(1, self.num_tree_per_iteration)
        self.iter = len(self._host_models) // K
        if self.train_set is not None:
            for j, tree in enumerate(new):
                kk = j % K
                pred = self._predict_host_tree_binned(tree, self.device_data)
                self.scores = self.scores.at[:, kk].add(pred)
                for i, vd in enumerate(self._valid_device):
                    vpred = self._predict_host_tree_binned(tree, vd)
                    self._valid_scores[i] = (
                        self._valid_scores[i].at[:, kk].add(vpred))
        self._stacked_cache = None

    def load_model_trees(self, text: str) -> None:
        """Install a saved model's trees into THIS booster, keeping its
        train set and config (ResetTrainingData continue path,
        c_api.h:382-389): scores are replayed so further training
        continues from the loaded model."""
        donor = GBDT(self.config, None)
        donor.load_model_from_string(text)
        self.models = []
        self.iter = 0
        self.merge_from(donor)

    def reset_config(self, params: Dict[str, str]) -> None:
        """Reference ResetConfig (c_api.cpp Booster::ResetConfig): re-read
        training hyperparameters; the dataset and model are kept."""
        from ..config import canonicalize_params
        self.config.update(canonicalize_params(dict(params)))
        self.config.check()
        self.shrinkage_rate = self.config.learning_rate
        if self.train_set is not None:
            self.growth = growth_params_from_config(self.config)
            self._setup_metrics()
            self._setup_build_program()   # drop stale growth/hist closures

    def set_leaf_value(self, tree_idx: int, leaf_idx: int,
                      val: float) -> None:
        """Reference SetLeafValue (c_api.h:723-734); adjusts train scores
        by the delta like GBDT does via the score updater."""
        models = self.models
        tree = models[tree_idx]
        old = float(tree.leaf_value[leaf_idx])
        tree.leaf_value[leaf_idx] = val
        self._stacked_cache = None
        if self.train_set is not None and abs(val - old) > 0:
            kk = tree_idx % max(1, self.num_tree_per_iteration)
            pred_new = self._predict_host_tree_binned(tree, self.device_data)
            tree.leaf_value[leaf_idx] = old
            pred_old = self._predict_host_tree_binned(tree, self.device_data)
            tree.leaf_value[leaf_idx] = val
            self.scores = self.scores.at[:, kk].add(pred_new - pred_old)

    # ------------------------------------------------------------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        if self._pr is not None:
            # global scores span other processes' devices: evaluate this
            # rank's own rows (the reference's machines likewise report
            # their local shard's training metric)
            return self._eval_set("training", self._pr.local_np(self.scores),
                                  self._label, self._weight, self._query)
        return self._eval_set("training", np.asarray(self.scores),
                              self._label, self._weight, self._query)

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for i, vs in enumerate(self.valid_sets):
            md = vs.metadata
            out.extend(self._eval_set(
                self.valid_names[i], np.asarray(self._valid_scores[i]),
                md.label, md.weight, md.query_boundaries))
        return out

    def _eval_set(self, name, scores, label, weight, query):
        results = []
        if label is None:
            return results
        label = np.asarray(label)
        s = scores if scores.shape[1] > 1 else scores[:, 0]
        for m in self.metrics:
            for mname, val, hib in m.eval(label, s, weight, query):
                results.append((name, mname, val, hib))
        return results

    # -- fused multi-iteration training blocks --------------------------
    def _can_block(self) -> bool:
        """Whether iterations can run as ONE jitted ``lax.scan`` block.

        The remote-device tunnel charges ~ms per enqueued op; a block
        collapses a whole window of iterations into a single dispatch
        (gradients → tree build → score update chained on device).
        Single-process device MESHES ride the same fused block since
        the partition-rule refactor: the scan body traces the
        distributed build (shard_map + overlapped psum wave) in place
        of the serial one, so a d-chip mesh pays one dispatch per
        window instead of one per iteration (``LGBM_TPU_MESH_BLOCK=0``
        is the per-iteration escape hatch / A-B baseline).  Excluded:
        multi-process training (per-iteration host-side mask
        globalization), custom fobj (host callback), leaf renewal
        (quantile-style refit), non-plain boosters (DART/RF override
        the iteration), and the per-phase timetag debug mode
        (host-driven waves).  Valid sets stay IN the block since r5:
        their per-tree scoring runs on device inside the scan
        (path-agreement matmul / node walk).  Bagging and
        feature_fraction stay IN the block: their masks are pure
        functions of (seed, iteration) / (seed, tree index), derived on
        device inside the scan body — identical to the per-iteration
        path's masks."""
        from ..utils.timetag import phases_enabled
        if phases_enabled():
            return False
        if _os.environ.get("LGBM_TPU_NO_BLOCK"):
            # debug / watchdog escape hatch: slow backends (scatter at
            # large n) can push a 32-iteration block past the device's
            # dispatch watchdog; per-iteration dispatches stay short
            return False
        if self.mesh_ctx is not None and self._pr is not None:
            return False
        return (self.boosting_name in ("gbdt", "goss")
                and self.fobj is None
                and self.objective is not None
                and not self.objective.need_renew_tree_output
                and getattr(self, "_block_backend_ok", True))

    def _block_fn(self, cap: int):
        """A jitted fixed-length-``cap`` scan block.  Iterations past
        ``n_active`` run masked: their score update is discarded and
        their trees are never materialized host-side.  Masking decouples
        requested block length from compiled scan length — compile
        count, not FLOPs, is the real cold-start cost on a remote TPU
        (~12-30 s per program vs ~10 ms per masked iteration).  See
        train_block for the reuse policy."""
        fn = self._block_fns.get(cap)
        if fn is not None:
            return fn
        fn = self._make_block_fn(cap)
        self._block_fns[cap] = fn
        return fn

    def _make_block_fn(self, cap: int):
        """Build (without caching) the jitted length-``cap`` block."""
        obj = self.objective
        growth = self.growth
        K = self.num_tree_per_iteration
        c = self.config
        n = self.num_data
        F = self.device_data.num_features
        ff_on = c.feature_fraction < 1.0
        kf = max(1, int(c.feature_fraction * F))

        # dd/bins_t are ARGUMENTS, not closures: closed-over device
        # arrays embed as constants in the compile payload — 28 MB of
        # bins at 1M rows made every remote compile ship a ~32 MB
        # program, and a 10.5M-row store (294 MB) overflowed the compile
        # tunnel's request limit outright (HTTP 413).  Valid sets ride
        # the same way: their DeviceData + running scores are scan
        # carries, so train-with-valid (+ early stopping at window
        # boundaries) STAYS on the fused path (VERDICT r4 #1; the
        # reference likewise scores valid data per tree without
        # decelerating training, gbdt.cpp:492+, score_updater.hpp:54-100)
        from ..learner.serial import (predict_built_tree,
                                      predict_built_tree_matmul)
        # the mesh path's scan body traces the SAME distributed build
        # closure the per-iteration path jits (_raw_build: in-program
        # row padding + registry sharding constraints + shard_map wave
        # loop), so the flight-recorder collective schedule per trace —
        # one hist_psum fingerprint per wave — is identical on both
        # paths; only the dispatch count changes (one per window)
        mesh_build = self._raw_build if self.mesh_ctx is not None else None

        def block(dd, bins_t, vds, scores, vscores, lr, it0, n_active):
            def body(carry, it):
                scores, vscores = carry
                active = it - it0 < n_active
                scores_in, vscores_in = scores, vscores
                if K == 1:
                    g, h = obj.get_gradients(scores[:, 0])
                    G, H = g[:, None], h[:, None]
                else:
                    G, H = obj.get_gradients(scores)
                # sampling derived on device, pure in iteration — the
                # same functions the per-iteration path uses, so bagged
                # (and GOSS: _block_sample override) configs stay on
                # the fused fast path
                G, H, bag = self._block_sample(G, H, it)
                # BYTE-identity fence (serial AND mesh since the
                # out-of-core round): eagerly — and in the streamed
                # trainer's standalone per-block programs — gradients
                # materialize as f32 program outputs before the build
                # consumes them; fused, XLA would contract producer/
                # consumer mul+add chains into FMAs with different
                # last-ulp rounding.  The barrier reproduces that
                # program boundary at zero runtime cost, which is what
                # lets boosting/streaming.py match this body bitwise.
                G, H = jax.lax.optimization_barrier((G, H))
                if bag is not None:
                    bag = jax.lax.optimization_barrier(bag)
                outs = []
                for k in range(K):
                    fmask = (_device_feature_mask(c.feature_fraction_seed,
                                                  it * K + k, F, kf)
                             if ff_on else None)
                    if mesh_build is not None:
                        bt = mesh_build(dd, G[:, k], H[:, k], bag, fmask)
                    else:
                        bt = build_tree(dd, G[:, k], H[:, k], growth,
                                        bag_mask=bag, feature_mask=fmask,
                                        bins_t=bins_t,
                                        hist_mode=c.hist_mode or None)
                    lv = jnp.where(bt.num_leaves > 1, bt.leaf_value,
                                   jnp.zeros_like(bt.leaf_value))
                    bt = bt._replace(leaf_value=lv)
                    if mesh_build is not None:
                        # byte-identity vs the per-iteration mesh path
                        # (LGBM_TPU_MESH_BLOCK=0): the fence keeps the
                        # build subgraph's internal fusion identical to
                        # its standalone jit, and the update mirrors
                        # _mesh_score_update / _mesh_valid_update's
                        # contraction-proof scale-then-gather shape —
                        # identical last-ulp rounding in any fusion
                        # context
                        bt = jax.lax.optimization_barrier(bt)
                        lv_s = lr * bt.leaf_value            # [L]
                        scores = scores.at[:, k].add(
                            lv_s[bt.row_leaf[:scores.shape[0]]])
                        bts = bt._replace(leaf_value=lv_s)
                        vscores = tuple(
                            vs.at[:, k].add(
                                predict_built_tree(bts, vd, vd.bins)
                                if vd.has_categorical else
                                predict_built_tree_matmul(bts, vd,
                                                          vd.bins))
                            for vs, vd in zip(vscores, vds))
                    else:
                        # serial branch fenced like the mesh branch
                        # since the out-of-core round: the barrier
                        # keeps the build subgraph's fusion identical
                        # to its standalone jit, and the updates use
                        # the contraction-proof scale-then-gather /
                        # scale-then-predict shapes — so the streamed
                        # trainer's standalone per-block dispatches
                        # (boosting/streaming.py) reproduce the same
                        # last-ulp rounding in any fusion context
                        bt = jax.lax.optimization_barrier(bt)
                        lv_s = lr * bt.leaf_value            # [L]
                        if bt.row_value.shape[0]:
                            # emitted by the final route kernel (already
                            # stump-masked); avoids the 1M-row gather
                            scores = scores.at[:, k].add(
                                lr * bt.row_value)
                        else:
                            scores = scores.at[:, k].add(
                                lv_s[bt.row_leaf])
                        # valid-set scoring per tree, on device: the
                        # path-agreement matmul (MXU) for numerical
                        # valid sets, the node walk where categorical
                        # splits need the bitset decision
                        bts = bt._replace(leaf_value=lv_s)
                        vscores = tuple(
                            vs.at[:, k].add(
                                predict_built_tree(bts, vd, vd.bins)
                                if vd.has_categorical else
                                predict_built_tree_matmul(bts, vd,
                                                          vd.bins))
                            for vs, vd in zip(vscores, vds))
                    outs.append(bt._replace(row_leaf=bt.row_leaf[:0],
                                            row_value=bt.row_value[:0]))
                stacked = (outs[0] if K == 1 else
                           jax.tree.map(lambda *xs: jnp.stack(xs), *outs))
                # masked residue iteration: keep the pre-iteration scores
                # (its trees are dropped host-side via the pending count)
                scores = jnp.where(active, scores, scores_in)
                vscores = tuple(jnp.where(active, vs, vi)
                                for vs, vi in zip(vscores, vscores_in))
                return (scores, vscores), stacked
            return jax.lax.scan(body, (scores, vscores),
                                it0 + jnp.arange(cap))

        from ..learner.serial import _COMPILE_LEAN_ROWS
        jit_kw = {}
        if _donation_enabled():
            # donate the running score state (train scores + valid
            # scores): the block returns their successors with
            # identical shape/dtype, so XLA aliases the buffers and
            # updates in place — no second [n, K] (+ valid) f32 live
            # set per dispatch.  Safe with _dispatch_retry: its
            # transient class surfaces at compile/enqueue, before
            # execution consumes the inputs; and safe with the
            # split-kernel fallback redispatch, which only ever fires
            # on a COMPILE failure (buffers untouched).
            jit_kw["donate_argnums"] = (3, 4)
        if n <= _COMPILE_LEAN_ROWS and _effort_opt_supported():
            # small data: XLA compile time dominates the cold start and
            # runtime barely responds to optimization effort — measured
            # 6.2 s -> 3.0 s compile with identical ms/iter at 7k rows
            return jax.jit(block, compiler_options={
                "exec_time_optimization_effort": -1.0}, **jit_kw)
        return jax.jit(block, **jit_kw)

    def _spawn_block_compile(self, L: int) -> None:
        """AOT-compile the length-``L`` block program on a background
        thread and install it when ready: recurring residue lengths
        (windowed runs, warm re-trains) upgrade from a borrowed longer
        program to the right size WITHOUT ever stalling the training
        loop on a 10-30 s XLA compile."""
        if L in self._block_fns or L in self._block_compiling:
            return
        counter_add("gbdt.block_compiles_bg")
        self._block_compiling.add(L)
        fn = self._make_block_fn(L)
        # install into THIS config generation's cache object: a
        # reset_config between the spawn and the install swaps the dict,
        # so a stale-config program can only ever land in the dead one
        fns = self._block_fns
        # avals only — capturing live arrays would pin the superseded
        # scores buffer (and a second device_data reference) for the
        # whole compile
        aval = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
            jnp.shape(x), jnp.result_type(x))
        args = (jax.tree.map(aval, self.device_data),
                jax.tree.map(aval, self._bins_t),
                jax.tree.map(aval, tuple(self._valid_device)),
                aval(self.scores),
                jax.tree.map(aval, tuple(self._valid_scores)),
                aval(jnp.float32(0)),
                aval(jnp.int32(0)), aval(jnp.int32(0)))

        def work():
            try:
                fns[L] = fn.lower(*args).compile()
                self._block_compiling.discard(L)
            except Exception as exc:    # noqa: BLE001
                # keep L in _block_compiling: a deterministic compile
                # failure must not be retried every window — borrowed
                # programs serve this length forever
                log_warning(f"background compile of block length {L} "
                            f"failed; keeping the borrowed program "
                            f"({exc})")

        import threading
        # NON-daemon: a daemon thread mid-XLA-compile at interpreter
        # shutdown races the runtime teardown and segfaults; a normal
        # thread just delays exit until the compile lands.  The handle
        # is kept so join_background can reap it (bounded shutdown)
        t = threading.Thread(target=work, daemon=False,
                             name=f"lgbm-tpu-block-compile-{L}")
        self._bg_threads = [th for th in self._bg_threads
                            if th.is_alive()]
        self._bg_threads.append(t)
        t.start()

    def join_background(self, timeout: Optional[float] = None) -> bool:
        """Wait for in-flight background block compiles (the bounded-
        shutdown contract: every spawned thread has a join path).
        Returns True when none remain; a compile still running after
        ``timeout`` seconds (per thread) leaves its thread alive —
        non-daemon, so it still finishes before interpreter exit."""
        for t in self._bg_threads:
            t.join(timeout)
        self._bg_threads = [t for t in self._bg_threads if t.is_alive()]
        return not self._bg_threads

    _BLOCK_CAP = 32

    def _dispatch_retry(self, fn, *args):
        """Run a PURE jitted dispatch with transient-failure retries
        (the reference's socket layer retries sends the same way,
        linkers_socket.cpp; on a tunneled TPU the transient class is
        RPC-flavored).  Safe because the block programs are functional —
        inputs are untouched until the result is assigned.  Covers the
        dispatch/compile path (where tunnel RPC failures surface
        synchronously); asynchronous execution faults still propagate
        at the next fetch.

        Backoff/deadline/transient classification live on the SHARED
        retry utility (``utils/retry.py``) since the fault-tolerance
        round — the same policy the rendezvous and host collectives use;
        ``LGBM_TPU_RETRY_*`` env knobs tune all of them together."""
        from ..utils.retry import retry_call
        return retry_call(fn, *args, what="device_dispatch")

    def _maybe_split_kernel_fallback(self, exc) -> bool:
        """A Mosaic/VMEM compile failure of the fused split kernel must
        degrade to the XLA scan path, not kill training (ADVICE r5 #1).
        Returns True when the kernel was just disabled and the build
        programs were rebuilt — the caller should re-dispatch once."""
        from ..ops.pallas_split import disable_on_compile_error
        if not disable_on_compile_error(exc):
            return False
        counter_add("gbdt.split_kernel_fallbacks")
        obs_event("degrade", "split_kernel_fallback")
        if self.train_set is not None:
            self._setup_build_program()   # drop traces that bake the kernel
        return True

    def _pick_block_len(self, nb: int) -> int:
        """Compiled scan length for a block of ``nb`` active iterations.

        Right size is the next power of two (masked waste < 2x), but a
        fresh length costs a full XLA compile, so: reuse an exact-length
        program when one exists; otherwise borrow the smallest
        already-compiled length >= nb (a one-off residue — e.g. 100 =
        3x32 + 4 — should never compile a second program just to skip
        28 masked iterations).  Once the same length RECURS (windowed
        runs — output_freq / snapshot_freq — or warm re-trains, which
        would otherwise pay the masked waste on EVERY window), the right
        size compiles on a background thread and takes over when ready —
        the loop itself never stalls on a compile it can mask around."""
        L = 1
        while L < nb:
            L *= 2
        uses = self._block_len_uses.get(L, 0) + 1
        self._block_len_uses[L] = uses
        if L in self._block_fns:
            return L
        # snapshot: the background compile thread inserts into this dict
        # (iterating the live dict would raise on a concurrent insert)
        borrow = [l for l in list(self._block_fns) if l >= nb]
        if not borrow:
            return L                    # nothing to mask with: compile
        if uses >= 2:
            self._spawn_block_compile(L)
        return min(borrow)

    def train_block(self, num_iters: int) -> bool:
        """Run up to ``num_iters`` iterations, batching into scan blocks
        when possible.  Returns True when training finished (no more
        splittable leaves)."""
        from ..utils.timetag import tag
        done = 0
        K = self.num_tree_per_iteration
        c = self.config
        # stump-stop checks are OVERLAPPED: each block's last-iteration
        # leaf count is fetched asynchronously and inspected one block
        # later, so the device never idles a tunnel round-trip between
        # blocks (~120 ms each, ~12% of a 32-iteration block at 1M rows).
        # When a late check fires, the one extra dispatched block is all
        # stumps (zero score contribution) and is rolled back whole.
        # Valid ONLY when gradients are the sole per-iteration input: a
        # stump leaves scores (hence gradients) unchanged, so every
        # later iteration reproduces the stump.  Bagging/feature-
        # fraction resample per iteration/tree and CAN grow real trees
        # after a stump — those configs resolve each check immediately
        # (review r4 finding: a rolled-back real tree would leave its
        # score contribution behind).
        speculate = ((c.bagging_freq <= 0 or c.bagging_fraction >= 1.0)
                     and c.feature_fraction >= 1.0
                     and self.boosting_name == "gbdt")  # GOSS resamples
        prev_check = None                  # pending num_leaves slice
        stopped = False
        # LGBM_TPU_MESH_BLOCK=0: the fused-mesh A/B escape hatch —
        # per-ITERATION dispatch granularity (length-1 blocks of the
        # SAME compiled scan body), so the unfused baseline is
        # byte-identical by construction and the only variable is the
        # dispatch count.  Resolved per call: an env flip mid-run just
        # switches the next window's block length.
        cap = self._block_cap
        if (self.mesh_ctx is not None
                and _os.environ.get("LGBM_TPU_MESH_BLOCK", "1") == "0"):
            cap = 1
        while done < num_iters and not stopped:
            if not self._can_block():
                # unsupported config: per-iteration path
                if self.train_one_iter():
                    return True
                done += 1
                continue
            nb = min(num_iters - done, cap)
            L = self._pick_block_len(nb)
            # a length whose program is not cached yet pays trace +
            # XLA compile inside this dispatch: billed to the
            # `gbdt.block_compile` span so compile and steady-state
            # wall-clock separate in the run summary (the bench's
            # compile_s / steady_s split reads exactly this)
            compiling = L not in self._block_fns
            fn = self._block_fn(L)
            if compiling:
                counter_add("gbdt.block_compiles")
            self._gap_dispatch_start()
            with obs_span("gbdt.block_compile" if compiling
                          else "gbdt.block", iters=nb), \
                    tag("block") as tdone:
                args = (self.device_data, self._bins_t,
                        tuple(self._valid_device), self.scores,
                        tuple(self._valid_scores),
                        jnp.float32(self.shrinkage_rate),
                        jnp.int32(self.iter), jnp.int32(nb))
                try:
                    (self.scores, vscores), trees = self._dispatch_retry(
                        fn, *args)
                except Exception as exc:    # noqa: BLE001 - see below
                    # split-kernel compile failure: the block programs
                    # were rebuilt without the kernel — fetch the fresh
                    # one and dispatch again (same pure inputs)
                    if not self._maybe_split_kernel_fallback(exc):
                        raise
                    fn = self._block_fn(self._pick_block_len(nb))
                    (self.scores, vscores), trees = self._dispatch_retry(
                        fn, *args)
                self._gap_dispatch_done()
                self._valid_scores = list(vscores)
                tdone(trees.num_leaves)
            if compiling:
                # static XLA cost model (gated on LGBM_TPU_PROFILE /
                # LGBM_TPU_COST_MODEL: one extra lower+compile per
                # program length, acceptable in an explicit profiling
                # run) — FLOPs/bytes per block program for the
                # device_attribution roofline columns
                from ..obs import profiler as obs_profiler
                obs_profiler.record_program_cost(
                    f"gbdt.block[{L}]", fn, args,
                    module_hint="jit_block", iters=int(nb))
            # init-score bias rides the pending entry and is baked into
            # the first K host trees at flush (no separate per-iteration
            # bias-bake dispatch, which cost a whole extra XLA program)
            bias = (self.init_score_value
                    if (self._num_models() == 0
                        and abs(self.init_score_value) > 1e-15) else 0.0)
            self._pending.append((trees, self.shrinkage_rate, bias, nb * K))
            self.iter += nb
            self._stacked_cache = None
            done += nb
            nl = trees.num_leaves[nb - 1]
            if not speculate:
                stopped = self._check_block_stump(nl, rollback=0)
                continue
            try:
                nl.copy_to_host_async()
            # tpulint: disable=TPL006 -- prefetch-only; sync fetch follows
            except Exception:              # noqa: BLE001 - CPU backends
                pass
            if prev_check is not None:
                stopped = self._check_block_stump(prev_check, rollback=1)
            prev_check = nl
        if not stopped and prev_check is not None:
            stopped = self._check_block_stump(prev_check, rollback=0)
        return stopped

    def _check_block_stump(self, nl, rollback: int) -> bool:
        """Resolve an async stump check; on stop, drop the last
        ``rollback`` pending blocks (dispatched before the check
        resolved — all stumps, zero score contribution)."""
        last_nl = np.atleast_1d(jax.device_get(nl))
        if not all(int(x) <= 1 for x in last_nl):
            return False
        K = max(1, self.num_tree_per_iteration)
        for _ in range(min(rollback, len(self._pending))):
            _, _, _, cnt = self._pending.pop()
            self.iter -= cnt // K
        self.trim_trailing_stumps()
        log_warning(
            "stopped training because there are no more leaves "
            f"that meet the split requirements (iteration "
            f"{self.iter + 1})")
        obs_event("train_stop", "no_more_splits", iteration=self.iter)
        return True

    # ------------------------------------------------------------------
    def train(self, num_iterations: Optional[int] = None,
              callbacks: Sequence = ()) -> None:
        """Full training loop with early stopping + snapshots
        (reference GBDT::Train gbdt.cpp:309-327 + Application::Train).

        Under ``LGBM_TPU_TRACE_CONTRACT=1`` the whole loop runs inside a
        :class:`~lightgbm_tpu.obs.trace_contract.CompileTracker`: the
        first window is warmup, everything after must hit the trace
        cache — the report lands in the telemetry summary's
        ``trace_contract`` section (background block-length upgrades
        are counted separately, not as violations).

        Under ``LGBM_TPU_PROFILE=<dir>`` the loop additionally runs a
        WINDOWED device-time capture (``obs/profiler.py``): the first
        window is warmup, the next N windows are profiled, and the
        parsed per-span device-time / host-gap / roofline report lands
        in the summary's ``device_attribution`` section mid-train.

        Under ``LGBM_TPU_DETERMINISM=1`` every window boundary samples
        a canonical model/score digest into the ``determinism`` summary
        section (``obs/determinism.py``), the digest rides the multi-
        process ES sync as a cross-rank consistency check, and every
        keyed RNG derivation site counts into the RNG ledger — the
        runtime reproducibility contract the ``tools/replay_check.py``
        train-twice harness asserts on."""
        from ..obs import determinism, health, num_contract, ops_plane
        from ..obs.mem_contract import maybe_watermark
        from ..obs.profiler import maybe_profile
        from ..obs.trace_contract import maybe_track
        if determinism.enabled() and not self._resumed:
            # a fresh train() starts a fresh ledger; a resumed run keeps
            # accumulating so its digest stream continues the dead run's
            determinism.reset()
        if num_contract.enabled() and not self._resumed:
            # same fresh/resumed ledger discipline for the ulp contract
            num_contract.reset()
        # live ops plane (obs/ops_plane.py, LGBM_TPU_OPS_PORT): mount
        # the /metrics + /healthz scrape surface for this run; warming
        # until the first window lands (mark_ready below).  Host-side
        # only — zero device dispatches, zero recompiles (pinned by
        # tests/test_ops_plane.py).  The stall watchdog
        # (LGBM_TPU_WATCHDOG_S) arms around each window in _train.
        ops_plane.mount("train")
        wd = health.Watchdog.maybe("train")
        self._watchdog = wd
        # resolve the sentinel knob up front: LGBM_TPU_SENTINELS=1
        # activates the health plane even without an ops-plane mount,
        # so the warming->ready transitions below are live for it
        health.sentinels_enabled()
        health.mark_warming("train")
        try:
            with obs_span("gbdt.train"), maybe_track() as tracker, \
                    maybe_watermark("gbdt") as wm, \
                    maybe_profile("gbdt", sync=self._sync_pending) as prof:
                self._trace_tracker = tracker
                self._mem_watermark = wm
                self._profiler = prof
                try:
                    self._train(num_iterations, callbacks)
                finally:
                    self._trace_tracker = None
                    self._mem_watermark = None
                    self._profiler = None
        finally:
            self._watchdog = None
            if wd is not None:
                wd.stop()
        from ..obs import enabled as obs_enabled, gauge_set
        if obs_enabled():
            gauge_set("gbdt.iterations", int(self.iter))
            gauge_set("gbdt.num_trees", int(self._num_models()))
            from ..obs import summary as obs_summary
            c = obs_summary()["counters"]
            gaps = c.get("gbdt.dispatch_gaps", 0)
            if gaps:
                # the ROADMAP item-1 host-latency signal, live on EVERY
                # telemetry run — profiling off included
                gauge_set("gbdt.dispatch_gap_mean_s",
                          c.get("gbdt.dispatch_gap_s", 0.0) / gaps)

    def _sync_pending(self) -> None:
        """Block on in-flight device work (profile-capture hygiene:
        a stopped trace must contain the captured windows' ops).  Host
        code, not traced — the sync is the point."""
        jax.block_until_ready(self.scores)

    # -- dispatch-gap accounting (ROADMAP item 1) -----------------------
    def _gap_dispatch_start(self) -> None:
        """Called right before a training dispatch: the time since the
        PREVIOUS dispatch returned is host gap — objective/bookkeeping
        work the device spends idle waiting on.  Summed into the
        ``gbdt.dispatch_gap_s`` counter (mean gauge at end of train),
        so the per-iteration host-latency signal exists on every
        telemetry run, not just profiled ones."""
        from ..obs import enabled as obs_enabled
        t = self._t_dispatch_ret
        if t is not None and obs_enabled():
            counter_add("gbdt.dispatch_gap_s", time.perf_counter() - t)
            counter_add("gbdt.dispatch_gaps")

    def _gap_dispatch_done(self) -> None:
        self._t_dispatch_ret = time.perf_counter()

    def _train(self, num_iterations: Optional[int],
               callbacks: Sequence) -> None:
        from ..obs import determinism as _det
        from ..obs import health as _health
        from ..obs import num_contract as _num
        c = self.config
        iters = num_iterations or c.num_iterations
        # ES bookkeeping is INSTANCE state since the fault-tolerance
        # round: snapshots persist it and a resumed run keeps counting
        # stall rounds exactly where the dead run stood.  A fresh (non-
        # resumed) train() starts clean, as the old local dicts did.
        if not self._resumed:
            self._es_state = {"best_scores": {}, "best_iter": {},
                              "key_order": []}
        best_scores: Dict[str, float] = self._es_state["best_scores"]
        best_iter: Dict[str, int] = self._es_state["best_iter"]
        key_order: List[str] = self._es_state["key_order"]
        want_eval = bool(self.metrics
                         and (c.is_training_metric or self.valid_sets))
        es_on = c.early_stopping_round > 0 and bool(self.valid_sets)
        # output_freq silences PRINTING; early stopping still needs the
        # evals (the reference evaluates every iteration and prints
        # every output_freq, gbdt.cpp:492+)
        eval_freq = c.output_freq
        if eval_freq <= 0 and es_on:
            eval_freq = 1
        stopped_early = False
        # resumed: num_iterations is the dead run's TOTAL target and
        # self.iter sits mid-run — continue from there, keeping window
        # boundaries (eval/snapshot cadence) aligned with the original
        it = self.iter if self._resumed else 0
        while it < iters:
            # window to the next eval/snapshot boundary, run as one block
            window = iters - it
            if eval_freq > 0 and want_eval:
                window = min(window, eval_freq - (it % eval_freq))
            if c.snapshot_freq > 0:
                window = min(window, c.snapshot_freq - (it % c.snapshot_freq))
            prof = getattr(self, "_profiler", None)
            if prof is not None:
                # live device-time capture: bound windows so the
                # warmup/capture boundaries fall every few iterations
                # (a fused 500-iteration window would never hand the
                # profiler a post-warmup boundary to start at)
                window = prof.clamp_window(window)
            t0 = time.time()
            # stall watchdog (obs/health.py, LGBM_TPU_WATCHDOG_S):
            # armed around the window's dispatches; on expiry the
            # monitor thread names the active span in a health:stall
            # event + kill-survivable forensic dump while this thread
            # is still wedged.  watchdog.stall fault = synthetic hang.
            wd = self._watchdog
            if wd is not None:
                wd.arm("gbdt.block" if self._can_block()
                       else "gbdt.iteration",
                       it=int(it), window=int(window))
                _health.stall_fault(wd)
            try:
                if self._can_block():
                    # window == 1 (per-iteration eval cadence, the
                    # default with early stopping) STAYS on the fused
                    # path as a length-1 block program: one device
                    # dispatch carrying gradients → tree → score +
                    # valid-score updates, with the eval below reading
                    # the block-returned valid scores.  The old
                    # `window > 1` guard dropped to the unfused
                    # per-iteration path here — ~32 host-synced waves
                    # × ~0.1 s tunnel tax ≈ 3.7 s/iteration at bench
                    # shape (VERDICT r5 Weak #2's measured tail).
                    stop = self.train_block(window)
                    if _det.enabled():
                        # the fused block derives its masks INSIDE the
                        # scan from the same (seed, step) keys: ledger
                        # one derivation per masked iteration/tree
                        if c.bagging_freq > 0 and c.bagging_fraction < 1.0:
                            _det.rng_site("gbdt.bag_mask",
                                          "bagging_seed/epoch", n=window)
                        if c.feature_fraction < 1.0:
                            _det.rng_site(
                                "gbdt.feature_mask",
                                "feature_fraction_seed/tree_idx",
                                n=window * self.num_tree_per_iteration)
                    it = self.iter if stop else it + window
                else:
                    stop = self.train_one_iter()
                    it += 1
            finally:
                if wd is not None:
                    wd.disarm()
            # first window done == warmup over (idempotent; see train())
            tracker = getattr(self, "_trace_tracker", None)
            if tracker is not None:
                tracker.mark_steady()
            # /healthz: warming -> ready once the first window (compile
            # included) lands; sticky stalled/degraded never downgrade
            _health.mark_ready()
            if prof is not None:
                # window boundary: warmup -> start capture -> after N
                # windows stop + parse + attach device_attribution.
                # A boundary that did heavy profiler work (trace
                # start/stop+parse) must not bill itself to the next
                # window's dispatch-gap counter
                if prof.window(it=int(it)):
                    self._t_dispatch_ret = None
            # mem.leak fault: grow a module-lifetime sink by one fresh
            # device buffer per window (the leak class the watermark
            # contract catches; it != 0 defeats constant folding)
            from ..utils.faults import fault_flag
            if fault_flag("mem.leak"):
                # memcheck: disable=MEM005 -- intentional fault-
                # injection leak sink, armed only by chaos/tier-1 tests
                _MEM_LEAK_SINK.append(
                    jnp.full((_MEM_LEAK_ELEMS,), float(it), jnp.float32))
            wm = getattr(self, "_mem_watermark", None)
            if wm is not None:
                # one sample per window boundary: the leak gate
                wm.sample("gbdt.window", it=int(it))
                if self._donate_active:
                    # donation-effectiveness: the in-place score update
                    # must keep exactly ONE live [n, K] f32 set
                    wm.check_donation(self.scores.shape,
                                      self.scores.dtype, expected=1)
            if _det.enabled():
                # reproducibility contract: one canonical model/score
                # digest per window boundary (obs/determinism.py) —
                # flushing pending device trees costs one batched
                # device_get per window, paid only under the contract
                _det.window_digest(self, int(it))
            if _health.sentinels_enabled() or _num.enabled():
                # ONE score fetch shared by two consumers — a host
                # fetch like the eval below, zero extra device
                # dispatches: the non-finite sentinel (obs/health.py;
                # a NaN grad/hess poisons the scores it folds into, so
                # this names the window) and the ulp-drift contract
                # (obs/num_contract.py: canonical f32 root-sum vs the
                # f64 host oracle over the same fetched bytes).
                s_np = (self._pr.local_np(self.scores)
                        if self._pr is not None
                        else np.asarray(self.scores))
                if _health.sentinels_enabled():
                    _health.check_scores(s_np, window=int(it))
                if _num.enabled():
                    _num.window_check(s_np, it=int(it))
            if stop:
                break
            if want_eval and eval_freq > 0 and it % eval_freq == 0:
                results = []
                with obs_span("gbdt.eval", it=it):
                    if c.is_training_metric:
                        results.extend(self.eval_train())
                    results.extend(self.eval_valid())
                if self._pr is not None and results:
                    # rank-identical stop decisions (r4 weak #3): local
                    # metric values can differ across ranks (training
                    # metric over the local shard; float ties) — every
                    # rank adopts rank 0's values before deciding, the
                    # way the reference pins decisions to identical
                    # synced state (application.cpp:249-254)
                    from ..io.distributed import jax_process_allgather
                    from ..obs import flight_recorder
                    # the metric sync doubles as the window-boundary
                    # schedule cross-check: every rank's collective
                    # flight-recorder fingerprint rides the SAME
                    # allgather (zero extra collectives; a mismatch
                    # takes the rare second gather to localize the
                    # first diverging site+rank — see
                    # obs/flight_recorder.py)
                    gathered = jax_process_allgather(
                        {"vals": [float(r[2]) for r in results],
                         "fr": flight_recorder.fingerprint(),
                         "det": _det.fingerprint()})
                    vals = gathered[0]["vals"]
                    flight_recorder.window_check(
                        [g["fr"] for g in gathered],
                        allgather=jax_process_allgather)
                    # the model is replicated state: every rank's window
                    # digest must agree (obs/determinism.py; the digest
                    # rode the SAME gather — zero extra collectives)
                    _det.window_check([g["det"] for g in gathered],
                                      it=int(it))
                    results = [(n, m, float(v), h) for (n, m, _, h), v
                               in zip(results, vals)]
                if _health.sentinels_enabled():
                    # loss-spike + non-finite-metric sentinels over the
                    # values this boundary already computed
                    _health.check_metrics(results, window=int(it))
                if c.output_freq > 0 and it % c.output_freq == 0:
                    msgs = [f"{name} {mname} : {val:.6f}"
                            for name, mname, val, hib in results]
                    if msgs:
                        log_info(f"[{it}]\t" + "\t".join(msgs)
                                 + f"\t({time.time() - t0:.3f}s)")
                # early stopping on valid metrics: ANY single metric
                # stalling for early_stopping_round triggers the stop
                # (reference EvalAndCheckEarlyStopping / the python
                # callback, callback.py:142+ — round 4's all-metrics
                # rule could train forever on one still-improving
                # metric, review r5)
                if es_on:
                    for name, mname, val, hib in results:
                        if name == "training":
                            continue
                        key = f"{name}:{mname}"
                        if key not in key_order:
                            key_order.append(key)
                        better = (val > best_scores.get(key, -np.inf) if hib
                                  else val < best_scores.get(key, np.inf))
                        if better:
                            best_scores[key] = val
                            best_iter[key] = it
                    stalled = next(
                        (k for k in key_order
                         if it - best_iter[k] >= c.early_stopping_round),
                        None)
                    if stalled is not None:
                        self.best_iteration = best_iter[stalled]
                        for key, val in best_scores.items():
                            nm, mname = key.split(":", 1)
                            self.best_score.setdefault(nm, {})[mname] = val
                        log_info(f"early stopping at iteration {it}, "
                                 f"best iteration {self.best_iteration}")
                        obs_event("early_stop", stalled, iteration=it,
                                  best_iteration=self.best_iteration)
                        stopped_early = True
                        break
            if c.snapshot_freq > 0 and it % c.snapshot_freq == 0:
                self.save_snapshot(it)
        if not stopped_early and es_on and key_order:
            # the stall window never elapsed: still report the best seen
            # (the python callback raises at the final iteration with
            # the first metric's best, callback.py:113-117)
            self.best_iteration = best_iter[key_order[0]]
            for key, val in best_scores.items():
                nm, mname = key.split(":", 1)
                self.best_score.setdefault(nm, {})[mname] = val
        self.trim_trailing_stumps()

    def trim_trailing_stumps(self) -> None:
        """Drop trailing all-stump iterations (the per-iteration stop check
        only runs every `_sync_freq` iterations on remote devices, so a run
        can end with undetected stump trees; reference pops them,
        gbdt.cpp:462-468)."""
        K = self.num_tree_per_iteration
        if not self._pending and not self._host_models:
            return
        self._flush_pending()
        trimmed = 0
        while (len(self._host_models) >= K
               and all(t.num_leaves <= 1 for t in self._host_models[-K:])):
            self._host_models = self._host_models[:-K]
            self.iter -= 1
            trimmed += 1
        if trimmed:
            self._stacked_cache = None
            log_warning(f"dropped {trimmed} trailing iteration(s) with no "
                        f"splittable leaves")

    # -- snapshot / resume (fault tolerance) ----------------------------
    def save_snapshot(self, iteration: Optional[int] = None) -> Optional[str]:
        """Write an atomic snapshot (model + f32 score state + manifest)
        and prune to ``snapshot_keep`` (see ``boosting/snapshot.py``).

        Multi-process: rank 0 writes (every rank used to race the same
        path), under a cross-rank COMMIT BARRIER — ranks first publish
        ``(iteration, model_digest)`` over the host collective and the
        write proceeds only when every rank reports the same pair (a
        desynced mesh must not commit a snapshot that only rank 0's
        model matches); a second collective after the write keeps
        non-zero ranks from racing past an uncommitted manifest."""
        it = self.iter if iteration is None else iteration
        if jax.process_count() > 1:
            from ..io.distributed import jax_process_allgather
            return self._snapshot_barrier(it, jax_process_allgather,
                                          jax.process_index())
        from .snapshot import write_snapshot
        return write_snapshot(self, it)

    def _snapshot_barrier(self, iteration: int, allgather,
                          rank: int) -> Optional[str]:
        """The commit-barrier protocol, parameterized over the
        collective so tier-1 pins it in-process (ThreadedAllgather)."""
        from ..obs import event
        from .snapshot import write_snapshot
        d = self.digest(include_scores=False)
        acks = allgather({"iteration": int(iteration), "digest": d})
        if any(a != acks[0] for a in acks[1:]):
            event("elastic", "barrier_mismatch", iteration=int(iteration),
                  acks=len(acks))
            raise RuntimeError(
                f"snapshot commit barrier at iteration {iteration} "
                f"refused: ranks disagree on (iteration, digest): {acks}")
        path = None
        if rank == 0:
            path = write_snapshot(self, iteration)
        # commit confirmation: no rank proceeds (or treats the snapshot
        # as durable) until rank 0's manifest is on disk
        allgather({"committed": int(iteration)})
        return path

    def resume_from_snapshot(self, path_or_dir: str) -> int:
        """Restore trees, scores, and early-stopping state from the
        latest VALID snapshot under ``path_or_dir`` (a manifest path, a
        snapshot model path, an ``output_model`` prefix, or a
        directory), so a subsequent ``train(total_target)`` continues
        exactly where the dead run died.  Returns the restored
        iteration.

        Scores restore bit-for-bit from the snapshot's f32 state
        sidecar when present (the resumed run is then numerically
        IDENTICAL to an uninterrupted one); without a usable sidecar
        they are replayed from the restored trees — a last-ulp
        approximation, warned about."""
        from .snapshot import resolve_snapshot, config_hash
        with obs_span("snapshot.resume"):
            return self._resume_from_snapshot(path_or_dir, resolve_snapshot,
                                              config_hash)

    def _resume_from_snapshot(self, path_or_dir, resolve_snapshot,
                              config_hash) -> int:
        manifest = resolve_snapshot(path_or_dir)
        if manifest is None:
            raise FileNotFoundError(
                f"no valid snapshot found at {path_or_dir!r}")
        if self.train_set is None:
            raise ValueError("resume_from_snapshot needs a booster with "
                             "an attached training set")
        if manifest["config_hash"] != config_hash(self.config):
            log_warning("resuming with a DIFFERENT config than the "
                        "snapshot was written with; the continued run "
                        "will not match an uninterrupted one")
        # world-size-sensitive fields must MATCH the live mesh: a
        # 2-process snapshot resumed on 1 process (or vice versa) has a
        # different score layout and row sharding — refuse instead of
        # silently training on (older manifests lack the field: warn)
        snap_world = manifest.get("world_size")
        live_world = jax.process_count()
        if snap_world is None:
            if live_world > 1:
                log_warning("snapshot manifest predates world-size "
                            "tracking; cannot verify it matches this "
                            f"{live_world}-process mesh")
        elif int(snap_world) != live_world:
            raise ValueError(
                f"cannot resume: snapshot was written on a "
                f"{int(snap_world)}-process mesh, this run has "
                f"{live_world} process(es); re-shard via elastic "
                f"training (parallel/elastic.py) or restart training")

        from ..utils.file_io import open_read
        with open_read(manifest["model_path"]) as f:
            text = f.read()
        donor = GBDT(self.config, None)
        donor.load_model_from_string(text)
        if donor.num_tree_per_iteration != self.num_tree_per_iteration:
            raise ValueError("cannot resume: num_tree_per_iteration "
                             "differs between snapshot and config")
        fmap = {f: i for i, f in enumerate(self.train_set.used_features)}
        for t in donor.models:
            t.align_with_mappers(self.train_set.mappers, fmap)
        self.models = list(donor.models)
        self.iter = manifest["iteration"]
        self.init_score_value = manifest.get("init_score_value", 0.0)
        self._es_state = {
            "best_scores": dict(manifest.get("best_scores", {})),
            "best_iter": {k: int(v) for k, v in
                          manifest.get("best_iter", {}).items()},
            "key_order": list(manifest.get("key_order", []))}
        self._restore_scores(manifest)
        self.load_snapshot_extra_state(manifest.get("extra_state", {}))
        self._resumed = True
        self._stacked_cache = None
        log_info(f"resumed from snapshot {manifest['model_path']} at "
                 f"iteration {self.iter} ({len(self._host_models)} trees)")
        return self.iter

    def snapshot_extra_state(self) -> Dict:
        """Variant bookkeeping the snapshot manifest must carry beyond
        trees + scores + ES state (DART overrides with its per-tree
        drop weights); JSON-serializable."""
        return {}

    def load_snapshot_extra_state(self, extra: Dict) -> None:
        """Inverse of :meth:`snapshot_extra_state` on resume."""

    def _restore_scores(self, manifest: Dict) -> None:
        """Exact restore from the f32 sidecar when it fits this booster
        (same train shape, same attached valid sets); tree replay
        otherwise."""
        K = max(1, self.num_tree_per_iteration)
        state = None
        if manifest.get("state_path") and self._pr is None:
            state = np.load(manifest["state_path"])
            s = state.get("scores")
            want = (self.num_data, K)
            if s is None or s.shape != want:
                log_warning(f"snapshot score state has shape "
                            f"{None if s is None else s.shape}, booster "
                            f"needs {want}; replaying trees instead")
                state = None
        if state is not None:
            restored = np.asarray(state["scores"], np.float32)
            if self.mesh_ctx is not None:
                # registry placement (scores rule), like _init_train
                self.scores = self.mesh_ctx.place_scores(restored)
            else:
                self.scores = jax.device_put(restored)
            for i in range(len(self._valid_scores)):
                vs = state.get(f"valid_scores_{i}")
                if vs is not None and vs.shape == tuple(
                        self._valid_scores[i].shape):
                    self._valid_scores[i] = jnp.asarray(
                        np.asarray(vs, np.float32))
                else:
                    self._replay_valid_scores(i)
            return
        # fallback: replay restored trees (tree 0 carries the baked
        # init-score bias, so the replay starts from zero)
        self.scores = jnp.zeros_like(self.scores)
        for j, tree in enumerate(self._host_models):
            pred = self._predict_host_tree_binned(tree, self.device_data)
            self.scores = self.scores.at[:, j % K].add(pred)
        for i in range(len(self._valid_scores)):
            self._replay_valid_scores(i)

    def _replay_valid_scores(self, i: int) -> None:
        K = max(1, self.num_tree_per_iteration)
        vd = self._valid_device[i]
        score = jnp.zeros_like(self._valid_scores[i])
        for j, tree in enumerate(self._host_models):
            pred = self._predict_host_tree_binned(tree, vd)
            score = score.at[:, j % K].add(pred)
        self._valid_scores[i] = score

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        return self._num_models()

    @property
    def current_iteration(self) -> int:
        return self.iter

    def _stacked(self, dd_max_bins: int):
        if self._stacked_cache is None and self.models:
            self._stacked_cache = stack_trees(self.models, max_bins=dd_max_bins)
        return self._stacked_cache

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """Raw scores for a raw feature matrix (binned through the train
        mappers, then jitted stacked-tree traversal)."""
        if self.train_set is None:
            # loaded model without dataset: host-tree prediction
            return self._predict_loaded(X, num_iteration)
        valid = self.train_set.create_valid(np.asarray(X),
                                            prediction_mode=True)
        dd = to_device(valid)
        K = self.num_tree_per_iteration
        n = X.shape[0]
        T = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            T = min(T, num_iteration * K)
        # init score is baked into tree 0 (AddBias), so start from zero
        out = np.zeros((n, K), np.float64)
        if T == 0:
            out += self.init_score_value
            return out if K > 1 else out[:, 0]
        if self.config is not None and self.config.pred_early_stop:
            return self._predict_raw_early_stop(dd, n, K, T)
        bundle_kw = self._bundle_kw(dd)
        # the matmul predictor (predict_binned_matmul): every node
        # decision at once + one path-agreement contraction — no gathers,
        # no depth loop.  The gather walk serializes depth x trees x rows
        # (minutes at 500 deep trees x 2e5 rows; long dispatches fault
        # the TPU worker).  Covers categorical splits (vectorized bitset
        # lookup) and >256-bin ids (f32 select einsums) since r4; only
        # EFB-bundled columns still take the chunked walk.
        use_matmul = not bundle_kw
        from ..models.tree import (build_path_matrices, predict_binned_matmul,
                                   predict_binned_chunked)
        tchunk = int(_os.environ.get("LGBM_TPU_PRED_TREE_CHUNK",
                                     16 if use_matmul else 128))
        rchunk = int(_os.environ.get("LGBM_TPU_PRED_ROW_CHUNK",
                                     4096 if use_matmul else 1 << 16))
        for k in range(K):
            idx = list(range(k, T, K))
            trees_k = [self.models[i] for i in idx]
            # mask width +2: the sentinel miss bin must index an
            # always-False slot (never clamp onto a real bin)
            sub = stack_trees(trees_k, max_bins=dd.max_bins + 2)
            if use_matmul:
                P, plen = build_path_matrices(trees_k)
                out[:, k] += np.asarray(predict_binned_matmul(
                    sub, jnp.asarray(P), jnp.asarray(plen), dd.bins,
                    dd.nan_bins, dd.default_bins, dd.missing_types,
                    tchunk=tchunk, rchunk=rchunk))
            else:
                out[:, k] += np.asarray(predict_binned_chunked(
                    sub, dd.bins, dd.nan_bins, dd.default_bins,
                    dd.missing_types, tchunk=tchunk, rchunk=rchunk,
                    **bundle_kw))
        return out if K > 1 else out[:, 0]

    def _predict_raw_early_stop(self, dd, n: int, K: int, T: int) -> np.ndarray:
        """Prediction early stopping (reference
        `src/boosting/prediction_early_stop.cpp:1-100`): every
        ``pred_early_stop_freq`` rounds, rows whose margin exceeds
        ``pred_early_stop_margin`` stop accumulating further trees.
        Margin: binary = 2*|score| (`:60`), multiclass = top1 - top2
        (`:38`).  Vectorized: trees run in round chunks over the
        still-active rows."""
        c = self.config
        freq = max(1, c.pred_early_stop_freq)
        margin = c.pred_early_stop_margin
        out = np.zeros((n, K), np.float64)
        active = np.ones(n, bool)
        rounds = -(-(T // K) // freq)
        bundle_kw = self._bundle_kw(dd)
        for r in range(rounds):
            if not active.any():
                break
            rows = np.nonzero(active)[0]
            # pad the active set to a power-of-two bucket: the jitted
            # tree walk compiles per row-count, and shrinking every
            # round would otherwise compile every round
            bucket = 1 << (len(rows) - 1).bit_length()
            rows_pad = np.resize(rows, bucket)
            bins_sub = dd.bins[rows_pad]
            for k in range(K):
                idx = [i for i in range(k, T, K)][r * freq:(r + 1) * freq]
                if not idx:
                    continue
                sub = stack_trees([self.models[i] for i in idx],
                                  max_bins=dd.max_bins + 2,
                                  pad_leaves=self.growth.num_leaves
                                  if self.train_set is not None else 0)
                out[rows, k] += np.asarray(predict_binned(
                    sub, bins_sub, dd.nan_bins, dd.default_bins,
                    dd.missing_types, **bundle_kw))[:len(rows)]
            if K == 1:
                stop = 2.0 * np.abs(out[rows, 0]) > margin
            else:
                part = np.partition(out[rows], K - 2, axis=1)
                stop = (part[:, K - 1] - part[:, K - 2]) > margin
            active[rows[stop]] = False
        return out if K > 1 else out[:, 0]

    def _predict_loaded(self, X, num_iteration=-1):
        X = np.asarray(X, np.float64)
        K = max(1, self.num_tree_per_iteration)
        T = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            T = min(T, num_iteration * K)
        out = np.zeros((X.shape[0], K))
        for i in range(T):
            out[:, i % K] += self.models[i].predict_batch(X)
        return out if K > 1 else out[:, 0]

    def predict(self, X: np.ndarray, raw_score: bool = False,
                num_iteration: int = -1) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration)
        if raw_score or self.objective is None:
            return raw
        if self.average_output:
            T = max(1, len(self.models) // max(1, self.num_tree_per_iteration))
            raw = raw / T
        return np.asarray(self.objective.convert_output(jnp.asarray(raw)))

    def predict_leaf(self, X: np.ndarray,
                     num_iteration: int = -1) -> np.ndarray:
        """Per-tree leaf indices (PredictLeafIndex).

        ``num_iteration`` truncation lives HERE — the same seam
        ``predict_raw`` uses — so every surface (``Booster.predict``,
        sklearn, C API, serve) slices identically, multiclass included
        (``num_iteration * num_tree_per_iteration`` trees), and the
        truncated trees are never stacked or walked at all."""
        from ..models.tree import predict_leaf_binned
        models = self.models
        if num_iteration is not None and num_iteration > 0:
            K = max(1, self.num_tree_per_iteration)
            models = models[:num_iteration * K]
        valid = (self.train_set.create_valid(np.asarray(X),
                                             prediction_mode=True)
                 if self.train_set is not None else None)
        if valid is None:
            Xf = np.asarray(X, np.float64)
            out = np.zeros((len(X), len(models)), np.int32)
            for i, t in enumerate(models):
                out[:, i] = t.predict_leaf_batch(Xf)
            return out
        dd = to_device(valid)
        st = stack_trees(models, max_bins=dd.max_bins + 2)
        return np.asarray(predict_leaf_binned(
            st, dd.bins, dd.nan_bins, dd.default_bins, dd.missing_types,
            **self._bundle_kw(dd)))

    # ------------------------------------------------------------------
    def digest(self, include_scores: bool = True) -> str:
        """Canonical model/score sha256 (the reproducibility contract's
        unit of comparison — see ``obs/determinism.py`` for the exact
        field canonicalization).  Two trainings from identical data,
        config, and seeds must produce identical digests; the bench
        stamps this on every model-training leg as ``model_digest``."""
        from ..obs import determinism
        return determinism.model_digest(self, include_scores=include_scores)

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        """Reference FeatureImportance (gbdt_model_text.cpp:284+)."""
        n = self.max_feature_idx + 1
        imp = np.zeros(n)
        T = len(self.models)
        if num_iteration and num_iteration > 0:
            T = min(T, num_iteration * self.num_tree_per_iteration)
        for t in self.models[:T]:
            for node in range(t.num_leaves - 1):
                f = int(t.split_feature[node])
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += max(0.0, float(t.split_gain[node]))
        return imp

    # -- model text IO (reference gbdt_model_text.cpp:235-315) -----------
    def save_model_to_string(self, num_iteration: int = -1) -> str:
        lines = [self.boosting_name if self.boosting_name != "gbdt" else "tree"]
        lines.append(f"version={K_MODEL_VERSION}")
        lines.append(f"num_class={self.num_class}")
        lines.append(f"num_tree_per_iteration={self.num_tree_per_iteration}")
        lines.append("label_index=0")
        lines.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective is not None:
            lines.append(f"objective={self.objective.to_string()}")
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("feature_infos=" + " ".join(self._feature_infos()))
        T = len(self.models)
        if num_iteration and num_iteration > 0:
            T = min(T, num_iteration * self.num_tree_per_iteration)
        tree_strs = [f"Tree={i}\n" + self.models[i].to_string() + "\n"
                     for i in range(T)]
        lines.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
        lines.append("")
        body = "\n".join(lines) + "\n" + "".join(tree_strs)
        # feature importances footer
        imp = self.feature_importance("split", num_iteration)
        pairs = sorted([(int(imp[i]), self.feature_names[i])
                        for i in range(len(imp)) if imp[i] > 0],
                       key=lambda p: -p[0])
        body += "\nfeature importances:\n"
        body += "".join(f"{nm}={v}\n" for v, nm in pairs)
        return body

    def save_model(self, path: str, num_iteration: int = -1) -> None:
        from ..utils.file_io import open_write
        with open_write(path) as f:
            f.write(self.save_model_to_string(num_iteration))

    def load_model_from_string(self, text: str) -> None:
        """Reference LoadModelFromString (gbdt_model_text.cpp:317+)."""
        header, _, rest = text.partition("Tree=")
        kv: Dict[str, str] = {}
        for line in header.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
            elif line:
                kv[line] = ""
        self.num_class = int(kv.get("num_class", 1))
        self.num_tree_per_iteration = int(
            kv.get("num_tree_per_iteration", self.num_class))
        self.max_feature_idx = int(kv.get("max_feature_idx", 0))
        self.feature_names = kv.get("feature_names", "").split()
        self.average_output = "average_output" in kv
        obj_str = kv.get("objective", "")
        if obj_str and self.objective is None:
            name = obj_str.split()[0]
            params = dict(p.split(":", 1) for p in obj_str.split()[1:]
                          if ":" in p)
            cfg_params = {"objective": name}
            if "num_class" in params:
                cfg_params["num_class"] = int(params["num_class"])
            if "sigmoid" in params:
                cfg_params["sigmoid"] = float(params["sigmoid"])
            try:
                cfg = Config.from_params(cfg_params)
                self.objective = create_objective(cfg)
            except ValueError:
                self.objective = None
        self.models = []
        if rest:
            blocks = ("Tree=" + rest).split("Tree=")
            for blk in blocks:
                blk = blk.strip()
                if not blk or blk.startswith("feature importances"):
                    continue
                # strip the tree index line
                body = blk.split("\n", 1)[1] if "\n" in blk else ""
                body = body.split("feature importances:")[0]
                if "num_leaves=" in body:
                    self.models.append(Tree.from_string(body))
        self.iter = len(self.models) // max(1, self.num_tree_per_iteration)

    def _feature_infos(self) -> List[str]:
        if self.train_set is None:
            return ["none"] * (self.max_feature_idx + 1)
        infos = []
        for m in self.train_set.mappers:
            if m.is_trivial:
                infos.append("none")
            elif m.bin_type == 1:
                infos.append(":".join(str(c) for c in m.bin_2_categorical))
            else:
                infos.append(f"[{m.min_val!r}:{m.max_val!r}]")
        return infos

    # ------------------------------------------------------------------
    def refit_dataset(self, ds: BinnedDataset,
                      decay_rate: float = 0.9) -> None:
        """Re-estimate every tree's leaf values on a NEW dataset keeping
        the structures (reference RefitTree, gbdt.cpp:268-280 +
        application.cpp:293-318): attach the dataset, re-map each
        tree's thresholds through its mappers, and refit from the new
        rows' leaf assignments.  Shared by CLI task=refit and
        Booster.refit.  The EXISTING objective (e.g. parsed from the
        model header) is kept; one is created from the config only when
        none is set — a model loaded without params must not silently
        refit binary trees with the default regression gradients."""
        self.train_set = ds
        for t in self.models:
            t.align_with_mappers(
                ds.mappers, {f: i for i, f in enumerate(ds.used_features)})
        self.device_data = to_device(ds)
        self.num_data = ds.num_data
        if self.objective is None:
            self.objective = create_objective(self.config)
        self.objective.init(ds.metadata, ds.num_data)
        K = self.num_tree_per_iteration
        self.scores = jnp.zeros((ds.num_data, K), jnp.float32)
        from ..models.tree import predict_leaf_binned
        dd = self.device_data
        st = stack_trees(self.models, max_bins=dd.max_bins)
        pred_leaf = np.asarray(predict_leaf_binned(
            st, dd.bins, dd.nan_bins, dd.default_bins, dd.missing_types))
        self.refit(pred_leaf, decay_rate=decay_rate)

    def refit(self, pred_leaf: np.ndarray,
              decay_rate: float = 0.9) -> None:
        """Refit leaf outputs with new data (reference RefitTree
        gbdt.cpp:329-351 / FitByExistingTree + the python package's
        refit decay): ``new = decay_rate * old + (1 - decay_rate) *
        refit_output``; leaves no new row reaches keep their old output
        (a 0/0 would poison them with NaN for future rows).

        Sequential like the reference (ADVICE r4): the refit task
        (application.cpp:293-318) calls ``GBDT::Init`` with the new
        data, creating a FRESH ScoreUpdater — scores start at the
        dataset's init_score (or zero), with no old-model replay —
        then RefitTree's loop recomputes gradients at the current
        scores per iteration (``Boosting()``), refits that iteration's
        K trees, and ADDS each refitted tree's output to the scores
        (``AddScore``), so iteration i+1 fits the residual after
        refitted iteration i.  On exit ``self.scores`` equals the
        refitted model's prediction, preserving the invariant every
        other mutation path (rollback/merge/set_leaf_value) keeps."""
        K = self.num_tree_per_iteration
        models = self.models
        c = self.config
        n = pred_leaf.shape[0]
        scores_np = np.zeros((n, K), np.float32)
        ms = (self.train_set.metadata.init_score
              if self.train_set is not None else None)
        if ms is not None:
            # numcheck: disable=NUM002 -- same ingest cast as _boost
            # init: a data conversion at the model boundary
            scores_np = np.asarray(ms, np.float64).reshape(
                -1, K, order="F").astype(np.float32)
        for it in range(len(models) // K):
            self.scores = jnp.asarray(scores_np)
            grad, hess = self._gradients()
            g = np.asarray(grad)
            h = np.asarray(hess)
            for k in range(K):
                i = it * K + k
                tree = models[i]
                leaves = pred_leaf[:, i]
                nl = tree.num_leaves
                sg = np.zeros(nl)
                sh = np.zeros(nl)
                cnt = np.zeros(nl)
                np.add.at(sg, leaves, g[:, k])
                np.add.at(sh, leaves, h[:, k])
                np.add.at(cnt, leaves, 1.0)
                old = np.asarray(tree.leaf_value[:nl], np.float64)
                with np.errstate(divide="ignore", invalid="ignore"):
                    out = (-(np.sign(sg)
                             * np.maximum(np.abs(sg) - c.lambda_l1, 0.0))
                           / (sh + c.lambda_l2))
                new_vals = np.where(
                    cnt > 0,
                    decay_rate * old
                    + (1.0 - decay_rate) * out * self.shrinkage_rate,
                    old)                # untouched leaf keeps its output
                for l in range(nl):
                    tree.set_leaf_output(l, float(new_vals[l]))
                # AddScore: the refitted tree's output joins the scores
                # the NEXT iteration's gradients see
                scores_np[:, k] += np.asarray(
                    tree.leaf_value[:nl], np.float32)[leaves]
        self.scores = jnp.asarray(scores_np)
        self._stacked_cache = None
