"""Atomic, resumable training snapshots.

The fork's snapshot feature (reference ``gbdt.cpp:309-327``) WRITES a
model every ``snapshot_freq`` iterations but can never load one — a
preempted job loses everything.  This module closes the loop for
preemptible TPU pods:

* **Atomic writes** — every file lands as ``tmp + os.replace``
  (``utils/file_io.atomic_write``); a crash mid-write can only leave a
  stray ``.tmp``, never a torn file under a published name.
* **Commit marker** — each snapshot is (model text, f32 score state,
  JSON manifest); the manifest is written LAST and carries sha256 +
  size for the other two, so a snapshot is valid iff its manifest
  exists and verifies.  Loading walks candidates newest-first and
  auto-selects the latest snapshot that VALIDATES, silently skipping
  torn or truncated ones.
* **Exact resume** — the state sidecar stores the device f32 training
  scores (and per-valid-set scores) bit-for-bit.  Restoring them puts a
  resumed run in the IDENTICAL numeric state the dead run was in, so it
  continues bit-for-bit: the final model file is byte-identical to an
  uninterrupted run (tier-1 tested).  Replaying scores from the saved
  trees instead would re-round ``learning_rate * leaf`` through float64
  (host trees bake shrinkage at f64) where training rounded through
  f32 — a ~1-ulp score drift on a few percent of rows that can flip
  near-tie splits.  Tree replay remains the fallback when the sidecar
  is missing or shaped for a different dataset.
* **Retention** — only the newest ``snapshot_keep`` snapshots survive a
  write (default 2: current + one fallback for a crash mid-write of
  the current one).

Layout (flat, prefix-based — extends the fork's
``<output_model>.snapshot_iter_<N>`` naming)::

    <prefix>.snapshot_iter_<N>                 model text
    <prefix>.snapshot_iter_<N>.state.npz       f32 scores (train + valids)
    <prefix>.snapshot_iter_<N>.manifest.json   commit marker + checksums
"""
from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import counter_add, span
from ..utils.file_io import atomic_write
from ..utils.log import log_info, log_warning

MANIFEST_VERSION = 1
_SNAP_RE = re.compile(r"\.snapshot_iter_(\d+)\.manifest\.json$")
_BARRIER_RE = re.compile(r"\.barrier_iter_(\d+)\.manifest\.json$")


def snapshot_paths(prefix: str, iteration: int) -> Tuple[str, str, str]:
    base = f"{prefix}.snapshot_iter_{iteration}"
    return base, base + ".state.npz", base + ".manifest.json"


def _sha256_bytes(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def config_hash(config) -> str:
    """Stable hash of the training hyper-parameters (resume sanity
    check; path-like outputs excluded so moving the run directory does
    not flag a mismatch)."""
    d = config.to_dict()
    # excluded: path-like outputs, the resume/retention knobs themselves
    # (a resumed run necessarily differs in them), and verbosity — none
    # of these change what gets computed
    for k in ("output_model", "output_result", "data", "valid_data",
              "input_model", "machine_list_file", "machines",
              "resume_from", "snapshot_keep", "snapshot_freq", "verbose",
              "telemetry_output"):
        d.pop(k, None)
    payload = json.dumps(d, sort_keys=True, default=str)
    return _sha256_bytes(payload.encode())


def write_snapshot(gbdt, iteration: int, prefix: Optional[str] = None,
                   keep: Optional[int] = None) -> str:
    """Write one snapshot of ``gbdt`` at ``iteration`` and prune old
    ones.  Returns the model path.  Raises on write failure — a
    snapshot that cannot be written must be loud, and the torn bytes
    stay in ``.tmp`` files that never shadow a valid snapshot."""
    c = gbdt.config
    prefix = prefix or c.output_model
    keep = keep if keep is not None else getattr(c, "snapshot_keep", 2)
    model_path, state_path, manifest_path = snapshot_paths(prefix, iteration)

    with span("snapshot.write", iteration=int(iteration)) as sp:
        model_text = gbdt.save_model_to_string(-1)
        # two chunks: the `snapshot.write` fault point sits between them
        # (utils/file_io.atomic_write), so tests can tear the write mid-file
        atomic_write(model_path, model_text, chunks=2)

        # f32 score state: exact-resume sidecar.  Multi-process global
        # score arrays span other hosts' devices — skip the sidecar there
        # (resume falls back to tree replay).
        state = {}
        if getattr(gbdt, "_pr", None) is None and gbdt.train_set is not None:
            state["scores"] = np.asarray(gbdt.scores)
            for i, vs in enumerate(gbdt._valid_scores):
                state[f"valid_scores_{i}"] = np.asarray(vs)
        state_bytes = 0
        if state:
            import io
            buf = io.BytesIO()
            np.savez(buf, **state)
            state_bytes = len(buf.getvalue())
            atomic_write(state_path, buf.getvalue(), binary=True)

        es = getattr(gbdt, "_es_state", None) or {}
        import jax
        manifest = {
            "version": MANIFEST_VERSION,
            "iteration": int(iteration),
            # world-size-sensitive: resume on a different mesh size must
            # refuse (the score layout and row sharding would not match)
            "world_size": int(jax.process_count()),
            "num_trees": int(gbdt.num_trees()),
            "num_tree_per_iteration": int(max(1, gbdt.num_tree_per_iteration)),
            "init_score_value": float(gbdt.init_score_value),
            "config_hash": config_hash(c),
            "model_file": os.path.basename(model_path),
            "model_size": len(model_text.encode()),
            "model_sha256": _sha256_bytes(model_text.encode()),
            "state_file": os.path.basename(state_path) if state else "",
            "state_sha256": _sha256_file(state_path) if state else "",
            "best_scores": dict(es.get("best_scores", {})),
            "best_iter": {k: int(v) for k, v in es.get("best_iter", {}).items()},
            "key_order": list(es.get("key_order", [])),
            # variant bookkeeping beyond trees+scores (DART per-tree
            # weights): without it a resumed weighted-drop run diverges
            # from an uninterrupted one even with the keyed drop RNG
            "extra_state": gbdt.snapshot_extra_state(),
        }
        # manifest LAST: its appearance commits the snapshot
        atomic_write(manifest_path, json.dumps(manifest, indent=1))
        total_bytes = manifest["model_size"] + state_bytes
        sp["bytes"] = total_bytes
        counter_add("snapshot.writes")
        counter_add("snapshot.bytes_written", total_bytes)
    log_info(f"saved snapshot to {model_path} (iteration {iteration})")
    with span("snapshot.prune"):
        prune_snapshots(prefix, keep)
    return model_path


def list_snapshots(prefix_or_dir: str) -> List[Tuple[int, str]]:
    """All snapshot manifests for a prefix (or directory), as
    ``(iteration, manifest_path)`` sorted newest-first."""
    if os.path.isdir(prefix_or_dir):
        directory, stem = prefix_or_dir, ""
    else:
        directory = os.path.dirname(prefix_or_dir) or "."
        stem = os.path.basename(prefix_or_dir)
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _SNAP_RE.search(name)
        if m is None:
            continue
        if stem and not name.startswith(stem + ".snapshot_iter_"):
            continue
        out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(key=lambda t: -t[0])
    return out


def validate_snapshot(manifest_path: str) -> Optional[Dict]:
    """Parse + verify one snapshot.  Returns the manifest dict (with
    resolved ``model_path``/``state_path``) or None when anything —
    missing file, truncation, checksum mismatch, unparsable JSON — is
    wrong."""
    with span("snapshot.validate"):
        return _validate_snapshot(manifest_path)


def _validate_snapshot(manifest_path: str) -> Optional[Dict]:
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    directory = os.path.dirname(manifest_path) or "."
    model_path = os.path.join(directory, manifest.get("model_file", ""))
    try:
        if os.path.getsize(model_path) != manifest["model_size"]:
            return None
        if _sha256_file(model_path) != manifest["model_sha256"]:
            return None
    except (OSError, KeyError):
        return None
    manifest["model_path"] = model_path
    state_file = manifest.get("state_file", "")
    manifest["state_path"] = ""
    if state_file:
        state_path = os.path.join(directory, state_file)
        try:
            if _sha256_file(state_path) == manifest.get("state_sha256"):
                manifest["state_path"] = state_path
            else:
                log_warning(f"snapshot state {state_path} fails its "
                            f"checksum; resume will replay trees instead")
        except OSError:
            log_warning(f"snapshot state {state_path} is missing; "
                        f"resume will replay trees instead")
    return manifest


def latest_valid_snapshot(prefix_or_dir: str) -> Optional[Dict]:
    """Newest snapshot that validates (torn/corrupt ones are skipped
    with a warning — the atomicity contract means an older sibling is
    still intact)."""
    for it, manifest_path in list_snapshots(prefix_or_dir):
        manifest = validate_snapshot(manifest_path)
        if manifest is not None:
            return manifest
        log_warning(f"snapshot at iteration {it} is invalid "
                    f"({manifest_path}); trying the previous one")
    return None


def resolve_snapshot(path_or_dir: str) -> Optional[Dict]:
    """Accepts a manifest path, a snapshot model path, a prefix, or a
    directory; returns a validated manifest or None."""
    if path_or_dir.endswith(".manifest.json"):
        return validate_snapshot(path_or_dir)
    if os.path.isfile(path_or_dir + ".manifest.json"):
        return validate_snapshot(path_or_dir + ".manifest.json")
    return latest_valid_snapshot(path_or_dir)


def prune_snapshots(prefix: str, keep: int) -> None:
    """Drop all but the newest ``keep`` snapshots (and any stale
    ``.tmp`` residue of the pruned ones)."""
    if keep <= 0:
        return
    for it, manifest_path in list_snapshots(prefix)[keep:]:
        base = manifest_path[:-len(".manifest.json")]
        for path in (base, base + ".state.npz", manifest_path,
                     base + ".tmp", base + ".state.npz.tmp",
                     manifest_path + ".tmp"):
            try:
                os.unlink(path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# barrier snapshots (elastic training — parallel/elastic.py)
# ---------------------------------------------------------------------------
# Layout (the snapshot discipline, sharded):
#
#     <prefix>.barrier_iter_<N>              model text        (rank 0)
#     <prefix>.barrier_iter_<N>.shard<k>.npz shard k f32 scores (owner)
#     <prefix>.barrier_iter_<N>.manifest.json commit marker     (rank 0,
#                                             written LAST)
#
# Every rank writes its owned shards' score state, the ranks allgather
# (iteration, model_digest, shard shas) — a barrier COMMITS only when
# every rank published the same (iteration, digest) — and rank 0 writes
# the model text and then the manifest.  Because the manifest carries
# every shard's sha and only appears after all shard files exist, a
# SIGKILL anywhere in the sequence leaves either a complete barrier or
# a torn one that validation skips (recovery lands on the previous
# committed barrier, never a torn one).

def barrier_paths(prefix: str, iteration: int) -> Tuple[str, str]:
    base = f"{prefix}.barrier_iter_{iteration}"
    return base, base + ".manifest.json"


def barrier_shard_path(prefix: str, iteration: int, shard: int) -> str:
    return f"{prefix}.barrier_iter_{iteration}.shard{shard}.npz"


def write_barrier_shard(prefix: str, iteration: int, shard: int,
                        scores: np.ndarray) -> str:
    """Publish one shard's f32 score rows for a pending barrier;
    returns the payload sha256 (the commit allgather carries it into
    rank 0's manifest)."""
    import io as _io
    buf = _io.BytesIO()
    np.savez(buf, scores=np.asarray(scores, np.float32))
    payload = buf.getvalue()
    atomic_write(barrier_shard_path(prefix, iteration, shard), payload,
                 binary=True)
    counter_add("snapshot.barrier_shards")
    return _sha256_bytes(payload)


def commit_barrier(prefix: str, iteration: int, model_text: str,
                   shard_shas: Dict[int, str], meta: Dict,
                   keep: int = 2) -> str:
    """Rank 0's half of the barrier commit: model text, then the
    manifest LAST (its appearance is the global commit marker — it
    names every shard file's sha, all of which exist by now: the
    commit allgather collected them from their writers)."""
    model_path, manifest_path = barrier_paths(prefix, iteration)
    with span("snapshot.barrier", iteration=int(iteration)) as sp:
        atomic_write(model_path, model_text, chunks=2)
        manifest = {
            "version": MANIFEST_VERSION,
            "kind": "barrier",
            "iteration": int(iteration),
            "model_file": os.path.basename(model_path),
            "model_size": len(model_text.encode()),
            "model_sha256": _sha256_bytes(model_text.encode()),
            "shards": {str(s): sha
                       for s, sha in sorted(shard_shas.items())},
            **meta,
        }
        atomic_write(manifest_path, json.dumps(manifest, indent=1))
        sp["bytes"] = manifest["model_size"]
        counter_add("snapshot.barrier_commits")
    log_info(f"committed barrier snapshot at iteration {iteration} "
             f"({len(shard_shas)} shards): {model_path}")
    prune_barriers(prefix, keep)
    return model_path


def list_barriers(prefix: str) -> List[Tuple[int, str]]:
    """All barrier manifests for a prefix, ``(iteration, path)``
    newest-first."""
    directory = os.path.dirname(prefix) or "."
    stem = os.path.basename(prefix)
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _BARRIER_RE.search(name)
        if m is None or not name.startswith(stem + ".barrier_iter_"):
            continue
        out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(key=lambda t: -t[0])
    return out


def validate_barrier(manifest_path: str) -> Optional[Dict]:
    """Parse + verify one barrier: manifest, model text, and EVERY
    shard state file against its recorded sha256.  None when anything
    is missing or torn — a barrier is all-or-nothing."""
    with span("snapshot.validate"):
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        directory = os.path.dirname(manifest_path) or "."
        model_path = os.path.join(directory,
                                  manifest.get("model_file", ""))
        try:
            if os.path.getsize(model_path) != manifest["model_size"]:
                return None
            if _sha256_file(model_path) != manifest["model_sha256"]:
                return None
        except (OSError, KeyError):
            return None
        base = manifest_path[:-len(".manifest.json")]
        shard_paths = {}
        for s, sha in manifest.get("shards", {}).items():
            path = f"{base}.shard{int(s)}.npz"
            try:
                if _sha256_file(path) != sha:
                    return None
            except OSError:
                return None
            shard_paths[int(s)] = path
        manifest["model_path"] = model_path
        manifest["shard_paths"] = shard_paths
        return manifest


def latest_valid_barrier(prefix: str,
                         num_shards: Optional[int] = None) -> Optional[Dict]:
    """Newest barrier that validates in full (and matches the
    protocol shard count when given — a barrier from a different
    protocol is a different identity domain, never silently resumed)."""
    for it, manifest_path in list_barriers(prefix):
        manifest = validate_barrier(manifest_path)
        if manifest is None:
            log_warning(f"barrier snapshot at iteration {it} is torn "
                        f"({manifest_path}); trying the previous one")
            continue
        if num_shards is not None \
                and int(manifest.get("num_shards", -1)) != int(num_shards):
            log_warning(
                f"barrier snapshot at iteration {it} was written for "
                f"{manifest.get('num_shards')} protocol shards, this "
                f"run uses {num_shards}; skipping it")
            continue
        return manifest
    return None


def barrier_candidates(prefix: str,
                       num_shards: Optional[int] = None) -> Dict[int, str]:
    """``{iteration: model_sha256}`` of every barrier that validates in
    full on THIS rank's view of shared storage.  Elastic restore
    allgathers these and adopts the newest barrier every member can
    see — a lagging filesystem view or a concurrent prune must never
    let ranks resume different iterations (that desync only surfaces
    later as a mid-train barrier-tag RuntimeError)."""
    out: Dict[int, str] = {}
    for it, manifest_path in list_barriers(prefix):
        manifest = validate_barrier(manifest_path)
        if manifest is None:
            continue
        if num_shards is not None \
                and int(manifest.get("num_shards", -1)) != int(num_shards):
            continue
        out[int(manifest["iteration"])] = manifest["model_sha256"]
    return out


def prune_barriers(prefix: str, keep: int) -> None:
    """Keep the newest ``keep`` COMMITTED barriers (same retention
    rationale as :func:`prune_snapshots`); uncommitted shard residue of
    pruned iterations goes with them."""
    if keep <= 0:
        return
    directory = os.path.dirname(prefix) or "."
    for it, manifest_path in list_barriers(prefix)[keep:]:
        base = manifest_path[:-len(".manifest.json")]
        victims = [base, manifest_path, base + ".tmp",
                   manifest_path + ".tmp"]
        try:
            for name in os.listdir(directory):
                full = os.path.join(directory, name)
                if full.startswith(base + ".shard"):
                    victims.append(full)
        except OSError:
            pass
        for path in victims:
            try:
                os.unlink(path)
            except OSError:
                pass
