from .gbdt import GBDT
from .variants import DART, GOSS, RF, create_boosting
