"""SHAP feature contributions (TreeSHAP).

Counterpart of the reference ``Tree::PredictContrib`` path
(`/root/reference/src/io/tree.cpp` TreeSHAP / `include/LightGBM/tree.h`
PredictContrib usage in `src/boosting/gbdt_prediction.cpp`): the exact
polynomial-time TreeSHAP algorithm (Lundberg et al.) over the flat tree
arrays, host-side numpy.  Output layout matches the reference /
``pred_contrib=True``: ``[n, num_features + 1]`` with the expected value
in the last column (per class for multiclass).
"""
from __future__ import annotations

from typing import List

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, f, z, o, w):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w


def _extend_path(path: List[_PathElement], unique_depth, zero_fraction,
                 one_fraction, feature_index):
    path.append(_PathElement(feature_index, zero_fraction, one_fraction,
                             1.0 if unique_depth == 0 else 0.0))
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path: List[_PathElement], unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - path[i].pweight * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_path_sum(path: List[_PathElement], unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = (path[i].pweight - tmp * zero_fraction
                                * ((unique_depth - i) / (unique_depth + 1)))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def _tree_shap(tree, x, phi, node, unique_depth, parent_path,
               parent_zero_fraction, parent_one_fraction,
               parent_feature_index):
    path = [(_PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                          p.pweight)) for p in parent_path]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:   # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += (w * (el.one_fraction - el.zero_fraction)
                                      * tree.leaf_value[leaf])
        return

    hot, cold = _decide(tree, x, node)
    w = float(tree.internal_count[node])
    hot_count = _node_count(tree, hot)
    cold_count = _node_count(tree, cold)

    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0
    feature = int(tree.split_feature[node])
    # if this feature was already on the path, undo it
    path_index = next((i for i in range(1, unique_depth + 1)
                       if path[i].feature_index == feature), None)
    if path_index is not None:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, x, phi, hot, unique_depth + 1, path,
               hot_count / w * incoming_zero_fraction,
               incoming_one_fraction, feature)
    _tree_shap(tree, x, phi, cold, unique_depth + 1, path,
               cold_count / w * incoming_zero_fraction, 0.0, feature)


def _decide(tree, x, node):
    if isinstance(x, np.ndarray) and x.dtype == np.bool_:
        # x is a precomputed per-node go-left decision vector
        nxt = tree.left_child[node] if x[node] else tree.right_child[node]
    else:
        nxt = tree._decision(x, node)
    other = (tree.right_child[node] if nxt == tree.left_child[node]
             else tree.left_child[node])
    return int(nxt), int(other)


def _decision_matrix(tree, X: np.ndarray) -> np.ndarray:
    """Vectorized per-(row, node) go-left decisions -> bool [n, m].

    Lets the exact TreeSHAP recursion run once per *distinct* decision
    pattern instead of once per row (rows that decide identically at
    every internal node get identical phi)."""
    n = X.shape[0]
    m = tree.num_leaves - 1
    from ..models.tree import (K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK,
                               _K_ZERO_THRESHOLD, _bitset_to_values)
    from ..io.binning import MISSING_NAN, MISSING_ZERO
    D = np.zeros((n, m), bool)
    for node in range(m):
        f = int(tree.split_feature[node])
        fval = X[:, f]
        dt = int(tree.decision_type[node])
        mt = (dt >> 2) & 3
        nan = np.isnan(fval)
        if dt & K_CATEGORICAL_MASK:
            ci = int(tree.threshold_bin[node])
            members = np.asarray(_bitset_to_values(
                tree.cat_threshold[tree.cat_boundaries[ci]:
                                   tree.cat_boundaries[ci + 1]]))
            ok = ~nan & (fval >= 0)
            cats = np.where(ok, fval, -1).astype(np.int64)
            D[:, node] = np.isin(cats, members) & ok
            continue
        fval0 = np.where(nan & (mt != MISSING_NAN), 0.0, fval)
        is_missing = (((mt == MISSING_ZERO)
                       & (np.abs(fval0) <= _K_ZERO_THRESHOLD))
                      | ((mt == MISSING_NAN) & nan))
        dl = bool(dt & K_DEFAULT_LEFT_MASK)
        D[:, node] = np.where(is_missing, dl,
                              fval0 <= float(tree.threshold[node]))
    return D


def _node_count(tree, node):
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def _expected_value(tree, node=0):
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    return _expected(tree, 0)


def _expected(tree, node):
    if node < 0:
        return float(tree.leaf_value[~node])
    w = float(tree.internal_count[node])
    l, r = int(tree.left_child[node]), int(tree.right_child[node])
    return (_node_count(tree, l) / w * _expected(tree, l)
            + _node_count(tree, r) / w * _expected(tree, r))


def predict_contrib(gbdt, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
    """[n, F+1] SHAP values (+ expected value last column)."""
    X = np.asarray(X, np.float64)
    n = X.shape[0]
    F = gbdt.max_feature_idx + 1
    K = max(1, gbdt.num_tree_per_iteration)
    T = len(gbdt.models)
    if num_iteration and num_iteration > 0:
        T = min(T, num_iteration * K)
    out = np.zeros((n, K, F + 1))
    for i in range(T):
        t = gbdt.models[i]
        k = i % K
        if t.num_leaves == 1:
            out[:, k, F] += float(t.leaf_value[0])
            continue
        ev = _expected_value(t)
        out[:, k, F] += ev
        D = _decision_matrix(t, X)
        patterns, inverse = np.unique(D, axis=0, return_inverse=True)
        # hot loop: native exact-TreeSHAP recursion over the distinct
        # patterns (~1 ms per pattern-tree in Python — hours at 20k
        # rows x hundreds of trees; the reference runs it in C++ too)
        from .. import native
        m = t.num_leaves - 1
        phis = native.treeshap_patterns(
            patterns, t.split_feature[:m], t.left_child[:m],
            t.right_child[:m], t.leaf_value[:t.num_leaves],
            t.internal_count[:m].astype(np.float64),
            t.leaf_count[:t.num_leaves].astype(np.float64), F)
        if phis is None:               # no toolchain: Python fallback
            phis = np.zeros((len(patterns), F + 1))
            for p in range(len(patterns)):
                _tree_shap(t, patterns[p], phis[p], 0, 0, [], 1.0, 1.0,
                           -1)
        out[:, k, :F] += phis[inverse, :F]
    if K == 1:
        return out[:, 0, :]
    return out.reshape(n, K * (F + 1))
