"""Overlapped multi-chip wave reduction — chunked async psum with a
double-buffered sibling-subtract/apply.

The data-parallel learner's per-wave collective is ONE ``psum`` of the
active-leaf histogram block ``[A, G, B, 3]``
(`parallel/learners.py`, the ReduceScatter seam of the reference's
`data_parallel_tree_learner.cpp:147-162`).  The unoverlapped schedule
serializes wire and compute: the whole reduction must land before the
first byte of sibling subtraction / split scanning runs.  This module
lowers the SAME logical reduction to ``LGBM_TPU_OVERLAP_CHUNKS``
independent ``psum``s over disjoint stored-column ranges and
double-buffers the per-chunk consumers: chunk ``c``'s sibling
subtraction and histogram-state scatter issue as soon as chunk ``c``
lands, while chunk ``c+1``'s reduction is still in flight — XLA's async
collectives (all-reduce start/done on ICI) overlap the remaining wire
time with that compute.  The cross-feature split scan still joins all
chunks (its argmax spans every feature), so the hidden latency is the
reduction tail, which is exactly the part that grows with chip count.

BIT-EXACTNESS (the multi-chip acceptance contract): ``psum`` reduces
elementwise across shards, so reducing disjoint column slices and
concatenating is bit-identical to reducing the whole block — same adds,
same per-element order, no reassociation.  The per-chunk subtract and
scatters touch disjoint column ranges of the same state, preserving the
unoverlapped read-before-write semantics (the parent slot may BE the
small-child slot; each chunk reads its parent columns before writing
them, exactly like the full-block path).  ``tests/test_overlap.py``
pins tree-for-tree bit equality on a 2-shard CPU mesh and
``__graft_entry__.dryrun_multichip`` re-runs the divergence-envelope
gate with overlap on.

SCHEDULE CONTRACT (spmdcheck + flight recorder): the recorded schedule
is the LOGICAL one — one ``parallel.learners.hist_psum`` fingerprint
per wave with the full ``[A, G, B, 3]`` operand, identical to the
unoverlapped path in site/op/axis/shape/order (``tests/test_overlap.py``
pins digest equality).  The chunked lowering is rank-invariant by
construction: chunk boundaries derive from the static column count, so
every rank issues the identical physical sequence too.

Knobs: ``LGBM_TPU_OVERLAP=0`` disables (plain single-psum schedule);
``LGBM_TPU_OVERLAP_CHUNKS`` sets the chunk count (default 2; clamped to
the column count).
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs.flight_recorder import record as _fr_record


def overlap_enabled() -> bool:
    """Whether the data-parallel wave reduction runs double-buffered
    (default ON: bit-exact vs the serial-psum schedule, so there is no
    accuracy trade — ``LGBM_TPU_OVERLAP=0`` is the A/B escape hatch)."""
    return os.environ.get("LGBM_TPU_OVERLAP", "1") != "0"


def overlap_chunks() -> int:
    return max(1, int(os.environ.get("LGBM_TPU_OVERLAP_CHUNKS", "2") or 2))


def _chunk_bounds(G: int, chunks: int) -> List[Tuple[int, int]]:
    """Static column-range boundaries: ``chunks`` near-equal slices of
    ``[0, G)`` (clamped to at most one column per chunk)."""
    chunks = max(1, min(chunks, G))
    step = -(-G // chunks)
    return [(lo, min(lo + step, G)) for lo in range(0, G, step)]


def wave_psum(x: jnp.ndarray, axis: str,
              chunks: Optional[int] = None) -> jnp.ndarray:
    """The logical ``psum(x, axis)`` of a ``[A, G, ...]`` wave block,
    lowered to independent column-chunk psums (bit-identical; the
    chunks pipeline against each other on the interconnect)."""
    if chunks is None:
        chunks = overlap_chunks()
    bounds = _chunk_bounds(x.shape[1], chunks)
    if len(bounds) <= 1:
        return jax.lax.psum(x, axis)
    return jnp.concatenate(
        [jax.lax.psum(x[:, lo:hi], axis) for lo, hi in bounds], axis=1)


def reduce_apply_overlapped(hist_state: jnp.ndarray, new_h: jnp.ndarray,
                            act_small: jnp.ndarray, act_parent: jnp.ndarray,
                            act_sibling: jnp.ndarray, L: int, axis: str,
                            chunks: Optional[int] = None):
    """Double-buffered reduce + per-wave histogram bookkeeping: the
    overlapped drop-in for ``psum`` followed by
    :func:`~lightgbm_tpu.learner.serial.apply_hist_wave`.

    Per column chunk: reduce the local block, derive the sibling by
    parent-minus-child subtraction, and persist both children into the
    per-leaf state — so each chunk's subtract/scatter consumes its
    reduction as it lands while later chunks are still on the wire.
    Returns ``(hist_state, ids [2A], grid [2A, G, B, 3])`` with values
    bit-identical to the unoverlapped path (see module docstring).
    """
    if chunks is None:
        chunks = overlap_chunks()
    # the LOGICAL schedule entry: one reduction per wave, full operand —
    # identical fingerprint to the unoverlapped `_psum` record
    _fr_record("parallel.learners.hist_psum", "psum", axis, new_h)
    parent_safe = jnp.clip(act_parent, 0, L - 1)
    small_slot = jnp.where(act_small >= 0, act_small, L)
    sib_slot = jnp.where(act_sibling >= 0, act_sibling, L)
    h_parts: List[jnp.ndarray] = []
    sib_parts: List[jnp.ndarray] = []
    for lo, hi in _chunk_bounds(new_h.shape[1], chunks):
        h_c = jax.lax.psum(new_h[:, lo:hi], axis)        # [A, gc, B, 3]
        parent_c = hist_state[parent_safe, lo:hi]
        sib_c = parent_c - h_c
        hist_state = hist_state.at[small_slot, lo:hi].set(h_c, mode="drop")
        hist_state = hist_state.at[sib_slot, lo:hi].set(sib_c, mode="drop")
        h_parts.append(h_c)
        sib_parts.append(sib_c)
    new_h_red = jnp.concatenate(h_parts, axis=1)
    sib_h = jnp.concatenate(sib_parts, axis=1)
    ids = jnp.concatenate([act_small, act_sibling])      # [2A]
    grid = jnp.concatenate([new_h_red, sib_h], axis=0)   # [2A, G, B, 3]
    return hist_state, ids, grid
