"""Leaf-compacted deep-wave histograms — the TPU ``DataPartition`` analog.

Why: per-row MXU work in the wide one-hot kernel
(`ops/pallas_histogram.py`) scales with ``cols = round128(C *
round8(A))`` — every row is contracted against the value columns of ALL
``A`` active leaf slots even though it contributes to exactly one.
``tests/data/north_star.json`` quantifies the collapse on the bench
device: 1.08–1.13 ns/row at A <= 32 degrades to 2.55 at 64 and 8.79 at
128 (MXU util 1.18 -> 0.61) — and 128-slot waves are the dominant
regime of the reference's 255-leaf headline configs (the 0.27x ranking
leg, README).  The reference solves the same problem on CPU with
``DataPartition``'s leaf-contiguous row layout + ordered gradients
(`/root/reference/src/treelearner/data_partition.hpp`,
`serial_tree_learner.cpp` ordered-bin path): each leaf's histogram only
ever touches that leaf's rows.

This module is the TPU-native analog, in three steps per deep wave:

1. **plan** (:func:`compact_plan`, plain XLA): bucket every row by its
   active-slot *group* (``COMPACT_GROUP = 32`` slots per group — the
   measured flat-regime boundary), stable-sort rows by group, and pad
   each group's segment to a whole number of row tiles.  Rows whose
   leaf is not active (bagged-out ``-1`` included) sort into a trailing
   trash segment and are DROPPED from the compacted stream — deep
   waves histogram only the smaller children, so this alone removes
   the ~half of the stream the wide kernel reads and multiplies by
   zero.
2. **regroup**: one gather applies the permutation to the bins/value
   streams.  It rides the wave's existing pending-split application:
   the routed ``leaf2`` from `ops/pallas_route.py` (whose kernel has
   already streamed the bins once to apply the previous wave's splits)
   is consumed directly, so the plan adds no extra leaf computation —
   the learner (`learner/serial.py`) routes, then compacts from the
   routed vector.
3. **grouped kernel** (:func:`hist_active_compact`): the one-hot matmul
   kernel runs over the compacted stream with a *per-tile* active set
   of ``COMPACT_GROUP`` slots — ``cols = round128(C * 32)`` instead of
   ``round128(C * 128)`` — restoring the flat ~1.1 ns/row profile.
   Each tile's group (and so its output block and its slice of the
   per-group active table) is selected by a scalar-prefetched
   ``tile_group`` vector (`pltpu.PrefetchScalarGridSpec`): segments
   are group-contiguous, so every output block is visited in one
   consecutive run and plain ``@pl.when(first-tile-of-group)``
   zero-init + VMEM accumulation works exactly like the wide kernel's
   row grid.

Cost model: the wide kernel pays ``n * cols_wide`` MACs; the compacted
path pays ``~n_active * cols_group`` MACs plus a stable segment-sort of
an ``[n]`` int32 key and one bins/vals gather.  At A=128 / C=4 that is
a 4x MAC reduction on <= ~half the rows; the sort+gather are measured
per-device by the wave microbench (`bench.py` ``wave_kernel`` table),
which records ns/row per active-slot bucket so this regression class
stays visible in every ``BENCH_r*.json``.

Exactness: identical quantized inputs accumulate in int32 exactly in
both kernels, so the compacted path is BIT-identical to the wide
kernel on the default int8 modes; float modes differ from the scatter
oracle only by f32 summation order (tests pin bit-exactness with
dyadic-rational values, tolerance otherwise).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_histogram import (DEFAULT_ROW_TILE, _VMEM_BUDGET_BYTES,
                               _cell_vmem_bytes, _col_layout, _feat_tile_cap,
                               _onehot_bins, _pick_row_tile, _round_up,
                               _weighted_cols, bin_stride, combine_hist_cols,
                               is_quantized)

# leaf slots per compacted tile group.  32 is the measured flat-regime
# boundary of the wide kernel (north_star.json: 1.13 ns/row at 32 vs
# 8.79 at 128) — and for the default C=4 int8h mode, C*32 = 128 fills
# the lane dimension exactly, so no output column is wasted.
COMPACT_GROUP = 32


def compact_slot_threshold() -> int:
    """Waves with more active slots than this take the compacted path
    (env-tunable for A/B: ``LGBM_TPU_COMPACT_SLOTS``)."""
    return int(os.environ.get("LGBM_TPU_COMPACT_SLOTS", COMPACT_GROUP))


def compact_config_ok(max_bins: int, mode: str) -> bool:
    """VMEM feasibility of the grouped kernel: the shared per-grid-cell
    model (`ops/vmem.hist_cell_ok`) at the compacted cell — same
    resident arrays at the group column count, plus the (negligible)
    [G, 1] group-active slice and [1, T] compacted leaf row, at the
    1024-row fallback tile."""
    from .vmem import hist_cell_ok
    extra = COMPACT_GROUP * 4 + 2 * 1024 * 4   # group actives + leaf row
    return hist_cell_ok(max_bins, COMPACT_GROUP, mode, extra_bytes=extra)


def compact_plan(hist_leaf: jnp.ndarray, active: jnp.ndarray,
                 num_leaf_slots: int, row_tile: int):
    """Leaf-compaction plan for one wave: ``-> (src, tile_group,
    group_active)``.

    Args:
      hist_leaf: ``[n_pad]`` int32 leaf per row (bagged-out/padding
        rows carry ``-1``) — the ROUTED vector, i.e. the wave's pending
        splits have already been applied by the route kernel.
      active: ``[A]`` int32 active leaf ids (``-1`` padding).
      num_leaf_slots: static leaf-slot count L (bounds the inverse
        lookup table).
      row_tile: the kernel's row-tile T; every group segment pads to a
        multiple of it, and every group keeps >= 1 tile so its output
        block is always zero-initialized (an unvisited block would
        hand garbage to an active-but-empty leaf, e.g. bagged to 0
        rows).

    Returns:
      src: ``[n_c]`` int32 — source row for each compacted row, ``-1``
        for segment padding; ``n_c = n_pad + n_groups * T`` (static).
      tile_group: ``[n_c // T]`` int32 — the group each row tile
        serves, non-decreasing; tiles past the used region map to the
        trailing trash group ``n_groups``.
      group_active: ``[G, n_groups + 1]`` int32 — per-group active-leaf
        table (column g = slots ``[g*G, (g+1)*G)``), ``-2`` padding so
        neither real leaves nor the ``-1`` of padding rows match.
    """
    n_pad = hist_leaf.shape[0]
    A = active.shape[0]
    G = COMPACT_GROUP
    T = row_tile
    n_groups = -(-A // G)
    L = num_leaf_slots

    # slot of each row in the active list; A = inactive/bagged-out
    safe_act = jnp.where(active >= 0, active, L)
    inv = jnp.full((L + 1,), A, jnp.int32).at[safe_act].set(
        jnp.arange(A, dtype=jnp.int32), mode="drop")
    slot = jnp.where(hist_leaf >= 0,
                     inv[jnp.clip(hist_leaf, 0, L - 1)], A)      # [n_pad]
    grp = jnp.where(slot < A, slot // G, n_groups)

    # stable segment sort by group: rows keep dataset order inside a
    # group (the reference's leaf-contiguous index layout)
    order = jnp.argsort(grp, stable=True)
    sorted_grp = grp[order]
    cnt = jnp.bincount(grp, length=n_groups + 1)[:n_groups]
    pc = jnp.maximum(((cnt + T - 1) // T) * T, T)    # >= 1 tile per group
    pstart = jnp.concatenate(
        [jnp.zeros(1, pc.dtype), jnp.cumsum(pc)])    # [n_groups + 1]
    ustart = jnp.concatenate(
        [jnp.zeros(1, cnt.dtype), jnp.cumsum(cnt)])  # unpadded starts
    rank = (jnp.arange(n_pad, dtype=jnp.int32)
            - ustart[jnp.clip(sorted_grp, 0, n_groups)])
    n_c = n_pad + n_groups * T                       # static bound
    dst = jnp.where(sorted_grp < n_groups,
                    pstart[jnp.clip(sorted_grp, 0, n_groups - 1)] + rank,
                    n_c)                             # trash rows: dropped
    src = jnp.full((n_c,), -1, jnp.int32).at[dst].set(
        order.astype(jnp.int32), mode="drop")

    # tile -> group.  Group starts are non-decreasing and empty groups
    # are zero-width, so "last group starting at or before this tile"
    # is the occupier; tiles past the used region land on the trash
    # block n_groups (searchsorted returns n_groups + 1 there).
    t0 = jnp.arange(n_c // T, dtype=pstart.dtype) * T
    tile_group = (jnp.searchsorted(pstart, t0, side="right")
                  .astype(jnp.int32) - 1)
    tile_group = jnp.clip(tile_group, 0, n_groups)

    ga = jnp.full(((n_groups + 1) * G,), -2, jnp.int32)
    ga = jax.lax.dynamic_update_slice(
        ga, jnp.where(active >= 0, active, -2).astype(jnp.int32), (0,))
    group_active = ga.reshape(n_groups + 1, G).T     # [G, n_groups + 1]
    return src, tile_group, group_active


def _hist_compact_kernel(tg_ref, ga_ref, bins_ref, vals_ref, leaf_ref,
                         *refs, n_cols: int, B: int, pad_cols: int,
                         seeded: bool = False):
    """One (feature-tile, row-tile) cell of the grouped kernel.  Same
    body as the wide ``_hist_kernel`` at the group's column count; the
    accumulator zero-init fires on the first tile of each group run
    (groups are tile-contiguous, so each output block is one
    consecutive visit).

    ``seeded``: the out-of-core fold variant — the first tile of each
    group run LOADS the carried accumulator block (aliased to the
    output, see the wide kernel) instead of zeroing, making a per-block
    call a bitwise extension of the monolithic one.  The trailing trash
    group seeds garbage, adds only masked zeros, and is dropped at
    unpack — deterministic and harmless.
    """
    if seeded:
        acc_ref, out_ref = refs
    else:
        (out_ref,) = refs
    i = pl.program_id(1)
    prev = tg_ref[jnp.maximum(i - 1, 0)]
    first = jnp.logical_or(i == 0, tg_ref[i] != prev)

    @pl.when(first)
    def _():
        if seeded:
            out_ref[:] = acc_ref[:]
        else:
            out_ref[:] = jnp.zeros_like(out_ref)

    quant = vals_ref.dtype == jnp.int8
    cdt = jnp.int8 if quant else jnp.bfloat16
    oh = _onehot_bins(bins_ref[:].astype(jnp.int32), B, cdt)
    # [G, 1] group actives vs [1, T] compacted leaves -> [G, T] mask;
    # segment-padding rows carry leaf -1 and actives pad with -2, so
    # padding never matches (its bins column is garbage by design)
    m = ga_ref[:] == leaf_ref[:]
    vw = _weighted_cols(m, vals_ref[:], n_cols, pad_cols, cdt)
    out_ref[:] += jax.lax.dot_general(
        oh, vw, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32 if quant else jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "max_bins", "num_leaf_slots", "mode",
                     "row_tile", "interpret", "raw"))
def hist_active_compact(bins_t: jnp.ndarray,
                        vals: jnp.ndarray,
                        row_leaf: jnp.ndarray,
                        active: jnp.ndarray,
                        scales: jnp.ndarray | None = None,
                        acc: jnp.ndarray | None = None,
                        *,
                        num_features: int,
                        max_bins: int,
                        num_leaf_slots: int,
                        mode: str = "hilo",
                        row_tile: int = DEFAULT_ROW_TILE,
                        interpret: bool = False,
                        raw: bool = False) -> jnp.ndarray:
    """Leaf-compacted histograms for the active leaves: same contract as
    ``hist_active_pallas`` (``-> [A, F, B, 3]`` f32) with per-row MXU
    work independent of ``A``.

    ``row_leaf`` must be the full ``[n_pad]`` padded leaf vector
    (padding rows ``-1``).  Unlike the wide kernel, ``-1`` padding
    entries of ``active`` yield exact ZERO slots (their rows never
    enter the compacted stream), matching the scatter oracle.

    ``acc`` / ``raw``: the out-of-core fold operands, mirroring the
    wide kernel — ``acc`` is the carried RAW accumulator
    (:func:`compact_raw_layout`, donated via ``input_output_aliases``),
    ``raw=True`` returns the raw grid for the next block's carry
    (finalize with :func:`unpack_hist_compact_raw`).  NOTE: on float
    modes a per-block compact call is NOT chain-exact against the
    monolithic call (block-local group padding changes f32 add order),
    so the fold seam (``learner.serial.make_hist_fold_fn``) only routes
    quantized modes here — int32 accumulation is order-independent.
    """
    F_pad, n_pad = bins_t.shape
    C = vals.shape[0]
    A = active.shape[0]
    B = bin_stride(max_bins)
    G = COMPACT_GROUP
    n_groups = -(-A // G)

    Cc, Gp, cols = _col_layout(G, mode)
    assert Cc == C and Gp == G, (Cc, C, Gp)
    T = _pick_row_tile(n_pad, B, cols, C, row_tile)
    assert n_pad % T == 0, (n_pad, T)
    pad_cols = cols - C * Gp

    src, tile_group, group_active = compact_plan(
        row_leaf.astype(jnp.int32), active.astype(jnp.int32),
        num_leaf_slots, T)
    sc = jnp.maximum(src, 0)
    # the regroup gather: one pass over the bins/value streams applies
    # the leaf-contiguous permutation (the DataPartition::Split +
    # ordered-gradients analog in one shot)
    bins_c = jnp.take(bins_t, sc, axis=1)            # [F_pad, n_c]
    vals_c = jnp.take(vals, sc, axis=1)              # [C, n_c]
    leaf_c = jnp.where(src >= 0, row_leaf.astype(jnp.int32)[sc],
                       -1)[None, :]                  # [1, n_c]

    # feature tiling: identical VMEM model to the wide kernel, at the
    # group column count
    ft_cap = max(1, _feat_tile_cap(B, cols, T, C))
    if ft_cap >= F_pad:
        feat_tile = F_pad
    else:
        feat_tile = max(8, (ft_cap // 8) * 8)
    F_grid = _round_up(F_pad, feat_tile)
    if F_grid != F_pad:
        bins_c = jnp.pad(bins_c, ((0, F_grid - F_pad), (0, 0)))
    nft = F_grid // feat_tile
    n_c = bins_c.shape[1]

    seeded = acc is not None
    in_specs = [
        pl.BlockSpec((G, 1), lambda j, i, tg: (0, tg[i]),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((feat_tile, T), lambda j, i, tg: (j, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((C, T), lambda j, i, tg: (0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, T), lambda j, i, tg: (0, i),
                     memory_space=pltpu.VMEM),
    ]
    operands = [group_active, bins_c, vals_c, leaf_c]
    if seeded:
        # the carried accumulator walks the OUTPUT's block schedule so
        # the first-tile-of-group seed-load reads the matching block;
        # aliased in place (with PrefetchScalarGridSpec the alias index
        # COUNTS the scalar-prefetch operand: tile_group=0, ga=1,
        # bins=2, vals=3, leaf=4, acc=5)
        in_specs.append(pl.BlockSpec((feat_tile * B, cols),
                                     lambda j, i, tg: (tg[i] * nft + j, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(acc)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nft, n_c // T),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((feat_tile * B, cols),
                               lambda j, i, tg: (tg[i] * nft + j, 0),
                               memory_space=pltpu.VMEM),
    )
    out = pl.pallas_call(
        functools.partial(_hist_compact_kernel, n_cols=C, B=B,
                          pad_cols=pad_cols, seeded=seeded),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            ((n_groups + 1) * F_grid * B, cols),
            jnp.int32 if is_quantized(mode) else jnp.float32),
        input_output_aliases=({5: 0} if seeded else {}),
        interpret=interpret,
    )(tile_group, *operands)

    if raw:
        return out
    return unpack_hist_compact_raw(out, A, num_features, max_bins, mode,
                                   scales)


def compact_raw_layout(n_pad: int, num_active: int, num_features: int,
                       max_bins: int, mode: str,
                       row_tile: int = DEFAULT_ROW_TILE):
    """``-> (((n_groups+1)*F_grid*B, cols), dtype)`` of the RAW grouped
    accumulator — the streamed-fold carry for ``hist_active_compact``
    (twin of ``pallas_histogram.hist_raw_layout``; same tile arithmetic
    as the kernel, so it is call-invariant across same-shaped blocks)."""
    B = bin_stride(max_bins)
    G = COMPACT_GROUP
    n_groups = -(-num_active // G)
    C, Gp, cols = _col_layout(G, mode)
    T = _pick_row_tile(n_pad, B, cols, C, row_tile)
    ft_cap = max(1, _feat_tile_cap(B, cols, T, C))
    F_pad = num_features
    feat_tile = F_pad if ft_cap >= F_pad else max(8, (ft_cap // 8) * 8)
    F_grid = _round_up(F_pad, feat_tile)
    dtype = jnp.int32 if is_quantized(mode) else jnp.float32
    return ((n_groups + 1) * F_grid * B, cols), dtype


def unpack_hist_compact_raw(out: jnp.ndarray, num_active: int,
                            num_features: int, max_bins: int, mode: str,
                            scales: jnp.ndarray | None = None):
    """RAW grouped accumulator -> ``[A, F, B, 3]`` f32 (trash block
    dropped).  One-shot finalization of a streamed compact fold chain."""
    A = num_active
    B = bin_stride(max_bins)
    G = COMPACT_GROUP
    n_groups = -(-A // G)
    C, Gp, cols = _col_layout(G, mode)
    F_grid = out.shape[0] // ((n_groups + 1) * B)
    out = out.reshape(n_groups + 1, F_grid, B, cols)[
        :n_groups, :, :, :C * Gp]
    out = out.reshape(n_groups, F_grid, B, C, Gp)
    out = out.transpose(0, 4, 1, 2, 3).reshape(n_groups * Gp, F_grid, B, C)
    out = out[:A, :num_features]
    return combine_hist_cols(out, mode, scales)
