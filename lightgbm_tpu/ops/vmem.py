"""Shared VMEM feasibility model for every Pallas kernel in the repo.

Through PR 5 each kernel carried its own copy of the same question —
"does this config's per-grid-cell working set fit the ~16 MB/core VMEM
with headroom?" — as ``pallas_histogram._cell_vmem_bytes`` /
``_feat_tile_cap``, ``compact.compact_config_ok``, and the split
kernel's ``_vmem_budget_bytes`` leaf-tile chooser.  PR 1's ADVICE-r5
fix (the pallas_split lane cap blowing VMEM and surfacing as a Mosaic
crash instead of a fallback) showed what happens when a kernel ships
WITHOUT the model.  This module is the single home for that
arithmetic, pure int math with **no jax import**, so:

* every kernel dispatcher keys its config gate on one budget
  (``VMEM_BUDGET_BYTES``, measured headroom under the v5e's ~16 MB/core
  — see the provenance note below), and
* the memcheck static analyzer (``tools/memcheck``, rule MEM004) can
  enforce "no ``pallas_call`` without a VMEM-model predicate" by
  KEYING ON THIS MODULE: ``VMEM_GUARDS`` below names the sanctioned
  predicates; any module that dispatches a Pallas kernel must reference
  one of them (or any ``*vmem*`` helper) on its guard path.

Budget provenance: 12 MiB per grid cell.  The previous spread-matmul
kernel demonstrably ran larger footprints on the v5e, so 12 MiB under
the ~16 MB/core ceiling leaves room for the streamed inputs'
double-buffering (counted inside :func:`cell_vmem_bytes`) plus Mosaic's
own scratch.  The split kernel's budget is the same default, overridable
for hardware-verified tuning via ``LGBM_TPU_SPLIT_VMEM_MB``.
"""
from __future__ import annotations

import os

LANE = 128

# per-grid-cell VMEM budget for the histogram-family kernels' resident
# arrays (f32 accumulator + bf16 one-hot + bins tile + value columns)
VMEM_BUDGET_BYTES = 12 * 1024 * 1024

# The sanctioned VMEM-guard predicate names: tools/memcheck rule MEM004
# parses this tuple (statically — no import) and requires every module
# with a `pallas_call` to reference one of these names, or any name
# containing "vmem", on its dispatch path.  Extend this tuple when a
# new kernel family grows its own predicate.
VMEM_GUARDS = (
    "pallas_config_ok",      # wide one-hot histogram + route table model
    "fused_config_ok",       # fused route+hist kernel
    "compact_config_ok",     # leaf-compacted deep-wave kernel
    "hist_cell_ok",          # the generic predicate below
    "hist_fold_cell_ok",     # accumulator-seeded streamed-fold variant
    "split_lane_chunk_features",   # fused split kernel's lane chunking
    "split_scan_chunk_features",   # XLA split scan's HBM chunking
)


def next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def bin_stride(max_bins: int) -> int:
    """Per-feature bin stride used by the kernels' joint index space."""
    return max(8, next_pow2(max_bins))


def col_layout(A: int, mode: str) -> tuple[int, int, int]:
    """-> (C, A_pad, cols): value columns, padded active slots, lane-
    aligned total output columns."""
    C = {"hilo": 5, "ghilo": 4, "hhilo": 4, "int8h": 4,
         "int8hh": 5}.get(mode, 3)
    A_pad = round_up(A, 8)
    cols = round_up(C * A_pad, LANE)
    return C, A_pad, cols


def cell_vmem_bytes(ft: int, B: int, cols: int, T: int, C: int) -> int:
    """VMEM footprint of one (feature-tile, row-tile) histogram grid
    cell: the f32 accumulator, the bf16 one-hot, the weighted value
    block, the bins tile (double-buffered), and the packed values."""
    return (ft * B * cols * 4        # accumulator (out block)
            + ft * B * T * 2         # one-hot bf16
            + T * cols * 2           # vw bf16
            + 2 * ft * T             # bins tile, double-buffered
            + 2 * T * C * 4)         # vals, double-buffered


def feat_tile_cap(B: int, cols: int, T: int, C: int) -> int:
    """Largest feature tile whose grid cell fits the VMEM budget."""
    ft = max(1, VMEM_BUDGET_BYTES // (B * (cols * 4 + T * 2)))
    while ft > 1 and cell_vmem_bytes(ft, B, cols, T, C) > VMEM_BUDGET_BYTES:
        ft -= 1
    return ft


def pick_row_tile(n_pad: int, B: int, cols: int, C: int,
                  requested: int) -> int:
    """Largest power-of-two tile <= ``requested`` that divides ``n_pad``
    and whose minimum-feature-tile grid cell fits the VMEM budget."""
    T = requested
    while T > 1024 and (
            n_pad % T != 0
            or cell_vmem_bytes(8, B, cols, T, C) > VMEM_BUDGET_BYTES):
        T //= 2
    return T


def hist_cell_ok(max_bins: int, active_slots: int, mode: str,
                 row_tile: int = 1024, extra_bytes: int = 0) -> bool:
    """The generic histogram-kernel feasibility predicate: does the
    minimum-feature-tile grid cell at ``active_slots`` output slots fit
    the budget (at the 1024-row fallback tile ``pick_row_tile`` halves
    down to)?  ``extra_bytes`` covers kernel-specific residents (the
    compacted kernel's group-active slice + leaf row)."""
    B = bin_stride(max_bins)
    C, _, cols = col_layout(active_slots, mode)
    return (cell_vmem_bytes(8, B, cols, row_tile, C) + extra_bytes
            <= VMEM_BUDGET_BYTES)


def hist_fold_cell_ok(max_bins: int, active_slots: int, mode: str,
                      row_tile: int = 1024, extra_bytes: int = 0) -> bool:
    """Feasibility of the accumulator-SEEDED histogram cell (the
    out-of-core fold variant of the kernels): on top of
    :func:`hist_cell_ok`'s residents, the carried accumulator operand
    adds one more ``[ft*B, cols]`` block (same element size as the
    output; int32 on the quantized modes) fetched into VMEM for the
    seed-load.  ``extra_bytes`` composes with kernel-specific residents
    exactly as in :func:`hist_cell_ok` (the compacted fold passes its
    group-active slice + leaf row through here)."""
    B = bin_stride(max_bins)
    C, _, cols = col_layout(active_slots, mode)
    seed = 8 * B * cols * 4              # acc block at the min feat tile
    return hist_cell_ok(max_bins, active_slots, mode, row_tile,
                        extra_bytes + seed)


def split_vmem_budget_bytes() -> int:
    """Working-set budget for the fused split kernel's leaf-tile choice
    (env-tunable: the split kernel holds ~6 concurrent [3*Lc, FB] f32
    arrays in its missing path — see ops/pallas_split.py)."""
    return int(float(os.environ.get("LGBM_TPU_SPLIT_VMEM_MB", 12))
               * (1 << 20))


# ---------------------------------------------------------------------------
# split-scan working-set model (ISSUE 9): both split-finder paths chunk
# the FEATURE axis under the budgets below, so the 255-bin MSLR shape
# (136 features x 256-bin stride) stays inside memory on either path.
# ---------------------------------------------------------------------------

# F*B lane cap per fused-split-kernel call (ops/pallas_split.py: at the
# old 32768 cap the kernel's [3*Lc, FB] f32 intermediates blew the
# ~16 MB/core VMEM).  Wider feature sets run as per-chunk kernel calls.
SPLIT_MAX_LANES = 16384

# concurrent [2, slots, F, B] f32 grids the XLA scan's missing-direction
# variant holds live (lg/lh/lc, rg/rh/rc, num_gain, ok, var_best,
# num_gain_b — ops/split.py:195-223); the no-missing path halves the
# stack and drops the direction axis.
SPLIT_SCAN_LIVE_GRIDS = 10
SPLIT_SCAN_LIVE_GRIDS_NOMISS = 6


def split_lane_chunk_features(num_features: int, B: int) -> int:
    """Features per fused-split-kernel chunk: the largest count whose
    F*B lane width fits ``SPLIT_MAX_LANES`` AND stays LANE-aligned (the
    kernel's block width requirement).  ``B`` is the power-of-two bin
    stride, so alignment needs chunk counts in multiples of
    ``LANE // B`` when ``B < LANE``."""
    fc = max(1, SPLIT_MAX_LANES // B)
    step = max(1, LANE // B)
    fc -= fc % step
    return max(step, min(num_features, fc)) if fc else step


def split_scan_bytes(slots: int, num_features: int, B: int,
                     any_missing: bool = True) -> int:
    """Live HBM bytes of one XLA split scan over a ``[slots, F, B]``
    grid — the ~10-grid f32 stack of the missing-direction variant."""
    if any_missing:
        return SPLIT_SCAN_LIVE_GRIDS * 2 * slots * num_features * B * 4
    return SPLIT_SCAN_LIVE_GRIDS_NOMISS * slots * num_features * B * 4


def split_scan_budget_bytes() -> int:
    """HBM budget for the split scan's live intermediates
    (``LGBM_TPU_SPLIT_SCAN_MB`` overrides; default 512 MiB — small next
    to the 14 GiB device budget, large enough that the default HIGGS
    shapes never chunk)."""
    return int(float(os.environ.get("LGBM_TPU_SPLIT_SCAN_MB", 512))
               * (1 << 20))


def split_scan_chunk_features(slots: int, num_features: int, B: int,
                              any_missing: bool = True) -> int:
    """Features per XLA-scan chunk so the live stack fits the budget.
    Returns ``num_features`` (no chunking) when the whole scan fits —
    the default HIGGS/63-bin shapes — and chunks only when the stack
    would exceed the budget (the 255-bin MSLR regime).
    ``LGBM_TPU_SPLIT_CHUNK_F`` forces an explicit chunk width."""
    forced = os.environ.get("LGBM_TPU_SPLIT_CHUNK_F")
    if forced:
        return max(1, min(num_features, int(forced)))
    per_f = split_scan_bytes(slots, 1, B, any_missing)
    fc = max(1, split_scan_budget_bytes() // max(1, per_f))
    return min(num_features, fc)
