"""Gradient histogram construction — the hottest op in GBDT training.

TPU-native redesign of the reference histogram machinery
(`/root/reference/src/io/dataset.cpp:587-752` ``Dataset::ConstructHistograms``,
`src/io/dense_bin.hpp` ``ConstructHistogram`` inner loops, and the OpenCL
kernels `src/treelearner/ocl/histogram{16,64,256}.cl`):

* The reference iterates feature groups with OpenMP, gathering ordered
  gradients per leaf; the GPU path packs 4 features per workgroup and uses
  local-memory atomic float adds.
* Here there is ONE dense binned matrix ``[n, F]`` and one op that produces
  histograms for ALL leaves at once, keyed by the current row→leaf
  assignment: an XLA scatter-add over a flat ``(leaf, feature, bin)`` index
  space.  No atomics are needed — XLA serializes duplicate indices in the
  scatter, and on TPU the scatter lowers to an efficient sorted-segment
  loop.  A Pallas one-hot-matmul kernel (``pallas_histogram.py``) can swap
  in behind the same interface for the MXU fast path.

Histogram cell layout matches ``HistogramBinEntry`` (`bin.h:27-55`):
``(sum_grad, sum_hess, count)`` as a trailing axis of size 3, float32
(the reference GPU path is also single-precision by default,
`docs/GPU-Performance.rst:135-161`).

The sibling-subtraction trick (`feature_histogram.hpp:64-70` ``Subtract``)
is :func:`subtract_histogram`; the reference's ``FixHistogram``
(`dataset.cpp:754-773`) reconstructs skipped default bins — unnecessary
here because the dense scatter visits every row, but leaf-total
consistency is still enforced in the split scan by using leaf sums from
the partition, not the histogram.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# Rows per scatter chunk: caps the [chunk, F, 3] update intermediate that
# XLA may materialize when it cannot fuse the broadcast into the scatter.
_DEFAULT_CHUNK = 1 << 18


def _scatter_chunk(hist: jnp.ndarray, bins: jnp.ndarray, bin_offsets: jnp.ndarray,
                   row_leaf: jnp.ndarray, vals: jnp.ndarray,
                   total_bins: int) -> jnp.ndarray:
    """Scatter-add one row-chunk into the flat [num_leaves*total_bins, 3] hist."""
    # [chunk, F] global bin index within a leaf's histogram
    idx = row_leaf[:, None] * total_bins + bin_offsets[None, :] + bins.astype(jnp.int32)
    return hist.at[idx].add(vals[:, None, :], mode="drop")


def build_histograms(bins: jnp.ndarray,
                     grad: jnp.ndarray,
                     hess: jnp.ndarray,
                     row_leaf: jnp.ndarray,
                     bin_offsets: jnp.ndarray,
                     num_leaves: int,
                     total_bins: int,
                     chunk_rows: int = _DEFAULT_CHUNK) -> jnp.ndarray:
    """Build per-leaf gradient histograms for every feature in one pass.

    Args:
      bins: ``[n, F]`` integer binned matrix (uint8/int32).
      grad, hess: ``[n]`` float32 gradients / hessians.
      row_leaf: ``[n]`` int32 leaf id per row; negative ids (e.g. bagged-out
        rows) are dropped by the scatter.
      bin_offsets: ``[F]`` int32 per-feature offset into the flat bin space
        (``FeatureInfo.bin_offsets[:-1]``).
      num_leaves: static leaf-slot count L.
      total_bins: static sum of per-feature bin counts.

    Returns:
      ``[L, total_bins, 3]`` float32 histogram (sum_grad, sum_hess, count).
    """
    n = bins.shape[0]
    vals = jnp.stack(
        [grad, hess, jnp.ones_like(grad)], axis=-1).astype(jnp.float32)
    # negative leaf ids -> out-of-range index -> dropped by scatter mode="drop"
    safe_leaf = jnp.where(row_leaf < 0, num_leaves, row_leaf).astype(jnp.int32)
    hist = jnp.zeros((num_leaves * total_bins, 3), dtype=jnp.float32)
    bin_offsets = bin_offsets.astype(jnp.int32)

    if n <= chunk_rows:
        hist = _scatter_chunk(hist, bins, bin_offsets, safe_leaf, vals, total_bins)
    else:
        num_chunks = (n + chunk_rows - 1) // chunk_rows
        pad = num_chunks * chunk_rows - n
        if pad:
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            vals = jnp.pad(vals, ((0, pad), (0, 0)))
            # padded rows get leaf id == num_leaves -> dropped
            safe_leaf = jnp.pad(safe_leaf, (0, pad), constant_values=num_leaves)
        bins_c = bins.reshape(num_chunks, chunk_rows, -1)
        vals_c = vals.reshape(num_chunks, chunk_rows, 3)
        leaf_c = safe_leaf.reshape(num_chunks, chunk_rows)

        def body(h, xs):
            b, v, l = xs
            return _scatter_chunk(h, b, bin_offsets, l, v, total_bins), None

        hist, _ = jax.lax.scan(body, hist, (bins_c, vals_c, leaf_c))
    return hist.reshape(num_leaves, total_bins, 3)


def build_histogram_single(bins: jnp.ndarray,
                           grad: jnp.ndarray,
                           hess: jnp.ndarray,
                           row_mask: jnp.ndarray,
                           bin_offsets: jnp.ndarray,
                           total_bins: int,
                           chunk_rows: int = _DEFAULT_CHUNK) -> jnp.ndarray:
    """Histogram over one row subset (the "smaller leaf" in the reference's
    smaller/larger strategy, `serial_tree_learner.cpp:358-372`).

    Returns ``[total_bins, 3]``.
    """
    leaf = jnp.where(row_mask, 0, -1).astype(jnp.int32)
    hist = build_histograms(bins, grad, hess, leaf, bin_offsets,
                            num_leaves=1, total_bins=total_bins,
                            chunk_rows=chunk_rows)
    return hist[0]


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """Sibling histogram by subtraction (`feature_histogram.hpp:64-70`)."""
    return parent - child


def pad_to_feature_grid(hist_flat: jnp.ndarray, bin_offsets: jnp.ndarray,
                        num_bins: jnp.ndarray, max_bins: int) -> jnp.ndarray:
    """Reshape flat ``[..., total_bins, 3]`` histograms to a padded
    ``[..., F, max_bins, 3]`` grid for the vectorized split scan.

    Out-of-range (padding) bins read bin 0 of the feature but are masked in
    the scan via ``num_bins``; to keep them harmless we instead clamp the
    gather index to the feature's own range and zero the result.
    """
    F = bin_offsets.shape[0]
    b = jnp.arange(max_bins)
    # [F, max_bins] flat index, clamped inside each feature's span
    idx = bin_offsets[:, None] + jnp.minimum(b[None, :], num_bins[:, None] - 1)
    valid = b[None, :] < num_bins[:, None]
    grid = hist_flat[..., idx, :]              # [..., F, max_bins, 3]
    return grid * valid[..., None].astype(grid.dtype)


def unbundle_grid(grid: jnp.ndarray,
                  leaf_sum_grad: jnp.ndarray,
                  leaf_sum_hess: jnp.ndarray,
                  leaf_count: jnp.ndarray,
                  feat_group: jnp.ndarray,
                  feat_offset: jnp.ndarray,
                  num_bins: jnp.ndarray,
                  default_bins: jnp.ndarray,
                  out_stride: int) -> jnp.ndarray:
    """Expand EFB group-column histograms into per-feature grids.

    ``grid`` is ``[A, G, Bg, 3]`` over the stored group columns; returns
    ``[A, F, B, 3]`` over logical features with ``B = out_stride``.  For a
    bundled feature the shared default cell is reconstructed from the
    leaf totals by subtraction — exactly the reference's ``FixHistogram``
    (`/root/reference/src/io/dataset.cpp:754-773`), which rebuilds the
    skipped default bin the same way.

    Args:
      grid: [A, G, Bg, 3] group histograms (grad, hess, count).
      leaf_sum_grad/hess/count: [A] authoritative totals per grid row.
      feat_group/feat_offset/num_bins/default_bins: [F] bundle layout
        (`io/dataset.py` BundleInfo encoding; offset -1 = identity).
      out_stride: per-feature bin stride of the output grid.
    """
    A, G, Bg, _ = grid.shape
    B = out_stride
    b = jnp.arange(B, dtype=jnp.int32)[None, :]             # [1, B]
    off = feat_offset[:, None]
    db = default_bins[:, None]
    nb = num_bins[:, None]
    ident = off < 0                                         # [F, 1]
    src = jnp.where(ident, b, off + b - (b > db))           # [F, B]
    valid = (b < nb) & (ident | (b != db))
    src = jnp.clip(src, 0, Bg - 1)
    idx = feat_group[:, None] * Bg + src                    # [F, B]
    flat = grid.reshape(A, G * Bg, 3)
    out = flat[:, idx]                                      # [A, F, B, 3]
    out = jnp.where(valid[None, :, :, None], out, 0.0)
    # reconstruct the folded default cell for bundled features
    sums = jnp.sum(out, axis=2)                             # [A, F, 3]
    totals = jnp.stack([leaf_sum_grad, leaf_sum_hess,
                        leaf_count], axis=-1)[:, None, :]   # [A, 1, 3]
    fix = totals - sums
    at_default = ((b == db) & ~ident)[None, :, :, None]     # [1, F, B, 1]
    return jnp.where(at_default, out + fix[:, :, None, :], out)
