"""Pallas TPU fused best-split search — one kernel per wave.

The XLA expression of the split scan (`ops/split.py:find_best_splits`)
is ~50 small ops per wave on `[2A, F, B, 3]` grids; at 9 waves per
iteration the op-count overhead is row-independent and becomes the
dominant per-iteration fixed cost on small-to-medium datasets (measured
~6 ms/iteration at 1M rows vs a ~23 ms/iteration row-scaled cost —
VERDICT r4 #4).  This kernel computes the whole numerical scan — both
missing-direction variants, constraint masking, and the joint
(feature, bin, direction) argmax — in ONE Pallas call over a
``[leaves, F*B]`` lanes layout.

Semantics mirror `find_best_splits`'s numerical path exactly
(reference `feature_histogram.hpp:312-452`):
  * prefix sums over the bin axis give left-side sums per threshold,
  * the missing cell (NaN bin, or the zero bin for
    ``MissingType::Zero``) is excluded from the scan and added wholly
    to the left side in the "missing left" variant,
  * constraints: ``min_data_in_leaf``/``min_sum_hessian_in_leaf`` on
    both sides, no threshold at/after ``num_bins-1`` (-2 with a NaN
    bin), no split ON the zero-missing cell, variant 1 only where the
    feature actually has a missing type,
  * ties: variant 0 (missing right) wins, then lowest feature, then
    lowest bin — the same order the XLA path's argmax chain yields.

The bin prefix sums run as ``log2(B)`` masked-roll rounds on the VPU
(segment-local: rolled-in lanes from the previous feature's segment are
zeroed), with gradients/hessians/counts stacked on sublanes so one
round advances all three.  Floating-point association therefore differs
from ``jnp.cumsum`` in the last ulp — the same envelope the psum
reassociation in the distributed learners already documents; the oracle
test gates sums at ~1e-6 relative and decisions for equality on
non-degenerate gains.

Categorical features are not expressed here; datasets with any
categorical feature stay on the XLA path (`learner/serial.py` gates).

Measured flip envelope (binary_classification example, 7k rows, 255
bins): one near-tie split flip in tree 0 vs the XLA path on TPU (CPU
interpret mode builds the IDENTICAL tree — the flip is compiled-kernel
last-ulp rounding on quantized-histogram near-ties).  Through 100
iterations of bagging+feature_fraction the flip cascades to a model
whose held-out AUC moved -0.0098; without sampling the kernel model
scored +0.0027 — i.e. run-variance on a 7k-row example, not a quality
penalty.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.binning import MISSING_NAN, MISSING_ZERO
from .split import (K_EPSILON, K_MIN_SCORE, SplitParams, SplitResult,
                    leaf_output, leaf_split_gain)

LANE = 128

# F*B lane cap PER KERNEL CALL: at the old 32768 cap the kernel's
# [3*Lc, FB] f32 intermediates (ghc/gs/cl0/cl1, ~12 MB each at Lc=32)
# blew the ~16 MB per-core VMEM and surfaced as a Mosaic compile crash
# instead of a fallback (ADVICE r5 #1).  16384 keeps the minimum Lc=8
# tile inside the budget below; the tile shrinks as FB grows toward it.
# Wider feature sets now CHUNK the feature axis into per-call slices of
# this width (ISSUE 9) instead of falling off the kernel path — the
# chunk choice lives in the shared VMEM model
# (`ops/vmem.py:split_lane_chunk_features`), so memcheck's MEM004 and
# this dispatcher agree on where feasibility is decided.
from .vmem import SPLIT_MAX_LANES as MAX_LANES
from .vmem import split_lane_chunk_features

# VMEM working-set budget for the leaf-tile choice: the kernel holds
# roughly 6 concurrent [3*Lc, FB] f32 arrays in the missing path
# (stacked channels, masked copies, both prefix-sum variants), so the
# live set is ~72*Lc*FB bytes.  12 MiB leaves headroom under the ~16 MB
# per-core VMEM for pipelining + the in/out blocks.  Override for
# hardware-verified tuning with LGBM_TPU_SPLIT_VMEM_MB.
_WORKING_SET_BYTES_PER_CELL = 72


def _vmem_budget_bytes() -> int:
    # the shared VMEM model (ops/vmem.py) owns the knob so memcheck's
    # MEM004 and this kernel agree on where feasibility is decided
    from .vmem import split_vmem_budget_bytes
    return split_vmem_budget_bytes()


# module-global kill switch: flipped by disable_on_compile_error when a
# Mosaic/VMEM compile failure escapes the static gates anyway; every
# later trace falls back to the XLA scan path (GBDT rebuilds its
# programs — see _shared_serial_build's split_kernel cache key)
_DISABLED = [False]

# markers of a kernel-compile-class failure (vs a transient RPC fault,
# which the retry layer owns)
COMPILE_FAILURE_MARKERS = ("Mosaic", "mosaic", "VMEM", "vmem",
                           "Failed to compile", "XLA compilation",
                           "jellyfish", "INTERNAL: Compile")


def split_kernel_disabled() -> bool:
    return _DISABLED[0]


def disable_split_kernel(reason: str = "") -> None:
    if not _DISABLED[0]:
        _DISABLED[0] = True
        from ..utils.log import log_once
        # deduped: tests re-arm via enable_split_kernel and retried
        # dispatches can re-trip this every block — one line per process
        log_once("pallas_split.disabled",
                 "fused split kernel disabled for this process; "
                 "falling back to the XLA scan path"
                 + (f" ({reason})" if reason else ""))


def enable_split_kernel() -> None:
    """Re-arm (tests)."""
    _DISABLED[0] = False


def disable_on_compile_error(exc: BaseException) -> bool:
    """If ``exc`` looks like a kernel compile failure, disable the
    kernel process-wide and return True (caller should rebuild + retry
    its program once)."""
    if _DISABLED[0]:
        return False
    msg = str(exc)
    if any(m in msg for m in COMPILE_FAILURE_MARKERS):
        disable_split_kernel(msg[:200])
        return True
    return False


def split_kernel_ok(num_features: int, B: int,
                    has_categorical: bool, num_rows: int = 0) -> bool:
    """Whether the fused split kernel can express this config (numerical
    features only, power-of-two bin stride, F*B lane-aligned) AND is the
    right default for it.

    Measured A/B on the v5e: at 7k rows the kernel HALVES warm
    time/iteration (the XLA scan's ~50-op-per-wave overhead dominates
    row work), while at 1M rows it is ~5% slower (the ops hide behind
    row-scaled kernels and the fused call adds its own per-wave cost).
    Default: on for datasets at/below the compile-lean row threshold,
    where op overhead rules; LGBM_TPU_SPLIT_KERNEL=1/0 forces."""
    if has_categorical or _DISABLED[0]:
        return False
    env = os.environ.get("LGBM_TPU_SPLIT_KERNEL", "")
    if env in ("0", "false"):
        return False
    if B & (B - 1) or B > 256:
        return False
    FB = num_features * B
    # at/below the lane cap the single-call path needs LANE alignment;
    # above it the feature axis chunks into lane-aligned, zero-padded
    # slices (split_lane_chunk_features), so any width is expressible
    if FB <= MAX_LANES and FB % LANE != 0:
        return False
    if env in ("1", "true"):
        return True
    lean = int(os.environ.get("LGBM_TPU_COMPILE_LEAN_ROWS", 65536))
    return num_rows <= lean


def _leaf_tile(L2: int, FB: int = LANE) -> int:
    """Leaf-tile height, budgeted against the F*B lane width so the
    kernel's ~[3*Lc, FB] f32 working set stays inside VMEM (ADVICE r5
    #1: a fixed 32-leaf tile at wide FB compile-crashed instead of
    shrinking).  Power of two in [8, 32]."""
    cap = 32
    budget = _vmem_budget_bytes()
    while cap > 8 and cap * FB * _WORKING_SET_BYTES_PER_CELL > budget:
        cap //= 2
    t = 8
    while t < min(L2, cap):
        t *= 2
    return t


def _seg_cumsum(x, lane_mod, B):
    """Forward prefix sum within each B-lane segment (masked rolls)."""
    k = 1
    while k < B:
        sh = pltpu.roll(x, k, 1)
        x = x + jnp.where(lane_mod >= k, sh, 0.0)
        k *= 2
    return x


def _seg_suffix(x, lane_mod, B, FB):
    """Suffix sum within each B-lane segment (left-roll = right-roll by
    FB-k: pltpu.roll requires a non-negative shift)."""
    k = 1
    while k < B:
        sh = pltpu.roll(x, FB - k, 1)
        x = x + jnp.where(lane_mod < B - k, sh, 0.0)
        k *= 2
    return x


def _split_kernel(g_ref, h_ref, c_ref, tot_ref, const_ref, out_ref, *,
                  B: int, FB: int, Lc: int, any_missing: bool):
    """One leaf-tile: full numerical split scan -> [Lc, LANE] packed
    (gain, feat, bin, default_left, lg, lh, lc)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (3 * Lc, FB), 1)
    lane_mod = lane & (B - 1)

    vmask = const_ref[0:1, :]          # valid & not missing cell
    miss = const_ref[1:2, :]           # the missing cell
    ok_base = const_ref[2:3, :]        # threshold-position validity
    hasmiss = const_ref[3:4, :]        # feature has a missing type
    fmask = const_ref[4:5, :]          # feature_fraction mask
    # hyper-parameters ride in lane memory (they may be traced values
    # when the caller's params pytree crosses a jit boundary)
    l1 = const_ref[5, 0]
    l2 = const_ref[5, 1]
    min_d = const_ref[5, 2]
    min_he = const_ref[5, 3]           # min_sum_hessian + kEpsilon

    # g/h/c stacked on sublanes so one roll round advances all three
    # (rank-2 refs only: rank-3 blocks crash the Mosaic lowering)
    ghc = jnp.concatenate([g_ref[:], h_ref[:], c_ref[:]], axis=0)
    gs = ghc * vmask                                    # scanned cells
    cl0 = _seg_cumsum(gs, lane_mod, B)                  # missing-right
    if any_missing:
        m_only = ghc * miss
        sfx = _seg_suffix(m_only, lane_mod, B, FB)
        m_at0 = jnp.where(lane_mod == 0, sfx, 0.0)      # seg total -> lane 0
        mb = _seg_cumsum(m_at0, lane_mod, B)            # bcast over segment
        cl1 = cl0 + mb                                  # missing-left

    def gain_of(lg, lh):
        # ThresholdL1 applied unconditionally: sign(s)*max(|s|-l1,0)
        # reduces exactly to s at l1=0
        lg = jnp.sign(lg) * jnp.maximum(jnp.abs(lg) - l1, 0.0)
        return lg * lg / (lh + l2)

    # fresh iota, NOT a slice of `lane`: a sliced iota feeding the
    # min-reduce crashes the Mosaic/jellyfish lowering (Check failed:
    # limits[i] <= dim(i)) on this toolchain
    lane1 = jax.lax.broadcasted_iota(jnp.int32, (Lc, FB), 1)
    tg = tot_ref[:, 0:1]
    th = tot_ref[:, 1:2]
    tc = tot_ref[:, 2:3]

    def variant(cl, extra_ok):
        lg, lh, lc = cl[:Lc], cl[Lc:2 * Lc], cl[2 * Lc:]
        rg, rh, rc = tg - lg, th - lh, tc - lc
        ok = ((lc >= min_d) & (rc >= min_d)
              & (lh >= min_he) & (rh >= min_he)
              & (ok_base > 0.5) & (fmask > 0.5) & extra_ok)
        gain = gain_of(lg, lh) + gain_of(rg, rh)
        return jnp.where(ok, gain, K_MIN_SCORE), lg, lh, lc

    g0, lg0, lh0, lc0 = variant(cl0, True)
    if any_missing:
        g1, lg1, lh1, lc1 = variant(cl1, hasmiss > 0.5)
        use1 = g1 > g0                        # tie -> variant 0
        gv = jnp.where(use1, g1, g0)
        lgv = jnp.where(use1, lg1, lg0)
        lhv = jnp.where(use1, lh1, lh0)
        lcv = jnp.where(use1, lc1, lc0)
        varv = use1.astype(jnp.float32)
    else:
        gv, lgv, lhv, lcv = g0, lg0, lh0, lc0
        varv = jnp.zeros_like(g0)

    best = jnp.max(gv, axis=1, keepdims=True)                  # [Lc, 1]
    at_best = gv >= best                    # ties -> lowest joint index
    idx = jnp.min(jnp.where(at_best, lane1, FB), axis=1,
                  keepdims=True)                               # [Lc, 1]
    sel = (lane1 == idx).astype(jnp.float32)

    def pick(x):
        return jnp.sum(x * sel, axis=1, keepdims=True)

    out_lane = jax.lax.broadcasted_iota(jnp.int32, (Lc, LANE), 1)
    idx_f = idx.astype(jnp.float32)
    feat = jnp.floor(idx_f / B)
    binv = idx_f - feat * B
    vals = [best, feat, binv, pick(varv), pick(lgv), pick(lhv),
            pick(lcv)]
    out = jnp.zeros((Lc, LANE), jnp.float32)
    for i, v in enumerate(vals):
        out = jnp.where(out_lane == i, v, out)
    out_ref[:] = out


def find_best_splits_pallas(grid: jnp.ndarray,
                            leaf_sum_grad: jnp.ndarray,
                            leaf_sum_hess: jnp.ndarray,
                            leaf_count: jnp.ndarray,
                            num_bins: jnp.ndarray,
                            missing_types: jnp.ndarray,
                            default_bins: jnp.ndarray,
                            *,
                            B: int,
                            params: SplitParams,
                            feature_mask: jnp.ndarray | None = None,
                            any_missing: bool = True,
                            interpret: bool = False) -> SplitResult:
    """Drop-in numerical-only twin of :func:`ops.split.find_best_splits`
    over a ``[L2, F, B, 3]`` padded grid (``B`` = bin stride).

    Feature sets wider than the per-call lane cap (``F*B >
    SPLIT_MAX_LANES`` — the 255-bin MSLR shape) run as PER-CHUNK kernel
    calls over lane-aligned feature slices (`ops/vmem.py
    split_lane_chunk_features`), merged on the raw packed gains with
    the earlier chunk winning exact ties — the same lowest-feature
    tie-break the single call's joint argmax applies.  Short last
    chunks zero-pad their features (``num_bins = 0`` masks every lane
    to ``K_MIN_SCORE``), so per-chunk results match the single-call
    scan bitwise."""
    L2, F, Bg, _ = grid.shape
    assert Bg == B
    if F * B <= MAX_LANES:
        out = _scan_feature_chunk(
            grid, leaf_sum_grad, leaf_sum_hess, leaf_count, num_bins,
            missing_types, default_bins, feature_mask, B=B,
            params=params, any_missing=any_missing, interpret=interpret)
    else:
        fc = split_lane_chunk_features(F, B)
        out = None
        for s in range(0, F, fc):
            e = min(F, s + fc)
            out_c = _scan_feature_chunk(
                grid[:, s:e], leaf_sum_grad, leaf_sum_hess, leaf_count,
                num_bins[s:e], missing_types[s:e], default_bins[s:e],
                feature_mask[s:e] if feature_mask is not None else None,
                B=B, params=params, any_missing=any_missing,
                interpret=interpret, pad_features=fc)
            if s:
                out_c = out_c.at[:, 1].add(float(s))    # global feature id
                take = out_c[:, 0] > out[:, 0]          # tie -> earlier chunk
                out = jnp.where(take[:, None], out_c, out)
            else:
                out = out_c

    parent_gain = leaf_split_gain(leaf_sum_grad, leaf_sum_hess,
                                  params.lambda_l1, params.lambda_l2)
    gain_shift = parent_gain + params.min_gain_to_split

    b_lg, b_lh, b_lc = out[:, 4], out[:, 5], out[:, 6]
    b_rg = leaf_sum_grad - b_lg
    b_rh = leaf_sum_hess - b_lh
    b_rc = leaf_count - b_lc
    l1, l2 = params.lambda_l1, params.lambda_l2
    return SplitResult(
        gain=(out[:, 0] - gain_shift).astype(jnp.float32),
        feature=out[:, 1].astype(jnp.int32),
        threshold=out[:, 2].astype(jnp.int32),
        default_left=out[:, 3] > 0.5,
        is_categorical=jnp.zeros(L2, bool),
        cat_mask=jnp.zeros((L2, B), bool),
        left_sum_grad=b_lg, left_sum_hess=b_lh, left_count=b_lc,
        right_sum_grad=b_rg, right_sum_hess=b_rh, right_count=b_rc,
        left_output=leaf_output(b_lg, b_lh, l1, l2),
        right_output=leaf_output(b_rg, b_rh, l1, l2),
    )


def _scan_feature_chunk(grid, leaf_sum_grad, leaf_sum_hess, leaf_count,
                        num_bins, missing_types, default_bins,
                        feature_mask, *, B: int, params: SplitParams,
                        any_missing: bool, interpret: bool,
                        pad_features: int = 0) -> jnp.ndarray:
    """One lane-cap-sized kernel call: scan a ``[L2, Fc, B, 3]`` grid
    slice and return the packed per-leaf winner ``[L2, LANE]`` (raw
    gain, LOCAL feature, bin, default_left, left sums).  With
    ``pad_features`` the slice zero-pads to that width (masked lanes,
    LANE-aligned)."""
    L2, F, Bg, _ = grid.shape
    if pad_features and F < pad_features:
        grid = jnp.pad(grid, ((0, 0), (0, pad_features - F),
                              (0, 0), (0, 0)))
        num_bins = jnp.pad(num_bins, (0, pad_features - F))
        missing_types = jnp.pad(missing_types, (0, pad_features - F))
        default_bins = jnp.pad(default_bins, (0, pad_features - F))
        if feature_mask is not None:
            feature_mask = jnp.pad(feature_mask, (0, pad_features - F))
        F = pad_features
    FB = F * B
    Lc = _leaf_tile(L2, FB)
    L_pad = -(-L2 // Lc) * Lc

    chans = [jnp.pad(grid[..., i].reshape(L2, FB),
                     ((0, L_pad - L2), (0, 0))) for i in range(3)]

    tot = jnp.zeros((L_pad, LANE), jnp.float32)
    tot = tot.at[:L2, 0].set(leaf_sum_grad)
    tot = tot.at[:L2, 1].set(leaf_sum_hess)
    tot = tot.at[:L2, 2].set(leaf_count)

    # dataset-constant lane masks (loop-invariant: XLA hoists them out
    # of the wave scan)
    bin_ids = jnp.arange(B)[None, :]                       # [1, B]
    valid = bin_ids < num_bins[:, None]                    # [F, B]
    has_nan = (missing_types == MISSING_NAN)[:, None]
    is_zero = (missing_types == MISSING_ZERO)[:, None]
    nanb = jnp.where(has_nan[:, 0], num_bins - 1, -1)[:, None]
    missb = jnp.where(has_nan[:, 0], nanb[:, 0],
                      jnp.where(is_zero[:, 0], default_bins, -1))[:, None]
    miss_cell = (bin_ids == missb) & valid
    max_t = jnp.where(has_nan[:, 0], num_bins - 2, num_bins - 1)[:, None]
    ok_base = (bin_ids < max_t) & ~(miss_cell & is_zero)
    hasmiss = jnp.broadcast_to(missb >= 0, (F, B))
    fm = (jnp.broadcast_to(feature_mask[:, None], (F, B))
          if feature_mask is not None else jnp.ones((F, B), bool))
    consts = jnp.stack([
        (valid & ~miss_cell).reshape(FB), miss_cell.reshape(FB),
        ok_base.reshape(FB), hasmiss.reshape(FB), fm.reshape(FB),
        jnp.zeros(FB, bool), jnp.zeros(FB, bool), jnp.zeros(FB, bool),
    ]).astype(jnp.float32)                                  # [8, FB]
    hp = jnp.zeros(FB, jnp.float32)
    hp = hp.at[0].set(params.lambda_l1).at[1].set(params.lambda_l2)
    hp = hp.at[2].set(params.min_data_in_leaf * 1.0)
    hp = hp.at[3].set(params.min_sum_hessian_in_leaf + K_EPSILON)
    consts = consts.at[5].set(hp)

    kern = functools.partial(
        _split_kernel, B=B, FB=FB, Lc=Lc, any_missing=any_missing)
    return pl.pallas_call(
        kern,
        grid=(L_pad // Lc,),
        in_specs=[
            pl.BlockSpec((Lc, FB), lambda i: (i, 0)),
            pl.BlockSpec((Lc, FB), lambda i: (i, 0)),
            pl.BlockSpec((Lc, FB), lambda i: (i, 0)),
            pl.BlockSpec((Lc, LANE), lambda i: (i, 0)),
            pl.BlockSpec((8, FB), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((Lc, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((L_pad, LANE), jnp.float32),
        interpret=interpret,
    )(*chans, tot, consts)[:L2]
