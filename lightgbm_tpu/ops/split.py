"""Vectorized best-split search over histograms.

TPU-native redesign of the reference's per-feature sequential scans
(`/root/reference/src/treelearner/feature_histogram.hpp`):

* ``FindBestThresholdSequence`` (`feature_histogram.hpp:312-452`) — a
  sequential two-direction scan with missing-value default-direction
  handling.  Here: prefix sums (``cumsum``) over the bin axis for ALL
  (leaf, feature) pairs at once, two missing-direction variants evaluated
  in parallel, and one big masked argmax.  No sequential code.
* ``FindBestThresholdCategorical`` (`feature_histogram.hpp:104-259`) —
  one-hot (one-vs-rest) search for low-cardinality features
  (``max_cat_to_onehot``) and the sorted many-vs-many scan (bins ordered
  by grad/(hess+cat_smooth), both directions, capped at
  ``max_cat_threshold``) — both vectorized with argsort + cumsum.
* ``GetLeafSplitGain`` / ``CalculateSplittedLeafOutput``
  (`feature_histogram.hpp:291-308`) — exact L1/L2-regularized formulas.

Semantics: threshold ``t`` sends ``bin <= t`` left; missing values (NaN
bin for MissingType::NaN, the zero/default bin for MissingType::Zero) go
to the side chosen by ``default_left``.  Split gain reported is the
improvement over the parent (reference ``SplitInfo.gain`` = child gains −
``min_gain_shift``).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..io.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_EPSILON = 1e-15          # reference kEpsilon (feature_histogram.hpp)
K_MIN_SCORE = -1e30        # reference kMinScore


class SplitParams(NamedTuple):
    """Static split hyper-parameters (subset of TreeConfig, config.h:201-236)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_cat_threshold: int = 32
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_to_onehot: int = 4


class SplitResult(NamedTuple):
    """Best split per leaf — the SplitInfo analog (`split_info.hpp`).

    All fields are ``[L]`` (or ``[L, B]`` for the categorical mask); a
    jittable pytree, so it can cross collective boundaries in the
    distributed learners the way SplitInfo crosses the wire in the
    reference (`parallel_tree_learner.h:184-207`).
    """
    gain: jnp.ndarray           # f32, improvement over parent; <=0 -> no split
    feature: jnp.ndarray        # i32 used-feature index
    threshold: jnp.ndarray      # i32 bin threshold (numerical)
    default_left: jnp.ndarray   # bool missing direction
    is_categorical: jnp.ndarray  # bool
    cat_mask: jnp.ndarray       # bool [L, B]: bins going LEFT (categorical)
    left_sum_grad: jnp.ndarray
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray     # f32 (histogram counts are f32)
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """Soft-threshold (reference ``ThresholdL1``, feature_histogram.hpp:283)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_split_gain(sum_grad: jnp.ndarray, sum_hess: jnp.ndarray,
                    l1: float, l2: float) -> jnp.ndarray:
    """``GetLeafSplitGain`` (feature_histogram.hpp:291-297)."""
    t = threshold_l1(sum_grad, l1)
    return t * t / (sum_hess + l2)


def leaf_output(sum_grad: jnp.ndarray, sum_hess: jnp.ndarray,
                l1: float, l2: float) -> jnp.ndarray:
    """``CalculateSplittedLeafOutput`` (feature_histogram.hpp:305-308)."""
    return -threshold_l1(sum_grad, l1) / (sum_hess + l2)


def _split_gain(lg, lh, rg, rh, l1, l2):
    return (leaf_split_gain(lg, lh, l1, l2)
            + leaf_split_gain(rg, rh, l1, l2))


def _select_miss_bin(is_miss_cell, g, h, c):
    """Missing-cell stats per (leaf, feature): single-nonzero selection.

    ``is_miss_cell`` is one-hot over the bin axis (at most one missing
    cell per feature), so each sum picks exactly one histogram cell —
    exact in any order, and registered as a sanctioned numcheck context
    (tools/numcheck/reduction_registry.py)."""
    miss_g = jnp.sum(jnp.where(is_miss_cell[None], g, 0.0), axis=-1)     # [L, F]
    miss_h = jnp.sum(jnp.where(is_miss_cell[None], h, 0.0), axis=-1)
    miss_c = jnp.sum(jnp.where(is_miss_cell[None], c, 0.0), axis=-1)
    return miss_g, miss_h, miss_c


def find_best_splits(hist: jnp.ndarray,
                     leaf_sum_grad: jnp.ndarray,
                     leaf_sum_hess: jnp.ndarray,
                     leaf_count: jnp.ndarray,
                     num_bins: jnp.ndarray,
                     missing_types: jnp.ndarray,
                     default_bins: jnp.ndarray,
                     is_categorical: jnp.ndarray,
                     params: SplitParams,
                     feature_mask: jnp.ndarray | None = None,
                     any_categorical: bool = True,
                     any_missing: bool = True,
                     feature_chunk: int | None = None) -> SplitResult:
    """Best split for every leaf over every feature, fully vectorized.

    Args:
      hist: ``[L, F, B, 3]`` padded histogram grid (grad, hess, count).
      leaf_sum_grad/hess/count: ``[L]`` totals from the data partition
        (authoritative, like the reference using leaf sums rather than
        histogram sums for the parent side).
      num_bins: ``[F]`` true bin count per feature (incl. NaN bin).
      missing_types: ``[F]`` MissingType enum per feature.
      default_bins: ``[F]`` bin holding the value 0.0 per feature.
      is_categorical: ``[F]`` bool.
      params: static SplitParams.
      feature_mask: optional ``[F]`` bool — feature_fraction sampling
        (`serial_tree_learner.cpp:240-266` analog).
      feature_chunk: optional static chunk width along the FEATURE axis:
        the scan runs per chunk and the per-chunk winners merge with the
        argmax's first-max tie-break, bounding the live ``~10 x
        [2, L, Fc, B]`` f32 stack (`ops/vmem.py
        split_scan_chunk_features` picks Fc so the 255-bin MSLR shape
        stays inside the HBM budget).  Every per-(leaf, feature) value
        is feature-independent, so chunked == unchunked bitwise.

    Returns:
      SplitResult with per-leaf best splits.
    """
    F = hist.shape[1]
    l1, l2 = params.lambda_l1, params.lambda_l2
    parent_gain = leaf_split_gain(leaf_sum_grad, leaf_sum_hess, l1, l2)
    gain_shift = parent_gain + params.min_gain_to_split

    def block(s, e):
        fm = feature_mask[s:e] if feature_mask is not None else None
        return _find_best_splits_block(
            hist[:, s:e], leaf_sum_grad, leaf_sum_hess, leaf_count,
            num_bins[s:e], missing_types[s:e], default_bins[s:e],
            is_categorical[s:e], params, fm, any_categorical, any_missing)

    if feature_chunk is None or feature_chunk >= F:
        res = block(0, F)
    else:
        # merge on the RAW gain (pre-shift): chunks are in feature
        # order and ties keep the EARLIER chunk, reproducing the
        # global argmax's first-max winner exactly
        res = None
        for s in range(0, F, feature_chunk):
            r = block(s, min(F, s + feature_chunk))
            r = r._replace(feature=(r.feature + s).astype(jnp.int32))
            if res is None:
                res = r
            else:
                take = r.gain > res.gain
                res = jax.tree.map(
                    lambda cur, new: jnp.where(
                        take.reshape((-1,) + (1,) * (cur.ndim - 1)),
                        new, cur),
                    res, r)
    return res._replace(gain=(res.gain - gain_shift).astype(jnp.float32))


def _find_best_splits_block(hist, leaf_sum_grad, leaf_sum_hess, leaf_count,
                            num_bins, missing_types, default_bins,
                            is_categorical, params: SplitParams,
                            feature_mask, any_categorical: bool,
                            any_missing: bool) -> SplitResult:
    """One feature block of :func:`find_best_splits`: the full scan over
    ``[L, Fc, B, 3]`` returning the per-leaf winner with its RAW gain
    (no parent shift — the caller merges chunks on raw gains, then
    subtracts the shift once)."""
    L, F, B, _ = hist.shape
    g = hist[..., 0]
    h = hist[..., 1]
    c = hist[..., 2]
    bin_ids = jnp.arange(B)

    tg = leaf_sum_grad[:, None]                     # [L, 1]
    th = leaf_sum_hess[:, None]
    tc = leaf_count[:, None]

    l1, l2 = params.lambda_l1, params.lambda_l2
    min_d = params.min_data_in_leaf * 1.0
    min_h = params.min_sum_hessian_in_leaf

    valid_bin = bin_ids[None, :] < num_bins[:, None]                     # [F, B]

    # ---- numerical scan -------------------------------------------------
    has_nan = (missing_types == MISSING_NAN)                             # [F]
    is_zero_missing = (missing_types == MISSING_ZERO)
    nan_bin = jnp.where(has_nan, num_bins - 1, -1)                       # [F]
    # the "missing cell" per feature: NaN bin or (zero) default bin
    miss_bin = jnp.where(has_nan, nan_bin,
                         jnp.where(is_zero_missing, default_bins, -1))   # [F]
    is_miss_cell = bin_ids[None, :] == miss_bin[:, None]                 # [F, B]
    has_missing = (miss_bin >= 0)                                        # [F]

    vb = valid_bin[None, :, :]
    g_scan = jnp.where(vb & ~is_miss_cell[None], g, 0.0)
    h_scan = jnp.where(vb & ~is_miss_cell[None], h, 0.0)
    c_scan = jnp.where(vb & ~is_miss_cell[None], c, 0.0)

    miss_g, miss_h, miss_c = _select_miss_bin(is_miss_cell, g, h, c)     # [L, F]

    cl_g = jnp.cumsum(g_scan, axis=-1)                                   # [L, F, B]
    cl_h = jnp.cumsum(h_scan, axis=-1)
    cl_c = jnp.cumsum(c_scan, axis=-1)

    max_t = jnp.where(has_nan, num_bins - 2, num_bins - 1)               # [F]
    t_ok = bin_ids[None, :] < max_t[:, None]                             # [F, B]

    if not any_missing:
        # no feature has a missing type: single-direction scan, half the
        # arrays (statically specialized like the categorical skip)
        lg, lh, lc = cl_g, cl_h, cl_c
        rg = tg[:, :, None] - lg
        rh = th[:, :, None] - lh
        rc = tc[:, :, None] - lc
        num_gain = _split_gain(lg, lh, rg, rh, l1, l2)                   # [L, F, B]
        ok = ((lc >= min_d) & (rc >= min_d)
              & (lh >= min_h + K_EPSILON) & (rh >= min_h + K_EPSILON))
        ok &= t_ok[None, :, :]
        num_gain = jnp.where(ok, num_gain, K_MIN_SCORE)
        best_bin = jnp.argmax(num_gain, axis=-1)                         # [L, F]
        num_best_gain = jnp.take_along_axis(
            num_gain, best_bin[..., None], axis=-1)[..., 0]

        def sel(x):
            return jnp.take_along_axis(x, best_bin[..., None],
                                       axis=-1)[..., 0]

        num_lg, num_lh, num_lc = sel(lg), sel(lh), sel(lc)
        num_default_left = jnp.zeros_like(best_bin, dtype=bool)
    else:
        # variant 0: missing right;  variant 1: missing left
        lg = jnp.stack([cl_g, cl_g + miss_g[..., None]], axis=0)         # [2, L, F, B]
        lh = jnp.stack([cl_h, cl_h + miss_h[..., None]], axis=0)
        lc = jnp.stack([cl_c, cl_c + miss_c[..., None]], axis=0)
        rg = tg[None, :, :, None] - lg
        rh = th[None, :, :, None] - lh
        rc = tc[None, :, :, None] - lc

        num_gain = _split_gain(lg, lh, rg, rh, l1, l2)                   # [2, L, F, B]

        ok = ((lc >= min_d) & (rc >= min_d)
              & (lh >= min_h + K_EPSILON) & (rh >= min_h + K_EPSILON))
        ok &= t_ok[None, None, :, :]
        # variant 1 (missing left) only meaningful when the feature has missing
        ok &= jnp.stack([jnp.ones_like(has_missing),
                         has_missing], axis=0)[:, None, :, None]
        # don't split ON the missing cell for zero-missing (it's out of order)
        ok &= ~(is_miss_cell & is_zero_missing[:, None])[None, None, :, :]
        num_gain = jnp.where(ok, num_gain, K_MIN_SCORE)

        # best variant per (L, F, B) -> best bin per (L, F)
        var_best = jnp.argmax(num_gain, axis=0)                          # [L, F, B]
        num_gain_b = jnp.max(num_gain, axis=0)
        best_bin = jnp.argmax(num_gain_b, axis=-1)                       # [L, F]
        num_best_gain = jnp.take_along_axis(
            num_gain_b, best_bin[..., None], axis=-1)[..., 0]            # [L, F]
        best_var = jnp.take_along_axis(
            var_best, best_bin[..., None], axis=-1)[..., 0]              # [L, F]

        def sel(x):  # x: [2, L, F, B] -> [L, F] at (best_var, best_bin)
            xb = jnp.take_along_axis(x, best_bin[None, ..., None],
                                     axis=-1)[..., 0]
            return jnp.take_along_axis(
                xb, best_var[None, ...], axis=0)[0]

        num_lg, num_lh, num_lc = sel(lg), sel(lh), sel(lc)
        num_default_left = best_var.astype(bool)
    # features with missing but no observed missing in this leaf: reference
    # sends missing with the majority — we keep scan choice (tie -> right)

    # ---- categorical (statically skipped for all-numerical datasets) ----
    if any_categorical:
        cat = _categorical_splits(g, h, c, tg, th, tc, num_bins, valid_bin,
                                  params)
        (cat_gain, cat_mask_lr, cat_lg, cat_lh, cat_lc) = cat
        use_cat = is_categorical[None, :]                                # [1, F]
    else:
        cat_gain = jnp.full((L, F), K_MIN_SCORE)
        cat_mask_lr = jnp.zeros((L, F, B), bool)
        cat_lg = cat_lh = cat_lc = jnp.zeros((L, F))
        use_cat = jnp.zeros((1, F), bool)
    feat_gain = jnp.where(use_cat, cat_gain, num_best_gain)              # [L, F]
    if feature_mask is not None:
        feat_gain = jnp.where(feature_mask[None, :], feat_gain, K_MIN_SCORE)

    best_feat = jnp.argmax(feat_gain, axis=-1)                           # [L]
    best_gain = jnp.take_along_axis(feat_gain, best_feat[:, None], axis=-1)[:, 0]

    def pick(x):  # [L, F] -> [L]
        return jnp.take_along_axis(x, best_feat[:, None], axis=-1)[:, 0]

    bf_cat = jnp.take_along_axis(
        use_cat.repeat(L, 0), best_feat[:, None], axis=-1)[:, 0]
    b_lg = jnp.where(bf_cat, pick(cat_lg), pick(num_lg))
    b_lh = jnp.where(bf_cat, pick(cat_lh), pick(num_lh))
    b_lc = jnp.where(bf_cat, pick(cat_lc), pick(num_lc))
    b_rg = leaf_sum_grad - b_lg
    b_rh = leaf_sum_hess - b_lh
    b_rc = leaf_count - b_lc

    eff_l2 = jnp.where(bf_cat, l2 + params.cat_l2, l2)
    left_out = -threshold_l1(b_lg, l1) / (b_lh + eff_l2)
    right_out = -threshold_l1(b_rg, l1) / (b_rh + eff_l2)

    cat_mask_best = jnp.take_along_axis(
        cat_mask_lr, best_feat[:, None, None], axis=1)[:, 0, :]          # [L, B]

    return SplitResult(
        gain=best_gain.astype(jnp.float32),       # RAW (caller shifts)
        feature=best_feat.astype(jnp.int32),
        threshold=pick(best_bin).astype(jnp.int32),
        default_left=jnp.where(bf_cat, False, pick(num_default_left)),
        is_categorical=bf_cat,
        cat_mask=cat_mask_best,
        left_sum_grad=b_lg, left_sum_hess=b_lh, left_count=b_lc,
        right_sum_grad=b_rg, right_sum_hess=b_rh, right_count=b_rc,
        left_output=left_out, right_output=right_out,
    )


def _categorical_splits(g, h, c, tg, th, tc, num_bins, valid_bin,
                        params: SplitParams):
    """One-hot + sorted many-vs-many categorical split search
    (`feature_histogram.hpp:104-259`).  Returns per-(leaf, feature) best
    gain, the left-going bin mask, and left-side sums."""
    L, F, B = g.shape
    l1 = params.lambda_l1
    l2 = params.lambda_l2 + params.cat_l2
    min_d = params.min_data_in_leaf * 1.0
    min_h = params.min_sum_hessian_in_leaf

    occupied = valid_bin[None] & (c > 0)                                 # [L, F, B]

    # --- one-vs-rest: left = single category k --------------------------
    oh_lg, oh_lh, oh_lc = g, h, c
    oh_rg = tg[..., None] - oh_lg
    oh_rh = th[..., None] - oh_lh
    oh_rc = tc[..., None] - oh_lc
    oh_gain = _split_gain(oh_lg, oh_lh, oh_rg, oh_rh, l1, l2)
    oh_ok = (occupied & (oh_lc >= min_d) & (oh_rc >= min_d)
             & (oh_lh >= min_h + K_EPSILON) & (oh_rh >= min_h + K_EPSILON))
    oh_gain = jnp.where(oh_ok, oh_gain, K_MIN_SCORE)
    oh_best = jnp.argmax(oh_gain, axis=-1)                               # [L, F]
    oh_best_gain = jnp.max(oh_gain, axis=-1)

    # --- many-vs-many: sort by grad/(hess+cat_smooth), scan both ends ---
    ratio = g / (h + params.cat_smooth)
    sort_key = jnp.where(occupied, ratio, jnp.inf)
    order = jnp.argsort(sort_key, axis=-1)                               # [L, F, B]
    sg = jnp.take_along_axis(g, order, axis=-1)
    sh = jnp.take_along_axis(h, order, axis=-1)
    sc = jnp.take_along_axis(c, order, axis=-1)
    occ_sorted = jnp.take_along_axis(occupied, order, axis=-1)
    n_occ = jnp.sum(occupied, axis=-1)                                   # [L, F]

    def direction(sg, sh, sc, occ_sorted):
        csg = jnp.cumsum(sg, axis=-1)
        csh = jnp.cumsum(sh, axis=-1)
        csc = jnp.cumsum(sc, axis=-1)
        # count OCCUPIED categories in the prefix (raw position would be
        # wrong in the backward scan, whose prefix starts with the
        # unoccupied inf-key slots argsort pushed to the end)
        k_occ = jnp.cumsum(occ_sorted.astype(jnp.int32), axis=-1)
        mg = _split_gain(csg, csh, tg[..., None] - csg,
                         th[..., None] - csh, l1, l2)
        okk = ((csc >= min_d) & (tc[..., None] - csc >= min_d)
               & (csh >= min_h + K_EPSILON)
               & (th[..., None] - csh >= min_h + K_EPSILON)
               & occ_sorted                                # split at an occupied slot
               & (k_occ <= params.max_cat_threshold)
               & (k_occ < n_occ[..., None]))
        mg = jnp.where(okk, mg, K_MIN_SCORE)
        best_k = jnp.argmax(mg, axis=-1)
        return (jnp.max(mg, axis=-1), best_k,
                jnp.take_along_axis(csg, best_k[..., None], -1)[..., 0],
                jnp.take_along_axis(csh, best_k[..., None], -1)[..., 0],
                jnp.take_along_axis(csc, best_k[..., None], -1)[..., 0])

    fw = direction(sg, sh, sc, occ_sorted)
    bw = direction(sg[..., ::-1], sh[..., ::-1], sc[..., ::-1],
                   occ_sorted[..., ::-1])

    use_bw = bw[0] > fw[0]
    mv_gain = jnp.where(use_bw, bw[0], fw[0])
    mv_lg = jnp.where(use_bw, bw[2], fw[2])
    mv_lh = jnp.where(use_bw, bw[3], fw[3])
    mv_lc = jnp.where(use_bw, bw[4], fw[4])

    # reconstruct left mask over original bins for the winning direction
    pos = jnp.argsort(order, axis=-1)                                    # rank of each bin
    kf = fw[1][..., None]
    kb = bw[1][..., None]
    in_fw = pos <= kf
    in_bw = (B - 1 - pos) <= kb
    mv_mask = jnp.where(use_bw[..., None], in_bw, in_fw) & occupied

    # --- select one-hot vs many-vs-many per feature cardinality ---------
    use_onehot = (num_bins <= params.max_cat_to_onehot)[None, :]         # [1, F]
    cat_gain = jnp.where(use_onehot, oh_best_gain, mv_gain)
    oh_mask = (jnp.arange(B)[None, None, :] == oh_best[..., None])
    cat_mask = jnp.where(use_onehot[..., None], oh_mask, mv_mask)
    cat_lg = jnp.where(use_onehot,
                       jnp.take_along_axis(g, oh_best[..., None], -1)[..., 0],
                       mv_lg)
    cat_lh = jnp.where(use_onehot,
                       jnp.take_along_axis(h, oh_best[..., None], -1)[..., 0],
                       mv_lh)
    cat_lc = jnp.where(use_onehot,
                       jnp.take_along_axis(c, oh_best[..., None], -1)[..., 0],
                       mv_lc)
    return cat_gain, cat_mask, cat_lg, cat_lh, cat_lc
