"""Pallas TPU row-routing kernel — split application without gathers.

The TPU counterpart of the reference's ``DataPartition::Split``
(`/root/reference/src/treelearner/data_partition.hpp`, threaded index
shuffling) combined with the per-row split decision of
``Dataset::Split`` (`src/io/dataset.h:412-419`).  Our row→leaf vector
design needs, per wave, for every row: look up its leaf's chosen split
(feature, threshold, default direction, categorical mask), read the row's
bin at that feature, and move the row to the right-child id if it goes
right.

In XLA this is a chain of ``[n]``-sized gathers from small tables plus a
``take_along_axis`` over the ``[n, G]`` matrix — each of which lowers to
a slow serialized gather on TPU (~3-25 ms per pass at 1M rows).  Here the
whole decision runs in VMEM per row-tile:

* leaf one-hot ``[L_pad, T]`` (compare against an iota — no gather),
* ALL per-leaf split data — including the split feature's group column,
  EFB offset, bin count, default bin, and missing metadata — fetched by
  ONE small matmul ``tabs[16, L_pad] @ ohL -> [16, T]``,
* the row's stored value at its split feature's group column by a masked
  sublane reduction over the ``[G, T]`` bins tile (no gather), then the
  EFB inverse mapping ``col -> feature bin`` in registers
  (`io/dataset.py` BundleInfo encoding; identity when offset < 0),
* categorical membership by ``cat_mask[B, L_pad] @ ohL`` + a bin one-hot
  reduction.

Two leaf vectors ride together (``row_leaf`` for all rows, ``hist_leaf``
with bagged-out rows parked at -1) so both are routed in one pass.

Streams ``bins_t`` (uint8) + the leaf vectors once per wave — the whole
route costs ~1 stream pass instead of ~50 ms of gathers.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.binning import MISSING_NAN, MISSING_ZERO

from .pallas_histogram import DEFAULT_ROW_TILE

LANE = 128

# tabs row layout (per-leaf split decision table)
_T_GROUP, _T_THR, _T_DL, _T_ISCAT, _T_SEL, _T_NEWID = 0, 1, 2, 3, 4, 5
_T_OFF, _T_NB, _T_DB, _T_MT, _T_NANB = 6, 7, 8, 9, 10
# per-leaf OUTPUT value as a hi+lo bf16 pair (exact to ~2^-17 through the
# bf16 MXU pass) — used by the final per-tree route to emit each row's
# leaf value, replacing the ~7ms/iter XLA gather lv[row_leaf]
_T_LVH, _T_LVL = 11, 12
_T_ROWS = 16


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def table_precision(L_pad: int, num_groups: int):
    """MXU precision for the per-leaf table selection dot.

    The table rows carry integers (leaf ids < L, group ids < G, bin ids
    < 256).  bf16 holds integers exactly up to 256, so when every value
    fits, the default single-pass bf16 dot is exact and 6x cheaper than
    HIGHEST (f32-via-bf16x6); larger configs keep HIGHEST."""
    if L_pad <= 256 and num_groups <= 256:
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST


def selection_dtype(tab_prec):
    """Operand dtype for the table-selection dots: bf16-exact configs
    also BUILD the ``[L_pad, T]`` leaf one-hot and the tables in bf16 —
    the one-hot is ~1 GB of VMEM writes per wave at 1M rows in f32,
    halved here (0/1 one-hots and <256 integer tables are bf16-exact)."""
    import jax.numpy as _jnp
    return (_jnp.bfloat16 if tab_prec == jax.lax.Precision.DEFAULT
            else _jnp.float32)


def _route_kernel(bins_ref, leaf2_ref, tabs_ref, cat_ref, out_ref, *,
                  B: int, tab_prec=jax.lax.Precision.HIGHEST,
                  any_cat: bool = True):
    _route_body(bins_ref, leaf2_ref, tabs_ref, cat_ref, out_ref, B=B,
                tab_prec=tab_prec, any_cat=any_cat)


def _route_body(bins_ref, leaf2_ref, tabs_ref, cat_ref, out_ref, *, B: int,
                tab_prec=jax.lax.Precision.HIGHEST, any_cat: bool = True):
    leaf = leaf2_ref[0:1, :]                                  # [1, T] i32
    T = leaf.shape[1]
    L_pad = tabs_ref.shape[1]
    G_pad = bins_ref.shape[0]

    iota_l = jax.lax.broadcasted_iota(jnp.int32, (L_pad, T), 0)
    sel_dt = selection_dtype(tab_prec)
    ohL = (iota_l == leaf).astype(sel_dt)                     # [L_pad, T]
    # tab_prec (see table_precision): bf16-exact configs use the single
    # default pass — and build ohL/tables in bf16 outright (see
    # selection_dtype); larger ids need HIGHEST.  The cat/ohL dots below
    # stay at default precision — 0/1 operands are exact in bf16 and the
    # MXU accumulates in f32.
    sel16 = jnp.dot(tabs_ref[:].astype(sel_dt), ohL,
                    preferred_element_type=jnp.float32,
                    precision=tab_prec)                       # [16, T]
    g_row = sel16[_T_GROUP:_T_GROUP + 1, :]
    thr = sel16[_T_THR:_T_THR + 1, :]
    dl = sel16[_T_DL:_T_DL + 1, :]
    iscat = sel16[_T_ISCAT:_T_ISCAT + 1, :]
    selm = sel16[_T_SEL:_T_SEL + 1, :]
    new_id = sel16[_T_NEWID:_T_NEWID + 1, :]
    off = sel16[_T_OFF:_T_OFF + 1, :]
    nb = sel16[_T_NB:_T_NB + 1, :]
    db = sel16[_T_DB:_T_DB + 1, :]
    mt = sel16[_T_MT:_T_MT + 1, :]
    nanb = sel16[_T_NANB:_T_NANB + 1, :]

    binsf = bins_ref[:].astype(jnp.int32).astype(jnp.float32)  # [G, T]
    iota_g = jax.lax.broadcasted_iota(
        jnp.int32, (G_pad, T), 0).astype(jnp.float32)
    ohG = jnp.where(iota_g == g_row, 1.0, 0.0)                # [G, T]
    c = jnp.sum(ohG * binsf, axis=0, keepdims=True)           # [1, T]

    # EFB inverse mapping: stored column value -> feature bin
    one = jnp.ones_like(c)
    zero = jnp.zeros_like(c)
    rank = c - off
    gt_db = jnp.where(rank >= db, one, zero)
    in_range = jnp.where((rank >= 0) & (rank < nb - 1), one, zero)
    b_bundled = jnp.where(in_range > 0.5, rank + gt_db, db)
    b = jnp.where(off < -0.5, c, b_bundled)                   # [1, T]

    # all masks ride as f32 0/1 values (Mosaic rejects bool-valued selects)
    is_missing = jnp.where(
        ((mt == float(MISSING_NAN)) & (b == nanb))
        | ((mt == float(MISSING_ZERO)) & (b == db)), one, zero)

    le_thr = jnp.where(b <= thr, one, zero)
    num_left = jnp.where(is_missing > 0.5, dl, le_thr)
    if any_cat:
        catrow = jnp.dot(cat_ref[:].astype(sel_dt), ohL,
                         preferred_element_type=jnp.float32)  # [B, T]
        iota_b = jax.lax.broadcasted_iota(
            jnp.int32, (B, T), 0).astype(jnp.float32)
        cat_left = jnp.sum(
            jnp.where(iota_b == b, catrow, 0.0), axis=0,
            keepdims=True)                                    # [1, T]
        go_left = jnp.where(iscat > 0.5, cat_left, num_left)
    else:
        # no categorical features in the dataset: skip the [B, L] @
        # [L, T] membership dot + bin one-hot reduction entirely
        go_left = num_left
    in_tree = jnp.where(leaf >= 0, one, zero)
    moved = selm * (one - jnp.minimum(go_left, one)) * in_tree
    nid = new_id.astype(jnp.int32)

    rl = jnp.where(moved > 0.5, nid, leaf)                    # row_leaf'
    hl = leaf2_ref[1:2, :]
    out_ref[0:1, :] = rl
    out_ref[1:2, :] = jnp.where(hl >= 0, rl, hl)              # hist_leaf'
    return rl


def _route_values_kernel(bins_ref, leaf2_ref, tabs_ref, cat_ref, out_ref,
                         val_ref, *, B: int,
                         tab_prec=jax.lax.Precision.HIGHEST,
                         any_cat: bool = True):
    """Route + emit each row's POST-route leaf value (final tree pass).

    The value rides the tabs as a hi+lo bf16 pair selected by a second
    leaf one-hot built from the routed ids; rows outside the tree
    (leaf -1, padding) emit 0."""
    rl = _route_body(bins_ref, leaf2_ref, tabs_ref, cat_ref, out_ref, B=B,
                     tab_prec=tab_prec, any_cat=any_cat)
    T = rl.shape[1]
    L_pad = tabs_ref.shape[1]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (L_pad, T), 0)
    # stays f32: the LVL row is the f32 RESIDUAL of the hi/lo pair —
    # not bf16-representable; a bf16 cast here would silently collapse
    # the pair back to bf16 leaf values (the 0.006-AUC drift the hi/lo
    # route values exist to prevent)
    ohL2 = (iota_l == rl).astype(jnp.float32)
    sel2 = jnp.dot(tabs_ref[_T_LVH:_T_LVL + 1, :], ohL2,
                   preferred_element_type=jnp.float32)        # [2, T]
    val_ref[0:1, :] = sel2[0:1, :] + sel2[1:2, :]


def _leaf_tables(feature, threshold, default_left, is_categorical, sel,
                 new_id, missing_types, nan_bins, default_bins, feat_group,
                 feat_offset, num_bins, L_pad, leaf_values=None):
    """Pack the [16, L_pad] per-leaf decision table (tiny [L] gathers)."""
    L = feature.shape[0]
    f = feature
    tabs = jnp.zeros((_T_ROWS, L_pad), jnp.float32)
    tabs = tabs.at[_T_GROUP, :L].set(feat_group[f].astype(jnp.float32))
    tabs = tabs.at[_T_THR, :L].set(threshold.astype(jnp.float32))
    tabs = tabs.at[_T_DL, :L].set(default_left.astype(jnp.float32))
    tabs = tabs.at[_T_ISCAT, :L].set(is_categorical.astype(jnp.float32))
    tabs = tabs.at[_T_SEL, :L].set(sel.astype(jnp.float32))
    tabs = tabs.at[_T_NEWID, :L].set(new_id.astype(jnp.float32))
    tabs = tabs.at[_T_OFF, :L].set(feat_offset[f].astype(jnp.float32))
    tabs = tabs.at[_T_NB, :L].set(num_bins[f].astype(jnp.float32))
    tabs = tabs.at[_T_DB, :L].set(default_bins[f].astype(jnp.float32))
    tabs = tabs.at[_T_MT, :L].set(missing_types[f].astype(jnp.float32))
    tabs = tabs.at[_T_NANB, :L].set(nan_bins[f].astype(jnp.float32))
    if leaf_values is not None:
        from .pallas_histogram import split_hi_lo
        hi, lo = split_hi_lo(leaf_values.astype(jnp.float32))
        tabs = tabs.at[_T_LVH, :L].set(hi)
        tabs = tabs.at[_T_LVL, :L].set(lo)
    return tabs


@functools.partial(jax.jit,
                   static_argnames=("row_tile", "interpret", "any_cat"))
def route_rows_pallas(bins_t: jnp.ndarray,
                      leaf2: jnp.ndarray,
                      feature: jnp.ndarray,
                      threshold: jnp.ndarray,
                      default_left: jnp.ndarray,
                      is_categorical: jnp.ndarray,
                      cat_mask: jnp.ndarray,
                      sel: jnp.ndarray,
                      new_id: jnp.ndarray,
                      missing_types: jnp.ndarray,
                      nan_bins: jnp.ndarray,
                      default_bins: jnp.ndarray,
                      feat_group: jnp.ndarray,
                      feat_offset: jnp.ndarray,
                      num_bins: jnp.ndarray,
                      *,
                      row_tile: int = DEFAULT_ROW_TILE,
                      interpret: bool = False,
                      any_cat: bool = True) -> jnp.ndarray:
    """Apply this wave's splits to both leaf vectors: ``-> [2, n_pad]``.

    Args:
      bins_t: ``[G_pad, n_pad]`` uint8 (shared with the hist kernel).
      leaf2: ``[2, n_pad]`` int32 — row 0 = row_leaf (all rows), row 1 =
        hist_leaf (bagged-out rows parked at -1).  Padding rows = -1.
      feature/threshold/default_left/is_categorical/sel/new_id: ``[L]``
        per-leaf split decision tables (from the wave's SplitResult);
        ``sel`` marks the leaves actually split this wave.
      cat_mask: ``[L, B]`` bool — FEATURE bins going left (categorical).
      missing_types/nan_bins/default_bins/num_bins: ``[F]`` per-feature
        metadata (feature-bin space).
      feat_group/feat_offset: ``[F]`` EFB layout (offset -1 = identity).

    Rows whose leaf is unselected, bagged out, or padding are unchanged.
    """
    return _route_call(bins_t, leaf2, feature, threshold, default_left,
                       is_categorical, cat_mask, sel, new_id, missing_types,
                       nan_bins, default_bins, feat_group, feat_offset,
                       num_bins, None, row_tile, interpret, any_cat)


@functools.partial(jax.jit,
                   static_argnames=("row_tile", "interpret", "any_cat"))
def route_rows_values_pallas(bins_t: jnp.ndarray,
                             leaf2: jnp.ndarray,
                             feature: jnp.ndarray,
                             threshold: jnp.ndarray,
                             default_left: jnp.ndarray,
                             is_categorical: jnp.ndarray,
                             cat_mask: jnp.ndarray,
                             sel: jnp.ndarray,
                             new_id: jnp.ndarray,
                             missing_types: jnp.ndarray,
                             nan_bins: jnp.ndarray,
                             default_bins: jnp.ndarray,
                             feat_group: jnp.ndarray,
                             feat_offset: jnp.ndarray,
                             num_bins: jnp.ndarray,
                             leaf_values: jnp.ndarray,
                             *,
                             row_tile: int = DEFAULT_ROW_TILE,
                             interpret: bool = False,
                             any_cat: bool = True):
    """Final per-tree route: apply pending splits AND emit each row's
    leaf value — ``-> (leaf2 [2, n_pad] i32, values [n_pad] f32)``.

    Replaces the score-update gather ``leaf_value[row_leaf]`` (an
    XLA-serialized ~7 ms/iter op at 1M rows) with one extra table-row
    dot inside the route pass.  Values ride the MXU as hi+lo bf16 pairs
    (exact to ~2^-17); out-of-tree rows (leaf -1 / padding) emit 0.
    """
    return _route_call(bins_t, leaf2, feature, threshold, default_left,
                       is_categorical, cat_mask, sel, new_id, missing_types,
                       nan_bins, default_bins, feat_group, feat_offset,
                       num_bins, leaf_values, row_tile, interpret, any_cat)


def _route_call(bins_t, leaf2, feature, threshold, default_left,
                is_categorical, cat_mask, sel, new_id, missing_types,
                nan_bins, default_bins, feat_group, feat_offset, num_bins,
                leaf_values, row_tile, interpret, any_cat=True):
    """Shared table/spec construction for both route entry points."""
    G_pad, n_pad = bins_t.shape
    L = feature.shape[0]
    B = cat_mask.shape[1]
    T = row_tile
    assert n_pad % T == 0
    L_pad = _round_up(max(L, 8), LANE)
    with_values = leaf_values is not None

    tabs = _leaf_tables(feature, threshold, default_left, is_categorical,
                        sel, new_id, missing_types, nan_bins, default_bins,
                        feat_group, feat_offset, num_bins, L_pad,
                        leaf_values=leaf_values)
    cat = jnp.zeros((B, L_pad), jnp.float32)
    cat = cat.at[:, :L].set(cat_mask.T.astype(jnp.float32))

    in_specs = [
        pl.BlockSpec((G_pad, T), lambda r: (0, r),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((2, T), lambda r: (0, r),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((_T_ROWS, L_pad), lambda r: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((B, L_pad), lambda r: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    leaf2_spec = pl.BlockSpec((2, T), lambda r: (0, r),
                              memory_space=pltpu.VMEM)
    tab_prec = table_precision(L_pad, G_pad)
    if not with_values:
        return pl.pallas_call(
            functools.partial(_route_kernel, B=B, tab_prec=tab_prec,
                              any_cat=any_cat),
            grid=(n_pad // T,),
            in_specs=in_specs,
            out_specs=leaf2_spec,
            out_shape=jax.ShapeDtypeStruct((2, n_pad), jnp.int32),
            interpret=interpret,
        )(bins_t, leaf2, tabs, cat)

    leaf2_new, vals = pl.pallas_call(
        functools.partial(_route_values_kernel, B=B, tab_prec=tab_prec,
                          any_cat=any_cat),
        grid=(n_pad // T,),
        in_specs=in_specs,
        out_specs=(
            leaf2_spec,
            pl.BlockSpec((1, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((2, n_pad), jnp.int32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        ),
        interpret=interpret,
    )(bins_t, leaf2, tabs, cat)
    return leaf2_new, vals[0]


def route_rows_xla(bins: jnp.ndarray,
                   leaf2: jnp.ndarray,
                   feature: jnp.ndarray,
                   threshold: jnp.ndarray,
                   default_left: jnp.ndarray,
                   is_categorical: jnp.ndarray,
                   cat_mask: jnp.ndarray,
                   sel: jnp.ndarray,
                   new_id: jnp.ndarray,
                   missing_types: jnp.ndarray,
                   nan_bins: jnp.ndarray,
                   default_bins: jnp.ndarray,
                   feat_group: jnp.ndarray,
                   feat_offset: jnp.ndarray,
                   num_bins: jnp.ndarray) -> jnp.ndarray:
    """Same contract from untransposed ``[n, G]`` bins (CPU backend +
    equivalence oracle for the kernel)."""
    n = bins.shape[0]
    rl = leaf2[0, :n]
    hl = leaf2[1, :n]
    safe = jnp.maximum(rl, 0)
    f = feature[safe]
    g = feat_group[f]
    # numcheck: disable=NUM001 -- int32 one-hot group select (g is
    # feat_group, not a gradient); integer adds are exact in any order
    c = jnp.sum(jnp.where(g[:, None] == jnp.arange(bins.shape[1])[None, :],
                          bins.astype(jnp.int32), 0), axis=1)
    b = unbundle_bin(c, feat_offset[f], num_bins[f], default_bins[f])
    mt = missing_types[f]
    is_missing = (((mt == MISSING_NAN) & (b == nan_bins[f]))
                  | ((mt == MISSING_ZERO) & (b == default_bins[f])))
    num_left = jnp.where(is_missing, default_left[safe], b <= threshold[safe])
    cat_left = cat_mask[safe, jnp.minimum(b, cat_mask.shape[1] - 1)]
    go_left = jnp.where(is_categorical[safe], cat_left, num_left)
    moved = sel[safe] & ~go_left & (rl >= 0)
    rl2 = jnp.where(moved, new_id[safe], rl)
    hl2 = jnp.where(hl >= 0, rl2, hl)
    out = jnp.stack([rl2, hl2])
    if leaf2.shape[1] != n:
        pad = jnp.full((2, leaf2.shape[1] - n), -1, jnp.int32)
        out = jnp.concatenate([out, pad], axis=1)
    return out


def unbundle_bin(col: jnp.ndarray, off: jnp.ndarray, nb: jnp.ndarray,
                 db: jnp.ndarray) -> jnp.ndarray:
    """EFB inverse mapping: stored column value -> feature bin
    (`io/dataset.py` BundleInfo encoding; identity when ``off < 0``)."""
    rank = col - off
    in_range = (rank >= 0) & (rank < nb - 1)
    b_bundled = jnp.where(in_range, rank + (rank >= db), db)
    return jnp.where(off < 0, col, b_bundled)
