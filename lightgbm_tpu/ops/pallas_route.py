"""Pallas TPU row-routing kernel — split application without gathers.

The TPU counterpart of the reference's ``DataPartition::Split``
(`/root/reference/src/treelearner/data_partition.hpp`, threaded index
shuffling) combined with the per-row split decision of
``Dataset::Split`` (`src/io/dataset.h:412-419`).  Our row→leaf vector
design needs, per wave, for every row: look up its leaf's chosen split
(feature, threshold, default direction, categorical mask), read the row's
bin at that feature, and move the row to the right-child id if it goes
right.

In XLA this is a chain of ``[n]``-sized gathers from small tables plus a
``take_along_axis`` over the ``[n, F]`` matrix — each of which lowers to
a slow serialized gather on TPU (~3-25 ms per pass at 1M rows).  Here the
whole decision runs in VMEM per row-tile:

* leaf one-hot ``[L_pad, T]`` (compare against an iota — no gather),
* per-leaf split tables fetched by ONE small matmul
  ``tabs[8, L_pad] @ ohL -> [8, T]``,
* the row's bin at its split feature by a masked sublane reduction over
  the ``[F, T]`` bins tile (no gather),
* per-feature missing metadata by another small matmul over the feature
  one-hot,
* categorical membership by ``cat_mask[B, L_pad] @ ohL`` + a bin one-hot
  reduction.

Two leaf vectors ride together (``row_leaf`` for all rows, ``hist_leaf``
with bagged-out rows parked at -1) so both are routed in one pass.

Streams ``bins_t`` (uint8) + the leaf vectors once per wave — the whole
route costs ~1 stream pass instead of ~50 ms of gathers.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.binning import MISSING_NAN, MISSING_ZERO

LANE = 128
DEFAULT_ROW_TILE = 1024


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _route_kernel(bins_ref, leaf2_ref, tabs_ref, cat_ref, fmeta_ref,
                  out_ref, *, B: int):
    leaf = leaf2_ref[0:1, :]                                  # [1, T] i32
    T = leaf.shape[1]
    L_pad = tabs_ref.shape[1]
    F_pad = bins_ref.shape[0]

    iota_l = jax.lax.broadcasted_iota(jnp.int32, (L_pad, T), 0)
    ohL = (iota_l == leaf).astype(jnp.float32)                # [L_pad, T]
    sel8 = jnp.dot(tabs_ref[:], ohL,
                   preferred_element_type=jnp.float32)        # [8, T]
    f_row = sel8[0:1, :]
    thr = sel8[1:2, :]
    dl = sel8[2:3, :]
    iscat = sel8[3:4, :]
    selm = sel8[4:5, :]
    new_id = sel8[5:6, :]

    binsf = bins_ref[:].astype(jnp.int32).astype(jnp.float32)  # [F, T]
    iota_f = jax.lax.broadcasted_iota(
        jnp.int32, (F_pad, T), 0).astype(jnp.float32)
    ohF = (iota_f == f_row).astype(jnp.float32)               # [F, T]
    b = jnp.sum(ohF * binsf, axis=0, keepdims=True)           # [1, T]

    fm = jnp.dot(fmeta_ref[:], ohF,
                 preferred_element_type=jnp.float32)          # [4, T]
    mt = fm[0:1, :]
    nanb = fm[1:2, :]
    defb = fm[2:3, :]

    # all masks ride as f32 0/1 values (Mosaic rejects bool-valued selects)
    one = jnp.ones_like(b)
    zero = jnp.zeros_like(b)
    is_missing = jnp.where(
        ((mt == float(MISSING_NAN)) & (b == nanb))
        | ((mt == float(MISSING_ZERO)) & (b == defb)), one, zero)

    catrow = jnp.dot(cat_ref[:], ohL,
                     preferred_element_type=jnp.float32)      # [B, T]
    iota_b = jax.lax.broadcasted_iota(
        jnp.int32, (B, T), 0).astype(jnp.float32)
    cat_left = jnp.sum(
        jnp.where(iota_b == b, catrow, 0.0), axis=0,
        keepdims=True)                                        # [1, T]

    le_thr = jnp.where(b <= thr, one, zero)
    num_left = jnp.where(is_missing > 0.5, dl, le_thr)
    go_left = jnp.where(iscat > 0.5, cat_left, num_left)
    in_tree = jnp.where(leaf >= 0, one, zero)
    moved = selm * (one - jnp.minimum(go_left, one)) * in_tree
    nid = new_id.astype(jnp.int32)

    rl = jnp.where(moved > 0.5, nid, leaf)                    # row_leaf'
    hl = leaf2_ref[1:2, :]
    out_ref[0:1, :] = rl
    out_ref[1:2, :] = jnp.where(hl >= 0, rl, hl)              # hist_leaf'


@functools.partial(jax.jit,
                   static_argnames=("row_tile", "interpret"))
def route_rows_pallas(bins_t: jnp.ndarray,
                      leaf2: jnp.ndarray,
                      feature: jnp.ndarray,
                      threshold: jnp.ndarray,
                      default_left: jnp.ndarray,
                      is_categorical: jnp.ndarray,
                      cat_mask: jnp.ndarray,
                      sel: jnp.ndarray,
                      new_id: jnp.ndarray,
                      missing_types: jnp.ndarray,
                      nan_bins: jnp.ndarray,
                      default_bins: jnp.ndarray,
                      *,
                      row_tile: int = DEFAULT_ROW_TILE,
                      interpret: bool = False) -> jnp.ndarray:
    """Apply this wave's splits to both leaf vectors: ``-> [2, n_pad]``.

    Args:
      bins_t: ``[F_pad, n_pad]`` uint8 (shared with the hist kernel).
      leaf2: ``[2, n_pad]`` int32 — row 0 = row_leaf (all rows), row 1 =
        hist_leaf (bagged-out rows parked at -1).  Padding rows = -1.
      feature/threshold/default_left/is_categorical/sel/new_id: ``[L]``
        per-leaf split decision tables (from the wave's SplitResult);
        ``sel`` marks the leaves actually split this wave.
      cat_mask: ``[L, B]`` bool — bins going left for categorical splits.
      missing_types/nan_bins/default_bins: ``[F]`` per-feature metadata.

    Rows whose leaf is unselected, bagged out, or padding are unchanged.
    """
    F_pad, n_pad = bins_t.shape
    L = feature.shape[0]
    B = cat_mask.shape[1]
    T = row_tile
    assert n_pad % T == 0
    L_pad = _round_up(max(L, 8), LANE)

    tabs = jnp.zeros((8, L_pad), jnp.float32)
    tabs = tabs.at[0, :L].set(feature.astype(jnp.float32))
    tabs = tabs.at[1, :L].set(threshold.astype(jnp.float32))
    tabs = tabs.at[2, :L].set(default_left.astype(jnp.float32))
    tabs = tabs.at[3, :L].set(is_categorical.astype(jnp.float32))
    tabs = tabs.at[4, :L].set(sel.astype(jnp.float32))
    tabs = tabs.at[5, :L].set(new_id.astype(jnp.float32))

    cat = jnp.zeros((B, L_pad), jnp.float32)
    cat = cat.at[:, :L].set(cat_mask.T.astype(jnp.float32))

    F = missing_types.shape[0]
    fmeta = jnp.zeros((4, F_pad), jnp.float32)
    fmeta = fmeta.at[0, :F].set(missing_types.astype(jnp.float32))
    fmeta = fmeta.at[1, :F].set(nan_bins.astype(jnp.float32))
    fmeta = fmeta.at[2, :F].set(default_bins.astype(jnp.float32))

    return pl.pallas_call(
        functools.partial(_route_kernel, B=B),
        grid=(n_pad // T,),
        in_specs=[
            pl.BlockSpec((F_pad, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, L_pad), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, L_pad), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4, F_pad), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2, T), lambda r: (0, r),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2, n_pad), jnp.int32),
        interpret=interpret,
    )(bins_t, leaf2, tabs, cat, fmeta)


def route_rows_xla(bins: jnp.ndarray,
                   leaf2: jnp.ndarray,
                   feature: jnp.ndarray,
                   threshold: jnp.ndarray,
                   default_left: jnp.ndarray,
                   is_categorical: jnp.ndarray,
                   cat_mask: jnp.ndarray,
                   sel: jnp.ndarray,
                   new_id: jnp.ndarray,
                   missing_types: jnp.ndarray,
                   nan_bins: jnp.ndarray,
                   default_bins: jnp.ndarray) -> jnp.ndarray:
    """Same contract from untransposed ``[n, F]`` bins (CPU backend +
    equivalence oracle for the kernel)."""
    n = bins.shape[0]
    rl = leaf2[0, :n]
    hl = leaf2[1, :n]
    safe = jnp.maximum(rl, 0)
    f = feature[safe]
    b = jnp.sum(jnp.where(f[:, None] == jnp.arange(bins.shape[1])[None, :],
                          bins.astype(jnp.int32), 0), axis=1)
    mt = missing_types[f]
    is_missing = (((mt == MISSING_NAN) & (b == nan_bins[f]))
                  | ((mt == MISSING_ZERO) & (b == default_bins[f])))
    num_left = jnp.where(is_missing, default_left[safe], b <= threshold[safe])
    cat_left = cat_mask[safe, jnp.minimum(b, cat_mask.shape[1] - 1)]
    go_left = jnp.where(is_categorical[safe], cat_left, num_left)
    moved = sel[safe] & ~go_left & (rl >= 0)
    rl2 = jnp.where(moved, new_id[safe], rl)
    hl2 = jnp.where(hl >= 0, rl2, hl)
    out = jnp.stack([rl2, hl2])
    if leaf2.shape[1] != n:
        pad = jnp.full((2, leaf2.shape[1] - n), -1, jnp.int32)
        out = jnp.concatenate([out, pad], axis=1)
    return out
