"""Pallas TPU histogram kernel — one-hot matmuls on the MXU.

The TPU answer to the reference's OpenCL histogram machinery
(`/root/reference/src/treelearner/ocl/histogram256.cl:94-130` local-memory
atomic float adds, `src/treelearner/gpu_tree_learner.cpp:581-654` kernel
variants, `:890-975` async pipeline).  TPUs have no atomics, so the
scatter-add becomes dense linear algebra:

For one row-tile of ``T`` rows we build, entirely in VMEM,

* ``oh``  ``[F*B, T]``   one-hot of each row's (feature, bin) joint index,
* ``vw``  ``[T, cols]``  per-row values ``(grad, hess, 1)`` replicated into
  the column block of the row's leaf — nonzero only where the row's leaf
  is in the ``active`` list (the wave's "smaller children",
  `serial_tree_learner.cpp:358-372`),

and accumulate ``oh @ vw -> [F*B, cols]`` into a VMEM accumulator over the
row grid.  The one-hot itself is produced by a tiny MXU matmul
(``spread.T @ bins`` replicates each feature's bin id across its B output
rows) followed by one vector compare — no gathers, no cross-lane
reshapes.

The column count adapts to the wave: ``cols = round128(C * round8(A))``,
so MXU work scales with the number of active leaves — the first waves of
a tree (1, 2, 4, ... active leaves) cost a fraction of a full wave.  The
staged wave plan in ``learner/serial.py`` exploits this by growing the
active-slot count as the tree grows.

Memory layout notes:

* ``bins_t`` is the binned matrix TRANSPOSED to ``[F, n]`` uint8 (one
  byte per element on the HBM stream; converted to bf16 in VMEM —
  bin ids <= 256 are exact in bf16; larger bin counts are routed to the
  scatter backend by :func:`pallas_config_ok`).  The transpose is done
  once per dataset; the kernel then streams ``[Ft, T]`` blocks with the
  row dimension on lanes.
* bins are laid out at a fixed power-of-two stride ``B`` per feature, so
  the output is directly the padded ``[A, F, B, 3]`` grid the vectorized
  split scan consumes — no ragged offsets.
* precision: the one-hot is exact in bf16.  Values are either bf16
  (``mode="bf16"``, C=3) or split into hi+lo bf16 pairs
  (``mode="hilo"``, C=5) giving ~f32 accuracy at 5/3 the MACs; counts are
  exact either way (MXU accumulates in f32).  This mirrors the
  reference's GPU single-precision trade-off
  (`docs/GPU-Performance.rst:135-161`).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
DEFAULT_ROW_TILE = 1024
# cap for the [Ft*B, cols] f32 VMEM accumulator
_ACC_VMEM_BYTES = 6 * 1024 * 1024


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def bin_stride(max_bins: int) -> int:
    """Per-feature bin stride used by the kernel's joint index space."""
    return max(8, _next_pow2(max_bins))


def _col_layout(A: int, mode: str) -> tuple[int, int, int]:
    """-> (C, A_pad, cols): value columns, padded active slots, lane-
    aligned total output columns."""
    C = 5 if mode == "hilo" else 3
    A_pad = _round_up(A, 8)
    cols = _round_up(C * A_pad, LANE)
    return C, A_pad, cols


def pallas_config_ok(max_bins: int, num_leaves: int, mode: str) -> bool:
    """Whether the matmul kernel can handle this config exactly.

    * bin ids ride through bf16, exact only up to 256 — larger bin counts
      (``Dataset`` switches to int32 bins past 256) need the scatter path;
    * the ``[feat_tile*B, cols]`` f32 accumulator must fit the minimum
      feat_tile of 8 within VMEM.
    """
    if max_bins > 256:
        return False
    # the route kernel builds a [round128(L), T] f32 leaf one-hot in VMEM
    # (ops/pallas_route.py); past ~1024 leaves it no longer fits
    if num_leaves > 1024:
        return False
    B = bin_stride(max_bins)
    # the staged wave plan (learner/serial.py stage_plan) caps active
    # slots at 128 regardless of num_leaves
    _, _, cols = _col_layout(min(max(1, num_leaves // 2), 128), mode)
    return 8 * B * cols * 4 <= 12 * 1024 * 1024


def transpose_bins(bins: jnp.ndarray, row_tile: int = DEFAULT_ROW_TILE,
                   feat_tile: int | None = None) -> jnp.ndarray:
    """``[n, F] uint8 -> [F_pad, n_pad] uint8`` once-per-dataset prep."""
    n, F = bins.shape
    n_pad = _round_up(n, row_tile)
    F_pad = _round_up(F, feat_tile or F)
    out = jnp.zeros((F_pad, n_pad), jnp.uint8)
    return jax.lax.dynamic_update_slice(
        out, bins.T.astype(jnp.uint8), (0, 0))


def pack_values(grad: jnp.ndarray, hess: jnp.ndarray, mode: str,
                row_tile: int = DEFAULT_ROW_TILE) -> jnp.ndarray:
    """Build the per-row value columns ``[n_pad, C]`` once per tree.

    mode="bf16": C=3 ``(g, h, 1)``; mode="hilo": C=5
    ``(g_hi, g_lo, h_hi, h_lo, 1)`` with ``x == x_hi + x_lo`` to ~2^-17.
    """
    n = grad.shape[0]
    ones = jnp.ones_like(grad)
    if mode == "hilo":
        g_hi = grad.astype(jnp.bfloat16).astype(jnp.float32)
        h_hi = hess.astype(jnp.bfloat16).astype(jnp.float32)
        cols = [g_hi, grad - g_hi, h_hi, hess - h_hi, ones]
    else:
        cols = [grad, hess, ones]
    vals = jnp.stack(cols, axis=-1).astype(jnp.float32)
    n_pad = _round_up(n, row_tile)
    if n_pad != n:
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))
    return vals


def _spread_matrix(feat_tile: int, B: int) -> np.ndarray:
    """``[Ft*B, Ft]`` bf16 constant: ``spread[f*B+b, f] = 1``."""
    s = np.zeros((feat_tile * B, feat_tile), np.float32)
    for f in range(feat_tile):
        s[f * B:(f + 1) * B, f] = 1.0
    return s.astype(jnp.bfloat16)


def _hist_kernel(active_ref, bins_ref, vals_ref, leaf_ref, spread_ref,
                 out_ref, *, n_cols: int, B: int, pad_cols: int):
    """One (feature-tile, row-tile) grid cell; accumulates over row tiles."""
    rt = pl.program_id(1)

    @pl.when(rt == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # [Ft*B, T] — each feature's bin id replicated across its B rows
    binsrep = jnp.dot(spread_ref[:],
                      bins_ref[:].astype(jnp.int32).astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    brow = jax.lax.broadcasted_iota(
        jnp.int32, binsrep.shape, 0) & (B - 1)
    oh = (binsrep == brow.astype(jnp.float32)).astype(jnp.bfloat16)

    # [T, A_pad] leaf membership mask over the active-leaf list
    m = (leaf_ref[:] == active_ref[:]).astype(jnp.bfloat16)
    vals = vals_ref[:]                                       # [T, C] f32
    blocks = [m * vals[:, c:c + 1].astype(jnp.bfloat16) for c in range(n_cols)]
    if pad_cols:
        blocks.append(jnp.zeros((m.shape[0], pad_cols), jnp.bfloat16))
    vw = jnp.concatenate(blocks, axis=1)                     # [T, cols]

    out_ref[:] += jax.lax.dot_general(
        oh, vw, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "max_bins", "mode", "row_tile",
                     "interpret"))
def hist_active_pallas(bins_t: jnp.ndarray,
                       vals: jnp.ndarray,
                       row_leaf: jnp.ndarray,
                       active: jnp.ndarray,
                       *,
                       num_features: int,
                       max_bins: int,
                       mode: str = "hilo",
                       row_tile: int = DEFAULT_ROW_TILE,
                       interpret: bool = False) -> jnp.ndarray:
    """Histograms for the active leaves: ``-> [A, F, B, 3]`` float32.

    Args:
      bins_t: ``[F_pad, n_pad]`` uint8 transposed binned matrix
        (:func:`transpose_bins`).
      vals: ``[n_pad, C]`` f32 packed value columns (:func:`pack_values`).
      row_leaf: ``[n]`` int32 leaf per row; rows whose leaf is not in
        `active` (including bagged-out ``-1``) contribute nothing.
      active: ``[A]`` int32 leaf ids to histogram; ``-1`` entries are
        padding (their output slots contain garbage from bagged-out rows
        and must be dropped by the caller).
      num_features: true F (<= F_pad).
      max_bins: true per-feature bin-count bound; output B = its stride.

    Returns:
      ``[A, F, B, 3]`` f32 with B = ``bin_stride(max_bins)``, cells
      ``(sum_grad, sum_hess, count)``.

    MXU cost scales with ``round128(C*round8(A))`` — small waves are
    proportionally cheap.
    """
    F_pad, n_pad = bins_t.shape
    C = vals.shape[1]
    A = active.shape[0]
    B = bin_stride(max_bins)
    T = row_tile
    assert n_pad % T == 0, (n_pad, T)

    _, A_pad, cols = _col_layout(A, "hilo" if C == 5 else "bf16")
    pad_cols = cols - C * A_pad
    # feature tile: bounded by the f32 accumulator's VMEM budget; when
    # tiling, the block's sublane dim must be a multiple of 8 (Mosaic
    # tiling constraint — a full-array block is exempt)
    ft_cap = max(1, _ACC_VMEM_BYTES // (B * cols * 4))
    if ft_cap >= F_pad:
        feat_tile = F_pad
    else:
        feat_tile = max(8, (ft_cap // 8) * 8)
    F_grid = _round_up(F_pad, feat_tile)
    if F_grid != F_pad:
        bins_t = jnp.pad(bins_t, ((0, F_grid - F_pad), (0, 0)))

    leaf = jnp.full((n_pad, 1), -1, jnp.int32)
    leaf = jax.lax.dynamic_update_slice(
        leaf, row_leaf.astype(jnp.int32)[:, None], (0, 0))
    act = jnp.full((1, A_pad), -2, jnp.int32)
    act = jax.lax.dynamic_update_slice(
        act, active.astype(jnp.int32)[None, :], (0, 0))
    # padded rows carry leaf -1; bagged-out rows carry -1 too.  Use -2 for
    # active padding so neither lands in a real column block; -1 actives
    # (wave padding) DO accumulate bagged-out rows, caller drops them.
    spread = jnp.asarray(_spread_matrix(feat_tile, B))

    grid = (F_grid // feat_tile, n_pad // T)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_cols=C, B=B, pad_cols=pad_cols),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, A_pad), lambda f, r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((feat_tile, T), lambda f, r: (f, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T, C), lambda f, r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T, 1), lambda f, r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((feat_tile * B, feat_tile), lambda f, r: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((feat_tile * B, cols),
                               lambda f, r: (f, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F_grid * B, cols), jnp.float32),
        interpret=interpret,
    )(act, bins_t, vals, leaf, spread)

    # [F_grid*B, cols] -> [A, F, B, C'] -> combine hi/lo -> [A, F, B, 3]
    out = out.reshape(F_grid, B, cols)[:, :, :C * A_pad]
    out = out.reshape(F_grid, B, C, A_pad)
    out = out.transpose(3, 0, 1, 2)[:A, :num_features]       # [A, F, B, C]
    if C == 5:
        g = out[..., 0] + out[..., 1]
        h = out[..., 2] + out[..., 3]
        out = jnp.stack([g, h, out[..., 4]], axis=-1)
    return out


# ---------------------------------------------------------------------------
# XLA scatter reference implementation (CPU path + equivalence oracle)
# ---------------------------------------------------------------------------
def hist_active_scatter(bins: jnp.ndarray,
                        grad: jnp.ndarray,
                        hess: jnp.ndarray,
                        row_leaf: jnp.ndarray,
                        active: jnp.ndarray,
                        *,
                        max_bins: int,
                        num_leaf_slots: int) -> jnp.ndarray:
    """Same contract as :func:`hist_active_pallas` (exact f32 scatter),
    from the untransposed ``[n, F]`` integer bins.  The direct analog of
    the reference CPU construction (`dataset.cpp:587-752`) restricted to
    the active leaves."""
    n, F = bins.shape
    A = active.shape[0]
    B = bin_stride(max_bins)
    L = num_leaf_slots
    safe_act = jnp.where(active >= 0, active, L)
    inv = jnp.full((L + 1,), A, jnp.int32).at[safe_act].set(
        jnp.arange(A, dtype=jnp.int32), mode="drop")
    slot = jnp.where(row_leaf >= 0,
                     inv[jnp.clip(row_leaf, 0, L)], A)       # [n]
    idx = (slot[:, None] * (F * B)
           + jnp.arange(F, dtype=jnp.int32)[None, :] * B
           + bins.astype(jnp.int32))                         # [n, F]
    vals = jnp.stack([grad, hess, jnp.ones_like(grad)], -1)  # [n, 3]
    hist = jnp.zeros((A * F * B, 3), jnp.float32)
    hist = hist.at[idx].add(vals[:, None, :].astype(jnp.float32),
                            mode="drop")
    return hist.reshape(A, F, B, 3)


def default_backend() -> str:
    forced = os.environ.get("LGBM_TPU_HIST_BACKEND", "")
    if forced:
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "scatter"
