"""Pallas TPU histogram kernel — one-hot matmuls on the MXU.

The TPU answer to the reference's OpenCL histogram machinery
(`/root/reference/src/treelearner/ocl/histogram256.cl:94-130` local-memory
atomic float adds, `src/treelearner/gpu_tree_learner.cpp:581-654` kernel
variants, `:890-975` async pipeline).  TPUs have no atomics, so the
scatter-add becomes dense linear algebra:

For one row-tile of ``T`` rows we build, entirely in VMEM,

* ``oh``  ``[F*B, T]``   one-hot of each row's (feature, bin) joint index,
* ``vw``  ``[T, cols]``  per-row values ``(grad, hess, 1)`` replicated into
  the column block of the row's leaf — nonzero only where the row's leaf
  is in the ``active`` list (the wave's "smaller children",
  `serial_tree_learner.cpp:358-372`),

and accumulate ``oh @ vw -> [F*B, cols]`` into a VMEM accumulator over the
row grid.  The one-hot itself is produced by per-feature broadcast
compares against a bin iota (:func:`_onehot_bins`) — no gathers, no
cross-lane reshapes, and no intermediate beyond the bf16 one-hot.

The column count adapts to the wave: ``cols = round128(C * round8(A))``,
so MXU work scales with the number of active leaves — the first waves of
a tree (1, 2, 4, ... active leaves) cost a fraction of a full wave.  The
staged wave plan in ``learner/serial.py`` exploits this by growing the
active-slot count as the tree grows.

Memory layout notes:

* ``bins_t`` is the binned matrix TRANSPOSED to ``[F, n]`` uint8 (one
  byte per element on the HBM stream; converted to bf16 in VMEM —
  bin ids <= 256 are exact in bf16; larger bin counts are routed to the
  scatter backend by :func:`pallas_config_ok`).  The transpose is done
  once per dataset; the kernel then streams ``[Ft, T]`` blocks with the
  row dimension on lanes.
* bins are laid out at a fixed power-of-two stride ``B`` per feature, so
  the output is directly the padded ``[A, F, B, 3]`` grid the vectorized
  split scan consumes — no ragged offsets.
* precision: the one-hot is exact in bf16.  Values are either bf16
  (``mode="bf16"``, C=3) or split into hi+lo bf16 pairs
  (``mode="hilo"``, C=5) giving ~f32 accuracy at 5/3 the MACs; counts are
  exact either way (MXU accumulates in f32).  This mirrors the
  reference's GPU single-precision trade-off
  (`docs/GPU-Performance.rst:135-161`).  The default is the QUANTIZED
  path (``mode="int8h"``, :func:`pack_values_q`): int8 operands on the
  MXU's 2.1x-throughput integer path with EXACT int32 accumulation.

On 4-bit bin packing (the reference's ``dense_nbits_bin.hpp`` /
Feature4 DWORD lever, twice proposed as the HBM lever): measured
against, deliberately not built.  Device traces of the fused kernel
(r4) show the wave cost is MXU/VPU-bound at every bench shape — the
bins stream is ~28 MB of a ~550 MB/wave total at 1M rows, under 10% of
wave wall-clock even before the one-hot build's VPU cost; halving it at
``max_bin<=15`` caps out at a few percent on a config the benchmarks
don't use.  The lever that actually paid on this hardware is the int8
MXU path above (34->42M row-iters/s measured at bench shapes).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the VMEM feasibility model (budget, per-grid-cell arithmetic, tile
# caps) is SHARED across every Pallas kernel — ops/vmem.py is its home
# (and what tools/memcheck's MEM004 keys on); the old underscore names
# stay bound here for the kernels and tests that grew up on them
from .vmem import (VMEM_BUDGET_BYTES as _VMEM_BUDGET_BYTES,
                   cell_vmem_bytes as _cell_vmem_bytes,
                   feat_tile_cap as _feat_tile_cap, hist_cell_ok,
                   next_pow2 as _next_pow2,
                   pick_row_tile as _pick_row_tile,
                   round_up as _round_up)

LANE = 128
# rows per kernel grid step; env-tunable for A/B perf work.  2048 beats
# 1024 by ~5% on the bench (fewer grid steps to amortize per-tile fixed
# cost); kernels halve it per-config when the VMEM cell won't fit (high
# bin counts).  transpose_bins/pack_values pad to this, so any power-of-
# two tile <= it divides n_pad; pallas_route imports it for the same
# reason.
DEFAULT_ROW_TILE = int(os.environ.get("LGBM_TPU_ROW_TILE", 2048))


def split_hi_lo(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Split f32 into a bf16-representable hi + f32 residual lo.

    Done by BIT-MASKING the low 16 mantissa bits, NOT by
    ``x.astype(bf16).astype(f32)``: XLA's simplifier folds that convert
    pair to a no-op under jit, which silently turned every hi/lo pair
    into (x, 0) — hilo histograms degraded to plain bf16 and the
    route-emitted leaf values lost their lo correction (found via a
    500-iteration parity run drifting ~0.006 AUC from the exact scatter
    path).  The masked hi is exactly bf16-representable (truncation), so
    the MXU's operand rounding keeps it intact and ``hi + lo == x``
    recovers f32 to ~2^-15 relative after the lo product's own rounding.
    """
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    hi = jax.lax.bitcast_convert_type(
        bits & jnp.uint32(0xFFFF0000), jnp.float32)
    return hi, x - hi


# layout arithmetic shared with the VMEM model (ops/vmem.py owns it so
# the feasibility predicates and the kernels can never disagree on it)
from .vmem import bin_stride, col_layout as _col_layout  # noqa: E402


def is_quantized(mode: str) -> bool:
    return mode in ("int8", "int8h", "int8hh")


def pallas_config_ok(max_bins: int, num_leaves: int, mode: str) -> bool:
    """Whether the matmul kernel can handle this config exactly.

    * bin ids ride through bf16, exact only up to 256 — larger bin counts
      (``Dataset`` switches to int32 bins past 256) need the scatter path;
    * the ``[feat_tile*B, cols]`` f32 accumulator must fit the minimum
      feat_tile of 8 within VMEM.
    """
    if max_bins > 256:
        return False
    # the route kernel builds a [round128(L), T] f32 leaf one-hot in VMEM
    # (ops/pallas_route.py); past ~1024 leaves it no longer fits
    if num_leaves > 1024:
        return False
    # the staged wave plan (learner/serial.py stage_plan) caps active
    # slots at 128 regardless of num_leaves; the minimum feature tile
    # of 8 must fit the full VMEM model at the 1024-row fallback tile
    # (_pick_row_tile halves down to it) — ADVICE r2: the accumulator
    # alone under-counts
    return hist_cell_ok(max_bins, min(max(1, num_leaves // 2), 128), mode)


def transpose_bins(bins: jnp.ndarray, row_tile: int = DEFAULT_ROW_TILE,
                   feat_tile: int | None = None) -> jnp.ndarray:
    """``[n, F] uint8 -> [F_pad, n_pad] uint8`` once-per-dataset prep."""
    n, F = bins.shape
    n_pad = _round_up(n, row_tile)
    F_pad = _round_up(F, feat_tile or F)
    out = jnp.zeros((F_pad, n_pad), jnp.uint8)
    return jax.lax.dynamic_update_slice(
        out, bins.T.astype(jnp.uint8), (0, 0))


def transpose_bins_host(bins: "np.ndarray", row_tile: int = DEFAULT_ROW_TILE,
                        feat_tile: int | None = None) -> "np.ndarray":
    """Host (numpy) twin of :func:`transpose_bins` — same padding layout.
    Used at booster init on small datasets, where the jitted transpose's
    one-time compile costs more than the extra host->device copy."""
    import numpy as np
    n, F = bins.shape
    n_pad = _round_up(n, row_tile)
    F_pad = _round_up(F, feat_tile or F)
    out = np.zeros((F_pad, n_pad), np.uint8)
    out[:F, :n] = np.asarray(bins, np.uint8).T
    return out


def pack_values(grad: jnp.ndarray, hess: jnp.ndarray, mode: str,
                row_tile: int = DEFAULT_ROW_TILE) -> jnp.ndarray:
    """Build the per-row value rows ``[C, n_pad]`` once per tree.

    mode="bf16": C=3 ``(g, h, 1)``; mode="hilo": C=5
    ``(g_hi, g_lo, h_hi, h_lo, 1)`` with ``x == x_hi + x_lo`` to ~2^-17.

    Rows-on-lanes layout: the row dimension is the minor (lane) axis both
    here and in the kernels, so the host-side pad/stack write dense
    ``[C, n]`` tiles (the previous ``[n, C]`` layout put C=3 on lanes —
    a ~2.3 ms/iter pad+copy at 1M rows); padding rows carry 0.
    """
    n = grad.shape[0]
    n_pad = _round_up(n, row_tile)
    pad = (0, n_pad - n)

    def p(x):
        return jnp.pad(x.astype(jnp.float32), pad)

    if mode == "hilo":
        g_hi, g_lo = split_hi_lo(grad)
        h_hi, h_lo = split_hi_lo(hess)
        rows = [p(g_hi), p(g_lo), p(h_hi), p(h_lo),
                p(jnp.ones_like(grad))]
    elif mode == "ghilo":
        # hi/lo for GRADIENTS only (C=4).  Parity data: this does NOT
        # help — grad bin sums tolerate bf16; kept for the record
        g_hi, g_lo = split_hi_lo(grad)
        rows = [p(g_hi), p(g_lo), p(hess), p(jnp.ones_like(grad))]
    elif mode == "hhilo":
        # hi/lo for HESSIANS only (C=4): the recorded parity table shows
        # hessian precision is what drives 500-iteration quality (gains
        # and leaf outputs divide by hessian sums), while gradient sums
        # tolerate bf16 — 4/3 the MXU work of bf16 for hilo-grade AUC
        h_hi, h_lo = split_hi_lo(hess)
        rows = [p(grad), p(h_hi), p(h_lo), p(jnp.ones_like(grad))]
    else:
        rows = [p(grad), p(hess), p(jnp.ones_like(grad))]
    return jnp.stack(rows, axis=0)


def pack_values_q(grad: jnp.ndarray, hess: jnp.ndarray, mode: str,
                  row_tile: int = DEFAULT_ROW_TILE,
                  key: jnp.ndarray | None = None,
                  scales: jnp.ndarray | None = None):
    """Quantized value rows for the int8 MXU path: ``-> (vals int8
    [C, n_pad], scales f32 [2])``.

    The TPU answer to the reference 4.x quantized-training idea
    (gradient discretization): the MXU's int8 path runs 2.1x the bf16
    throughput on this hardware (370 vs 178 Tops/s measured), and the
    one-hot operand is 0/1 so every histogram cell accumulates EXACTLY
    in int32 (<= n*127 < 2^31 for n <= 16M rows — no float rounding at
    all; the only error is the per-row quantization).

    mode="int8": C=3 ``(g_q, h_q, 1)``, g/h at 127/max|.| scales.
    mode="int8h": C=4 ``(g_q, h_hi, h_lo, 1)`` — the hessian rides as a
    two-level int8 pair (hi at sh/127, lo quantizes the hi residual at
    sh/16129, ~14-bit absolute precision) because leaf values and gains
    divide by hessian sums (see default_hist_mode's parity notes).
    mode="int8hh": C=5 — hi/lo pairs for BOTH gradient and hessian
    (~14-bit each; 5/4 the MXU work of int8h).

    ``key``: optional PRNG key for stochastic rounding (unbiased sums:
    E[q] == x, so quantization noise averages out over a leaf instead
    of accumulating a rounding bias).

    ``scales``: optional precomputed ``[2] f32 (sg, sh)`` — the streamed
    fold path (``boosting/streaming.py``) quantizes each BLOCK of a tree
    with the tree's GLOBAL absmax scales (host-computed over every
    block), so per-row int8 codes — and therefore the exact int32 bin
    sums — are bitwise what the monolithic in-memory pack produces.
    When omitted, scales are derived from this call's rows as before.
    """
    n = grad.shape[0]
    n_pad = _round_up(n, row_tile)
    pad = (0, n_pad - n)
    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    if scales is None:
        sg = jnp.maximum(jnp.max(jnp.abs(g)), 1e-30)
        sh = jnp.maximum(jnp.max(jnp.abs(h)), 1e-30)
    else:
        sg, sh = scales[0], scales[1]

    def q(x, scale, sub):
        t = x * (127.0 / scale)
        if key is not None:
            t = t + jax.random.uniform(
                jax.random.fold_in(key, sub), t.shape, minval=-0.5,
                maxval=0.5)
        return jnp.clip(jnp.round(t), -127, 127)

    def hilo8(x, scale, sub):
        hi = jnp.clip(jnp.round(x * (127.0 / scale)), -127, 127)
        lo = q(x - hi * (scale / 127.0), scale / 127.0, sub)
        return hi, lo

    if mode == "int8hh":
        ghi, glo = hilo8(g, sg, 0)
        hhi, hlo = hilo8(h, sh, 1)
        rows = [ghi, glo, hhi, hlo, jnp.ones_like(ghi)]
    elif mode == "int8h":
        hhi, hlo = hilo8(h, sh, 1)
        rows = [q(g, sg, 0), hhi, hlo, jnp.ones_like(hhi)]
    else:
        rows = [q(g, sg, 0), q(h, sh, 1), jnp.ones_like(g)]
    vals = jnp.stack([jnp.pad(r, pad) for r in rows], axis=0)
    return vals.astype(jnp.int8), jnp.stack([sg, sh])


def dequant_hist(out_i32: jnp.ndarray, scales: jnp.ndarray,
                 mode: str) -> jnp.ndarray:
    """``[A, F, B, C] int32 (+ scales) -> [A, F, B, 3] f32`` — undo
    :func:`pack_values_q` after exact integer accumulation."""
    sg, sh = scales[0], scales[1]
    out = out_i32.astype(jnp.float32)
    if mode == "int8hh":
        g = out[..., 0] * (sg / 127.0) + out[..., 1] * (sg / 16129.0)
        h = out[..., 2] * (sh / 127.0) + out[..., 3] * (sh / 16129.0)
        cnt = out[..., 4]
    elif mode == "int8h":
        g = out[..., 0] * (sg / 127.0)
        h = out[..., 1] * (sh / 127.0) + out[..., 2] * (sh / 16129.0)
        cnt = out[..., 3]
    else:
        g = out[..., 0] * (sg / 127.0)
        h = out[..., 1] * (sh / 127.0)
        cnt = out[..., 2]
    return jnp.stack([g, h, cnt], axis=-1)


def _onehot_bins(bins_i32: jnp.ndarray, B: int,
                 dtype=jnp.bfloat16) -> jnp.ndarray:
    """``[Ft, T] i32 -> [Ft*B, T]`` joint (feature, bin) one-hot
    (bf16, or int8 on the quantized path).

    ONE rank-3 broadcast-compare ``[Ft, 1, T] == [1, B, T]`` reshaped to
    ``[Ft*B, T]`` (leading-dim merge, layout-free) — no matmul, no f32
    intermediate, and no per-feature concatenate: the concat of Ft
    ``[B, T]`` slices re-copied the whole one-hot (~3.6 GB/wave of extra
    VMEM traffic at 1M rows), which set the measured ~2.6 ms/wave floor
    that dominated small waves."""
    Ft, T = bins_i32.shape
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (1, B, T), 1)
    oh = bins_i32[:, None, :] == iota_b
    if dtype == jnp.int8:
        # via i32: direct i1->i8 hits Mosaic's unsupported
        # (8,128)->(32,128) relayout
        return oh.astype(jnp.int32).reshape(Ft * B, T).astype(jnp.int8)
    return oh.astype(dtype).reshape(Ft * B, T)


def _weighted_cols(m_bool: jnp.ndarray, vals: jnp.ndarray, n_cols: int,
                   pad_cols: int, dtype) -> jnp.ndarray:
    """``(m_bool [A_pad, T], vals [C, T]) -> vw [cols, T]`` in ``dtype``
    (bf16, or int8 on the quantized path), rows ordered ``c * A_pad + a``
    (c-major, matching the caller's output unpack).  One rank-3
    broadcast + leading-dim merge — a per-column concat would re-copy
    the whole block.  int8 uses a select, not a multiply (Mosaic has no
    vector<i8> muli legalization)."""
    A_pad, T = m_bool.shape
    if dtype == jnp.int8:
        # build in i32, narrow once: Mosaic has no vector<i8> muli and
        # i1->i8 relayout ((8,128) -> (32,128) tiling) is unsupported,
        # but i32 compute + one trunc to i8 legalizes cleanly
        mi = m_bool.astype(jnp.int32)
        vw = (vals.astype(jnp.int32)[:n_cols, None, :]
              * mi[None, :, :]).reshape(n_cols * A_pad, T).astype(jnp.int8)
    else:
        vw = (vals[:n_cols, None, :].astype(dtype)
              * m_bool.astype(dtype)[None, :, :]).reshape(
                  n_cols * A_pad, T)
    if pad_cols:
        vw = jnp.concatenate(
            [vw, jnp.zeros((pad_cols, T), dtype)], axis=0)
    return vw


def _hist_kernel(active_ref, bins_ref, vals_ref, leaf_ref,
                 *refs, n_cols: int, B: int, pad_cols: int,
                 seeded: bool = False):
    """One (feature-tile, row-tile) grid cell; accumulates over row tiles.

    Everything rides rows-on-lanes: the leaf mask is built ``[A_pad, T]``
    (no per-tile transpose of the leaf row) and the weighted values as
    ``vw [cols, T]``, contracted against the one-hot on the lane
    dimension of BOTH operands.

    ``seeded``: the out-of-core fold variant.  Instead of zero-initing
    the accumulator on the first row tile of each feature block, the
    kernel LOADS a carried accumulator operand (``acc_ref``, aliased to
    the output buffer via ``input_output_aliases`` so the seed is a
    donated in-place init, not a copy).  A per-block call is then a
    bitwise EXTENSION of the monolithic kernel: same adds in the same
    order, just split across calls — which is what puts streamed
    training in the byte-identity domain on the kernel backends.
    """
    if seeded:
        acc_ref, out_ref = refs
    else:
        (out_ref,) = refs
    rt = pl.program_id(1)

    @pl.when(rt == 0)
    def _():
        if seeded:
            out_ref[:] = acc_ref[:]
        else:
            out_ref[:] = jnp.zeros_like(out_ref)

    quant = vals_ref.dtype == jnp.int8
    cdt = jnp.int8 if quant else jnp.bfloat16
    # [Ft*B, T] joint (feature, bin) one-hot
    oh = _onehot_bins(bins_ref[:].astype(jnp.int32), B, cdt)

    # [A_pad, T] leaf membership mask over the active-leaf list
    m = active_ref[:] == leaf_ref[:]
    vals = vals_ref[:]                                 # [C, T] f32/int8
    vw = _weighted_cols(m, vals, n_cols, pad_cols, cdt)      # [cols, T]

    out_ref[:] += jax.lax.dot_general(
        oh, vw, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32 if quant else jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "max_bins", "mode", "row_tile",
                     "interpret", "raw"))
def hist_active_pallas(bins_t: jnp.ndarray,
                       vals: jnp.ndarray,
                       row_leaf: jnp.ndarray,
                       active: jnp.ndarray,
                       scales: jnp.ndarray | None = None,
                       acc: jnp.ndarray | None = None,
                       *,
                       num_features: int,
                       max_bins: int,
                       mode: str = "hilo",
                       row_tile: int = DEFAULT_ROW_TILE,
                       interpret: bool = False,
                       raw: bool = False) -> jnp.ndarray:
    """Histograms for the active leaves: ``-> [A, F, B, 3]`` float32.

    Args:
      bins_t: ``[F_pad, n_pad]`` uint8 transposed binned matrix
        (:func:`transpose_bins`).
      vals: ``[C, n_pad]`` f32 packed value rows (:func:`pack_values`).
      row_leaf: ``[n]`` int32 leaf per row; rows whose leaf is not in
        `active` (including bagged-out ``-1``) contribute nothing.
      active: ``[A]`` int32 leaf ids to histogram; ``-1`` entries are
        padding (their output slots contain garbage from bagged-out rows
        and must be dropped by the caller).
      acc: optional carried RAW accumulator ``[F_grid*B, cols]``
        (:func:`hist_raw_layout`; donated — the kernel seeds its output
        buffer from it in place via ``input_output_aliases`` instead of
        zero-initing).  The out-of-core fold operand: this call's rows
        extend the accumulation bitwise, exactly as if they had been
        part of one monolithic call.
      num_features: true F (<= F_pad).
      max_bins: true per-feature bin-count bound; output B = its stride.
      raw: return the RAW ``[F_grid*B, cols]`` kernel accumulator
        (int32 on the quantized path) instead of unpacking — the carry
        for the next block's ``acc``.  Unpack once at the end of the
        fold chain with :func:`unpack_hist_raw`.

    Returns:
      ``[A, F, B, 3]`` f32 with B = ``bin_stride(max_bins)``, cells
      ``(sum_grad, sum_hess, count)`` — or the raw accumulator when
      ``raw=True``.

    MXU cost scales with ``round128(C*round8(A))`` — small waves are
    proportionally cheap.
    """
    F_pad, n_pad = bins_t.shape
    C = vals.shape[0]
    A = active.shape[0]
    B = bin_stride(max_bins)

    _, A_pad, cols = _col_layout(A, mode)
    T = _pick_row_tile(n_pad, B, cols, C, row_tile)
    assert n_pad % T == 0, (n_pad, T)
    pad_cols = cols - C * A_pad
    # feature tile: bounded by the per-grid-cell VMEM footprint (f32
    # accumulator + the bf16 one-hot + the bins tile — ADVICE r2: the
    # accumulator alone under-counts by the one-hot's tens of MB on wide
    # low-bin datasets); when tiling, the block's sublane dim must be a
    # multiple of 8 (Mosaic tiling constraint — full-array is exempt)
    ft_cap = max(1, _feat_tile_cap(B, cols, T, C))
    if ft_cap >= F_pad:
        feat_tile = F_pad
    else:
        feat_tile = max(8, (ft_cap // 8) * 8)
    F_grid = _round_up(F_pad, feat_tile)
    if F_grid != F_pad:
        bins_t = jnp.pad(bins_t, ((0, F_grid - F_pad), (0, 0)))

    leaf = jnp.full((1, n_pad), -1, jnp.int32)
    leaf = jax.lax.dynamic_update_slice(
        leaf, row_leaf.astype(jnp.int32)[None, :], (0, 0))
    act = jnp.full((A_pad, 1), -2, jnp.int32)
    act = jax.lax.dynamic_update_slice(
        act, active.astype(jnp.int32)[:, None], (0, 0))
    # padded rows carry leaf -1; bagged-out rows carry -1 too.  Use -2 for
    # active padding so neither lands in a real column block; -1 actives
    # (wave padding) DO accumulate bagged-out rows, caller drops them.
    grid = (F_grid // feat_tile, n_pad // T)
    seeded = acc is not None
    in_specs = [
        pl.BlockSpec((A_pad, 1), lambda f, r: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((feat_tile, T), lambda f, r: (f, r),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((C, T), lambda f, r: (0, r),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, T), lambda f, r: (0, r),
                     memory_space=pltpu.VMEM),
    ]
    operands = [act, bins_t, vals, leaf]
    if seeded:
        # the carried accumulator mirrors the OUTPUT's block walk
        # ((f, 0): per-feature-tile, revisited across row tiles) so the
        # rt==0 seed-load reads the matching seed block; aliasing it to
        # the output (input index 4 -> output 0) makes the seed a
        # donated in-place init — no extra HBM buffer, no copy
        in_specs.append(pl.BlockSpec((feat_tile * B, cols),
                                     lambda f, r: (f, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(acc)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_cols=C, B=B, pad_cols=pad_cols,
                          seeded=seeded),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((feat_tile * B, cols),
                               lambda f, r: (f, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (F_grid * B, cols),
            jnp.int32 if is_quantized(mode) else jnp.float32),
        input_output_aliases=({4: 0} if seeded else {}),
        interpret=interpret,
    )(*operands)

    if raw:
        return out
    # [F_grid*B, cols] -> [A, F, B, C'] -> combine hi/lo -> [A, F, B, 3]
    return _unpack_hist(out, B, cols, C, A_pad, A, num_features, mode,
                        scales)


def hist_raw_layout(n_pad: int, num_active: int, num_features: int,
                    max_bins: int, mode: str,
                    row_tile: int = DEFAULT_ROW_TILE):
    """``-> ((F_grid*B, cols), dtype)`` of the RAW wide-kernel
    accumulator for this config — the shape a streamed fold carries
    across blocks (``acc`` / ``raw=True`` in :func:`hist_active_pallas`).

    Replicates the kernel's own tile arithmetic (row tile from the VMEM
    model, feature tile from :func:`feat_tile_cap`), so the carry can be
    allocated before the first call.  ``num_features`` must equal the
    bins' F_pad (streamed sources transpose with ``feat_tile=None``, so
    F_pad == F); ``n_pad`` is the per-block padded row count — every
    block of a stream uses the same one, which is what keeps the layout
    call-invariant.
    """
    B = bin_stride(max_bins)
    C, A_pad, cols = _col_layout(num_active, mode)
    T = _pick_row_tile(n_pad, B, cols, C, row_tile)
    ft_cap = max(1, _feat_tile_cap(B, cols, T, C))
    F_pad = num_features
    feat_tile = F_pad if ft_cap >= F_pad else max(8, (ft_cap // 8) * 8)
    F_grid = _round_up(F_pad, feat_tile)
    dtype = jnp.int32 if is_quantized(mode) else jnp.float32
    return (F_grid * B, cols), dtype


def unpack_hist_raw(out: jnp.ndarray, num_active: int, num_features: int,
                    max_bins: int, mode: str,
                    scales: jnp.ndarray | None = None) -> jnp.ndarray:
    """RAW wide-kernel accumulator -> ``[A, F, B, 3]`` f32.  The one-shot
    finalization of a streamed fold chain (dequantize / combine hi-lo
    exactly once, after all blocks have accumulated exactly)."""
    B = bin_stride(max_bins)
    C, A_pad, cols = _col_layout(num_active, mode)
    return _unpack_hist(out, B, cols, C, A_pad, num_active, num_features,
                        mode, scales)


def _unpack_hist(out, B, cols, C, A_pad, A, num_features, mode, scales):
    """``[F_grid*B, cols] -> [A, F, B, 3] f32``: undo the kernel's
    c-major column layout and combine hi/lo (or dequantize) columns."""
    F_grid = out.shape[0] // B
    out = out.reshape(F_grid, B, cols)[:, :, :C * A_pad]
    out = out.reshape(F_grid, B, C, A_pad)
    out = out.transpose(3, 0, 1, 2)[:A, :num_features]       # [A, F, B, C]
    return combine_hist_cols(out, mode, scales)


def combine_hist_cols(out, mode, scales):
    """``[..., C]`` raw kernel value columns -> ``[..., 3]`` f32
    ``(sum_grad, sum_hess, count)``: combine hi/lo pairs or dequantize.
    Shared by the wide kernel's unpack and the leaf-compacted kernel
    (``ops/compact.py``), so the two paths cannot drift."""
    if is_quantized(mode):
        return dequant_hist(out, scales, mode)
    C = out.shape[-1]
    if C == 5:
        g = out[..., 0] + out[..., 1]
        h = out[..., 2] + out[..., 3]
        out = jnp.stack([g, h, out[..., 4]], axis=-1)
    elif C == 4 and mode == "hhilo":
        h = out[..., 1] + out[..., 2]
        out = jnp.stack([out[..., 0], h, out[..., 3]], axis=-1)
    elif C == 4:
        g = out[..., 0] + out[..., 1]
        out = jnp.stack([g, out[..., 2], out[..., 3]], axis=-1)
    return out


# ---------------------------------------------------------------------------
# XLA scatter reference implementation (CPU path + equivalence oracle)
# ---------------------------------------------------------------------------
def hist_active_scatter(bins: jnp.ndarray,
                        grad: jnp.ndarray,
                        hess: jnp.ndarray,
                        row_leaf: jnp.ndarray,
                        active: jnp.ndarray,
                        *,
                        max_bins: int,
                        num_leaf_slots: int) -> jnp.ndarray:
    """Same contract as :func:`hist_active_pallas` (exact f32 scatter),
    from the untransposed ``[n, F]`` integer bins.  The direct analog of
    the reference CPU construction (`dataset.cpp:587-752`) restricted to
    the active leaves."""
    n, F = bins.shape
    A = active.shape[0]
    B = bin_stride(max_bins)
    L = num_leaf_slots
    safe_act = jnp.where(active >= 0, active, L)
    inv = jnp.full((L + 1,), A, jnp.int32).at[safe_act].set(
        jnp.arange(A, dtype=jnp.int32), mode="drop")
    slot = jnp.where(row_leaf >= 0,
                     inv[jnp.clip(row_leaf, 0, L)], A)       # [n]
    idx = (slot[:, None] * (F * B)
           + jnp.arange(F, dtype=jnp.int32)[None, :] * B
           + bins.astype(jnp.int32))                         # [n, F]
    vals = jnp.stack([grad, hess, jnp.ones_like(grad)], -1)  # [n, 3]
    hist = jnp.zeros((A * F * B, 3), jnp.float32)
    hist = hist.at[idx].add(vals[:, None, :].astype(jnp.float32),
                            mode="drop")
    return hist.reshape(A, F, B, 3)


def default_backend() -> str:
    """"compact" (the wide MXU kernel + leaf-compacted deep waves,
    ``ops/compact.py``) on TPU, "scatter" elsewhere.  The compact
    backend degrades to plain "pallas" per-config via
    ``learner.serial.resolve_backend`` (small trees never reach the
    slot threshold; VMEM-infeasible groups fall back), so forcing
    ``LGBM_TPU_NO_COMPACT=1`` only matters for A/B on deep trees."""
    forced = os.environ.get("LGBM_TPU_HIST_BACKEND", "")
    if forced:
        return forced
    if jax.default_backend() != "tpu":
        return "scatter"
    return "pallas" if os.environ.get("LGBM_TPU_NO_COMPACT") else "compact"


# ---------------------------------------------------------------------------
# Fused route + histogram kernel: one bins stream per wave instead of two
# ---------------------------------------------------------------------------
def _hist_route_kernel(active_ref, bins_ref, vals_ref, leaf2_ref, rtabs_ref,
                       cat_ref, out_ref, leaf2_out_ref, *,
                       n_cols: int, B: int, Bcat: int, pad_cols: int,
                       tab_prec, any_cat: bool = True):
    """Apply the previous wave's pending splits to the leaf vectors, then
    histogram the active leaves — both from ONE VMEM-resident bins tile.
    The route logic matches ``ops/pallas_route.py`` (same table layout)."""
    from .pallas_route import (_T_GROUP, _T_THR, _T_DL, _T_ISCAT, _T_SEL,
                               _T_NEWID, _T_OFF, _T_NB, _T_DB, _T_MT,
                               _T_NANB)
    from ..io.binning import MISSING_NAN, MISSING_ZERO
    rt = pl.program_id(0)

    @pl.when(rt == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    binsf32 = bins_ref[:].astype(jnp.int32).astype(jnp.float32)  # [G, T]
    G_pad, T = binsf32.shape
    L_pad = rtabs_ref.shape[1]

    # ---- route (previous wave's pending splits) -----------------------
    leaf = leaf2_ref[0:1, :]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (L_pad, T), 0)
    from .pallas_route import selection_dtype
    sel_dt = selection_dtype(tab_prec)
    ohL = (iota_l == leaf).astype(sel_dt)
    # tab_prec (pallas_route.table_precision): bf16-exact configs use the
    # single default pass; ids past 256 need HIGHEST (the cat dot's 0/1
    # operands are exact at default precision)
    sel16 = jnp.dot(rtabs_ref[:].astype(sel_dt), ohL,
                    preferred_element_type=jnp.float32,
                    precision=tab_prec)
    g_row = sel16[_T_GROUP:_T_GROUP + 1, :]
    thr = sel16[_T_THR:_T_THR + 1, :]
    dl = sel16[_T_DL:_T_DL + 1, :]
    iscat = sel16[_T_ISCAT:_T_ISCAT + 1, :]
    selm = sel16[_T_SEL:_T_SEL + 1, :]
    new_id = sel16[_T_NEWID:_T_NEWID + 1, :]
    off = sel16[_T_OFF:_T_OFF + 1, :]
    nb = sel16[_T_NB:_T_NB + 1, :]
    db = sel16[_T_DB:_T_DB + 1, :]
    mt = sel16[_T_MT:_T_MT + 1, :]
    nanb = sel16[_T_NANB:_T_NANB + 1, :]

    iota_g = jax.lax.broadcasted_iota(
        jnp.int32, (G_pad, T), 0).astype(jnp.float32)
    ohG = jnp.where(iota_g == g_row, 1.0, 0.0)
    c = jnp.sum(ohG * binsf32, axis=0, keepdims=True)

    one = jnp.ones_like(c)
    zero = jnp.zeros_like(c)
    rank = c - off
    gt_db = jnp.where(rank >= db, one, zero)
    in_range = jnp.where((rank >= 0) & (rank < nb - 1), one, zero)
    b_bundled = jnp.where(in_range > 0.5, rank + gt_db, db)
    b = jnp.where(off < -0.5, c, b_bundled)
    is_missing = jnp.where(
        ((mt == float(MISSING_NAN)) & (b == nanb))
        | ((mt == float(MISSING_ZERO)) & (b == db)), one, zero)
    le_thr = jnp.where(b <= thr, one, zero)
    num_left = jnp.where(is_missing > 0.5, dl, le_thr)
    if any_cat:
        catrow = jnp.dot(cat_ref[:].astype(sel_dt), ohL,
                         preferred_element_type=jnp.float32)
        iota_b = jax.lax.broadcasted_iota(
            jnp.int32, (Bcat, T), 0).astype(jnp.float32)
        cat_left = jnp.sum(jnp.where(iota_b == b, catrow, 0.0), axis=0,
                           keepdims=True)
        go_left = jnp.where(iscat > 0.5, cat_left, num_left)
    else:
        go_left = num_left
    in_tree = jnp.where(leaf >= 0, one, zero)
    moved = selm * (one - jnp.minimum(go_left, one)) * in_tree
    nid = new_id.astype(jnp.int32)
    rl = jnp.where(moved > 0.5, nid, leaf)
    hl_old = leaf2_ref[1:2, :]
    hl = jnp.where(hl_old >= 0, rl, hl_old)
    leaf2_out_ref[0:1, :] = rl
    leaf2_out_ref[1:2, :] = hl

    # ---- histogram with the routed in-bag leaves ----------------------
    # rows-on-lanes throughout: mask [A_pad, T] straight off the routed
    # leaf row (no [1,T]->[T,1] relayout), vw [cols, T], lane contraction
    quant = vals_ref.dtype == jnp.int8
    cdt = jnp.int8 if quant else jnp.bfloat16
    oh = _onehot_bins(bins_ref[:].astype(jnp.int32), B, cdt)
    m = active_ref[:] == hl                                   # [A_pad, T]
    vals = vals_ref[:]                                        # [C, T]
    vw = _weighted_cols(m, vals, n_cols, pad_cols, cdt)       # [cols, T]
    out_ref[:] += jax.lax.dot_general(
        oh, vw, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32 if quant else jnp.float32)


def fused_config_ok(num_groups: int, max_bins: int, num_leaves: int,
                    mode: str) -> bool:
    """Fusion needs the whole feature set in one tile (the route reads the
    split feature's column, which may live in any tile) plus the usual
    kernel bounds."""
    if not pallas_config_ok(max_bins, num_leaves, mode):
        return False
    B = bin_stride(max_bins)
    C, _, cols = _col_layout(min(max(1, num_leaves // 2), 128), mode)
    # feasibility at the 1024-row fallback tile (the kernel halves its
    # row tile per-config until the whole feature set fits)
    return num_groups <= _feat_tile_cap(B, cols, 1024, C)


@functools.partial(
    jax.jit,
    static_argnames=("num_features", "max_bins", "mode", "row_tile",
                     "interpret", "any_cat"))
def hist_route_pallas(bins_t, vals, leaf2, active,
                      feature, threshold, default_left, is_categorical,
                      cat_mask, sel, new_id, missing_types, nan_bins,
                      default_bins, feat_group, feat_offset, num_bins_arr,
                      scales=None,
                      *, num_features: int, max_bins: int,
                      mode: str = "hilo", row_tile: int = DEFAULT_ROW_TILE,
                      interpret: bool = False, any_cat: bool = True):
    """Fused previous-wave routing + active-leaf histograms.

    -> ``(hist [A, F, B, 3] f32, leaf2_new [2, n_pad] i32)``.  Same
    contracts as :func:`hist_active_pallas` +
    ``ops.pallas_route.route_rows_pallas`` composed (route first).
    Requires ``fused_config_ok``.
    """
    from .pallas_route import _T_ROWS, _leaf_tables
    F_pad, n_pad = bins_t.shape
    C = vals.shape[0]
    A = active.shape[0]
    B = bin_stride(max_bins)

    _, A_pad, cols = _col_layout(A, mode)
    # the fused kernel holds ALL stored columns in one tile: halve the
    # row tile until that cell fits the VMEM budget
    T = row_tile
    while T > 1024 and (
            n_pad % T != 0
            or _cell_vmem_bytes(F_pad, B, cols, T, C) > _VMEM_BUDGET_BYTES):
        T //= 2
    assert n_pad % T == 0 and leaf2.shape == (2, n_pad)
    pad_cols = cols - C * A_pad
    L = feature.shape[0]
    L_pad = _round_up(max(L, 8), LANE)
    Bcat = cat_mask.shape[1]

    rtabs = _leaf_tables(feature, threshold, default_left, is_categorical,
                         sel, new_id, missing_types, nan_bins, default_bins,
                         feat_group, feat_offset, num_bins_arr, L_pad)
    cat = jnp.zeros((Bcat, L_pad), jnp.float32)
    cat = cat.at[:, :L].set(cat_mask.T.astype(jnp.float32))
    act = jnp.full((A_pad, 1), -2, jnp.int32)
    act = jax.lax.dynamic_update_slice(
        act, active.astype(jnp.int32)[:, None], (0, 0))

    from .pallas_route import table_precision
    out, leaf2_new = pl.pallas_call(
        functools.partial(_hist_route_kernel, n_cols=C, B=B, Bcat=Bcat,
                          pad_cols=pad_cols, any_cat=any_cat,
                          tab_prec=table_precision(L_pad, F_pad)),
        grid=(n_pad // T,),
        in_specs=[
            pl.BlockSpec((A_pad, 1), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((F_pad, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_T_ROWS, L_pad), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Bcat, L_pad), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((F_pad * B, cols), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, T), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(
                (F_pad * B, cols),
                jnp.int32 if is_quantized(mode) else jnp.float32),
            jax.ShapeDtypeStruct((2, n_pad), jnp.int32),
        ),
        interpret=interpret,
    )(act, bins_t, vals, leaf2, rtabs, cat)

    out = _unpack_hist(out, B, cols, C, A_pad, A, num_features, mode,
                       scales)
    return out, leaf2_new
