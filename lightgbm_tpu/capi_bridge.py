"""Python side of the C API (handle registry + raw-pointer marshalling).

The reference exposes 55 ``LGBM_*`` functions from C++
(`/root/reference/src/c_api.cpp`, `include/LightGBM/c_api.h`).  Here the
native shim (`capi/lightgbm_tpu_c.cpp`) embeds a CPython interpreter and
calls THIS module with integer handles and raw buffer addresses; all
object lifetime lives in the registry below.  The C surface keeps the
reference's names and call shapes for the core train/predict workflow.

Raw pointers arrive as ``int`` addresses and are wrapped zero-copy with
``ctypes`` + ``np.frombuffer`` — the same marshalling direction as the
reference's Python package, inverted.
"""
from __future__ import annotations

import ctypes
from typing import Dict

import numpy as np

_handles: Dict[int, object] = {}
_next = [1]


def _put(obj) -> int:
    h = _next[0]
    _next[0] += 1
    _handles[h] = obj
    return h


def _get(h: int):
    return _handles[int(h)]


def free_handle(h: int) -> None:
    _handles.pop(int(h), None)


def _wrap_f64(ptr: int, n: int) -> np.ndarray:
    buf = (ctypes.c_double * n).from_address(int(ptr))
    return np.frombuffer(buf, dtype=np.float64, count=n)


def _wrap_f32(ptr: int, n: int) -> np.ndarray:
    buf = (ctypes.c_float * n).from_address(int(ptr))
    return np.frombuffer(buf, dtype=np.float32, count=n)


def _parse_params(params: str) -> dict:
    out = {}
    for tok in params.replace("\t", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return out


# -- datasets (LGBM_DatasetCreateFromMat c_api.h) -------------------------
def dataset_from_mat(ptr: int, nrow: int, ncol: int, is_row_major: int,
                     params: str, ref_handle: int) -> int:
    X = _wrap_f64(ptr, nrow * ncol)
    X = (X.reshape(nrow, ncol) if is_row_major
         else X.reshape(ncol, nrow).T).copy()
    import lightgbm_tpu as lgb
    ref = _get(ref_handle) if ref_handle else None
    ds = lgb.Dataset(X, params=_parse_params(params), reference=ref)
    return _put(ds)


def dataset_set_field(h: int, name: str, ptr: int, n: int,
                      is_float64: int) -> None:
    arr = _wrap_f64(ptr, n) if is_float64 else _wrap_f32(ptr, n)
    _get(h).set_field(name, np.array(arr))


def dataset_num_data(h: int) -> int:
    return int(_get(h).num_data())


def dataset_num_feature(h: int) -> int:
    return int(_get(h).num_feature())


# -- boosters (LGBM_BoosterCreate / UpdateOneIter / ...) ------------------
def booster_create(train_handle: int, params: str) -> int:
    from lightgbm_tpu.basic import Booster
    return _put(Booster(params=_parse_params(params),
                        train_set=_get(train_handle)))


def booster_create_from_modelfile(path: str) -> int:
    from lightgbm_tpu.basic import Booster
    return _put(Booster(model_file=path))


def booster_add_valid(h: int, valid_handle: int, name: str) -> None:
    _get(h).add_valid(_get(valid_handle), name)


def booster_update_one_iter(h: int) -> int:
    return int(bool(_get(h).update()))


def booster_num_classes(h: int) -> int:
    return int(max(1, _get(h)._gbdt.num_class))


def booster_current_iteration(h: int) -> int:
    return int(_get(h).current_iteration)


def booster_predict_for_mat(h: int, ptr: int, nrow: int, ncol: int,
                            is_row_major: int, raw_score: int,
                            num_iteration: int, out_ptr: int) -> int:
    X = _wrap_f64(ptr, nrow * ncol)
    X = (X.reshape(nrow, ncol) if is_row_major
         else X.reshape(ncol, nrow).T).copy()
    pred = _get(h).predict(X, raw_score=bool(raw_score),
                           num_iteration=num_iteration)
    pred = np.ascontiguousarray(pred, dtype=np.float64).reshape(-1)
    ctypes.memmove(int(out_ptr), pred.ctypes.data, pred.nbytes)
    return int(pred.size)


def booster_save_model(h: int, path: str, num_iteration: int) -> None:
    _get(h).save_model(path, num_iteration=num_iteration)


def booster_model_to_string(h: int) -> str:
    return _get(h).model_to_string()
