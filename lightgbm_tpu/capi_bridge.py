"""Python side of the C API (handle registry + raw-pointer marshalling).

The reference exposes its 51 ``LGBM_*`` functions from C++
(`/root/reference/src/c_api.cpp`, `include/LightGBM/c_api.h:41-760`).
Here the native shim (`capi/lightgbm_tpu_c.cpp`) embeds a CPython
interpreter and calls THIS module with integer handles and raw buffer
addresses; all object lifetime lives in the registry below.  The C
surface keeps the reference's names, call shapes, and 0/-1 return
convention for the full dataset / booster / network workflow.

Raw pointers arrive as ``int`` addresses and are wrapped zero-copy with
``ctypes`` + ``np.frombuffer`` — the same marshalling direction as the
reference's Python package, inverted.

Sparse inputs (CSR/CSC) are densified at the boundary: the TPU core is a
dense binned column store (SURVEY §7 drops the sparse-bin variants in
favor of EFB + dense kernels), so sparse C API calls exist for call-shape
parity, not for memory parity.
"""
from __future__ import annotations

import ctypes
import json
import os
from typing import Dict, List, Optional

import numpy as np

_handles: Dict[int, object] = {}
_next = [1]

# C_API_DTYPE_* (c_api.h:22-29)
_DTYPE_FLOAT32 = 0
_DTYPE_FLOAT64 = 1
_DTYPE_INT32 = 2
_DTYPE_INT64 = 3

_CTYPES = {
    _DTYPE_FLOAT32: (ctypes.c_float, np.float32),
    _DTYPE_FLOAT64: (ctypes.c_double, np.float64),
    _DTYPE_INT32: (ctypes.c_int32, np.int32),
    _DTYPE_INT64: (ctypes.c_int64, np.int64),
}

# C_API_PREDICT_* (c_api.h:31-36)
_PREDICT_NORMAL = 0
_PREDICT_RAW = 1
_PREDICT_LEAF = 2
_PREDICT_CONTRIB = 3


def _put(obj) -> int:
    h = _next[0]
    _next[0] += 1
    _handles[h] = obj
    return h


def _get(h: int):
    return _handles[int(h)]


def free_handle(h: int) -> None:
    _handles.pop(int(h), None)


def _wrap(ptr: int, n: int, dtype: int) -> np.ndarray:
    ct, npt = _CTYPES[int(dtype)]
    buf = (ct * n).from_address(int(ptr))
    return np.frombuffer(buf, dtype=npt, count=n)


def _wrap_f64(ptr: int, n: int) -> np.ndarray:
    return _wrap(ptr, n, _DTYPE_FLOAT64)


def _wrap_f32(ptr: int, n: int) -> np.ndarray:
    return _wrap(ptr, n, _DTYPE_FLOAT32)


def _wrap_mat(ptr: int, nrow: int, ncol: int, is_row_major: int,
              dtype: int = _DTYPE_FLOAT64) -> np.ndarray:
    X = _wrap(ptr, nrow * ncol, dtype)
    return (X.reshape(nrow, ncol) if is_row_major
            else X.reshape(ncol, nrow).T).astype(np.float64, copy=True)


def _csr_to_dense(indptr_ptr: int, indptr_type: int, indices_ptr: int,
                  data_ptr: int, data_type: int, nindptr: int,
                  nelem: int, num_col: int) -> np.ndarray:
    """CSR triplet buffers -> dense [nrow, ncol] f64
    (LGBM_DatasetCreateFromCSR shape, c_api.h:147-172)."""
    indptr = _wrap(indptr_ptr, nindptr, indptr_type).astype(np.int64)
    indices = _wrap(indices_ptr, nelem, _DTYPE_INT32).astype(np.int64)
    data = _wrap(data_ptr, nelem, data_type).astype(np.float64)
    nrow = nindptr - 1
    ncol = int(num_col) if num_col > 0 else (
        int(indices.max()) + 1 if nelem else 0)
    X = np.zeros((nrow, ncol), np.float64)
    row = np.repeat(np.arange(nrow), np.diff(indptr))
    X[row, indices] = data
    return X


def _csc_to_dense(col_ptr_ptr: int, col_ptr_type: int, indices_ptr: int,
                  data_ptr: int, data_type: int, ncol_ptr: int,
                  nelem: int, num_row: int) -> np.ndarray:
    col_ptr = _wrap(col_ptr_ptr, ncol_ptr, col_ptr_type).astype(np.int64)
    indices = _wrap(indices_ptr, nelem, _DTYPE_INT32).astype(np.int64)
    data = _wrap(data_ptr, nelem, data_type).astype(np.float64)
    ncol = ncol_ptr - 1
    nrow = int(num_row) if num_row > 0 else (
        int(indices.max()) + 1 if nelem else 0)
    X = np.zeros((nrow, ncol), np.float64)
    col = np.repeat(np.arange(ncol), np.diff(col_ptr))
    X[indices, col] = data
    return X


def _parse_params(params: str) -> dict:
    out = {}
    for tok in params.replace("\t", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return out


# -- datasets -------------------------------------------------------------
def dataset_from_mat(ptr: int, data_type: int, nrow: int, ncol: int,
                     is_row_major: int, params: str, ref_handle: int) -> int:
    X = _wrap_mat(ptr, nrow, ncol, is_row_major, data_type)
    import lightgbm_tpu as lgb
    ref = _get(ref_handle) if ref_handle else None
    ds = lgb.Dataset(X, params=_parse_params(params), reference=ref)
    return _put(ds)


def dataset_from_file(filename: str, params: str, ref_handle: int) -> int:
    """LGBM_DatasetCreateFromFile (c_api.h:53-60): text/binary autodetect
    through the loader, honoring reference bin mappers."""
    import lightgbm_tpu as lgb
    ref = _get(ref_handle) if ref_handle else None
    ds = lgb.Dataset(filename, params=_parse_params(params), reference=ref)
    ds.construct()
    return _put(ds)


def dataset_from_csr(indptr_ptr: int, indptr_type: int, indices_ptr: int,
                     data_ptr: int, data_type: int, nindptr: int,
                     nelem: int, num_col: int, params: str,
                     ref_handle: int) -> int:
    X = _csr_to_dense(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                      data_type, nindptr, nelem, num_col)
    import lightgbm_tpu as lgb
    ref = _get(ref_handle) if ref_handle else None
    return _put(lgb.Dataset(X, params=_parse_params(params), reference=ref))


def dataset_from_csc(col_ptr_ptr: int, col_ptr_type: int, indices_ptr: int,
                     data_ptr: int, data_type: int, ncol_ptr: int,
                     nelem: int, num_row: int, params: str,
                     ref_handle: int) -> int:
    X = _csc_to_dense(col_ptr_ptr, col_ptr_type, indices_ptr, data_ptr,
                      data_type, ncol_ptr, nelem, num_row)
    import lightgbm_tpu as lgb
    ref = _get(ref_handle) if ref_handle else None
    return _put(lgb.Dataset(X, params=_parse_params(params), reference=ref))


class _StreamingDataset:
    """Push-rows staging buffer behind LGBM_DatasetCreateFromSampledColumn /
    CreateByReference + PushRows[ByCSR] (c_api.h:70-146).

    The reference pre-sizes bin mappers from sampled columns, then streams
    rows in.  Dense-first here: rows land in a preallocated f64 matrix and
    the real Dataset is constructed once every row has arrived (the
    sampled values only size the buffer — bin finding runs on the full
    data, a strictly better quantization than the reference's sample)."""

    def __init__(self, nrow: int, ncol: int, params: str,
                 reference=None):
        self.X = np.full((nrow, ncol), 0.0, np.float64)
        self.params = params
        self.reference = reference
        self.pushed = 0
        self._covered = np.zeros(nrow, bool)  # which row indices arrived
        self.dataset = None                  # becomes lgb.Dataset

    def push(self, rows: np.ndarray, start_row: int):
        if self.dataset is not None:
            raise RuntimeError(
                "dataset already finalized: all rows were pushed")
        end_row = start_row + rows.shape[0]
        if end_row > self.X.shape[0]:
            raise ValueError(
                f"push of rows [{start_row}, {end_row}) exceeds declared "
                f"nrow {self.X.shape[0]}")
        if self._covered[start_row:end_row].any():
            raise ValueError(
                f"rows in [{start_row}, {end_row}) were already pushed")
        self.X[start_row:end_row] = rows
        self._covered[start_row:end_row] = True
        self.pushed += rows.shape[0]
        # finalize only once EVERY row index has been written — a pure
        # count would finalize early (zero-filling gaps) on overlapping
        # or out-of-order pushes
        if self._covered.all():
            self._finish()

    def _finish(self):
        import lightgbm_tpu as lgb
        self.dataset = lgb.Dataset(self.X, params=_parse_params(self.params),
                                   reference=self.reference)
        self.dataset.construct()
        self.X = None

    # dataset-protocol passthroughs: once finished, behave as the Dataset
    def _require(self):
        if self.dataset is None:
            raise RuntimeError(
                f"dataset is still streaming: {self.pushed}/{len(self.X)} "
                "rows pushed")
        return self.dataset

    def __getattr__(self, name):
        return getattr(self._require(), name)


def dataset_from_sampled_column(nrow: int, ncol: int, params: str) -> int:
    """LGBM_DatasetCreateFromSampledColumn (c_api.h:70-84).  The sampled
    values themselves are not needed (see _StreamingDataset docstring);
    the call records the target shape for the PushRows stream."""
    return _put(_StreamingDataset(nrow, ncol, params))


def dataset_create_by_reference(ref_handle: int, nrow: int) -> int:
    ref = _get(ref_handle)
    if isinstance(ref, _StreamingDataset):
        ref = ref._require()
    return _put(_StreamingDataset(nrow, ref.num_feature(), "",
                                  reference=ref))


def dataset_push_rows(h: int, ptr: int, data_type: int, nrow: int,
                      ncol: int, start_row: int) -> None:
    rows = _wrap(ptr, nrow * ncol, data_type).reshape(nrow, ncol)
    _get(h).push(rows.astype(np.float64), int(start_row))


def dataset_push_rows_by_csr(h: int, indptr_ptr: int, indptr_type: int,
                             indices_ptr: int, data_ptr: int,
                             data_type: int, nindptr: int, nelem: int,
                             num_col: int, start_row: int) -> None:
    rows = _csr_to_dense(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    _get(h).push(rows, int(start_row))


def dataset_get_subset(h: int, idx_ptr: int, n_idx: int,
                       params: str) -> int:
    idx = _wrap(idx_ptr, n_idx, _DTYPE_INT32)
    return _put(_get(h).subset(np.array(idx), _parse_params(params)))


def dataset_set_feature_names(h: int, names_json: str) -> None:
    ds = _get(h)
    ds.construct()
    ds._constructed.feature_names = list(json.loads(names_json))


def dataset_get_feature_names(h: int) -> str:
    ds = _get(h)
    return json.dumps(list(ds.feature_names))


def dataset_save_binary(h: int, filename: str) -> None:
    _get(h).save_binary(filename)


def dataset_set_field(h: int, name: str, ptr: int, n: int,
                      dtype: int) -> None:
    arr = _wrap(ptr, n, dtype)
    _get(h).set_field(name, np.array(arr))


def dataset_get_field(h: int, name: str) -> tuple:
    """-> (address, length, c_api_dtype); keeps the buffer alive on the
    handle (reference returns a pointer into the Dataset, c_api.h:290-300)."""
    ds = _get(h)
    val = ds.get_field(name)
    if val is None:
        return (0, 0, _DTYPE_FLOAT32)
    if name == "group":
        arr = np.ascontiguousarray(val, np.int32)
        dt = _DTYPE_INT32
    elif name == "init_score":
        arr = np.ascontiguousarray(val, np.float64)
        dt = _DTYPE_FLOAT64
    else:
        arr = np.ascontiguousarray(val, np.float32)
        dt = _DTYPE_FLOAT32
    if not hasattr(ds, "_field_refs"):
        ds._field_refs = {}
    ds._field_refs[name] = arr
    return (arr.ctypes.data, int(arr.size), dt)


def dataset_num_data(h: int) -> int:
    return int(_get(h).num_data())


def dataset_num_feature(h: int) -> int:
    return int(_get(h).num_feature())


# -- boosters -------------------------------------------------------------
def booster_create(train_handle: int, params: str) -> int:
    from lightgbm_tpu.basic import Booster
    train = _get(train_handle)
    if isinstance(train, _StreamingDataset):
        train = train._require()
    return _put(Booster(params=_parse_params(params), train_set=train))


def booster_create_from_modelfile(path: str) -> int:
    from lightgbm_tpu.basic import Booster
    return _put(Booster(model_file=path))


def booster_load_model_from_string(model_str: str) -> int:
    from lightgbm_tpu.basic import Booster
    return _put(Booster(model_str=model_str))


def booster_merge(h: int, other_h: int) -> None:
    """LGBM_BoosterMerge (c_api.h:364-371): merge the other booster's
    trees in FRONT of this booster's, as copies (reference
    GBDT::MergeFrom, gbdt.h:50-67)."""
    _get(h)._gbdt.merge_from(_get(other_h)._gbdt)


def booster_add_valid(h: int, valid_handle: int, name: str) -> None:
    valid = _get(valid_handle)
    if isinstance(valid, _StreamingDataset):
        valid = valid._require()
    b = _get(h)
    # caller-supplied name when given, else the reference's
    # "valid_1"/"valid_2" convention: GetEval selects by data_idx, which
    # needs the sets distinguishable
    base = name.strip() if name else ""
    if not base or base in b._name_valid_sets:
        i = len(b._name_valid_sets) + 1
        while f"valid_{i}" in b._name_valid_sets:
            i += 1
        base = f"valid_{i}"
    b.add_valid(valid, base)


def booster_reset_training_data(h: int, train_handle: int) -> None:
    """LGBM_BoosterResetTrainingData (c_api.h:382-389): swap the train
    set, keeping the model (continue-training on new data)."""
    from lightgbm_tpu.basic import Booster
    b = _get(h)
    train = _get(train_handle)
    if isinstance(train, _StreamingDataset):
        train = train._require()
    nb = Booster(params=b.params, train_set=train)
    model = b.model_to_string()
    if b._gbdt.num_trees() > 0:
        nb._gbdt.load_model_trees(model)
    # valid sets survive ResetTrainingData (reference c_api.cpp
    # ResetTrainingData keeps the Booster's valid list)
    for vs, name in zip(b._valid_sets, b._name_valid_sets):
        nb.add_valid(vs, name)
    _handles[int(h)] = nb


def booster_reset_parameter(h: int, params: str) -> None:
    _get(h)._gbdt.reset_config(_parse_params(params))


def booster_update_one_iter(h: int) -> int:
    return int(bool(_get(h).update()))


def booster_update_one_iter_custom(h: int, grad_ptr: int, hess_ptr: int,
                                   n: int) -> int:
    import jax.numpy as jnp
    b = _get(h)
    K = max(1, b._gbdt.num_tree_per_iteration)
    grad = np.array(_wrap_f32(grad_ptr, n)).reshape(-1, K, order="F")
    hess = np.array(_wrap_f32(hess_ptr, n)).reshape(-1, K, order="F")
    return int(bool(b._gbdt.train_one_iter(jnp.asarray(grad),
                                           jnp.asarray(hess))))


def booster_rollback_one_iter(h: int) -> None:
    _get(h).rollback_one_iter()


def booster_num_classes(h: int) -> int:
    return int(max(1, _get(h)._gbdt.num_class))


def booster_current_iteration(h: int) -> int:
    return int(_get(h).current_iteration)


def booster_number_of_total_model(h: int) -> int:
    return int(_get(h).num_trees())


def booster_get_num_feature(h: int) -> int:
    return int(_get(h).num_feature())


def booster_get_feature_names(h: int) -> str:
    return json.dumps(_get(h).feature_name())


# eval plumbing: the reference's GetEval returns only metric VALUES in
# eval-name order for dataset idx (0 = train, i+1 = i-th valid),
# c_api.h:477-489 / c_api.cpp GetEval.
def _eval_results(b, data_idx: int) -> List[tuple]:
    g = b._gbdt
    if data_idx == 0:
        return b.eval_train()
    # select the idx-th valid set BY POSITION (names could collide)
    i = int(data_idx) - 1
    vs = g.valid_sets[i]
    md = vs.metadata
    return g._eval_set(g.valid_names[i], np.asarray(g._valid_scores[i]),
                       md.label, md.weight, md.query_boundaries)


def _metric_names(b) -> List[str]:
    # metadata query: read the configured metric names, don't run eval
    return [n for m in b._gbdt.metrics for n in m.names]


def booster_get_eval_counts(h: int) -> int:
    return len(_metric_names(_get(h)))


def booster_get_eval_names(h: int) -> str:
    return json.dumps(_metric_names(_get(h)))


def booster_get_eval(h: int, data_idx: int, out_ptr: int) -> int:
    res = _eval_results(_get(h), int(data_idx))
    vals = np.ascontiguousarray([v for _, _, v, _ in res], np.float64)
    ctypes.memmove(int(out_ptr), vals.ctypes.data, vals.nbytes)
    return int(vals.size)


def booster_get_num_predict(h: int, data_idx: int) -> int:
    b = _get(h)
    g = b._gbdt
    scores = g.scores if data_idx == 0 else g._valid_scores[data_idx - 1]
    return int(np.asarray(scores).size)


def booster_get_predict(h: int, data_idx: int, out_ptr: int) -> int:
    """Raw scores of the idx-th dataset (0=train), transformed by the
    objective the way the reference's GetPredict does (c_api.h:491-503)."""
    b = _get(h)
    g = b._gbdt
    scores = np.asarray(
        g.scores if data_idx == 0 else g._valid_scores[data_idx - 1])
    if g.objective is not None:
        out = np.asarray(g.objective.convert_output(scores))
    else:
        out = scores
    out = np.ascontiguousarray(out.reshape(-1), np.float64)
    ctypes.memmove(int(out_ptr), out.ctypes.data, out.nbytes)
    return int(out.size)


def _predict_kwargs(predict_type: int):
    return {"raw_score": predict_type == _PREDICT_RAW,
            "pred_leaf": predict_type == _PREDICT_LEAF,
            "pred_contrib": predict_type == _PREDICT_CONTRIB}


def booster_calc_num_predict(h: int, nrow: int, predict_type: int,
                             num_iteration: int) -> int:
    b = _get(h)
    g = b._gbdt
    K = max(1, g.num_tree_per_iteration)
    if predict_type == _PREDICT_LEAF:
        T = g.num_trees()
        if num_iteration > 0:
            T = min(T, num_iteration * K)
        return int(nrow * T)
    if predict_type == _PREDICT_CONTRIB:
        return int(nrow * K * (g.max_feature_idx + 2))
    return int(nrow * max(1, g.num_class))


def _capi_device_flag():
    """Whether the C surface routes through the TPU-resident serving
    predictor (``lightgbm_tpu/serve/``).  The shim drops the reference
    ``parameter`` string, so the knob is the ``LGBM_TPU_CAPI_DEVICE``
    env var: unset/``0`` keeps the legacy path (``None`` defers to
    ``Booster.predict``'s own default resolution)."""
    v = os.environ.get("LGBM_TPU_CAPI_DEVICE", "")
    return True if v not in ("", "0") else None


def _predict_to_buffer(b, X: np.ndarray, predict_type: int,
                       num_iteration: int, out_ptr: int) -> int:
    pred = b.predict(X, num_iteration=num_iteration,
                     device=_capi_device_flag(),
                     **_predict_kwargs(predict_type))
    pred = np.ascontiguousarray(pred, np.float64).reshape(-1)
    ctypes.memmove(int(out_ptr), pred.ctypes.data, pred.nbytes)
    return int(pred.size)


def booster_predict_for_mat(h: int, ptr: int, data_type: int, nrow: int,
                            ncol: int, is_row_major: int, predict_type: int,
                            num_iteration: int, out_ptr: int) -> int:
    X = _wrap_mat(ptr, nrow, ncol, is_row_major, data_type)
    return _predict_to_buffer(_get(h), X, predict_type, num_iteration,
                              out_ptr)


def booster_predict_for_csr(h: int, indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            nindptr: int, nelem: int, num_col: int,
                            predict_type: int, num_iteration: int,
                            out_ptr: int) -> int:
    X = _csr_to_dense(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                      data_type, nindptr, nelem, num_col)
    return _predict_to_buffer(_get(h), X, predict_type, num_iteration,
                              out_ptr)


def booster_predict_for_csc(h: int, col_ptr_ptr: int, col_ptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncol_ptr: int, nelem: int, num_row: int,
                            predict_type: int, num_iteration: int,
                            out_ptr: int) -> int:
    X = _csc_to_dense(col_ptr_ptr, col_ptr_type, indices_ptr, data_ptr,
                      data_type, ncol_ptr, nelem, num_row)
    return _predict_to_buffer(_get(h), X, predict_type, num_iteration,
                              out_ptr)


def booster_predict_for_file(h: int, data_filename: str, has_header: int,
                             result_filename: str, predict_type: int,
                             num_iteration: int) -> None:
    """LGBM_BoosterPredictForFile (c_api.h:524-542): parse with the native
    text parser, write one line per row (reference Predictor file flow,
    src/application/predictor.hpp:115-155)."""
    from lightgbm_tpu.io.loader import load_raw_matrix
    from lightgbm_tpu.utils.file_io import open_write
    X, _ = load_raw_matrix(data_filename, has_header=bool(has_header))
    b = _get(h)
    pred = b.predict(X, num_iteration=num_iteration,
                     device=_capi_device_flag(),
                     **_predict_kwargs(predict_type))
    pred = np.asarray(pred)
    if pred.ndim == 1:
        pred = pred[:, None]
    with open_write(result_filename) as f:
        for row in pred:
            f.write("\t".join(repr(float(v)) for v in row) + "\n")


def booster_save_model(h: int, path: str, num_iteration: int) -> None:
    _get(h).save_model(path, num_iteration=num_iteration)


def booster_model_to_string(h: int, num_iteration: int) -> str:
    return _get(h).model_to_string(num_iteration)


def booster_dump_model(h: int, num_iteration: int) -> str:
    return json.dumps(_get(h).dump_model(num_iteration))


def booster_get_leaf_value(h: int, tree_idx: int, leaf_idx: int) -> float:
    return float(_get(h)._gbdt.models[int(tree_idx)].leaf_value[int(leaf_idx)])


def booster_set_leaf_value(h: int, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    _get(h)._gbdt.set_leaf_value(int(tree_idx), int(leaf_idx), float(val))


def booster_feature_importance(h: int, num_iteration: int,
                               importance_type: int, out_ptr: int) -> int:
    imp = _get(h).feature_importance(
        "gain" if importance_type == 1 else "split", num_iteration)
    imp = np.ascontiguousarray(imp, np.float64)
    ctypes.memmove(int(out_ptr), imp.ctypes.data, imp.nbytes)
    return int(imp.size)


# -- network (LGBM_NetworkInit*, c_api.h:749-760) -------------------------
def network_init(machines: str, local_listen_port: int,
                 listen_time_out: int, num_machines: int) -> None:
    """Machine-list rendezvous -> jax.distributed (the socket-linker
    analog, linkers_socket.cpp:27-68: first machine is the coordinator,
    rank = position of the local endpoint in the list)."""
    if num_machines <= 1:
        return
    from lightgbm_tpu.parallel.mesh import init_distributed_from_machines
    init_distributed_from_machines(machines, local_listen_port, num_machines)


def network_free() -> None:
    import jax
    try:
        jax.distributed.shutdown()
    # tpulint: disable=TPL006 -- C-API free never raises (double-free ok)
    except Exception:
        pass


def network_init_with_functions(num_machines: int, rank: int,
                                reduce_scatter_addr: int,
                                allgather_addr: int) -> None:
    """LGBM_NetworkInitWithFunctions (c_api.h:760): the reference's
    pluggable-collective seam.  The C function pointers are wrapped with
    ctypes and installed as the host-side collective backend used by
    distributed ingest (io/distributed.py)."""
    from lightgbm_tpu.io import distributed as dist
    dist.install_external_collectives(num_machines, rank,
                                      reduce_scatter_addr, allgather_addr)
