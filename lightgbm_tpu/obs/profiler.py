"""Device-time attribution: profiler-backed span accounting.

The host-side telemetry spans (``obs/telemetry.py``) time DISPATCH,
not execution: a span around an async JAX dispatch closes when the
host returns, while XLA is still running.  Every open perf question on
the ROADMAP — per-iteration host latency on the mesh path, the 0.27x
ranking regime, the never-captured 255-bin leg — needs the other half:
where the DEVICE time goes, per phase.  This module is that layer.

* **Capture** — under ``LGBM_TPU_PROFILE=<dir>`` every training run
  profiles itself: once the first (warmup) window is done,
  ``jax.profiler.start_trace`` begins a WINDOWED capture (the next
  ``LGBM_TPU_PROFILE_WINDOWS`` windows of ``LGBM_TPU_PROFILE_ITERS``
  iterations each, so the trace stays bounded inside bench runs),
  then stops, parses, and drops the result into the telemetry summary
  as the ``device_attribution`` section.  While a capture is live,
  every telemetry span additionally emits a
  ``jax.profiler.TraceAnnotation`` with the same name (installed via
  :func:`telemetry.set_annotator` — one module-attribute read per span
  when inactive), so XLA ops attribute to the existing span tree
  without a second instrumentation pass.  Works on the CPU backend —
  tier-1 gates the whole pipeline without TPU hardware.

* **Parse** — :func:`parse_capture` reads the profiler's chrome-trace
  JSON (``plugins/profile/<ts>/*.trace.json.gz``; stdlib only) and
  :func:`attribute` reduces it to the per-span table: ``device_s`` per
  span (each HLO-op event joins the deepest annotation covering its
  midpoint, falling back to the latest annotation started before it —
  async dispatch runs AFTER its span closes), ``host_gap_s`` (device
  idle inside the training windows: the ROADMAP item-1 metric),
  collective wall time (op-name families: all-reduce / all-gather /
  reduce-scatter / all-to-all / collective-permute — the sites the
  flight recorder names), and per-program (``hlo_module``) totals.

* **Cost model** — :func:`record_program_cost` snapshots
  ``Compiled.cost_analysis()`` (FLOPs, bytes accessed) for each jitted
  program at block-compile time (gated on the same env: an extra
  lower+compile is acceptable in an explicit profiling run, never in a
  timed one); :func:`finalize` joins those with the measured
  per-program device time and the ``obs/chip_specs.py`` peak table
  into roofline columns — %-of-peak FLOPs/BW, arithmetic intensity,
  and a compute/memory/host ``bound`` verdict per program.

Capture is best-effort by construction: a profiler that fails to
start, a trace that fails to parse, disk full — all degrade to a
``device_attribution`` section carrying an ``error`` field.  Training
must never die for observability's sake.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry

__all__ = [
    "profile_dir", "cost_model_enabled", "maybe_profile", "capture",
    "step", "record_program_cost", "program_costs", "reset",
    "parse_capture", "attribute", "finalize_report",
    "ATTRIBUTION_SECTION",
]

PROFILE_ENV = "LGBM_TPU_PROFILE"
ATTRIBUTION_SECTION = "device_attribution"

# span-name prefixes the parser recognizes as OUR annotations (the
# telemetry span tree + the step markers) — everything else on the
# host timeline is runtime internals ($-prefixed python frames,
# PjitFunction, executor plumbing)
SPAN_PREFIXES = ("engine.", "gbdt.", "tree.", "serve.", "io.", "mesh.",
                 "collective.", "obj.", "snapshot.", "bench.", "profile.")
# training-window spans: their wall clock minus in-window device busy
# time is the host gap (idle device between consecutive dispatches)
WINDOW_SPANS = ("gbdt.block", "gbdt.block_compile", "gbdt.iteration")
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "all-to-all", "collective-permute", "psum")


def profile_dir() -> str:
    return os.environ.get(PROFILE_ENV, "")


def profile_windows() -> int:
    """Captured windows after warmup (bounded trace size)."""
    return max(1, int(os.environ.get("LGBM_TPU_PROFILE_WINDOWS", 2)))


def profile_window_iters() -> int:
    """Iterations per training window while a profile session is live
    (the session clamps the train loop's window so 'first N post-warmup
    iterations' is well defined even when the run would otherwise fuse
    everything into one block)."""
    return max(1, int(os.environ.get("LGBM_TPU_PROFILE_ITERS", 2)))


def cost_model_enabled() -> bool:
    """The static XLA cost model records when profiling is on, or
    standalone under ``LGBM_TPU_COST_MODEL=1`` (it costs one extra
    lower+compile per program — never free, so never default-on)."""
    return bool(profile_dir()) \
        or os.environ.get("LGBM_TPU_COST_MODEL", "") == "1"


# ---------------------------------------------------------------------------
# capture state (one live capture per process — jax.profiler is global)
# ---------------------------------------------------------------------------
_active_dir: Optional[str] = None
_program_costs: Dict[str, Dict[str, Any]] = {}


def _annotate(name: str):
    import jax
    return jax.profiler.TraceAnnotation(name)


class _NoopCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_CTX = _NoopCtx()


def step(name: str, num: int):
    """A ``jax.profiler.StepTraceAnnotation`` while a capture is live,
    else a shared no-op — per-batch/iteration step markers for the
    serving harness and the capture CLI."""
    if _active_dir is None:
        return _NOOP_CTX
    import jax
    return jax.profiler.StepTraceAnnotation(name, step_num=num)


def _start_capture(out_dir: str) -> bool:
    """Start the global jax profiler into ``out_dir``; install the span
    annotator.  Returns False (and logs once) when the profiler cannot
    start — the caller degrades to no capture."""
    global _active_dir
    if _active_dir is not None:
        return False                    # one capture at a time
    # a capture is only useful with live spans to annotate: enabling
    # telemetry here (in-memory summary only — no trace file unless one
    # was separately requested) makes LGBM_TPU_PROFILE self-sufficient
    telemetry.enable()
    try:
        import jax
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
    # tpulint: disable=TPL006 -- capture is best-effort; failure is logged
    except Exception as exc:            # noqa: BLE001 - degrade, never die
        from ..utils.log import log_once
        log_once("profiler_start_failed",
                 f"device-time capture failed to start ({exc}); "
                 f"continuing unprofiled", level="warning")
        return False
    _active_dir = out_dir
    telemetry.set_annotator(_annotate)
    return True


def _stop_capture(sync=None) -> Optional[str]:
    """Stop the live capture (after ``sync()`` blocks on in-flight
    work, so the captured windows' device ops land inside the trace).
    Returns the capture dir, or None when nothing was live."""
    global _active_dir
    out, _active_dir = _active_dir, None
    telemetry.set_annotator(None)
    if out is None:
        return None
    if sync is not None:
        try:
            sync()
        # tpulint: disable=TPL006 -- sync is best-effort capture hygiene
        except Exception:               # noqa: BLE001 - trace still stops
            pass
    try:
        import jax
        jax.profiler.stop_trace()
    # tpulint: disable=TPL006 -- capture is best-effort; failure is logged
    except Exception as exc:            # noqa: BLE001 - degrade, never die
        from ..utils.log import log_warning
        log_warning(f"device-time capture failed to stop cleanly: {exc}")
        return None
    return out


def reset() -> None:
    """Forget capture/cost state (tests); stops a leaked live capture."""
    global _program_costs
    if _active_dir is not None:
        _stop_capture()
    _program_costs = {}


class capture:
    """``with capture(out_dir, sync=...) as c:`` — plain bounded
    capture for tools (``tools/profile_capture.py``): annotated spans
    inside the block land in the trace; on exit the capture is parsed
    and ``c.report`` holds the attribution dict (also written to the
    telemetry summary section)."""

    def __init__(self, out_dir: str, sync=None, section: str
                 = ATTRIBUTION_SECTION):
        self.out_dir = out_dir
        self.sync = sync
        self.section = section
        self.report: Optional[Dict[str, Any]] = None

    def __enter__(self) -> "capture":
        self._started = _start_capture(self.out_dir)
        return self

    def __exit__(self, *exc) -> bool:
        if self._started:
            path = _stop_capture(self.sync)
            self.report = finalize_report(path or self.out_dir)
            telemetry.set_section(self.section, self.report)
        return False


# ---------------------------------------------------------------------------
# windowed training session
# ---------------------------------------------------------------------------
class _ProfileSession:
    """Windowed capture driven by the training loop: window 0 is
    warmup (block compiles + first-touch allocations), then
    ``profile_windows()`` captured windows, then stop + parse + attach
    the section — mid-train, so a long run carries a bounded trace."""

    def __init__(self, kind: str, out_dir: str, sync=None):
        self.kind = kind
        self.out_dir = out_dir
        self.sync = sync
        self.state = "warmup"           # -> capturing -> done
        self.windows_left = profile_windows()
        self.chunk = profile_window_iters()
        self.report: Optional[Dict[str, Any]] = None
        self._t0 = time.perf_counter()

    def clamp_window(self, requested: int) -> int:
        """Bound the train loop's next window while the session is
        live, so warmup/capture boundaries fall every ``chunk``
        iterations (a fused 500-iteration block would otherwise be one
        giant window and the capture would never start)."""
        if self.state == "done":
            return requested
        return max(1, min(requested, self.chunk))

    def window(self, it: int = -1) -> bool:
        """One training window finished.  Advances warmup -> capture
        -> done.  Returns True when this boundary did heavy profiler
        work (trace start / stop+parse) — the caller excludes that
        from its own host-latency accounting: observer overhead is not
        training host gap."""
        if self.state == "warmup":
            self.state = "capturing"
            if not _start_capture(self.out_dir):
                self.state = "done"
            return True
        if self.state == "capturing":
            self.windows_left -= 1
            if self.windows_left <= 0:
                self._finish(it)
                return True
        return False

    def _finish(self, it: int = -1) -> None:
        if self.state != "capturing":
            return
        self.state = "done"
        path = _stop_capture(self.sync)
        self.report = finalize_report(path or self.out_dir)
        self.report["kind"] = self.kind
        self.report["windows"] = profile_windows()
        self.report["window_iters"] = self.chunk
        if it >= 0:
            self.report["captured_through_iteration"] = int(it)
        telemetry.set_section(ATTRIBUTION_SECTION, self.report)

    def close(self) -> None:
        """End-of-train: stop a still-running capture (short runs end
        before the window budget is spent)."""
        self._finish()


class maybe_profile:
    """``with maybe_profile("gbdt", sync=...) as prof:`` — a live
    :class:`_ProfileSession` when ``LGBM_TPU_PROFILE`` names a capture
    directory, else None at ~zero cost (one env read per train)."""

    def __init__(self, kind: str, sync=None):
        self.kind = kind
        self.sync = sync
        self.session: Optional[_ProfileSession] = None

    def __enter__(self) -> Optional[_ProfileSession]:
        out = profile_dir()
        if out:
            self.session = _ProfileSession(self.kind, out, sync=self.sync)
        return self.session

    def __exit__(self, *exc) -> bool:
        if self.session is not None:
            self.session.close()
        return False


# ---------------------------------------------------------------------------
# static cost model
# ---------------------------------------------------------------------------
def _normalize_cost(ca) -> Dict[str, Optional[float]]:
    """``cost_analysis()`` returns a dict on new jax, ``[dict]`` on
    older; keys are xla's space-separated names."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {"flops": None, "bytes_accessed": None}
    flops = ca.get("flops")
    by = ca.get("bytes accessed", ca.get("bytes_accessed"))
    return {"flops": float(flops) if flops is not None else None,
            "bytes_accessed": float(by) if by is not None else None}


def record_program_cost(name: str, fn, args: Tuple = (),
                        module_hint: Optional[str] = None,
                        **attrs) -> Optional[Dict[str, Any]]:
    """Record FLOPs / bytes-accessed for one jitted program under
    ``name``.  ``fn`` is either an AOT ``Compiled`` (cost_analysis is
    free) or a ``jax.jit`` wrapper (one extra lower+compile — which is
    why this is gated on :func:`cost_model_enabled`).  The entry lands
    in the telemetry summary's ``xla_cost`` section immediately, so a
    killed run still carries every program compiled so far."""
    if not cost_model_enabled():
        return None
    try:
        if hasattr(fn, "cost_analysis"):
            ca = fn.cost_analysis()
        else:
            ca = fn.lower(*args).compile().cost_analysis()
    # tpulint: disable=TPL006 -- cost model is best-effort; logged once
    except Exception as exc:            # noqa: BLE001 - degrade, never die
        from ..utils.log import log_once
        log_once(f"cost_analysis_failed:{name}",
                 f"cost_analysis for {name} failed ({exc})",
                 level="warning")
        return None
    entry = _normalize_cost(ca)
    if module_hint is None:
        base = getattr(fn, "__name__", None)
        module_hint = f"jit_{base}" if base else None
    entry["hlo_module"] = module_hint
    entry.update(attrs)
    _program_costs[name] = entry
    telemetry.set_section("xla_cost", dict(_program_costs))
    return entry


def program_costs() -> Dict[str, Dict[str, Any]]:
    return dict(_program_costs)


# ---------------------------------------------------------------------------
# trace parsing (chrome trace JSON, stdlib only)
# ---------------------------------------------------------------------------
def find_trace_file(path: str) -> Optional[str]:
    """Resolve a capture root / session dir / trace file to the newest
    ``*.trace.json(.gz)`` (the chrome-trace sidecar the profiler
    writes; ``perfetto_trace.json.gz`` has the same events — either
    parses)."""
    if os.path.isfile(path):
        return path
    pats = (os.path.join(path, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(path, "*.trace.json.gz"),
            os.path.join(path, "plugins", "profile", "*",
                         "perfetto_trace.json.gz"))
    for pat in pats:
        hits = sorted(glob.glob(pat))
        if hits:
            return hits[-1]             # newest session sorts last
    return None


def parse_capture(path: str) -> Dict[str, Any]:
    """Parse one capture into ``{"annotations": [...], "ops": [...],
    "path": file}``.  Annotations are OUR span/step events (dotted
    names in :data:`SPAN_PREFIXES`) on any thread; ops are XLA
    executions — events carrying ``hlo_op``/``hlo_module`` args (CPU
    executor threads), or any timed event on a ``/device:*`` process
    (TPU device lines).  Times are seconds relative to the trace."""
    f = find_trace_file(path)
    if f is None:
        raise FileNotFoundError(f"no trace.json(.gz) under {path!r}")
    opener = gzip.open if f.endswith(".gz") else open
    with opener(f, "rt", encoding="utf-8") as fh:
        data = json.load(fh)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    procs: Dict[Any, str] = {}
    annos: List[Dict[str, Any]] = []
    ops: List[Dict[str, Any]] = []
    for ev in events:
        if not ev:
            continue
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                procs[ev.get("pid")] = ev.get("args", {}).get("name", "")
            continue
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        args = ev.get("args") or {}
        ts = float(ev.get("ts", 0.0)) / 1e6
        dur = float(ev.get("dur", 0.0)) / 1e6
        if "hlo_op" in args or "hlo_module" in args:
            ops.append({"name": name, "ts": ts, "dur": dur,
                        "module": args.get("hlo_module", "")})
        elif str(procs.get(ev.get("pid"), "")).startswith("/device:"):
            ops.append({"name": name, "ts": ts, "dur": dur,
                        "module": args.get("hlo_module", "")})
        elif name.startswith(SPAN_PREFIXES):
            annos.append({"name": name, "ts": ts, "dur": dur})
    annos.sort(key=lambda a: a["ts"])
    ops.sort(key=lambda o: o["ts"])
    return {"annotations": annos, "ops": ops, "path": f}


def _interval_union(iv: List[Tuple[float, float]]) -> float:
    total, end = 0.0, -1.0
    for s, e in sorted(iv):
        if s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def _is_collective(op_name: str) -> bool:
    n = op_name.lower()
    return any(n.startswith(c) or f"/{c}" in n for c in COLLECTIVE_OPS)


def attribute(parsed: Dict[str, Any]) -> Dict[str, Any]:
    """Reduce a parsed capture to the per-span device-time table.

    Each op joins the DEEPEST annotation covering its midpoint
    (deepest = latest-starting cover: our spans nest); ops that start
    after their span closed (async dispatch) fall back to the latest
    annotation STARTED at-or-before the op's start — in a dispatch
    loop that is exactly the span that enqueued them."""
    annos, ops = parsed["annotations"], parsed["ops"]
    spans: Dict[str, Dict[str, Any]] = {}
    programs: Dict[str, float] = {}
    device_total = attributed = collective_s = 0.0
    for op in ops:
        device_total += op["dur"]
        mod = op["module"] or "<unnamed>"
        programs[mod] = programs.get(mod, 0.0) + op["dur"]
        if _is_collective(op["name"]):
            collective_s += op["dur"]
        mid = op["ts"] + op["dur"] / 2.0
        owner = None
        for a in annos:                 # sorted by ts: last hit wins
            if a["ts"] > mid:
                break
            if a["ts"] + a["dur"] >= mid:
                owner = a
        if owner is None:
            for a in annos:
                if a["ts"] > op["ts"]:
                    break
                owner = a               # latest started at-or-before
        if owner is None:
            continue
        attributed += op["dur"]
        agg = spans.setdefault(owner["name"],
                               {"device_s": 0.0, "ops": 0})
        agg["device_s"] += op["dur"]
        agg["ops"] += 1

    # host gap: device idle inside the training windows (dispatch
    # return -> next dispatch's ops, the ROADMAP item-1 latency)
    windows = [(a["ts"], a["ts"] + a["dur"]) for a in annos
               if a["name"] in WINDOW_SPANS]
    window_wall = sum(e - s for s, e in windows)
    busy_in_windows = _interval_union(
        [(max(o["ts"], s), min(o["ts"] + o["dur"], e))
         for o in ops for s, e in windows
         if o["ts"] < e and o["ts"] + o["dur"] > s])
    # capture-wide accounting: wall from first annotation/op to the
    # last op end, minus total device busy
    points = ([a["ts"] for a in annos] + [o["ts"] for o in ops])
    ends = ([a["ts"] + a["dur"] for a in annos]
            + [o["ts"] + o["dur"] for o in ops])
    capture_wall = (max(ends) - min(points)) if points else 0.0
    device_busy = _interval_union([(o["ts"], o["ts"] + o["dur"])
                                   for o in ops])
    top = sorted(programs.items(), key=lambda kv: -kv[1])[:3]
    return {
        "source": parsed.get("path"),
        "device_time_s": round(device_total, 6),
        "attributed_s": round(attributed, 6),
        "coverage": round(attributed / device_total, 4)
        if device_total else None,
        "collective_s": round(collective_s, 6),
        "collective_frac": round(collective_s / device_total, 4)
        if device_total else None,
        "capture_wall_s": round(capture_wall, 6),
        "device_busy_s": round(device_busy, 6),
        "host_gap_s": round(max(0.0, window_wall - busy_in_windows), 6),
        "window_wall_s": round(window_wall, 6),
        "spans": {k: {"device_s": round(v["device_s"], 6),
                      "ops": v["ops"]}
                  for k, v in sorted(spans.items(),
                                     key=lambda kv: -kv[1]["device_s"])},
        "programs": {k: round(v, 6) for k, v in
                     sorted(programs.items(), key=lambda kv: -kv[1])},
        "top_programs": [[k, round(v, 6)] for k, v in top],
        "annotations": len(annos),
        "ops": len(ops),
    }


def finalize_report(path: str) -> Dict[str, Any]:
    """Parse + attribute a capture and join the recorded program costs
    into roofline columns.  Never raises: failures land as an
    ``error`` field so the summary section always exists."""
    try:
        report = attribute(parse_capture(path))
    # tpulint: disable=TPL006 -- attribution is best-effort; error recorded
    except Exception as exc:            # noqa: BLE001 - degrade, never die
        return {"error": f"{type(exc).__name__}: {exc}", "source": path}
    from .chip_specs import peaks_for, roofline
    peaks = peaks_for()
    rows = []
    measured = report["programs"]
    for name, cost in _program_costs.items():
        hint = cost.get("hlo_module") or ""
        dev_s = None
        for mod, s in measured.items():
            if hint and (mod == hint or mod.startswith(hint)):
                dev_s = s
                break
        row = {"program": name, "hlo_module": hint or None,
               "device_s": dev_s}
        row.update(roofline(cost.get("flops"), cost.get("bytes_accessed"),
                            dev_s, peaks))
        rows.append(row)
    report["cost_model"] = {
        "device_kind": peaks.get("kind"),
        "peaks": {k: peaks.get(k) for k in
                  ("flops_per_s", "hbm_bytes_per_s", "source",
                   "sentinel") if peaks.get(k) is not None},
        "programs": rows,
    }
    return report
