"""Runtime trace contract: zero recompiles after the warmup block.

The static linter (``tools/tpulint``) catches hazard PATTERNS; this
module checks the actual property they threaten — that the steady-state
training loop never re-enters XLA.  A recompile mid-run is either a
shape instability (a Python scalar that should be static, a
data-dependent pad) or a cache-key bug, and on a remote TPU it costs
10-30 s per occurrence while looking exactly like a slow iteration.

Mechanism: ``jax_log_compiles`` makes jax's lowering path log one
``Compiling <name> ...`` record per trace-cache miss
(``jax._src.interpreters.pxla``); :class:`CompileTracker` attaches a
logging handler, splits the stream at :meth:`mark_steady` (the caller
flags the end of warmup — ``GBDT._train`` does so after its first
window), and reports warmup vs steady counts.  Background AOT compiles
(``GBDT._spawn_block_compile`` upgrading a borrowed block length) are
deliberate steady-state compiles on a worker thread — the tracker
records the originating thread and excludes non-tracked threads from
the contract by default.

Wiring: ``LGBM_TPU_TRACE_CONTRACT=1`` makes ``GBDT.train`` run under a
tracker and feed a ``trace_contract`` section into the telemetry
summary (``obs.summary()["trace_contract"]``); a violation also emits a
``contract:recompile_after_warmup`` event and a WARNING log.
``tests/test_tpulint.py`` asserts the tier-1 training path reports
zero steady compiles.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional

from ..utils.log import log_warning
from . import telemetry

_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",     # "Compiling <name> with global shapes"
    "jax._src.dispatch",              # older jax variants log here
)
_COMPILE_PREFIX = "Compiling "

ENV_FLAG = "LGBM_TPU_TRACE_CONTRACT"


def contract_enabled() -> bool:
    return bool(os.environ.get(ENV_FLAG, ""))


class _Handler(logging.Handler):
    def __init__(self, tracker: "CompileTracker"):
        super().__init__(level=logging.DEBUG)
        self._tracker = tracker

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        # tpulint: disable=TPL006 -- logging.Handler.emit must not raise
        except Exception:               # noqa: BLE001 - malformed record
            return
        if msg.startswith(_COMPILE_PREFIX):
            self._tracker._record(msg, record.thread)


class CompileTracker:
    """Counts XLA trace-cache misses, split into warmup vs steady at
    :meth:`mark_steady`.  Context manager; re-entrant use is not
    supported (one tracker per training run)."""

    def __init__(self, track_threads: bool = True):
        self._handler = _Handler(self)
        self._events: List[Dict[str, Any]] = []
        self._steady_idx: Optional[int] = None
        from .lock_contract import named_lock
        self._lock = named_lock("trace_contract")
        self._track_threads = track_threads
        self._main_thread: Optional[int] = None
        self._prev_flag: Optional[bool] = None
        self._prev_levels: Dict[str, int] = {}
        self._prev_propagate: Dict[str, bool] = {}

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "CompileTracker":
        import jax
        self._main_thread = threading.get_ident()
        self._prev_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        for name in _COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            self._prev_levels[name] = lg.level
            self._prev_propagate[name] = lg.propagate
            if lg.level > logging.WARNING or lg.level == logging.NOTSET:
                lg.setLevel(logging.WARNING)
            # jax's stderr handler sits on the parent "jax" logger;
            # stop propagation so enabling jax_log_compiles for the
            # tracker doesn't spam the user's console
            lg.propagate = False
            lg.addHandler(self._handler)
        return self

    def __exit__(self, *exc) -> bool:
        import jax
        for name in _COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            lg.removeHandler(self._handler)
            lg.setLevel(self._prev_levels.get(name, logging.NOTSET))
            lg.propagate = self._prev_propagate.get(name, True)
        if self._prev_flag is not None:
            jax.config.update("jax_log_compiles", self._prev_flag)
        return False

    # -- recording ------------------------------------------------------
    def _record(self, msg: str, thread: Optional[int]) -> None:
        # "Compiling <name> with global shapes and types [...]" -> <name>
        name = msg[len(_COMPILE_PREFIX):].split(" ", 1)[0]
        with self._lock:
            self._events.append({"name": name, "thread": thread})

    def mark_steady(self) -> None:
        """Flag the end of warmup; idempotent — the FIRST call wins (a
        per-window caller can invoke it unconditionally)."""
        with self._lock:
            if self._steady_idx is None:
                self._steady_idx = len(self._events)

    # -- reporting ------------------------------------------------------
    def _split(self):
        with self._lock:
            cut = (self._steady_idx if self._steady_idx is not None
                   else len(self._events))
            warm, steady = self._events[:cut], self._events[cut:]
        if self._track_threads:
            background = [e for e in steady
                          if e["thread"] != self._main_thread]
            steady = [e for e in steady
                      if e["thread"] == self._main_thread]
        else:
            background = []
        return warm, steady, background

    def report(self) -> Dict[str, Any]:
        warm, steady, background = self._split()
        return {
            "compiles_warmup": len(warm),
            "compiles_steady": len(steady),
            "compiles_background": len(background),
            "steady_ok": not steady,
            "steady_names": sorted({e["name"] for e in steady}),
        }


class _NoTracker:
    """Shared no-op so call sites stay unconditional."""

    def mark_steady(self) -> None:
        pass


_NO_TRACKER = _NoTracker()


class maybe_track:
    """``with maybe_track() as t:`` — a live :class:`CompileTracker`
    when ``LGBM_TPU_TRACE_CONTRACT`` is set, else a no-op.  On exit of
    a live tracker the report lands in the telemetry summary's
    ``trace_contract`` section; a violation logs and emits an event."""

    def __init__(self) -> None:
        self._tracker: Optional[CompileTracker] = None

    def __enter__(self):
        if not contract_enabled():
            return _NO_TRACKER
        self._tracker = CompileTracker().__enter__()
        return self._tracker

    def __exit__(self, *exc) -> bool:
        if self._tracker is None:
            return False
        self._tracker.__exit__(*exc)
        rep = self._tracker.report()
        telemetry.set_section("trace_contract", rep)
        if not rep["steady_ok"]:
            telemetry.event("contract", "recompile_after_warmup",
                            count=rep["compiles_steady"],
                            names=rep["steady_names"])
            log_warning(
                f"trace contract violated: {rep['compiles_steady']} "
                f"recompile(s) after warmup "
                f"({', '.join(rep['steady_names'][:5])}) — a shape/"
                f"static-arg instability is re-entering XLA every run")
        return False
