"""Runtime reproducibility contract (``LGBM_TPU_DETERMINISM=1``).

The static half of the fourth wall is ``tools/detcheck``; this is the
runtime half, proving at run time what the analyzer argues statically:
training is a pure function of (data, config, seeds).

Three instruments, all riding existing seams (zero extra collectives,
near-zero cost when disabled):

* **Canonical digests** — :func:`model_digest` hashes every host tree's
  canonicalized structural fields plus the f32 score state (sha256).
  Under the contract, ``GBDT._train`` samples a digest at every window
  boundary into a ``(iteration, digest)`` ledger; two runs from
  identical seeds must produce identical ledgers, and the FIRST
  diverging window localizes when determinism broke (the train-twice
  harness ``tools/replay_check.py`` automates exactly that
  comparison).
* **Cross-rank window check** — on multi-process runs the latest
  digest rides the SAME early-stopping metric allgather the flight
  recorder uses; a rank whose model diverged is named, with the
  window, via a ``det:digest_mismatch`` event (models are replicated
  state: any mismatch is a determinism bug, full stop).
* **RNG ledger** — every keyed host-side RNG derivation site calls
  :func:`rng_site` with its ``(site, key-path)``; the counters land in
  the ``determinism`` summary section, so a replayed run can assert
  that not just the outputs but the *derivation traffic* matched.

The ``det.rng_drift`` fault point (``utils/faults.py``) injects a
mis-keyed derivation (DART consumes the next iteration's draws) to
prove the ledger trips and names the first diverging window — the same
proof-by-injection pattern as ``spmd.skip_record`` and ``mem.leak``.

Digest canonicalization (stable across paths and formats, documented
here as the contract): per tree, in model order —
``num_leaves``, ``num_cat``, and for the ``num_leaves - 1`` internal
nodes ``split_feature``, ``threshold`` (f64 bytes), ``decision_type``,
``left_child``, ``right_child``; the ``num_leaves`` ``leaf_value`` f64
bytes; the categorical ``cat_boundaries`` / ``cat_threshold`` bitset
words.  Score state is hashed as f32 bytes in C order.  Deliberately
EXCLUDED: gain/count diagnostics (reporting, not model) and
``threshold_bin`` (a binning-dependent cache of ``threshold`` that the
text format does not persist — the f64 threshold is what routes).
"""
from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import set_section
from .telemetry import event as obs_event

__all__ = ["enabled", "reset", "rng_site", "model_digest", "tree_digest",
           "window_digest", "fingerprint", "window_check", "section"]


def enabled() -> bool:
    return os.environ.get("LGBM_TPU_DETERMINISM", "0") == "1"


# ledger state (process-wide, reset per run by GBDT.train / tests)
_SITES: Dict[str, Dict] = {}
_DIGESTS: List[Tuple[int, str]] = []


def reset() -> None:
    _SITES.clear()
    _DIGESTS.clear()


def rng_site(site: str, key_path: str, n: int = 1) -> None:
    """Record ``n`` derivations at a keyed RNG ``site`` whose key is
    derived along ``key_path`` (e.g. ``"drop_seed/iteration"``).  No-op
    unless the contract is armed — one dict lookup when off."""
    if not enabled():
        return
    entry = _SITES.setdefault(site, {"key_path": key_path, "count": 0})
    entry["count"] += n


def tree_digest(h, t) -> None:
    """Feed one host tree's canonical fields into hasher ``h`` (the
    field list is the module-docstring contract)."""
    n = int(t.num_leaves)
    m = max(0, n - 1)
    h.update(np.int64([n, int(t.num_cat)]).tobytes())
    h.update(np.ascontiguousarray(t.split_feature[:m], np.int32).tobytes())
    h.update(np.ascontiguousarray(t.threshold[:m], np.float64).tobytes())
    h.update(np.ascontiguousarray(t.decision_type[:m], np.int8).tobytes())
    h.update(np.ascontiguousarray(t.left_child[:m], np.int32).tobytes())
    h.update(np.ascontiguousarray(t.right_child[:m], np.int32).tobytes())
    h.update(np.ascontiguousarray(t.leaf_value[:n], np.float64).tobytes())
    if t.num_cat:
        h.update(np.asarray(t.cat_boundaries, np.int64).tobytes())
        h.update(np.asarray(t.cat_threshold, np.uint32).tobytes())


def model_digest(gbdt, include_scores: bool = True) -> str:
    """sha256 hex digest of the booster's canonical model state (every
    host tree, pending device trees flushed first) plus — when
    ``include_scores`` and the score state is host-addressable — the
    running f32 train-score state.  Identical seeds + data + config
    must yield identical digests at every window; that IS the
    reproducibility contract."""
    h = hashlib.sha256()
    for t in gbdt.models:            # property: flushes pending blocks
        tree_digest(h, t)
    if include_scores and getattr(gbdt, "_pr", None) is None \
            and getattr(gbdt, "scores", None) is not None:
        h.update(np.ascontiguousarray(
            np.asarray(gbdt.scores), np.float32).tobytes())
    return h.hexdigest()


def window_digest(gbdt, it: int) -> str:
    """Sample the digest at a window boundary into the run ledger and
    refresh the ``determinism`` summary section."""
    d = model_digest(gbdt, include_scores=getattr(gbdt, "_pr", None) is None)
    _DIGESTS.append((int(it), d))
    set_section("determinism", section())
    return d


def fingerprint() -> str:
    """Latest sampled digest (rides the multi-process ES metric
    allgather — zero extra collectives)."""
    return _DIGESTS[-1][1] if _DIGESTS else ""


def window_check(fingerprints: List[str], it: int,
                 rank: Optional[int] = None) -> bool:
    """Cross-rank digest comparison at a window boundary: the model is
    replicated state, so ANY mismatch is a determinism bug.  Returns
    True when consistent; on mismatch emits a ``det:digest_mismatch``
    event naming the window and the first diverging rank."""
    if not fingerprints or all(f == fingerprints[0] for f in fingerprints):
        return True
    bad = next(i for i, f in enumerate(fingerprints)
               if f != fingerprints[0])
    obs_event("det", "digest_mismatch", window_it=int(it),
              first_diverging_rank=bad,
              digests=[f[:12] for f in fingerprints])
    from ..utils.log import log_warning
    log_warning(f"determinism contract violation at window it={it}: "
                f"rank {bad} model digest {fingerprints[bad][:12]} != "
                f"rank 0 {fingerprints[0][:12]}")
    return False


def section() -> Dict:
    """The ``determinism`` summary section: RNG-ledger counters plus the
    windowed digest ledger."""
    return {"sites": {k: dict(v) for k, v in sorted(_SITES.items())},
            "digests": [[it, d] for it, d in _DIGESTS]}


def first_divergence(a: List, b: List) -> Optional[Tuple[int, str, str]]:
    """Compare two digest ledgers ``[[it, digest], ...]``; None when
    identical, else ``(window_it, digest_a, digest_b)`` of the FIRST
    diverging window (a missing window counts as divergence).  This is
    the replay harness's core comparison (tools/replay_check.py)."""
    for (ia, da), (ib, db) in zip(a, b):
        if ia != ib or da != db:
            return (int(ia), str(da), str(db))
    if len(a) != len(b):
        n = min(len(a), len(b))
        longer = a if len(a) > len(b) else b
        return (int(longer[n][0]), "<absent>" if len(a) <= n else a[n][1],
                "<absent>" if len(b) <= n else b[n][1])
    return None
