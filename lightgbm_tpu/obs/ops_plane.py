"""Live ops plane: ``/metrics`` + ``/healthz`` + ``/drain`` over HTTP.

The telemetry subsystem (``obs/telemetry.py``) accumulates a run
summary you read AFTER the run; a system serving heavy traffic (or a
multi-hour TPU train) needs the same numbers scrapeable WHILE it runs.
This module is that surface, stdlib-only:

* a **metrics registry** fed by the existing telemetry hooks through
  one sink seam (``telemetry.set_sink``): counters and gauges mirror
  the run summary live, every span close feeds a **bounded
  rolling-window quantile sketch** of its duration (last
  ``LGBM_TPU_OPS_SKETCH`` samples, default 4096 — constant memory no
  matter how long the process serves).  When the plane is not mounted
  the sink is ``None`` and every telemetry call costs exactly what it
  did before (one attribute read on the already-enabled path; the
  disabled path is untouched);
* an **HTTP daemon thread** (``http.server.ThreadingHTTPServer`` on
  ``127.0.0.1:$LGBM_TPU_OPS_PORT``; ``0`` picks an ephemeral port)
  serving

  - ``GET /metrics`` — Prometheus text format v0.0.4: counters as
    ``lgbm_tpu_<name>_total``, gauges as ``lgbm_tpu_<name>``, events
    as ``lgbm_tpu_events_total{family=..,name=..}``, span sketches as
    ``lgbm_tpu_span_seconds{span=..,quantile=..}`` summaries, plus
    ``lgbm_tpu_health_state`` one-hot;
  - ``GET /healthz`` — the health state machine
    (``obs/health.py``: warming -> ready -> draining, sticky
    stalled/degraded) as JSON; HTTP 200 while live, 503 once stalled
    or degraded, so a load balancer can eject the replica;
  - ``POST|GET /drain`` — runs the registered drain hooks (the
    serving harness registers one: stop accepting, flush the queue,
    report) and returns their reports.

Mounted by both ``GBDT.train`` and ``serve.PredictionServer`` via
:func:`mount` (idempotent; first mount starts the thread, later mounts
attach as owners).  Mounting never touches the device: zero extra
dispatches, zero recompiles — the span-count and trace-contract tests
pin both.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .lock_contract import named_lock

__all__ = [
    "RollingQuantiles", "MetricsRegistry", "OpsPlane", "enabled",
    "mount", "plane", "shutdown", "sketch_cap",
]


def enabled() -> bool:
    return os.environ.get("LGBM_TPU_OPS_PORT", "") != ""


def sketch_cap() -> int:
    return max(16, int(os.environ.get("LGBM_TPU_OPS_SKETCH", "4096")))


class RollingQuantiles:
    """Bounded rolling-window quantile sketch: a fixed-size ring of the
    last ``cap`` samples.  ``count`` keeps the all-time total; the
    quantiles describe the window — exactly what a live latency
    readout wants (an all-time list both grows without bound and
    freezes the percentiles on ancient history)."""

    __slots__ = ("_buf", "_cap", "count")

    def __init__(self, cap: Optional[int] = None):
        self._cap = int(cap) if cap else sketch_cap()
        self._buf: List[float] = []
        self.count = 0

    def observe(self, v: float) -> None:
        if len(self._buf) < self._cap:
            self._buf.append(float(v))
        else:
            self._buf[self.count % self._cap] = float(v)
        self.count += 1

    def window(self) -> int:
        return len(self._buf)

    def quantiles(self, qs=(50.0, 99.0, 99.9)) -> Dict[float, float]:
        if not self._buf:
            return {}
        a = np.asarray(self._buf)
        return {float(q): float(np.percentile(a, q)) for q in qs}

    def stats_ms(self) -> Dict[str, Any]:
        """The serving-stats shape: count + p50/p99/p999 milliseconds."""
        q = self.quantiles()
        return {"count": self.count,
                "p50": round(q.get(50.0, 0.0) * 1e3, 3),
                "p99": round(q.get(99.0, 0.0) * 1e3, 3),
                "p999": round(q.get(99.9, 0.0) * 1e3, 3)}


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return repr(float(v)) if isinstance(v, float) else str(v)
    return "NaN"


class MetricsRegistry:
    """The telemetry sink (see ``telemetry.set_sink``): mirrors
    counters/gauges/events live and keeps one rolling duration sketch
    per span name.  Its lock is leaf-level — taken inside the telemetry
    lock on the write path, alone on the render path."""

    def __init__(self):
        self._lock = named_lock("metrics_registry")
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, Any] = {}
        self.events: Dict[str, int] = {}
        self.spans: Dict[str, RollingQuantiles] = {}
        # per-lock wait sketches + contended counts, fed by the runtime
        # lock contract (obs/lock_contract.py) when it is armed
        self.lock_waits: Dict[str, RollingQuantiles] = {}
        self.lock_contended: Dict[str, int] = {}

    # -- sink interface (called from telemetry, under its lock) ---------
    def counter(self, name: str, add: float, value: float) -> None:
        with self._lock:
            self.counters[name] = value

    def gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self.gauges[name] = value

    def event(self, key: str, count: int) -> None:
        with self._lock:
            self.events[key] = count

    def span(self, name: str, dur_s: float) -> None:
        with self._lock:
            sk = self.spans.get(name)
            if sk is None:
                sk = self.spans[name] = RollingQuantiles()
            sk.observe(dur_s)

    def lock_wait(self, name: str, wait_s: float,
                  contended: bool = False) -> None:
        with self._lock:
            sk = self.lock_waits.get(name)
            if sk is None:
                sk = self.lock_waits[name] = RollingQuantiles()
            sk.observe(wait_s)
            if contended:
                self.lock_contended[name] = \
                    self.lock_contended.get(name, 0) + 1

    # -- render ---------------------------------------------------------
    def render_prometheus(self) -> str:
        from . import health
        out: List[str] = []
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            events = dict(self.events)
            sketches = {k: (v.count, v.quantiles())
                        for k, v in self.spans.items()}
            lock_sketches = {k: (v.count, v.quantiles())
                             for k, v in self.lock_waits.items()}
            lock_contended = dict(self.lock_contended)
        for name in sorted(counters):
            mn = f"lgbm_tpu_{_sanitize(name)}_total"
            out.append(f"# TYPE {mn} counter")
            out.append(f"{mn} {_fmt(counters[name])}")
        for name in sorted(gauges):
            v = gauges[name]
            if not isinstance(v, (int, float, bool)):
                continue            # non-numeric gauges stay JSON-only
            mn = f"lgbm_tpu_{_sanitize(name)}"
            out.append(f"# TYPE {mn} gauge")
            out.append(f"{mn} {_fmt(v)}")
        if events:
            out.append("# TYPE lgbm_tpu_events_total counter")
            for key in sorted(events):
                family, _, name = key.partition(":")
                out.append(
                    f'lgbm_tpu_events_total{{family="{family}",'
                    f'name="{name}"}} {events[key]}')
        if sketches:
            out.append("# TYPE lgbm_tpu_span_seconds summary")
            for name in sorted(sketches):
                count, q = sketches[name]
                sn = _sanitize(name)
                for qv, val in sorted(q.items()):
                    out.append(
                        f'lgbm_tpu_span_seconds{{span="{sn}",'
                        f'quantile="{qv / 100.0:g}"}} {_fmt(val)}')
                out.append(
                    f'lgbm_tpu_span_seconds_count{{span="{sn}"}} {count}')
        if lock_sketches:
            out.append("# TYPE lgbm_tpu_lock_wait_seconds summary")
            for name in sorted(lock_sketches):
                count, q = lock_sketches[name]
                ln = _sanitize(name)
                for qv, val in sorted(q.items()):
                    out.append(
                        f'lgbm_tpu_lock_wait_seconds{{lock="{ln}",'
                        f'quantile="{qv / 100.0:g}"}} {_fmt(val)}')
                out.append(
                    f'lgbm_tpu_lock_wait_seconds_count{{lock="{ln}"}} '
                    f'{count}')
        if lock_contended:
            out.append("# TYPE lgbm_tpu_lock_contended_total counter")
            for name in sorted(lock_contended):
                out.append(
                    f'lgbm_tpu_lock_contended_total'
                    f'{{lock="{_sanitize(name)}"}} '
                    f'{lock_contended[name]}')
        st = health.state()
        out.append("# TYPE lgbm_tpu_health_state gauge")
        for s in ("warming", "ready", "draining", "degraded", "stalled"):
            out.append(f'lgbm_tpu_health_state{{state="{s}"}} '
                       f'{1 if st["state"] == s else 0}')
        return "\n".join(out) + "\n"


class _Handler:
    """Request handler factory bound to a plane instance (the stdlib
    handler is a class, so the plane rides a closure)."""

    @staticmethod
    def build(plane: "OpsPlane"):
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):   # silence per-request stderr
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _route(self) -> None:
                from . import health
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    self._send(200, plane.registry.render_prometheus(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    st = health.state()
                    st["owners"] = sorted(plane.owners)
                    st["uptime_s"] = round(time.time() - plane.t0, 3)
                    code = 503 if st["state"] in ("stalled",
                                                  "degraded") else 200
                    self._send(code, json.dumps(st), "application/json")
                elif path == "/drain":
                    self._send(200, json.dumps(plane.drain()),
                               "application/json")
                else:
                    self._send(404, json.dumps(
                        {"error": f"unknown path {path!r}",
                         "paths": ["/metrics", "/healthz", "/drain"]}),
                        "application/json")

            def do_GET(self):       # noqa: N802 - stdlib handler API
                self._route()

            def do_POST(self):      # noqa: N802 - stdlib handler API
                self._route()

        return Handler


class OpsPlane:
    """The mounted plane: registry + HTTP daemon thread + drain hooks."""

    def __init__(self, port: int):
        from http.server import ThreadingHTTPServer
        from . import telemetry
        self.t0 = time.time()
        self.owners: set = set()
        self.registry = MetricsRegistry()
        # registered from the owning (main) thread, swapped out by the
        # HTTP /drain thread: the hook list needs its own leaf lock
        self._hooks_lock = named_lock("ops_drain")
        self._drain_hooks: List[Callable[[], Any]] = []
        self._server = ThreadingHTTPServer(
            ("127.0.0.1", int(port)), _Handler.build(self))
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="lgbm-tpu-ops",
            daemon=True)
        self._thread.start()
        # the registry mirrors the run summary: the summary must be
        # accumulating for there to be anything to mirror
        telemetry.enable()
        telemetry.set_sink(self.registry)
        from ..utils.log import log_info
        log_info(f"ops plane listening on 127.0.0.1:{self.port} "
                 f"(/metrics /healthz /drain)")

    def register_drain(self, fn: Callable[[], Any]) -> None:
        with self._hooks_lock:
            self._drain_hooks.append(fn)

    def drain(self) -> Dict[str, Any]:
        """Run every registered drain hook (serving: stop accepting,
        flush the queue) and report.  Idempotent — hooks run once."""
        from . import health
        with self._hooks_lock:
            hooks, self._drain_hooks = self._drain_hooks, []
        health.mark_draining(requested=True)
        reports = []
        for fn in hooks:
            try:
                reports.append(fn())
            # tpulint: disable=TPL006 -- a failing hook must not mask
            # the other hooks' drains; the error lands in the report
            except Exception as exc:    # noqa: BLE001
                reports.append({"error": f"{type(exc).__name__}: {exc}"})
        return {"drained": bool(hooks), "reports": reports,
                "health": health.state()}

    def shutdown(self) -> None:
        from . import telemetry
        telemetry.set_sink(None)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


_lock = named_lock("ops_plane")
_plane: Optional[OpsPlane] = None


def plane() -> Optional[OpsPlane]:
    return _plane


def mount(owner: str) -> Optional[OpsPlane]:
    """Mount the ops plane for ``owner`` (``"train"`` / ``"serve"``).
    Returns None unless ``LGBM_TPU_OPS_PORT`` is set; the first mount
    starts the HTTP thread and installs the telemetry sink, later
    mounts just attach.  Never raises into the training/serving path —
    a busy port degrades to a logged warning."""
    global _plane
    if not enabled():
        return None
    with _lock:
        if _plane is None:
            from . import health
            try:
                _plane = OpsPlane(int(os.environ["LGBM_TPU_OPS_PORT"]))
            # tpulint: disable=TPL006 -- a busy port / denied bind must
            # degrade the ops plane, never the training run
            except Exception as exc:    # noqa: BLE001
                from ..utils.log import log_once
                log_once("ops_plane_bind_failed",
                         f"ops plane failed to start "
                         f"(LGBM_TPU_OPS_PORT="
                         f"{os.environ.get('LGBM_TPU_OPS_PORT')}): {exc}",
                         level="warning")
                return None
            health._set_active(True)
        _plane.owners.add(owner)
        return _plane


def shutdown() -> None:
    """Stop the HTTP thread and uninstall the sink (tests; graceful
    process teardown)."""
    global _plane
    with _lock:
        if _plane is not None:
            _plane.shutdown()
            _plane = None
