"""Structured training telemetry: spans, counters, gauges, JSONL traces.

The reference prints only eval lines; on a TPU mesh that leaves every
production question — where does wall-clock go (compile vs steady
state, histogram vs split vs routing, collective vs compute), did the
run degrade (kernel fallback, retries, injected faults), what did the
snapshot machinery cost — unanswerable.  This module is the phase-level
accounting the LightGBM paper used to justify its histogram design
(Ke et al., NeurIPS 2017, Table 2), grown into a run-queryable
subsystem:

* **Spans** — ``with span("tree_build") as s: ...; s["bytes"] = n``.
  Host-side wall-clock only, nestable (thread-local stack), NO implicit
  device syncs: a span around an async JAX dispatch times the host cost
  of that dispatch; callers that want device time must block first (the
  jit-adjacent block-loop boundaries already do).
* **Counters / gauges** — ``counter_add("retry.dispatch.retries")``,
  ``gauge_set("hbm_bytes", n)``.  Counters accumulate (floats allowed:
  backoff seconds ride the same channel), gauges overwrite.
* **Events** — one-shot occurrences (``event("fault", name)``: an
  injection fired, early stopping triggered).

Sinks:

* an in-memory **run summary** queryable as a plain dict
  (:func:`summary`): per-span count/total/max seconds, counters,
  gauges, event counts;
* a **JSONL event trace**, enabled via ``LGBM_TPU_TRACE=<path>`` or the
  ``telemetry_output`` config parameter.  Every record carries ``ts``
  (wall-clock start, epoch seconds), ``kind`` (``span`` | ``counter`` |
  ``gauge`` | ``event``), ``name``, and ``rank``; span records add
  ``dur_s`` (>= 0), ``depth``, and ``parent`` — spans are written on
  CLOSE, so a parent's record follows its children's;
* **per-rank files** in multi-host runs (the trace path gains a
  ``.rank<k>`` suffix, decided lazily at first write so enabling before
  ``jax.distributed.initialize`` still lands per-rank) with a rank-0
  **merged summary** over the existing host-collective path
  (:func:`merged_summary` + ``io/distributed.jax_process_allgather``).

Disabled telemetry is a guard-checked no-op — one module-attribute read
per call site — so instrumentation stays compiled into every path,
including per-iteration training loops and per-feature bin finding.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, IO, Optional

__all__ = [
    "enabled", "enable", "disable", "reset", "span", "counter_add",
    "gauge_set", "event", "summary", "merged_summary", "write_summary",
    "trace_path", "set_section", "set_annotator", "set_sink",
    "set_clock_offset", "set_rank",
]

def _named_rlock(name: str):
    # lazy: lock_contract imports only stdlib, so this is cycle-free
    from . import lock_contract
    return lock_contract.named_rlock(name)


_lock = _named_rlock("telemetry")
_tls = threading.local()            # per-thread span stack

# -- state (module-level flags keep the disabled path one attribute read)
_enabled = False
_trace_requested: Optional[str] = None   # path asked for; file opens lazily
_trace_file: Optional[IO[str]] = None
_trace_open_path: Optional[str] = None

_spans: Dict[str, list] = {}        # name -> [count, total_s, max_s]
_counters: Dict[str, float] = {}
_gauges: Dict[str, Any] = {}
_events: Dict[str, int] = {}
# named summary sections (e.g. "trace_contract"): written by subsystems
# that produce one structured result per run rather than a stream;
# stored even while telemetry is disabled — a contract check the user
# explicitly enabled must not vanish because tracing is off
_sections: Dict[str, Any] = {}
# span annotator hook (obs/profiler.py): while a device-time capture
# is live, every span ALSO enters a jax.profiler.TraceAnnotation of
# the same name, so XLA ops attribute to the span tree.  None (the
# default) costs one module-attribute read per span
_annotator = None
# live-metrics sink (obs/ops_plane.py MetricsRegistry): while the ops
# plane is mounted, every counter/gauge/event update and span close is
# mirrored into the scrapeable registry.  None (the default) costs one
# module-attribute read on the already-enabled path; the disabled path
# never reaches it — the PR 2 no-op envelope is untouched
_sink = None
# coordinator-clock offset of this rank (obs/fleet.py): when set, every
# trace record carries it as `clk_off_s` so tools/fleet_report.py can
# merge per-rank traces onto one clock (corrected_ts = ts + clk_off_s).
# None (the default) adds nothing — single-host traces are unchanged
_clk_off: Optional[float] = None
# (rank, world) override for fleets that are NOT a jax multi-process
# world (elastic workers: each is a world-1 jax process, but the
# ELASTIC rank/world decide trace-file suffixes and summary identity)
_rank_override = None


def set_clock_offset(offset_s: Optional[float]) -> None:
    """Install this rank's coordinator-clock offset (``obs/fleet.py``
    owns the estimation); ``None`` removes the stamp."""
    global _clk_off
    _clk_off = None if offset_s is None else float(offset_s)


def set_rank(rank: int, world: int) -> None:
    """Override the (rank, world) identity used for trace-record rank
    stamps, per-rank trace-file suffixes, and summaries.  Elastic
    training calls this after join/resync — its ranks come from the
    coordinator, not from jax.distributed."""
    global _rank_override
    _rank_override = (int(rank), max(int(world), 1))


def set_annotator(fn) -> None:
    """Install/remove the per-span annotation factory (``fn(name)`` ->
    context manager).  Owned by ``obs/profiler.py``."""
    global _annotator
    _annotator = fn


def set_sink(sink) -> None:
    """Install/remove the live-metrics sink (counter/gauge/event/span
    callbacks).  Owned by ``obs/ops_plane.py``; survives :func:`reset`
    — the plane's lifecycle is the process, not one run."""
    global _sink
    _sink = sink


def get_sink():
    """The installed sink or None.  Lock-free single attribute read —
    ``obs/lock_contract.py`` calls this from inside lock wrappers, so
    it must never take the telemetry lock."""
    return _sink


def _rank_world():
    """(rank, world) without initializing any jax backend: reads the
    distributed client state only when jax is already imported (the
    same best-effort probe the CLI's already-meshed check uses).  An
    elastic :func:`set_rank` override wins — those workers are world-1
    jax processes whose fleet identity lives with the coordinator."""
    if _rank_override is not None:
        return _rank_override
    jx = sys.modules.get("jax")
    if jx is None:
        return 0, 1
    try:
        from jax._src import distributed
        st = distributed.global_state
        if getattr(st, "client", None) is None:
            return 0, 1
        return int(st.process_id or 0), int(st.num_processes or 1)
    # tpulint: disable=TPL006 -- best-effort probe of private jax state
    except Exception:                   # noqa: BLE001 - probe is best-effort
        return 0, 1


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------
def enabled() -> bool:
    return _enabled


def enable(trace_path: Optional[str] = None) -> None:
    """Turn telemetry on.  ``trace_path`` additionally streams every
    record as one JSON line (appended; per-rank suffix in multi-host
    runs).  Idempotent; a second call can add a trace to an already
    enabled run."""
    global _enabled, _trace_requested
    with _lock:
        _enabled = True
        if trace_path:
            _trace_requested = trace_path


def disable() -> None:
    """Turn telemetry off (the accumulated summary is kept)."""
    global _enabled, _trace_file, _trace_open_path
    with _lock:
        _enabled = False
        if _trace_file is not None:
            try:
                _trace_file.close()
            except OSError:
                pass
        _trace_file = None
        _trace_open_path = None


def reset() -> None:
    """Clear the run summary and forget any requested trace (tests).
    Also rewinds the collective flight recorder — a fresh run must not
    inherit the previous run's schedule digest."""
    global _trace_requested, _held, _annotator, _clk_off, _rank_override
    with _lock:
        disable()
        _trace_requested = None
        _held = None
        _annotator = None
        _clk_off = None
        _rank_override = None
        _spans.clear()
        _counters.clear()
        _gauges.clear()
        _events.clear()
        _sections.clear()
        if getattr(_tls, "stack", None):
            _tls.stack = []
    from . import flight_recorder
    flight_recorder.reset()
    from . import profiler
    profiler.reset()
    from . import health
    health.reset()
    from . import fleet
    fleet.reset()


def trace_path() -> Optional[str]:
    """The trace file path actually written to (with any rank suffix),
    or the requested path when nothing has been written yet."""
    return _trace_open_path or _trace_requested


def _init_from_env() -> None:
    path = os.environ.get("LGBM_TPU_TRACE", "")
    if path:
        enable(trace_path=path)


# ---------------------------------------------------------------------------
# trace writing
# ---------------------------------------------------------------------------
_held = None                  # not None => buffer records instead of writing


def hold_trace() -> None:
    """Buffer trace records in memory instead of opening the trace
    file.  Called around the multi-host rendezvous
    (``parallel/mesh.init_distributed``): records emitted DURING the
    rendezvous (its own retry counters) must not open the trace file
    before the process knows its rank — every rank would grab the same
    unsuffixed path.  No-op when already holding."""
    global _held
    with _lock:
        if _held is None:
            _held = []


def release_trace() -> None:
    """Flush records buffered by :func:`hold_trace` (their ``rank``
    field is re-stamped — it was unknowable at emission) and resume
    direct writes."""
    global _held
    with _lock:
        pending, _held = _held, None
        if pending:
            rank, _ = _rank_world()
            for rec in pending:
                rec["rank"] = rank
                _trace_write(rec)


def _trace_write(record: Dict[str, Any]) -> None:
    """Append one JSONL record.  Caller holds ``_lock``.  The file
    opens lazily so multi-host runs that enable telemetry before
    ``jax.distributed.initialize`` still get per-rank files."""
    global _trace_file, _trace_open_path
    if _clk_off is not None and "clk_off_s" not in record:
        record["clk_off_s"] = _clk_off
    if _held is not None:
        _held.append(record)
        return
    if _trace_file is None:
        if not _trace_requested:
            return
        rank, world = _rank_world()
        path = _trace_requested
        if world > 1:
            path = f"{path}.rank{rank}"
        try:
            _trace_file = open(path, "a", buffering=1)
            _trace_open_path = path
        except OSError:
            return
    try:
        _trace_file.write(json.dumps(record) + "\n")
    except (OSError, ValueError):
        pass


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class _Discard:
    """Attr sink for the disabled path: swallows writes, costs nothing."""
    __slots__ = ()

    def __setitem__(self, key, value):
        pass

    def update(self, *args, **kwargs):
        pass


_DISCARD = _Discard()


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return _DISCARD

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0", "ts", "depth", "ann")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.depth = len(stack)
        stack.append(self.name)
        ann = _annotator
        if ann is not None:
            try:
                self.ann = ann(self.name)
                self.ann.__enter__()
            # tpulint: disable=TPL006 -- annotation is best-effort; a
            # profiler hiccup must not take the training span down
            except Exception:           # noqa: BLE001
                self.ann = None
        else:
            self.ann = None
        self.ts = time.time()
        self.t0 = time.perf_counter()
        return self.attrs

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        if self.ann is not None:
            try:
                self.ann.__exit__(*exc)
            # tpulint: disable=TPL006 -- annotation close is best-effort
            except Exception:           # noqa: BLE001
                pass
            self.ann = None
        stack = _tls.stack
        parent = ""
        if stack and stack[-1] is self.name:
            stack.pop()
            parent = stack[-1] if stack else ""
        rank, _ = _rank_world()
        with _lock:
            agg = _spans.get(self.name)
            if agg is None:
                agg = _spans[self.name] = [0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += dur
            if dur > agg[2]:
                agg[2] = dur
            sink = _sink
            if sink is not None:
                sink.span(self.name, dur)
            if _trace_requested:
                rec = {"ts": self.ts, "kind": "span", "name": self.name,
                       "rank": rank, "dur_s": dur, "depth": self.depth,
                       "parent": parent}
                if self.attrs:
                    rec.update(self.attrs)
                _trace_write(rec)
        return False


def span(name: str, **attrs):
    """Context manager timing the enclosed block under ``name``; yields
    a dict the block may add fields to (they land on the trace record).
    A shared no-op when telemetry is disabled."""
    if not _enabled:
        return _NOOP_SPAN
    return _Span(name, attrs)


# ---------------------------------------------------------------------------
# counters / gauges / events
# ---------------------------------------------------------------------------
def counter_add(name: str, n: float = 1) -> None:
    if not _enabled:
        return
    rank, _ = _rank_world()
    with _lock:
        _counters[name] = _counters.get(name, 0) + n
        sink = _sink
        if sink is not None:
            sink.counter(name, n, _counters[name])
        if _trace_requested:
            _trace_write({"ts": time.time(), "kind": "counter",
                          "name": name, "rank": rank, "add": n,
                          "value": _counters[name]})


def gauge_set(name: str, value: Any) -> None:
    if not _enabled:
        return
    rank, _ = _rank_world()
    with _lock:
        _gauges[name] = value
        sink = _sink
        if sink is not None:
            sink.gauge(name, value)
        if _trace_requested:
            _trace_write({"ts": time.time(), "kind": "gauge",
                          "name": name, "rank": rank, "value": value})


def event(kind: str, name: str, **fields) -> None:
    """Record a one-shot occurrence.  ``kind`` is a coarse family
    (``"fault"``, ``"early_stop"``, ...) kept distinct from the three
    structural kinds; the trace record's ``kind`` field is ``"event"``
    with the family under ``"family"``."""
    if not _enabled:
        return
    rank, _ = _rank_world()
    with _lock:
        key = f"{kind}:{name}"
        _events[key] = _events.get(key, 0) + 1
        sink = _sink
        if sink is not None:
            sink.event(key, _events[key])
        if _trace_requested:
            rec = {"ts": time.time(), "kind": "event", "name": name,
                   "rank": rank, "family": kind}
            if fields:
                rec.update(fields)
            _trace_write(rec)


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------
def set_section(name: str, data: Any) -> None:
    """Attach a named section to the run summary (overwrites).  Unlike
    counters/spans this is NOT gated on :func:`enabled` — sections are
    one-shot structured results (the trace-contract report) whose
    producers gate themselves."""
    with _lock:
        _sections[name] = data


def summary() -> Dict[str, Any]:
    """The in-memory run summary as a plain (JSON-serializable) dict.
    Carries this rank's collective flight-recorder state (ring + rolling
    digest) so any cross-rank summary merge doubles as a schedule
    cross-check (see :func:`merged_summary`)."""
    rank, world = _rank_world()
    from . import fleet, flight_recorder
    fr = flight_recorder.snapshot()
    sk = fleet.skew_snapshot()
    ck = fleet.clock()
    with _lock:
        out = {
            "rank": rank,
            "process_count": world,
            "spans": {k: {"count": v[0], "total_s": v[1], "max_s": v[2]}
                      for k, v in _spans.items()},
            "counters": dict(_counters),
            "gauges": dict(_gauges),
            "events": dict(_events),
        }
        if fr["count"]:
            out["flight_recorder"] = fr
        if sk is not None:
            out["collective_skew"] = sk
        if ck.get("offset_s") is not None:
            out["clock"] = ck
        out.update(_sections)
        return out


def merged_summary(allgather) -> Dict[str, Any]:
    """Every rank's summary merged into one dict (identical on all
    ranks — ``allgather`` is the host-collective seam, normally
    ``io.distributed.jax_process_allgather``).  ``ranks`` keeps each
    rank's full summary; ``counters``/``events`` sum and ``spans``
    combine across ranks.  The per-rank ``flight_recorder`` sections
    are cross-checked here: a schedule desync lands in
    ``flight_recorder_check`` naming the first diverging site+rank."""
    locals_ = allgather(summary())
    merged: Dict[str, Any] = {
        "process_count": len(locals_),
        "ranks": locals_,
        "spans": {},
        "counters": {},
        "events": {},
    }
    for s in locals_:
        for k, v in s.get("counters", {}).items():
            merged["counters"][k] = merged["counters"].get(k, 0) + v
        for k, v in s.get("events", {}).items():
            merged["events"][k] = merged["events"].get(k, 0) + v
        for k, v in s.get("spans", {}).items():
            agg = merged["spans"].setdefault(
                k, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += v["count"]
            agg["total_s"] += v["total_s"]
            agg["max_s"] = max(agg["max_s"], v["max_s"])
    from . import flight_recorder
    check = flight_recorder.cross_check_summaries(locals_)
    if check is not None:
        merged["flight_recorder_check"] = check
    # per-site collective arrival skew lifted fleet-wide: each rank's
    # wait totals side by side, plus the dominant straggler per site
    from . import fleet
    skew = fleet.merge_skew(locals_)
    if skew is not None:
        merged["collective_skew"] = skew
    # per-rank health state, first-class (the ranks already carry their
    # full `health` sections; the lift makes the fleet view one read):
    # `worst` is what a supervisor should act on
    hs = [(s.get("health") or {}).get("state") for s in locals_]
    if any(hs):
        order = ("ready", "warming", "draining", "degraded", "stalled")
        known = [h for h in hs if h in order]
        merged["health"] = {
            "ranks": hs,
            "worst": (max(known, key=order.index) if known else None),
        }
    return merged


def write_summary(path: str, merged: Optional[Dict[str, Any]] = None) -> None:
    """Atomically write a summary (merged or this rank's) as JSON."""
    from ..utils.file_io import atomic_write
    atomic_write(path, json.dumps(merged if merged is not None
                                  else summary(), indent=1))


_init_from_env()
