"""Low-overhead training telemetry (see ``obs/telemetry.py``).

Import seam for the rest of the library::

    from ..obs import span, counter_add, event
    with span("snapshot.write") as s:
        ...
        s["bytes"] = n

The collective flight recorder (``obs/flight_recorder.py``) rides the
same summary plumbing: ``from ..obs import flight_recorder``; the
device-time attribution layer (``obs/profiler.py`` — profiler-backed
capture, trace parser, XLA cost/roofline model) likewise:
``from ..obs import profiler``; the live ops plane (``obs/ops_plane.py``
— scrapeable /metrics + /healthz + /drain) and its health state
machine / stall watchdog / numerics sentinels (``obs/health.py``):
``from ..obs import health, ops_plane``.
"""
from .telemetry import (counter_add, disable, enable, enabled, event,
                        gauge_set, merged_summary, reset, set_annotator,
                        set_clock_offset, set_rank, set_section, set_sink,
                        span, summary, trace_path, write_summary)

__all__ = [
    "enabled", "enable", "disable", "reset", "span", "counter_add",
    "gauge_set", "event", "summary", "merged_summary", "write_summary",
    "trace_path", "set_section", "set_annotator", "set_sink",
    "set_clock_offset", "set_rank",
]
