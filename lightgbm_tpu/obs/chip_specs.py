"""Per-device-kind peak tables for the roofline columns.

The device-time attribution layer (``obs/profiler.py``) turns
``Compiled.cost_analysis()`` FLOPs/bytes plus measured per-program
device time into %-of-peak and arithmetic-intensity columns.  That
needs ONE small authoritative table of nameplate peaks per device
kind — kept here, jax-free except for the kind probe, so the trace
parser and ``tools/perf_report.py`` can import it without touching a
backend.

Numbers are NAMEPLATE (vendor-published) peaks, not measured: the
measured MXU ceiling on this bench device under-reads nameplate by
~5-10% (``tests/data/north_star.json`` ``peak_bf16_tmacs`` = 87.0
TMACs ~ 174 TFLOPs vs the 197 TFLOPs v5e nameplate — each chained
step pays a clip+cast epilogue).  Roofline percentages computed
against nameplate are therefore conservative; a program reading
">90% of peak" genuinely has no headroom.

The ``cpu`` entry is an explicit SENTINEL: tier-1 runs the whole
attribution pipeline on the CPU backend, where "% of peak" against a
per-box-variable peak would be meaningless — the sentinel keeps the
column arithmetic exercised (and flagged ``sentinel: true`` in every
report) without pretending to measure a CPU roofline.
"""
from __future__ import annotations

import sys
from typing import Any, Dict, Optional

__all__ = ["CHIP_PEAKS", "device_kind", "peaks_for", "roofline"]

# kind -> {flops_per_s (bf16 for TPUs), hbm_bytes_per_s, source}
CHIP_PEAKS: Dict[str, Dict[str, Any]] = {
    "tpu-v5e": {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
                "source": "v5e nameplate: 197 bf16 TFLOPs, 819 GB/s HBM"},
    "tpu-v5p": {"flops_per_s": 459e12, "hbm_bytes_per_s": 2765e9,
                "source": "v5p nameplate: 459 bf16 TFLOPs, 2765 GB/s HBM"},
    "tpu-v4": {"flops_per_s": 275e12, "hbm_bytes_per_s": 1228e9,
               "source": "v4 nameplate: 275 bf16 TFLOPs, 1228 GB/s HBM"},
    # sentinel, not a measurement: keeps the roofline arithmetic (and
    # its tier-1 gates) runnable on the CPU backend
    "cpu": {"flops_per_s": 1e11, "hbm_bytes_per_s": 5e10,
            "source": "CPU SENTINEL (tier-1 mechanics only)",
            "sentinel": True},
}


def _normalize(kind: str) -> Optional[str]:
    k = (kind or "").lower()
    if "v5e" in k or "v5 lite" in k or "v5lite" in k:
        return "tpu-v5e"
    if "v5p" in k or ("v5" in k and "lite" not in k):
        return "tpu-v5p"
    if "v4" in k:
        return "tpu-v4"
    if "cpu" in k or "host" in k:
        return "cpu"
    return None


def device_kind() -> str:
    """The current jax backend's device kind string (best effort; never
    initializes jax when it is not already imported)."""
    jx = sys.modules.get("jax")
    if jx is None:
        return "unknown"
    try:
        d = jx.devices()[0]
        return str(getattr(d, "device_kind", None) or d.platform)
    # tpulint: disable=TPL006 -- best-effort probe; "unknown" IS the answer
    except Exception:                   # noqa: BLE001 - probe is best-effort
        return "unknown"


def peaks_for(kind: Optional[str] = None) -> Dict[str, Any]:
    """Peak table entry for ``kind`` (default: the current device).
    Unknown kinds return an explicit no-peaks entry — roofline columns
    then carry ``null`` percentages instead of a made-up peak."""
    raw = kind if kind is not None else device_kind()
    key = _normalize(raw)
    if key is None:
        return {"kind": raw, "flops_per_s": None, "hbm_bytes_per_s": None,
                "source": f"no peak table entry for {raw!r}"}
    out = dict(CHIP_PEAKS[key])
    out["kind"] = raw
    out["key"] = key
    return out


def roofline(flops: Optional[float], bytes_accessed: Optional[float],
             device_time_s: Optional[float],
             peaks: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Roofline columns for one program.

    Static half (needs only the cost model): arithmetic intensity
    (FLOPs/byte) and the device's ridge point — ``ai < ridge`` means
    the program CANNOT be compute-bound on this chip no matter how
    well it runs.  Measured half (needs attributed device time):
    achieved FLOPs/s and bytes/s as a fraction of peak, and a
    ``bound`` verdict — ``compute`` / ``memory`` when the dominant
    fraction is meaningful, ``host`` when both are tiny (the device is
    starved: dispatch latency, not the kernel, is the bottleneck —
    exactly the ROADMAP item-1 signature)."""
    p = peaks if peaks is not None else peaks_for()
    pf, pb = p.get("flops_per_s"), p.get("hbm_bytes_per_s")
    out: Dict[str, Any] = {
        "flops": flops, "bytes_accessed": bytes_accessed,
        "arith_intensity": (flops / bytes_accessed
                            if flops and bytes_accessed else None),
        "ridge_flops_per_byte": (pf / pb if pf and pb else None),
        "pct_peak_flops": None, "pct_peak_bw": None, "bound": None,
    }
    if device_time_s and device_time_s > 0:
        if flops and pf:
            out["pct_peak_flops"] = round(
                100.0 * flops / device_time_s / pf, 3)
        if bytes_accessed and pb:
            out["pct_peak_bw"] = round(
                100.0 * bytes_accessed / device_time_s / pb, 3)
        cf = out["pct_peak_flops"] or 0.0
        cb = out["pct_peak_bw"] or 0.0
        if max(cf, cb) < 5.0:
            out["bound"] = "host"
        else:
            out["bound"] = "compute" if cf >= cb else "memory"
    elif out["arith_intensity"] is not None \
            and out["ridge_flops_per_byte"] is not None:
        # static-only verdict: which roof the program sits under
        out["bound"] = ("compute" if out["arith_intensity"]
                        >= out["ridge_flops_per_byte"] else "memory")
    return out
