"""Runtime HBM watermark contract — the memory half of memcheck.

The static analyzer (``tools/memcheck``) pins donation/footprint
hazards at the source level; this module is the runtime wall,
mirroring the trace-contract/flight-recorder pattern: under
``LGBM_TPU_MEM_CONTRACT=1`` the training loop samples device memory
once per window (and the serving harness once per batch) and enforces
two properties over the steady state:

* **leak gate** — once warmup is over (the first sampled window: block
  compiles and first-touch allocations land there), the sampled live
  bytes may not grow beyond ``baseline + tolerance``.  The comparison
  is against the steady-state BASELINE, not the previous sample, so a
  slow per-window creep (the classic "list appending device arrays"
  leak) accumulates into a violation instead of hiding under a
  per-step tolerance.  Tolerance: ``LGBM_TPU_MEM_TOL_BYTES`` (default
  1 MiB) + ``LGBM_TPU_MEM_TOL_FRAC`` (default 0.02) x baseline.
* **donation effectiveness** — when buffer donation is on (TPU/GPU;
  ``gbdt._donation_enabled``), the in-place score update must be
  observed working: at most ONE live device buffer with the score
  state's (shape, dtype) may exist at a window boundary.  A second
  live score set means XLA stopped aliasing the donated buffer (a
  silent 2x HBM regression at the 10.5M-row shape).

Violations emit a ``mem:watermark_violation`` telemetry event NAMING
THE SPAN that crossed the watermark, and the full report lands in the
run summary as the ``mem_contract`` / ``serve_mem_contract`` section
(the same surface the trace contract uses), so BENCH artifacts and
merged multi-host summaries carry it.

Sampling sources, best effort in order:

1. ``device.memory_stats()`` — real allocator numbers
   (``bytes_in_use`` / ``peak_bytes_in_use``) on TPU/GPU;
2. ``jax.live_arrays()`` — the sum of live buffer ``nbytes`` in this
   process.  The CPU backend returns no ``memory_stats``; live-array
   accounting keeps the leak gate meaningful there (tier-1 proves the
   contract on CPU), at the cost of not seeing allocator slack.

``peak_hbm_bytes()`` is the bench hook: the process-cumulative device
peak for the artifact's per-leg ``peak_hbm_bytes`` field, or
``(None, reason)`` on backends without allocator stats.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "enabled", "device_memory_sample", "peak_hbm_bytes", "Watermark",
    "maybe_watermark",
]


def enabled() -> bool:
    return os.environ.get("LGBM_TPU_MEM_CONTRACT", "") == "1"


def _tol_bytes() -> int:
    return int(os.environ.get("LGBM_TPU_MEM_TOL_BYTES", 1 << 20))


def _tol_frac() -> float:
    return float(os.environ.get("LGBM_TPU_MEM_TOL_FRAC", 0.02))


def device_memory_sample() -> Tuple[int, Optional[int], str]:
    """-> (live_bytes, peak_bytes_or_None, source).  Never raises."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
    # tpulint: disable=TPL006 -- best-effort probe; absence IS the signal
    except Exception:
        stats = None
    if stats:
        return (int(stats.get("bytes_in_use", 0)),
                int(stats.get("peak_bytes_in_use", 0)) or None,
                "memory_stats")
    try:
        live = jax.live_arrays()
        total = 0
        for a in live:
            nb = getattr(a, "nbytes", None)
            if nb is not None:
                total += int(nb)
        return total, None, "live_arrays"
    # tpulint: disable=TPL006 -- best-effort probe; absence IS the signal
    except Exception:
        return 0, None, "unavailable"


def peak_hbm_bytes() -> Tuple[Optional[int], Optional[str]]:
    """Process-cumulative device HBM peak for bench artifacts:
    (bytes, None) when the backend exposes allocator stats, else
    (None, reason)."""
    import jax
    try:
        d = jax.devices()[0]
        stats = d.memory_stats()
    # tpulint: disable=TPL006 -- best-effort probe; absence IS the signal
    except Exception as exc:
        return None, f"memory_stats probe failed: {type(exc).__name__}"
    if not stats:
        return None, (f"memory_stats unavailable on the "
                      f"{d.platform} backend")
    peak = stats.get("peak_bytes_in_use")
    if peak is None:
        return None, "allocator reports no peak_bytes_in_use"
    return int(peak), None


def count_live_like(shape, dtype) -> int:
    """Live device buffers matching (shape, dtype) in this process —
    the donation-effectiveness probe (an aliased in-place score update
    keeps exactly one)."""
    import jax
    try:
        live = jax.live_arrays()
    # tpulint: disable=TPL006 -- best-effort probe; absence IS the signal
    except Exception:
        return -1
    n = 0
    for a in live:
        if getattr(a, "shape", None) == tuple(shape) \
                and getattr(a, "dtype", None) == dtype:
            n += 1
    return n


class Watermark:
    """Per-run watermark state: call :meth:`sample` at every window/
    batch boundary, :meth:`finalize` once at the end (writes the
    summary section).  ``sampler`` is injectable for unit tests."""

    def __init__(self, kind: str, warmup: int = 1,
                 sampler: Callable[[], Tuple[int, Optional[int], str]]
                 = device_memory_sample):
        self.kind = kind
        self.warmup = max(0, int(warmup))
        self._sampler = sampler
        self.samples: List[Dict[str, Any]] = []
        self.violations: List[Dict[str, Any]] = []
        self.baseline: Optional[int] = None
        self.source = "unsampled"
        self.max_bytes = 0
        self.peak_bytes: Optional[int] = None
        self.donation_checked = False
        self.donation_ok = True

    def sample(self, span: str, **attrs) -> None:
        live, peak, source = self._sampler()
        self.source = source
        self.max_bytes = max(self.max_bytes, live)
        if peak is not None:
            self.peak_bytes = peak
        idx = len(self.samples)
        rec = {"span": span, "bytes": int(live), "idx": idx}
        rec.update(attrs)
        self.samples.append(rec)
        if source == "unavailable":
            return
        if idx < self.warmup:
            return
        if self.baseline is None:
            self.baseline = int(live)
            return
        tol = _tol_bytes() + int(_tol_frac() * self.baseline)
        if live > self.baseline + tol:
            grew = int(live - self.baseline)
            self.violations.append(
                {"span": span, "grew_bytes": grew, "bytes": int(live),
                 "baseline": self.baseline, "tol_bytes": tol, "idx": idx})
            from . import event
            event("mem", "watermark_violation", contract=self.kind,
                  span=span, grew_bytes=grew, baseline=self.baseline,
                  tol_bytes=tol)
            from ..utils.log import log_warning
            log_warning(
                f"mem contract violated in {self.kind}: live bytes grew "
                f"{grew} over the steady baseline {self.baseline} "
                f"(tol {tol}) at span {span!r} — a per-window leak")

    def check_donation(self, shape, dtype, expected: int = 1) -> None:
        """Donation-effectiveness probe (call when donation is ON):
        more than ``expected`` live (shape, dtype) buffers at a window
        boundary means the in-place update stopped aliasing."""
        n = count_live_like(shape, dtype)
        if n < 0:
            return
        self.donation_checked = True
        if n > expected:
            self.donation_ok = False
            self.violations.append(
                {"span": f"{self.kind}.donation", "live_score_buffers": n,
                 "expected": expected})
            from . import event
            event("mem", "watermark_violation", contract=self.kind,
                  span=f"{self.kind}.donation", live_score_buffers=n,
                  expected=expected)

    def report(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "source": self.source,
            "windows_sampled": len(self.samples),
            "baseline_bytes": self.baseline,
            "max_bytes": self.max_bytes,
            "peak_bytes": self.peak_bytes,
            "tol_bytes": (_tol_bytes()
                          + int(_tol_frac() * (self.baseline or 0))),
            "violations": self.violations[:16],
            "violation_count": len(self.violations),
            "donation_checked": self.donation_checked,
            "donation_ok": self.donation_ok,
            "steady_ok": not self.violations,
        }

    def finalize(self, section: Optional[str] = None) -> Dict[str, Any]:
        rep = self.report()
        from . import set_section
        set_section(section or "mem_contract", rep)
        return rep


class maybe_watermark:
    """``with maybe_watermark("gbdt") as wm:`` — a live
    :class:`Watermark` under ``LGBM_TPU_MEM_CONTRACT=1`` (section
    written on exit), else None at ~zero cost."""

    def __init__(self, kind: str, section: Optional[str] = None,
                 warmup: int = 1):
        self.kind = kind
        self.section = section
        self.warmup = warmup
        self.wm: Optional[Watermark] = None

    def __enter__(self) -> Optional[Watermark]:
        if enabled():
            self.wm = Watermark(self.kind, warmup=self.warmup)
        return self.wm

    def __exit__(self, *exc) -> bool:
        if self.wm is not None:
            self.wm.finalize(self.section)
        return False
