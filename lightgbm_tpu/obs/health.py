"""Live health plane: state machine, stall watchdog, numerics sentinels.

Every observability layer before this one (telemetry, flight recorder,
profiler, determinism) is post-hoc — summaries and JSONL traces parsed
after the run ends, which is exactly why the r5 bench timeout (rc=124)
died with nothing explaining *where* it hung.  This module is the live
half: state a scraper can read while the process is still running, and
a watchdog that names a stalled dispatch BEFORE the driver's SIGKILL
erases the evidence.  (The reference's YARN AM health-checks workers
over a socket the same way — ``linkers_socket.cpp:27-68`` — it just
never exports what it learns.)

Three pieces:

* **Health state machine** — ``warming -> ready -> draining`` with two
  sticky failure states, ``stalled`` (watchdog fired) and ``degraded``
  (a numerics sentinel tripped).  ``/healthz`` on the ops plane
  (``obs/ops_plane.py``) serves :func:`state`; every transition also
  lands as the ``health`` telemetry summary section, so merged
  multi-rank summaries carry per-rank health state.
* **Stall watchdog** — a monitor thread armed around each training
  window (``boosting/gbdt.py``) and serve batch (``serve/server.py``)
  via ``LGBM_TPU_WATCHDOG_S`` (seconds; default off).  On expiry it
  emits a ``health:stall`` event naming the active span, dumps
  all-thread stacks via :mod:`faulthandler`, appends the
  flight-recorder last-K collective ring, and writes a kill-survivable
  ``<trace>.forensic.json`` (tmp+rename through
  ``utils/file_io.atomic_write`` — the snapshot discipline, so a
  SIGKILL mid-dump can never publish a torn file).  The watchdog only
  OBSERVES: the stalled dispatch is left to finish (or to the driver's
  timeout) — killing a wedged XLA dispatch from a sibling thread would
  take the whole runtime down with it.
* **Numerics sentinels** — riding the existing window-boundary host
  fetches at zero extra device dispatches: non-finite score/metric
  detection (a NaN gradient or hessian poisons the score state it
  folds into) raising ``health:nonfinite``, and train-loss spike
  detection raising ``health:loss_spike``; both flip ``/healthz`` to
  ``degraded``.  On by default whenever the ops plane is mounted;
  force with ``LGBM_TPU_SENTINELS=1`` / off with ``=0``.

Fault points (``utils/faults.py``): ``watchdog.stall`` makes the armed
window sleep past the deadline (:func:`stall_fault`), ``health.nan_grad``
poisons one gradient element (``gbdt._gradients``) — tier-1 proves the
watchdog names the stalled span in the forensic dump and the sentinel
fires with the right window, both on CPU.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = [
    "state", "tracking", "mark_warming", "mark_ready", "mark_recovering",
    "mark_draining", "mark_degraded", "mark_stalled", "reset", "Watchdog",
    "watchdog_seconds", "stall_fault", "sentinels_enabled",
    "check_scores", "check_metrics", "forensic_path", "write_forensic",
]

from .lock_contract import named_condition, named_rlock

_lock = named_rlock("health")
_active = False                 # flipped by the ops plane / watchdog /
#                                  sentinels: mark_* are no-ops otherwise
# ordered by severity: a transition may only move DOWN this list via
# explicit reset (stalled/degraded are sticky — a scraper that polls
# after the incident must still see it).  `recovering` (elastic
# re-rendezvous in progress, parallel/elastic.py) is NOT sticky: a
# successful recovery walks ready -> recovering -> ready.
_SEVERITY = ("ready", "warming", "recovering", "draining", "degraded",
             "stalled")
_state: Dict[str, Any] = {"state": "disabled", "since": None, "detail": {}}
# sentinel memory: per-metric best (rolling reference for the spike
# check) and the one-shot flags so a poisoned run reports the FIRST
# offending window, not one event per boundary after it
_loss_best: Dict[str, float] = {}
_reported: Dict[str, bool] = {}


def _set_active(on: bool) -> None:
    global _active
    with _lock:
        _active = bool(on)
        if on and _state["state"] == "disabled":
            _transition("warming")


def tracking() -> bool:
    """Whether any live-health consumer (ops plane, watchdog,
    sentinels) is armed; ``mark_*`` are one-attr-read no-ops
    otherwise."""
    return _active


def state() -> Dict[str, Any]:
    """The current health state (what ``/healthz`` serves)."""
    with _lock:
        return {"state": _state["state"], "since": _state["since"],
                "detail": dict(_state["detail"])}


def _transition(new: str, **detail) -> None:
    """Move the state machine; sticky states only escalate.  Caller
    may hold ``_lock``.  Every transition refreshes the ``health``
    summary section so multi-rank merged summaries carry it."""
    with _lock:
        cur = _state["state"]
        if cur in _SEVERITY and new in _SEVERITY \
                and _SEVERITY.index(new) < _SEVERITY.index(cur) \
                and cur in ("stalled", "degraded", "draining"):
            # sticky: ready/warming never papers over an incident (or
            # an in-progress drain)
            _state["detail"].update(detail)
            return
        _state["state"] = new
        _state["since"] = time.time()
        _state["detail"].update(detail)
    from .telemetry import set_section
    set_section("health", state())


def mark_warming(plane: str = "") -> None:
    if not _active:
        return
    _transition("warming", **({"plane": plane} if plane else {}))


def mark_ready() -> None:
    if not _active:
        return
    _transition("ready")


def mark_recovering(**detail) -> None:
    """Elastic recovery in flight (rank lost / membership changed —
    ``parallel/elastic.py``): survivors are re-rendezvousing and
    resuming from the last committed barrier snapshot.  Non-sticky —
    a completed recovery returns ``/healthz`` to ``ready``."""
    if not _active:
        return
    _transition("recovering", **detail)


def mark_draining(**detail) -> None:
    if not _active:
        return
    _transition("draining", **detail)


def mark_degraded(reason: str, **detail) -> None:
    if not _active:
        return
    _transition("degraded", reason=reason, **detail)


def mark_stalled(span: str, **detail) -> None:
    if not _active:
        return
    _transition("stalled", stalled_span=span, **detail)


def reset() -> None:
    """Back to a clean slate (tests; a fresh run).  The active flag is
    kept — the ops plane stays mounted across runs in one process."""
    with _lock:
        _state["state"] = "warming" if _active else "disabled"
        _state["since"] = time.time() if _active else None
        _state["detail"] = {}
        _loss_best.clear()
        _reported.clear()


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------
def watchdog_seconds() -> Optional[float]:
    """The armed deadline from ``LGBM_TPU_WATCHDOG_S`` (default off)."""
    raw = os.environ.get("LGBM_TPU_WATCHDOG_S", "")
    if not raw:
        return None
    try:
        s = float(raw)
    except ValueError:
        return None
    return s if s > 0 else None


def forensic_path() -> Optional[str]:
    """Where the stall forensics land: ``LGBM_TPU_FORENSIC`` wins,
    else ``<trace>.forensic.json`` next to the JSONL trace, else None
    (the dump still reaches the ``forensic`` summary section)."""
    p = os.environ.get("LGBM_TPU_FORENSIC", "")
    if p:
        return p
    from .telemetry import trace_path
    tp = trace_path()
    return f"{tp}.forensic.json" if tp else None


def _thread_stacks() -> str:
    """All-thread stacks via :mod:`faulthandler` (the same dump a
    fatal signal would produce — C-level frames included on py>=3.12,
    and immune to an interpreter wedged in a lock)."""
    import faulthandler
    import tempfile
    with tempfile.TemporaryFile(mode="w+") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.seek(0)
        return f.read()


def build_forensic(span: str, plane: str, deadline_s: float,
                   attrs: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The forensic record: who stalled, every thread's stack, the
    last-K collective ring, and the run counters/events so far."""
    from . import flight_recorder
    from .telemetry import _rank_world, summary
    rank, world = _rank_world()
    s = summary()
    return {
        "ts": time.time(),
        "kind": "stall_forensic",
        "plane": plane,
        "span": span,
        "attrs": dict(attrs or {}),
        "deadline_s": deadline_s,
        "rank": rank,
        "process_count": world,
        "health": state(),
        "stacks": _thread_stacks(),
        "flight_recorder": flight_recorder.snapshot(),
        "counters": s.get("counters", {}),
        "events": s.get("events", {}),
    }


def write_forensic(dump: Dict[str, Any],
                   path: Optional[str] = None) -> Optional[str]:
    """Publish the forensic dump tmp+rename (the snapshot discipline:
    ``chunks=2`` routes the write through the ``snapshot.write`` fault
    point mid-payload, so tests prove a death mid-dump leaves the
    previous published file intact and the torn bytes in ``.tmp``).
    Also lands as the ``forensic`` summary section either way."""
    from .telemetry import set_section
    set_section("forensic", dump)
    path = path or forensic_path()
    if path is None:
        return None
    from ..utils.file_io import atomic_write
    atomic_write(path, json.dumps(dump, indent=1), chunks=2)
    return path


class Watchdog:
    """One monitor thread; :meth:`arm` around each training window /
    serve batch, :meth:`disarm` when the dispatch returns.  On expiry
    the active span is named in a ``health:stall`` event, ``/healthz``
    flips to ``stalled``, and the forensic dump is written — while the
    stalled dispatch is still in flight."""

    def __init__(self, plane: str, deadline_s: float):
        self.plane = plane
        self.deadline_s = float(deadline_s)
        self.fired = threading.Event()      # latest arm's expiry flag
        self._cv = named_condition("watchdog")
        self._armed: Optional[tuple] = None  # (seq, span, attrs, deadline)
        self._seq = 0
        self._stop = False
        _set_active(True)
        self._thread = threading.Thread(
            target=self._run, name=f"lgbm-tpu-watchdog-{plane}",
            daemon=True)
        self._thread.start()

    @classmethod
    def maybe(cls, plane: str) -> Optional["Watchdog"]:
        s = watchdog_seconds()
        return cls(plane, s) if s else None

    def arm(self, span: str, **attrs) -> None:
        from .telemetry import counter_add
        counter_add("watchdog.arms")
        with self._cv:
            self._seq += 1
            self.fired.clear()
            self._armed = (self._seq, span, attrs,
                           time.monotonic() + self.deadline_s)
            self._cv.notify()

    def disarm(self) -> None:
        with self._cv:
            self._armed = None
            self._cv.notify()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and self._armed is None:
                    self._cv.wait()
                if self._stop:
                    return
                _seq, span, attrs, deadline = self._armed
                wait = deadline - time.monotonic()
                if wait > 0:
                    # wait out (a slice of) the deadline, then
                    # re-evaluate: a disarm or re-arm in the meantime
                    # resets the loop
                    self._cv.wait(wait)
                    continue
                # past the deadline and still armed: fire once,
                # outside the lock (the dump takes real time and arm/
                # disarm from the worker thread must never block on it)
                self._armed = None
            try:
                self._fire(span, attrs)
            finally:
                self.fired.set()

    def _fire(self, span: str, attrs: Dict[str, Any]) -> None:
        from ..utils.log import log_warning
        from .telemetry import counter_add, event
        counter_add("watchdog.fires")
        event("health", "stall", span=span, plane=self.plane,
              deadline_s=self.deadline_s, **attrs)
        mark_stalled(span, plane=self.plane)
        log_warning(
            f"watchdog: span {span!r} ({self.plane}) exceeded "
            f"{self.deadline_s:g}s — dumping stacks + collective ring")
        try:
            path = write_forensic(
                build_forensic(span, self.plane, self.deadline_s, attrs))
            if path:
                log_warning(f"watchdog: forensics written to {path}")
        # tpulint: disable=TPL006 -- the dump is best-effort evidence;
        # a failed write must not take the monitor thread down
        except Exception as exc:        # noqa: BLE001
            log_warning(f"watchdog: forensic dump failed: {exc}")


def stall_fault(wd: Optional[Watchdog]) -> None:
    """The ``watchdog.stall`` injection seam: when armed, the calling
    (training/serving) thread sleeps IN-WINDOW until the watchdog
    names its span — the synthetic stall the forensics tests ride.
    No-op unless the fault is armed."""
    if wd is None:
        return
    from ..utils.faults import fault_flag
    if fault_flag("watchdog.stall"):
        wd.fired.wait(wd.deadline_s * 10 + 10)


# ---------------------------------------------------------------------------
# numerics sentinels
# ---------------------------------------------------------------------------
def _spike_factor() -> float:
    return float(os.environ.get("LGBM_TPU_SPIKE_FACTOR", "3.0"))


def sentinels_enabled() -> bool:
    """Sentinels ride the window-boundary host fetches when the ops
    plane is mounted (or forced via ``LGBM_TPU_SENTINELS=1``)."""
    raw = os.environ.get("LGBM_TPU_SENTINELS", "")
    if raw == "0":
        return False
    if raw == "1":
        _set_active(True)
        return True
    from . import ops_plane
    return ops_plane.plane() is not None


def check_scores(scores: np.ndarray, window: int) -> bool:
    """Non-finite detection over the ALREADY-FETCHED score state (the
    window-boundary host fetch the eval/ES sync performs anyway — zero
    extra device dispatches; a NaN/inf gradient or hessian poisons the
    scores it folds into within one iteration).  Returns True when
    clean."""
    from .telemetry import counter_add
    counter_add("health.sentinel_checks")
    finite = bool(np.isfinite(scores).all())
    if finite:
        return True
    if not _reported.get("nonfinite"):
        _reported["nonfinite"] = True
        bad = int(np.size(scores) - np.count_nonzero(np.isfinite(scores)))
        from ..utils.log import log_warning
        from .telemetry import event
        counter_add("health.nonfinite")
        event("health", "nonfinite", what="scores", window=int(window),
              bad_elements=bad)
        mark_degraded("nonfinite", window=int(window), what="scores",
                      bad_elements=bad)
        log_warning(
            f"health sentinel: {bad} non-finite score element(s) at "
            f"window {int(window)} — a NaN/inf gradient, hessian, or "
            f"leaf value entered the score state")
    return False


def check_leaf_values(leaf_values, window: int) -> bool:
    """Non-finite detection over an iteration's PRE-ZEROING leaf
    values (``gbdt._train_one_iter`` hands them over on the all-stump
    stop path: a non-finite grad/hess NaNs every split gain into a
    stump whose root value is non-finite, and the stump-zeroing used
    to erase the evidence before any score-level check could see it).
    Returns True when clean."""
    bad = sum(int(np.size(lv) - np.count_nonzero(np.isfinite(lv)))
              for lv in leaf_values)
    if not bad:
        return True
    if not _reported.get("nonfinite"):
        _reported["nonfinite"] = True
        from ..utils.log import log_warning
        from .telemetry import counter_add, event
        counter_add("health.nonfinite")
        event("health", "nonfinite", what="leaf_value",
              window=int(window), bad_elements=bad)
        mark_degraded("nonfinite", window=int(window), what="leaf_value",
                      bad_elements=bad)
        log_warning(
            f"health sentinel: non-finite leaf value(s) at window "
            f"{int(window)} — a NaN/inf gradient or hessian poisoned "
            f"the tree build (the all-stump stop was numerics, not "
            f"convergence)")
    return False


def check_metrics(results: List[tuple], window: int) -> bool:
    """Sentinels over the window's eval results (``(set, metric, value,
    higher_is_better)`` tuples, already host-side): non-finite metric
    values raise ``health:nonfinite``; a lower-is-better (loss-like)
    metric jumping past ``LGBM_TPU_SPIKE_FACTOR`` x its best-so-far
    raises ``health:loss_spike``.  Returns True when clean."""
    from ..utils.log import log_warning
    from .telemetry import counter_add, event
    ok = True
    for name, mname, val, hib in results:
        key = f"{name}:{mname}"
        if not np.isfinite(val):
            ok = False
            if not _reported.get(f"nonfinite:{key}"):
                _reported[f"nonfinite:{key}"] = True
                counter_add("health.nonfinite")
                event("health", "nonfinite", what=key, window=int(window))
                mark_degraded("nonfinite", window=int(window), what=key)
                log_warning(f"health sentinel: metric {key} is "
                            f"non-finite at window {int(window)}")
            continue
        if hib:
            continue
        best = _loss_best.get(key)
        if best is None or val < best:
            _loss_best[key] = float(val)
        elif best > 0 and val > best * _spike_factor():
            ok = False
            if not _reported.get(f"spike:{key}"):
                _reported[f"spike:{key}"] = True
                counter_add("health.loss_spikes")
                event("health", "loss_spike", what=key,
                      window=int(window), value=float(val),
                      best=float(best))
                mark_degraded("loss_spike", window=int(window), what=key,
                              value=float(val), best=float(best))
                log_warning(
                    f"health sentinel: {key} spiked to {val:.6g} "
                    f"(best {best:.6g}, factor {_spike_factor():g}) at "
                    f"window {int(window)} — training is diverging")
    return ok
