"""Collective flight recorder — the runtime half of the spmdcheck pair.

``tools/spmdcheck`` proves statically that no code path ISSUES a
rank-divergent collective schedule; this module proves at runtime that
the ranks actually DID issue the same one (PyTorch's NCCL flight
recorder attacks the same failure class from the same end).  The
reference never needs it — its blocking socket collectives deadlock
loudly and immediately on a schedule skew; XLA's async collectives on
ICI/DCN instead hang minutes later or silently mis-reduce, with
nothing naming the site that diverged (MULTICHIP_r05's ungated 1.63%
row-leaf skew is exactly the signature this recorder exists to
attribute).

Mechanics:

* every collective site — the ``shard_map`` wave collectives in
  ``parallel/learners.py`` (recorded at TRACE time: each process
  traces its own program, so trace-time Python is precisely where
  rank-conditional control flow can skew the schedule) and the host
  collectives in ``io/distributed.py`` / ``parallel/mesh.py``
  (recorded per call) — appends a ``(site, op, axis, shape, dtype)``
  fingerprint to a bounded per-rank ring buffer (``LGBM_TPU_FR_CAP``,
  default 128 entries) and folds it into a rolling sha1 digest that
  covers the ENTIRE history, not just the ring window;
* fingerprint digests are cross-checked across ranks at window
  boundaries, riding the existing host-collective merges: the
  eval-window metric sync in ``boosting/gbdt.py`` and the telemetry
  ``merged_summary`` path (every rank's summary carries its
  ``flight_recorder`` section);
* a mismatch emits a ``spmd:desync`` telemetry event naming the FIRST
  diverging site and rank, logs a WARNING, and drops the evidence into
  the summary under ``flight_recorder_check``;
* on retry exhaustion (``utils/retry.py``) the last-K schedule is
  dumped into the summary (``flight_recorder_dump``) — a hung
  collective's post-mortem names what this rank was doing.

Always on (recording is a lock + deque append + short sha1 at trace
time / per host collective — nowhere near any per-row path); disable
with ``LGBM_TPU_FLIGHT_RECORDER=0``.
"""
from __future__ import annotations

import hashlib
import os
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "record", "snapshot", "fingerprint", "reset", "enabled",
    "cross_check_summaries", "window_check", "dump_to_summary",
]

from .lock_contract import named_lock

_lock = named_lock("flight_recorder")
_CAP = max(8, int(os.environ.get("LGBM_TPU_FR_CAP", "128") or 128))
_ring: "deque[Dict[str, Any]]" = deque(maxlen=_CAP)
_count = 0                      # entries ever recorded (ring may be smaller)
_digest = ""                    # rolling sha1 over the full history


def enabled() -> bool:
    return os.environ.get("LGBM_TPU_FLIGHT_RECORDER", "1") != "0"


def reset() -> None:
    global _count, _digest
    with _lock:
        _ring.clear()
        _count = 0
        _digest = ""


def _fp_str(entry: Dict[str, Any]) -> str:
    return (f"{entry['site']}|{entry['op']}|{entry['axis']}|"
            f"{entry['shape']}|{entry['dtype']}")


def record(site: str, op: str, axis: Optional[str] = None,
           operand: Any = None) -> None:
    """Append one collective fingerprint.  ``operand`` may be a jax
    array/tracer (shape/dtype read from its aval — no device sync) or
    None for host object collectives whose payload sizes legitimately
    differ per rank (only rank-INVARIANT fields may enter the
    fingerprint, or the check would cry wolf)."""
    if not enabled():
        return
    from ..utils.faults import FaultInjected, fault_point
    try:
        # the injection seam for desync tests: an armed skip makes THIS
        # rank's schedule miss the site, exactly as rank-conditional
        # control flow would
        fault_point("spmd.skip_record")
    except FaultInjected:
        return
    shape = getattr(operand, "shape", None)
    dtype = getattr(operand, "dtype", None)
    entry = {
        "site": site, "op": op,
        "axis": None if axis is None else str(axis),
        "shape": None if shape is None else tuple(int(d) for d in shape),
        "dtype": None if dtype is None else str(dtype),
    }
    global _count, _digest
    with _lock:
        entry["seq"] = _count
        _ring.append(entry)
        _count += 1
        _digest = hashlib.sha1(
            (_digest + _fp_str(entry)).encode()).hexdigest()[:16]


def snapshot() -> Dict[str, Any]:
    """This rank's recorder state: total count, rolling digest, last-K
    entries (JSON-serializable — rides the telemetry summary)."""
    with _lock:
        return {"count": _count, "digest": _digest, "cap": _CAP,
                "last": [dict(e) for e in _ring]}


def fingerprint() -> List[Any]:
    """Compact ``[count, digest]`` for cheap per-window cross-checks."""
    with _lock:
        return [_count, _digest]


# ---------------------------------------------------------------------------
# cross-rank checking
# ---------------------------------------------------------------------------
def _first_divergence(snaps: Sequence[Optional[Dict[str, Any]]]
                      ) -> Optional[Dict[str, Any]]:
    """Locate the first schedule divergence across per-rank snapshots.
    Ranks are compared entry-by-entry on the fingerprint string; the
    diverging rank is the one whose stream differs from the majority
    (ties blame the shorter stream: a skipped collective shows up as a
    missing entry).  Returns None when the divergence predates every
    ring window (the digests still prove it happened)."""
    per_rank: List[Dict[int, Dict[str, Any]]] = []
    for s in snaps:
        entries = (s or {}).get("last", [])
        per_rank.append({int(e["seq"]): e for e in entries})
    counts = [(s or {}).get("count", 0) for s in snaps]
    all_seqs = sorted({q for m in per_rank for q in m})
    unknown = ("<evicted>", "<not-yet>")
    for seq in all_seqs:
        # a seq a rank counted but whose ring entry was evicted is
        # UNKNOWN, not divergent (only the window is bounded, not the
        # digest); a seq past a rank's count is handled after the loop
        fps = [(_fp_str(m[seq]) if seq in m
                else ("<evicted>" if seq < counts[r] else "<not-yet>"))
               for r, m in enumerate(per_rank)]
        vals = {fp for fp in fps if fp not in unknown}
        if len(vals) <= 1:
            continue
        # majority fingerprint; deviants are the diverging ranks
        tally: Dict[str, int] = {}
        for fp in fps:
            if fp not in unknown:
                tally[fp] = tally.get(fp, 0) + 1
        majority = max(sorted(tally), key=lambda k: tally[k])
        deviants = [r for r, fp in enumerate(fps)
                    if fp not in unknown and fp != majority]
        if not deviants:
            continue
        # shorter stream first: a skipped collective truncates it
        deviants.sort(key=lambda r: (counts[r], -r))
        site_entry = next((m[seq] for m in per_rank if seq in m), None)
        return {
            "seq": seq,
            "site": site_entry["site"] if site_entry else None,
            "op": site_entry["op"] if site_entry else None,
            "rank": deviants[0],
            "ranks": deviants,
            "entries": {r: (per_rank[r].get(seq) or fps[r])
                        for r in range(len(per_rank))},
        }
    # streams agree entry-for-entry but some rank stopped short: checks
    # run at synchronization barriers, so "not yet there" IS "skipped" —
    # the divergence sits at the shortest stream's end, and the site is
    # whatever the longer ranks issued there
    if len(set(counts)) > 1:
        seq = min(counts)
        site_entry = next((m[seq] for m in per_rank if seq in m), None)
        deviants = sorted([r for r, c in enumerate(counts) if c == seq],
                          key=lambda r: -r)
        return {
            "seq": seq,
            "site": site_entry["site"] if site_entry else None,
            "op": site_entry["op"] if site_entry else None,
            "rank": deviants[0],
            "ranks": deviants,
            "entries": {r: per_rank[r].get(seq) or "<missing>"
                        for r in range(len(per_rank))},
        }
    return None


def _report_desync(div: Optional[Dict[str, Any]],
                   counts: Sequence[int],
                   digests: Sequence[str]) -> Dict[str, Any]:
    from ..utils.log import log_warning
    from .telemetry import event
    out: Dict[str, Any] = {"ok": False, "counts": list(counts),
                           "digests": list(digests)}
    if div is not None:
        out["first_divergence"] = div
        log_warning(
            f"spmd desync: collective schedule diverged at seq "
            f"{div['seq']} site {div['site']!r} — rank {div['rank']} "
            f"disagrees (per-rank counts {list(counts)})")
        event("spmd", "desync", site=div["site"], rank=div["rank"],
              seq=div["seq"])
    else:
        out["first_divergence"] = None
        log_warning(
            f"spmd desync: schedule digests differ but the divergence "
            f"predates the ring window (counts {list(counts)}); raise "
            f"LGBM_TPU_FR_CAP to localize")
        event("spmd", "desync", site=None, rank=None, seq=None)
    return out


def cross_check_summaries(rank_summaries: Sequence[Dict[str, Any]]
                          ) -> Optional[Dict[str, Any]]:
    """Cross-rank schedule check over merged telemetry summaries (each
    carrying its rank's ``flight_recorder`` section).  Returns None
    when no rank recorded anything; otherwise a check report —
    ``{"ok": True, ...}`` or the desync evidence."""
    snaps = [s.get("flight_recorder") for s in rank_summaries]
    if not any(snaps):
        return None
    counts = [(s or {}).get("count", 0) for s in snaps]
    digests = [(s or {}).get("digest", "") for s in snaps]
    if len(set(counts)) == 1 and len(set(digests)) == 1:
        return {"ok": True, "count": counts[0], "digest": digests[0]}
    return _report_desync(_first_divergence(snaps), counts, digests)


def window_check(fingerprints: Sequence[Sequence[Any]],
                 allgather=None) -> bool:
    """Cheap per-window check over ``[count, digest]`` pairs gathered
    from every rank (piggybacked on an existing host collective, e.g.
    the eval-window metric sync).  On mismatch, a SECOND allgather (the
    rare path) exchanges the last-K rings to localize the first
    diverging site+rank.  Returns True when schedules agree."""
    from .telemetry import counter_add, set_section
    counter_add("spmd.window_checks")
    counts = [int(fp[0]) for fp in fingerprints]
    digests = [str(fp[1]) for fp in fingerprints]
    if len(set(counts)) == 1 and len(set(digests)) == 1:
        return True
    div = None
    if allgather is not None:
        snaps = allgather(snapshot())
        div = _first_divergence(snaps)
    report = _report_desync(div, counts, digests)
    set_section("flight_recorder_check", report)
    return False


def dump_to_summary(reason: str) -> None:
    """Drop the last-K schedule into the telemetry summary (called on
    retry exhaustion / gate failures): the post-mortem for a hung or
    failed collective is what this rank had issued up to that point."""
    from .telemetry import set_section
    dump = snapshot()
    dump["reason"] = reason
    set_section("flight_recorder_dump", dump)
