"""Runtime ulp contract (``LGBM_TPU_NUM_CONTRACT=1``).

The static half of the sixth wall is ``tools/numcheck``; this is the
runtime half, measuring at run time what the analyzer argues
statically: the canonical chunk+pairwise reduction discipline
(``learner/serial.py``'s ``root_stats`` family) keeps f32 accumulation
error bounded and partition-invariant.

One instrument, riding an existing seam (zero extra device
dispatches): at every window boundary ``GBDT._train`` already fetches
the f32 score state for the health sentinels; under this contract the
SAME fetched array feeds :func:`window_check`, which

* computes the **canonical f32 root-sum** — a NumPy mirror of the
  device-side STREAM_CHUNK + pairwise-halve reduction tree — and the
  **f64 host oracle** (``np.sum(..., dtype=float64)``) over the same
  bytes;
* converts their difference to **ulps at the accumulation scale**
  (f32 spacing at ``sum |scores|`` — the natural error unit of an f32
  reduction over that population; measuring at the result's own scale
  would explode on benign cancellation);
* appends ``(window, drift_ulps, oracle_hex)`` to the run ledger.
  The oracle value is recorded as ``float.hex()`` so two runs can be
  compared EXACTLY — a reassociated reducer (the ``num.reassoc``
  fault, the PR 14 bug class) perturbs the trained scores in their
  last ulps, and the ledger's exact oracle entries diverge where
  digests do.

The drift budget is shared BY NAME with the declarative registry:
``ULP_BUDGET`` must equal ``tol("score_root_ulp")`` in
``tools/numcheck/tolerance_registry.py`` (the package never imports
``tools/``; ``tests/test_numcheck.py`` pins the coherence, the same
name-sharing discipline as concheck's lock registry).  A trip emits a
``num:ulp_budget`` event and degrades ``/healthz`` — sticky, like the
non-finite sentinel.  Everything lands in the ``numerics`` summary
section.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import set_section
from .telemetry import event as obs_event

__all__ = ["enabled", "reset", "canonical_root_sum", "ulp_diff",
           "window_check", "ledger", "trips", "section", "ULP_BUDGET",
           "BUDGET_NAME"]

# shared by NAME with tools/numcheck/tolerance_registry.py
# ("score_root_ulp" row); tests/test_numcheck.py pins the coherence
BUDGET_NAME = "score_root_ulp"
ULP_BUDGET = 8

# the device-side canonical reduction grid (learner/serial.py
# STREAM_CHUNK) — mirrored here so the host replay reproduces the
# exact tree; tests pin the two constants equal
STREAM_CHUNK = 8192


def enabled() -> bool:
    return os.environ.get("LGBM_TPU_NUM_CONTRACT", "0") == "1"


# ledger state (process-wide, reset per run by GBDT.train / tests)
_LEDGER: List[Tuple[int, int, str]] = []   # (window_it, drift_ulps, hex)
_TRIPS: List[Dict] = []


def reset() -> None:
    _LEDGER.clear()
    _TRIPS.clear()


def canonical_root_sum(x) -> np.float32:
    """NumPy mirror of the canonical device reduction: zero-pad the
    flattened f32 array to the STREAM_CHUNK grid, pairwise-halve within
    chunks, pad the chunk axis to a power of two, pairwise-halve again.
    Bit-for-bit the same adds in the same order as
    ``reduce_chunk_sums(root_chunk_sums(...))`` performs on device."""
    v = np.ascontiguousarray(np.asarray(x, np.float32).reshape(-1))
    m = max(1, -(-v.size // STREAM_CHUNK))
    pad = m * STREAM_CHUNK - v.size
    if pad:
        v = np.concatenate([v, np.zeros(pad, np.float32)])
    v = v.reshape(m, STREAM_CHUNK)
    while v.shape[1] > 1:
        half = v.shape[1] // 2
        v = v[:, :half] + v[:, half:]
    v = v[:, 0]
    p = 1 << max(0, (m - 1).bit_length())
    if p > m:
        v = np.concatenate([v, np.zeros(p - m, np.float32)])
    while v.size > 1:
        half = v.size // 2
        v = v[:half] + v[half:]
    return np.float32(v[0])


def _ordered(x: np.float32) -> int:
    """Map f32 bits to integers monotonic in the float order (the
    standard lexicographic trick; ±0 map to the same key)."""
    u = int(np.float32(x).view(np.uint32))
    return (0x100000000 - u) if u & 0x80000000 else (u + 0x80000000)


def ulp_diff(a, b) -> int:
    """Distance between two f32 values in units in the last place
    (number of representable f32 values between them)."""
    return abs(_ordered(np.float32(a)) - _ordered(np.float32(b)))


def window_check(s_np: np.ndarray, it: int) -> Optional[int]:
    """Measure this window's accumulation drift over the fetched score
    state; returns the drift in ulps (None when skipped: contract off
    or non-finite scores — the health sentinel owns non-finite).

    Drift = |canonical f32 root-sum − f64 oracle| in units of the f32
    spacing at ``sum |scores|`` scale.  A budget trip is sticky
    degradation, not an exception: numerics drift is an observability
    fact the run should surface, not a crash."""
    if not enabled():
        return None
    s64 = np.asarray(s_np, np.float64)
    if not np.isfinite(s64).all():
        return None
    oracle = float(s64.sum())
    abssum = float(np.abs(s64).sum())
    canon = canonical_root_sum(s_np)
    if abssum == 0.0:
        drift = 0
    else:
        scale = float(np.spacing(np.float32(abssum)))
        drift = int(round(abs(float(canon) - oracle) / scale))
    _LEDGER.append((int(it), drift, float(oracle).hex()))
    if drift > ULP_BUDGET:
        info = {"window_it": int(it), "drift_ulps": drift,
                "budget": ULP_BUDGET, "budget_name": BUDGET_NAME,
                "canonical": float(canon), "oracle": oracle}
        _TRIPS.append(info)
        obs_event("num", "ulp_budget", **info)
        from . import health as _health
        _health.mark_degraded("ulp_budget", **info)
        from ..utils.log import log_warning
        log_warning(f"numerics contract violation at window it={it}: "
                    f"canonical f32 root-sum drifted {drift} ulps from "
                    f"the f64 oracle (budget {BUDGET_NAME}="
                    f"{ULP_BUDGET})")
    set_section("numerics", section())
    return drift


def ledger() -> List[Tuple[int, int, str]]:
    """The run's ``(window_it, drift_ulps, oracle_hex)`` entries."""
    return list(_LEDGER)


def trips() -> List[Dict]:
    return [dict(t) for t in _TRIPS]


def section() -> Dict:
    """The ``numerics`` summary section: budget, ledger, trips."""
    return {"budget_name": BUDGET_NAME, "budget_ulps": ULP_BUDGET,
            "windows": [[it, d, hx] for it, d, hx in _LEDGER],
            "trips": [dict(t) for t in _TRIPS]}
