"""Runtime lock-order contract — the dynamic half of concheck.

Under ``LGBM_TPU_LOCK_CONTRACT=1`` the package's locks are constructed
through :func:`named_lock` / :func:`named_rlock` /
:func:`named_condition`, which return wrapped primitives that record,
per process:

* the **acquisition-order graph**: an edge ``A -> B`` every time a
  thread acquires ``B`` while holding ``A``, with the ``file:line`` of
  BOTH acquisition sites.  Each new edge runs an **online cycle
  check** — a deadlock-in-waiting is reported the first time the
  closing edge appears, before any schedule ever wedges, naming every
  edge on the cycle with both sites.
* per-lock **wait/hold timing**: every acquire measures time-to-acquire
  (with a contended flag from a non-blocking first attempt) and every
  release measures hold time.  Samples flow through the telemetry sink
  (``MetricsRegistry.lock_wait``) to ``/metrics`` as
  ``lgbm_tpu_lock_wait_seconds{lock,quantile}``.
* **held-past-deadline** events: with ``LGBM_TPU_LOCK_HOLD_S=<sec>``
  set, a release after holding longer than the deadline records a
  violation carrying the owner thread's acquisition stack — the same
  shape the PR 13 watchdog forensic dump ingests via telemetry events.

Lock names are the SAME ids as ``tools/concheck/lock_registry.py``, so
a static CON002 finding and a runtime cycle report name the same edge.

Disabled (the default), the factories return plain ``threading``
primitives — zero overhead on the hot path.

This module imports ONLY the stdlib at module level (telemetry/faults
are imported lazily inside reporting helpers) so every other module —
including ``utils.log`` and ``utils.faults`` at the bottom of the
import graph — can adopt named locks without import cycles.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = [
    "enabled", "named_lock", "named_rlock", "named_condition",
    "Guarded", "violations", "reset", "snapshot",
    "ContractLock", "ContractRLock", "ContractCondition",
]

_WAIT_SAMPLES = 256          # bounded per-lock sample ring


def enabled() -> bool:
    """True when the contract is armed (read at lock creation)."""
    return os.environ.get("LGBM_TPU_LOCK_CONTRACT", "") == "1"


def _hold_deadline_s() -> float:
    raw = os.environ.get("LGBM_TPU_LOCK_HOLD_S", "")
    try:
        return float(raw) if raw else 0.0
    except ValueError:
        return 0.0


# ---------------------------------------------------------------------------
# process-wide state
# ---------------------------------------------------------------------------
_tls = threading.local()

# the graph lock is a RAW primitive (a wrapped one would report into
# itself); it is also declared in the registry as a leaf under every
# other lock so static analysis sees the same shape
_graph_lock = threading.Lock()
# edge -> (outer site, inner site) of the first observation
_edges: Dict[str, Dict[str, Tuple[str, str]]] = {}
_violations: List[Dict[str, Any]] = []
_stats: Dict[str, Dict[str, Any]] = {}


def _held_stack() -> List[Dict[str, Any]]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _caller_site() -> str:
    """``file:line`` of the nearest frame outside this module."""
    f = sys._getframe(1)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:                                 # pragma: no cover
        return "<unknown>:0"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _cycle_path(start: str, goal: str) -> Optional[List[str]]:
    """DFS path start -> ... -> goal in the edge graph (caller holds
    _graph_lock)."""
    stack = [(start, [start])]
    seen = {start}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == goal:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_violation(v: Dict[str, Any]) -> None:
    with _graph_lock:
        _violations.append(v)
    _emit_event("lock_contract.violation", kind=v.get("kind", "?"),
                detail=v.get("detail", ""))


def _emit_event(name: str, **attrs: Any) -> None:
    """Telemetry export with a re-entrancy guard: the telemetry module's
    own lock is a wrapped lock, so reporting from inside a wrapper must
    never re-enter the wrappers."""
    if getattr(_tls, "in_report", False):
        return
    _tls.in_report = True
    try:
        from . import telemetry
        # getattr indirection: this export path never feeds a traced
        # computation, and the indirection keeps detcheck's name-based
        # traced-scope walk from chasing telemetry out of a traced
        # caller that merely touches a contract lock
        _ca = getattr(telemetry, "counter_add")
        _ev = getattr(telemetry, "event")
        _ca("lock_contract.violations", 1)
        _ev(name, **attrs)
    # tpulint: disable=TPL006 -- best-effort telemetry export: a broken
    # sink must never raise out of a lock acquire/release
    except Exception:
        pass
    finally:
        _tls.in_report = False


def _report_wait(name: str, wait_s: float, contended: bool) -> None:
    with _graph_lock:
        st = _stats.setdefault(name, {
            "acquires": 0, "contended": 0, "wait_max_s": 0.0,
            "hold_max_s": 0.0,
            "waits": deque(maxlen=_WAIT_SAMPLES)})
        st["acquires"] += 1
        st["contended"] += 1 if contended else 0
        st["wait_max_s"] = max(st["wait_max_s"], wait_s)
        st["waits"].append(wait_s)
    if getattr(_tls, "in_report", False):
        return
    # the sink records samples under ITS registry lock: exporting a
    # wait for that same lock (or while this thread already holds it)
    # would re-acquire a non-reentrant lock the thread owns and
    # self-deadlock — keep those samples in _stats/snapshot() only
    if name == "metrics_registry" or any(
            rec["name"] == "metrics_registry"
            for rec in getattr(_tls, "held", None) or ()):
        return
    _tls.in_report = True
    try:
        from . import telemetry
        # getattr indirection: see _emit_event — observability export
        # only, firewalled from detcheck's traced-scope walk
        sink = getattr(telemetry, "get_sink")()
        _lw = getattr(sink, "lock_wait", None)
        if _lw is not None:
            _lw(name, wait_s, contended)
    # tpulint: disable=TPL006 -- best-effort telemetry export: a broken
    # sink must never raise out of a lock acquire
    except Exception:
        pass
    finally:
        _tls.in_report = False


def _report_hold(name: str, hold_s: float) -> None:
    with _graph_lock:
        st = _stats.get(name)
        if st is not None:
            st["hold_max_s"] = max(st["hold_max_s"], hold_s)


def _note_acquired(name: str, site: str) -> Dict[str, Any]:
    """Record edges + push the held record; returns the record."""
    stack = _held_stack()
    reentrant = any(rec["name"] == name for rec in stack)
    if stack and not reentrant:
        outer = stack[-1]
        a, b = outer["name"], name
        with _graph_lock:
            known = _edges.get(a, {})
            new_edge = b not in known
            if new_edge:
                cyc = _cycle_path(b, a)
                _edges.setdefault(a, {})[b] = (outer["site"], site)
            else:
                cyc = None
        if new_edge and cyc is not None:
            with _graph_lock:
                hops = []
                full = [a] + cyc           # a -> b -> ... -> a
                for i in range(len(full) - 1):
                    sa, sb = _edges.get(full[i], {}).get(
                        full[i + 1], ("?", "?"))
                    hops.append(f"{full[i]}@{sa} -> {full[i + 1]}@{sb}")
            detail = "; ".join(hops)
            _record_violation({
                "kind": "lock-order-cycle",
                "edge": (a, b),
                "sites": (outer["site"], site),
                "cycle": full,
                "detail": f"acquisition-order cycle closed by "
                          f"{a}@{outer['site']} -> {b}@{site}: {detail}",
            })
    deadline = _hold_deadline_s()
    rec = {
        "name": name, "site": site,
        # detcheck: disable=DET006 -- host-side lock timing; never feeds a traced computation
        "t": time.monotonic(),
        "stack": (traceback.format_stack()[:-2] if deadline > 0
                  else None),
        "deadline": deadline,
    }
    stack.append(rec)
    return rec


def _note_released(name: str) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i]["name"] == name:
            rec = stack.pop(i)
            # detcheck: disable=DET006 -- host-side lock timing; never feeds a traced computation
            hold = time.monotonic() - rec["t"]
            _report_hold(name, hold)
            if rec["deadline"] > 0 and hold > rec["deadline"]:
                owner = "".join(rec["stack"] or ())
                _record_violation({
                    "kind": "held-past-deadline",
                    "lock": name, "site": rec["site"],
                    "hold_s": round(hold, 6),
                    "deadline_s": rec["deadline"],
                    "thread": threading.current_thread().name,
                    "stack": owner,
                    "detail": f"lock '{name}' held {hold:.3f}s "
                              f"(deadline {rec['deadline']}s) by "
                              f"{threading.current_thread().name}, "
                              f"acquired at {rec['site']}",
                })
            return


def _maybe_slow_hold(name: str) -> None:
    """The ``lock.slow_hold`` fault point: sleep while holding a named
    lock so the contention/hold-deadline paths are testable."""
    if name == "faults":
        # the probe runs THROUGH the fault harness: probing the
        # harness's own lock would re-enter fault_point and self-
        # deadlock on the non-reentrant lock just acquired
        return
    if getattr(_tls, "in_report", False):
        return
    _tls.in_report = True
    try:
        from ..utils import faults
        if faults.fault_flag("lock.slow_hold"):
            time.sleep(0.05)
    # tpulint: disable=TPL006 -- the fault probe is test-only; a broken
    # harness must never raise out of a lock acquire
    except Exception:
        pass
    finally:
        _tls.in_report = False


# ---------------------------------------------------------------------------
# wrapped primitives
# ---------------------------------------------------------------------------
class _ContractBase:
    """Shared acquire/release bookkeeping for Lock and RLock."""

    def __init__(self, name: str, raw: Any) -> None:
        self.name = name
        self._raw = raw

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        site = _caller_site()
        contended = False
        t0 = time.monotonic()
        got = self._raw.acquire(False)
        if not got:
            contended = True
            if not blocking:
                _report_wait(self.name, time.monotonic() - t0, True)
                return False
            got = (self._raw.acquire(True, timeout) if timeout >= 0
                   else self._raw.acquire(True))
        wait = time.monotonic() - t0
        _report_wait(self.name, wait, contended)
        if not got:
            return False
        _note_acquired(self.name, site)
        _maybe_slow_hold(self.name)
        return True

    def release(self) -> None:
        _note_released(self.name)
        self._raw.release()

    def locked(self) -> bool:
        raw_locked = getattr(self._raw, "locked", None)
        if raw_locked is not None:
            return bool(raw_locked())
        return any(rec["name"] == self.name            # rlock fallback
                   for rec in _held_stack())

    def held_by_me(self) -> bool:
        return any(rec["name"] == self.name for rec in _held_stack())

    def __enter__(self) -> "_ContractBase":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:                    # pragma: no cover
        return f"<{type(self).__name__} {self.name!r}>"


class ContractLock(_ContractBase):
    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Lock())


class ContractRLock(_ContractBase):
    def __init__(self, name: str) -> None:
        super().__init__(name, threading.RLock())


class ContractCondition(_ContractBase):
    """Condition wrapper: ``wait`` surrenders the held record for its
    duration (the underlying lock really is released)."""

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Condition())

    def wait(self, timeout: Optional[float] = None) -> bool:
        _note_released(self.name)
        try:
            return self._raw.wait(timeout)
        finally:
            _note_acquired(self.name, _caller_site())

    def wait_for(self, predicate: Any,
                 timeout: Optional[float] = None) -> Any:
        _note_released(self.name)
        try:
            return self._raw.wait_for(predicate, timeout)
        finally:
            _note_acquired(self.name, _caller_site())

    def notify(self, n: int = 1) -> None:
        self._raw.notify(n)

    def notify_all(self) -> None:
        self._raw.notify_all()


class Guarded:
    """A value whose reads (:meth:`value`) and writes (:meth:`assign`)
    assert its lock is held by the calling thread — the runtime mirror
    of CON001.  A bare read/write records an ``unguarded-access``
    violation with the offender's file:line instead of raising
    (observability, not enforcement)."""

    def __init__(self, name: str, lock: Any, value: Any = None) -> None:
        self._name = name
        self._lock = lock
        self._value = value

    def _check(self, op: str) -> None:
        lk = self._lock
        ok = (lk.held_by_me() if isinstance(lk, _ContractBase)
              else True)
        if not ok:
            site = _caller_site()
            _record_violation({
                "kind": "unguarded-access",
                "name": self._name, "op": op, "site": site,
                "thread": threading.current_thread().name,
                "detail": f"{op} of guarded '{self._name}' at {site} "
                          f"without holding lock "
                          f"'{getattr(lk, 'name', '?')}'",
            })

    def value(self) -> Any:
        self._check("read")
        return self._value

    def assign(self, value: Any) -> None:
        self._check("write")
        self._value = value


# ---------------------------------------------------------------------------
# factories + inspection
# ---------------------------------------------------------------------------
def named_lock(name: str) -> Any:
    return ContractLock(name) if enabled() else threading.Lock()


def named_rlock(name: str) -> Any:
    return ContractRLock(name) if enabled() else threading.RLock()


def named_condition(name: str) -> Any:
    return (ContractCondition(name) if enabled()
            else threading.Condition())


def violations() -> List[Dict[str, Any]]:
    with _graph_lock:
        return list(_violations)


def snapshot() -> Dict[str, Any]:
    """Edges + per-lock stats (quantiles over the bounded sample ring),
    for tests and the watchdog forensic dump."""
    with _graph_lock:
        edges = {a: {b: sites for b, sites in inner.items()}
                 for a, inner in _edges.items()}
        stats: Dict[str, Any] = {}
        for name, st in _stats.items():
            waits = sorted(st["waits"])
            qs = {}
            for q in (50.0, 99.0):
                if waits:
                    idx = min(len(waits) - 1,
                              int(round((q / 100.0) * (len(waits) - 1))))
                    qs[q] = waits[idx]
            stats[name] = {
                "acquires": st["acquires"],
                "contended": st["contended"],
                "wait_max_s": st["wait_max_s"],
                "hold_max_s": st["hold_max_s"],
                "wait_quantiles_s": qs,
            }
        return {"edges": edges, "stats": stats,
                "violations": len(_violations)}


def reset() -> None:
    """Test isolation: drop the graph, stats, and violation log."""
    with _graph_lock:
        _edges.clear()
        _violations.clear()
        _stats.clear()
