"""Fleet observability primitives (ISSUE 17): the cross-rank half of
the per-process telemetry stack.

Four small, host-only pieces that ``parallel/elastic.py``,
``io/distributed.py`` and ``boosting/streaming.py`` plug into:

* **Clock alignment** — :func:`estimate_clock_offset` turns any
  "fetch the coordinator's wall clock" RPC into a midpoint-of-RTT
  offset estimate: ``offset = server_ts - (t_send + t_recv) / 2`` with
  error bound ``rtt / 2`` (the classic Cristian bound — the true
  offset lies within +-rtt/2 of the midpoint no matter how the
  one-way delays split).  The elastic client refreshes it per
  generation and installs it via :func:`set_clock`; telemetry then
  stamps ``clk_off_s`` into every trace record so
  ``tools/fleet_report.py`` can map all ranks onto the coordinator's
  clock (``corrected_ts = ts + clk_off_s``).
* **Collective wait accounting** — every host collective reports how
  its wall time split into ``wait_s`` (blocked on slower peers —
  arrival skew) vs ``xfer_s`` (the transport itself), keyed
  ``(site, generation, seq)`` so per-rank records of the same
  collective join exactly.  :func:`note_collective` aggregates the
  per-site totals this rank observed (waves, wait/xfer totals, how
  often THIS rank was the straggler — the last arrival waits ~0s);
  :func:`skew_snapshot` rides the run summary and
  :func:`merge_skew` lifts the per-rank sections into the
  ``collective_skew`` table of ``merged_summary``.
* **Recovery MTTR accounting** — :class:`RecoveryEpisode` carves one
  elastic recovery into contiguous phases
  ``detect -> resync -> reshard -> restore -> retrain`` (consecutive
  ``mark()`` boundaries partition the interval, so the per-phase
  durations sum EXACTLY to ``mttr_s`` by construction).  Episodes are
  recorded module-side (``recovery_episodes()``) independent of
  telemetry state — the chaos harness reads them from workers that
  never enabled tracing — and additionally emitted as
  ``elastic:recovery`` events carrying the phase breakdown.
* **The fleet ledger** — :class:`FleetLedger`, the coordinator's
  SIGKILL-survivable JSONL event history: no tmp files, no rename
  dance — one ``os.write`` on an ``O_APPEND`` fd per line, fsync'd
  line-at-a-time, so a killed coordinator leaves only complete,
  parseable lines behind.  This is the authoritative fleet history
  even when every worker died with its buffers.

Knobs: ``LGBM_TPU_CLOCK_SYNC`` (default on; ``0`` skips offset
estimation), ``LGBM_TPU_FLEET_LEDGER`` (ledger path; unset = no
ledger), ``LGBM_TPU_COLLECTIVE_SLOW`` (the ``collective.slow`` fault's
sub-deadline delay seconds, default 0.25).  All host-side; nothing in
this module reaches a traced program.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "clock_sync_enabled", "collective_slow_s", "ledger_path_env",
    "estimate_clock_offset", "set_clock", "clock", "next_seq",
    "note_collective", "skew_snapshot", "merge_skew",
    "RecoveryEpisode", "recovery_episodes", "FleetLedger",
    "read_ledger", "reset",
]

from .lock_contract import named_lock

_lock = named_lock("fleet")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------
def clock_sync_enabled() -> bool:
    """``LGBM_TPU_CLOCK_SYNC`` — on by default; ``0`` disables the
    per-generation offset estimation (records then carry no
    ``clk_off_s`` and the fleet report treats every rank as already on
    the coordinator clock)."""
    return os.environ.get("LGBM_TPU_CLOCK_SYNC", "1") != "0"


def collective_slow_s(deadline_s: Optional[float] = None) -> float:
    """The ``collective.slow`` fault's delay (``LGBM_TPU_COLLECTIVE_SLOW``
    seconds, default 0.25) — deliberately SUB-deadline: a straggler,
    not a lost rank.  Clamped to half the deadline so arming it can
    never turn skew injection into a spurious ``RankLostError``."""
    try:
        s = float(os.environ.get("LGBM_TPU_COLLECTIVE_SLOW", "0.25"))
    except ValueError:
        s = 0.25
    if s <= 0:
        s = 0.25
    if deadline_s and deadline_s > 0:
        s = min(s, max(deadline_s * 0.5, 0.01))
    return s


def ledger_path_env() -> Optional[str]:
    """``LGBM_TPU_FLEET_LEDGER`` — the coordinator ledger path."""
    return os.environ.get("LGBM_TPU_FLEET_LEDGER") or None


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------
_clock: Dict[str, Optional[float]] = {"offset_s": None, "err_s": None}


def estimate_clock_offset(fetch_server_ts: Callable[[], float],
                          samples: int = 4) -> Tuple[float, float]:
    """Midpoint-of-RTT offset of the server clock relative to this
    process: ``offset = server_ts - (t0 + t1) / 2`` from the
    minimum-RTT sample (the least-delayed exchange carries the
    tightest bound).  Returns ``(offset_s, err_s)`` with
    ``err_s = rtt_min / 2``; ``local_ts + offset_s`` lands on the
    server clock within ``+-err_s``."""
    best: Optional[Tuple[float, float]] = None
    for _ in range(max(int(samples), 1)):
        t0 = time.time()
        server_ts = float(fetch_server_ts())
        t1 = time.time()
        rtt = max(t1 - t0, 0.0)
        off = server_ts - (t0 + t1) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, off)
    assert best is not None
    return best[1], best[0] / 2.0


def set_clock(offset_s: float, err_s: Optional[float] = None) -> None:
    """Install this rank's coordinator-clock offset: telemetry stamps
    it into every subsequent trace record as ``clk_off_s``."""
    from . import telemetry
    with _lock:
        _clock["offset_s"] = float(offset_s)
        _clock["err_s"] = None if err_s is None else float(err_s)
    telemetry.set_clock_offset(float(offset_s))


def clock() -> Dict[str, Optional[float]]:
    with _lock:
        return dict(_clock)


# ---------------------------------------------------------------------------
# collective join keys + wait accounting
# ---------------------------------------------------------------------------
_seqs: Dict[str, int] = {}
_skew: Dict[str, Dict[str, Any]] = {}


def next_seq(site: str) -> int:
    """Per-site monotonic sequence for collectives that have no
    protocol-level round key (the jax / binfind allgathers).  Every
    rank runs the same collective schedule (the flight recorder
    gate), so equal sites count in lockstep and ``(site, seq)`` joins
    per-rank records of the same collective."""
    with _lock:
        _seqs[site] = _seqs.get(site, 0) + 1
        return _seqs[site]


def note_collective(site: str, generation: int, seq: int, wait_s: float,
                    xfer_s: float, nbytes: int = -1,
                    straggler: bool = False) -> None:
    """Accumulate this rank's wait/xfer split for one collective wave.
    ``straggler`` marks waves where THIS rank arrived last (it waited
    ~0s while every peer waited on it)."""
    del generation, seq                 # aggregated per site; the full
    #                                     join key lives on the record
    with _lock:
        st = _skew.get(site)
        if st is None:
            st = _skew[site] = {
                "waves": 0, "wait_total_s": 0.0, "wait_max_s": 0.0,
                "xfer_total_s": 0.0, "bytes_total": 0,
                "straggler_waves": 0,
            }
        st["waves"] += 1
        st["wait_total_s"] += wait_s if wait_s > 0.0 else 0.0
        if wait_s > st["wait_max_s"]:
            st["wait_max_s"] = wait_s
        st["xfer_total_s"] += xfer_s if xfer_s > 0.0 else 0.0
        if nbytes and nbytes > 0:
            st["bytes_total"] += nbytes
        if straggler:
            st["straggler_waves"] += 1


def skew_snapshot() -> Optional[Dict[str, Dict[str, Any]]]:
    """This rank's per-site wait accounting (rides the run summary as
    ``collective_skew``), or None when no collective reported."""
    with _lock:
        if not _skew:
            return None
        return {site: dict(st) for site, st in _skew.items()}


def merge_skew(rank_summaries: List[Dict[str, Any]]
               ) -> Optional[Dict[str, Any]]:
    """Lift the per-rank ``collective_skew`` sections into one fleet
    table: per site, each rank's total wait and straggler-wave count,
    plus the dominant straggler ("rank 2 last into ``hist_psum`` 87%
    of waves")."""
    sites: Dict[str, Dict[str, Any]] = {}
    nranks = len(rank_summaries)
    for r, s in enumerate(rank_summaries):
        for site, st in (s.get("collective_skew") or {}).items():
            agg = sites.setdefault(site, {
                "waves": 0,
                "per_rank_wait_s": [0.0] * nranks,
                "per_rank_straggler_waves": [0] * nranks,
                "wait_max_s": 0.0,
            })
            agg["waves"] = max(agg["waves"], int(st.get("waves", 0)))
            agg["per_rank_wait_s"][r] = round(
                float(st.get("wait_total_s", 0.0)), 6)
            agg["per_rank_straggler_waves"][r] = int(
                st.get("straggler_waves", 0))
            agg["wait_max_s"] = max(agg["wait_max_s"],
                                    float(st.get("wait_max_s", 0.0)))
    if not sites:
        return None
    for agg in sites.values():
        sw = agg["per_rank_straggler_waves"]
        total = sum(sw)
        if total:
            top = max(range(len(sw)), key=lambda r: sw[r])
            agg["straggler_rank"] = top
            agg["straggler_pct"] = round(100.0 * sw[top] / total, 1)
    return sites


# ---------------------------------------------------------------------------
# recovery MTTR accounting
# ---------------------------------------------------------------------------
RECOVERY_PHASES = ("detect", "resync", "reshard", "restore", "retrain")

_episodes: List[Dict[str, Any]] = []


class RecoveryEpisode:
    """One elastic recovery, carved into contiguous phases.

    The interval starts when the failed collective STARTED stalling
    (``stall_started``, monotonic — the deadline wait is the detect
    cost) and ends when training re-reaches the iteration it was at
    when the failure hit (``target_iter``).  ``mark(phase)`` closes
    the current phase at *now*; consecutive boundaries partition the
    interval, so ``mttr_s`` is DEFINED as the sum of the phase
    durations — the breakdown always sums to it exactly."""

    def __init__(self, error: str = "", generation: int = -1,
                 target_iter: int = 0,
                 stall_started: Optional[float] = None):
        now = time.monotonic()
        t0 = now if stall_started is None else float(stall_started)
        self._last = min(t0, now)
        self.error = str(error)
        self.generation = int(generation)
        self.target_iter = max(int(target_iter), 0)
        self.phases: Dict[str, float] = {}
        self.closed = False

    def mark(self, phase: str) -> None:
        """Close the running phase at now (repeat marks accumulate)."""
        if self.closed:
            return
        now = time.monotonic()
        self.phases[phase] = (self.phases.get(phase, 0.0)
                              + max(now - self._last, 0.0))
        self._last = now

    def finish(self, **extra: Any) -> Optional[Dict[str, Any]]:
        """Close the episode (the open tail is the ``retrain`` phase),
        record it module-side and emit the ``elastic:recovery`` event
        carrying the phase breakdown.  Returns the episode record."""
        if self.closed:
            return None
        self.mark("retrain")
        self.closed = True
        phases = {p: round(self.phases.get(p, 0.0), 6)
                  for p in RECOVERY_PHASES}
        rec: Dict[str, Any] = {
            "error": self.error, "generation": self.generation,
            "target_iter": self.target_iter,
            "phases": phases,
            "mttr_s": sum(phases.values()),
        }
        rec.update(extra)
        with _lock:
            _episodes.append(rec)
        from .telemetry import counter_add, event
        counter_add("elastic.recovery_episodes")
        event("elastic", "recovery", mttr_s=rec["mttr_s"],
              error=self.error, generation=self.generation,
              target_iter=self.target_iter,
              **{f"{p}_s": phases[p] for p in RECOVERY_PHASES})
        return rec

    def abandon(self) -> None:
        """A second interrupt landed before this episode closed: the
        new episode subsumes the interval; drop this one."""
        self.closed = True


def recovery_episodes() -> List[Dict[str, Any]]:
    """Every finished episode this process recorded (chaos workers
    ship this list in their result JSON; works with telemetry off)."""
    with _lock:
        return [dict(e) for e in _episodes]


# ---------------------------------------------------------------------------
# the coordinator's SIGKILL-survivable ledger
# ---------------------------------------------------------------------------
class FleetLedger:
    """Append-only JSONL event ledger: one ``os.write`` of a complete
    line on an ``O_APPEND`` fd, fsync'd per line — no tmp file, no
    rename, so a SIGKILL leaves only whole lines (every prior line is
    already durable and parseable)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        self._fd: Optional[int] = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._wlock = named_lock("fleet_ledger")

    def put_line(self, kind: str, **fields: Any) -> None:
        # detcheck: disable=DET006 -- ledger lines carry operator-facing wall-clock timestamps; never traced
        rec: Dict[str, Any] = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        line = (json.dumps(rec) + "\n").encode()
        with self._wlock:
            if self._fd is None:
                return
            try:
                os.write(self._fd, line)
                os.fsync(self._fd)
            except OSError:
                pass                # a full disk must not kill the fleet

    def close(self) -> None:
        with self._wlock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


def read_ledger(path: str) -> List[Dict[str, Any]]:
    """Parse a ledger strictly: every non-empty line must be valid
    JSON (the SIGKILL-survivability contract) — a torn line raises
    ``ValueError`` naming its line number."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                raise ValueError(
                    f"{path}:{i}: unparseable ledger line "
                    f"({line[:60]!r})") from None
    return out


def reset() -> None:
    """Forget per-run fleet state (tests; rides ``telemetry.reset``)."""
    with _lock:
        _seqs.clear()
        _skew.clear()
        _episodes.clear()
        _clock["offset_s"] = None
        _clock["err_s"] = None
