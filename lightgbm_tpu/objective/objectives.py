"""Objective functions: gradients/hessians as pure jnp transforms.

TPU-native counterparts of the reference objective classes
(`/root/reference/src/objective/regression_objective.hpp`,
`binary_objective.hpp`, `multiclass_objective.hpp`, `rank_objective.hpp`,
`xentropy_objective.hpp`; factory `objective_function.cpp:10-47`).  The
reference computes per-row gradients in OpenMP loops; here every objective
is one vectorized ``get_gradients(score) -> (grad, hess)`` suitable for
fusion into the jitted boosting step.  Interface parity:

* ``boost_from_score()`` — initial score (``BoostFromScore``,
  `objective_function.h:45`).
* ``renew_tree_output(...)`` — leaf re-fitting for percentile-based
  objectives (L1/quantile/MAPE — ``RenewTreeOutput``,
  `objective_function.h:40`, `regression_objective.hpp:196-259`).
* ``num_model_per_iteration`` — K trees/iter for multiclass
  (`objective_function.h:49`).
* ``convert_output`` — link inversion for prediction
  (sigmoid/exp/softmax).
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config


def _apply_weight(grad, hess, weight):
    if weight is None:
        return grad, hess
    return grad * weight, hess * weight


class ObjectiveFunction:
    """Base class (reference include/LightGBM/objective_function.h:14-79)."""
    name = "none"
    num_model_per_iteration = 1
    is_constant_hessian = False
    need_renew_tree_output = False

    def __init__(self, config: Config, metadata=None):
        self.config = config
        self.label: Optional[jnp.ndarray] = None
        self.weight: Optional[jnp.ndarray] = None
        self.query_boundaries = None
        self.num_data = 0

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        # host copies kept alongside the device arrays: BoostFromScore
        # runs once at booster init, where every eager device op over a
        # remote-TPU tunnel costs a ~1s mini-compile (label/weight arrive
        # host-side anyway, so this is free)
        self._label_np = (np.asarray(metadata.label, np.float32)
                          if metadata.label is not None
                          else np.zeros(num_data, np.float32))
        self._weight_np = (np.asarray(metadata.weight, np.float32)
                           if metadata.weight is not None else None)
        self.label = (jnp.asarray(metadata.label, jnp.float32)
                      if metadata.label is not None else jnp.zeros(num_data))
        self.weight = (jnp.asarray(metadata.weight, jnp.float32)
                       if metadata.weight is not None else None)
        if metadata.query_boundaries is not None:
            self.query_boundaries = np.asarray(metadata.query_boundaries)
        self._check_label()

    def _host_label_mean(self) -> float:
        """Weighted label mean, on host (see init)."""
        y = self._label_np
        if self._weight_np is not None:
            w = self._weight_np
            return float((y * w).sum() / w.sum())
        return float(y.mean())

    # True when boost_from_score keys on the WEIGHTED label mean
    # (xentlambda uses the unweighted one); multi-process init uses this
    # to pick the right global sufficient statistic
    boost_mean_weighted = True

    def globalize_rows(self, globalize, allgather) -> None:
        """Multi-process training: re-align per-row state to the GLOBAL
        row axis and recompute whole-dataset statistics with
        cross-process sufficient stats.  ``globalize(np [n_local, ...])
        -> global row-sharded array`` (pad rows 0); ``allgather(obj) ->
        per-rank list``.  Subclasses with extra per-row arrays or
        dataset-level scalars MUST override (and call super)."""
        self.label = globalize(np.asarray(self._label_np, np.float32))
        if self.weight is not None:
            self.weight = globalize(np.asarray(self._weight_np,
                                               np.float32))

    def boost_from_score_global(self, allgather) -> float:
        """Cross-process BoostFromScore: every current objective's init
        score is a function of the (un)weighted label mean, so allgather
        that sufficient statistic and re-derive through the objective's
        own link (logit/log/...) by evaluating boost_from_score on a
        one-row stand-in.  An objective whose init score is NOT a mean
        function (e.g. a future reference-parity weighted-median L1
        boost) MUST override with its own global statistic."""
        y = np.asarray(self._label_np, np.float64)
        use_w = self.boost_mean_weighted and self._weight_np is not None
        w = (np.asarray(self._weight_np, np.float64) if use_w
             else np.ones_like(y))
        sums = allgather([float((y * w).sum()), float(w.sum())])
        gmean = (sum(s[0] for s in sums)
                 / max(sum(s[1] for s in sums), 1e-30))
        saved = (self._label_np, self._weight_np)
        try:
            self._label_np = np.array([gmean], np.float64)
            self._weight_np = None
            return self.boost_from_score()
        finally:
            self._label_np, self._weight_np = saved

    def _check_label(self) -> None:
        pass

    def get_gradients(self, score: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def boost_from_score(self) -> float:
        return 0.0

    def convert_output(self, score: jnp.ndarray) -> jnp.ndarray:
        return score

    def renew_tree_output(self, score, row_leaf, num_leaves):
        """Return per-leaf output corrections, or None."""
        return None

    def to_string(self) -> str:
        return self.name


# ---------------------------------------------------------------------------
# Regression family (reference regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            self.raw_label = self.label
            self._label_np = (np.sign(self._label_np)
                              * np.sqrt(np.abs(self._label_np)))
            self.label = jnp.asarray(self._label_np)

    def get_gradients(self, score):
        grad = score - self.label
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, self.weight)

    def boost_from_score(self):
        # weighted mean label (regression_objective.hpp BoostFromScore)
        return self._host_label_mean()

    def convert_output(self, score):
        if self.sqrt:
            return jnp.sign(score) * score * score
        return score


class RegressionL1(ObjectiveFunction):
    name = "regression_l1"
    is_constant_hessian = True
    need_renew_tree_output = True
    _percentile = 0.5

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, self.weight)

    def renew_tree_output(self, score, row_leaf, num_leaves):
        # leaf output := percentile of (label - score) in the leaf
        # (RenewTreeOutput, regression_objective.hpp:196-259)
        return _leaf_percentile(self.label - score, row_leaf, num_leaves,
                                self._percentile, self.weight)


class Huber(ObjectiveFunction):
    name = "huber"
    is_constant_hessian = True

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.clip(diff, -self.alpha, self.alpha)
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, self.weight)


class Fair(ObjectiveFunction):
    name = "fair"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        diff = score - self.label
        denom = jnp.abs(diff) + self.c
        grad = self.c * diff / denom
        hess = self.c * self.c / (denom * denom)
        return _apply_weight(grad, hess, self.weight)


class Poisson(ObjectiveFunction):
    name = "poisson"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.max_delta_step = float(config.poisson_max_delta_step)

    def _check_label(self):
        if (self._label_np < 0).any():
            raise ValueError("poisson objective requires non-negative labels")

    def get_gradients(self, score):
        es = jnp.exp(score)
        grad = es - self.label
        hess = jnp.exp(score + self.max_delta_step)
        return _apply_weight(grad, hess, self.weight)

    def boost_from_score(self):
        return float(np.log(max(self._host_label_mean(), 1e-20)))

    def convert_output(self, score):
        return jnp.exp(score)


class Quantile(ObjectiveFunction):
    name = "quantile"
    is_constant_hessian = True
    need_renew_tree_output = True

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.where(diff >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, self.weight)

    def renew_tree_output(self, score, row_leaf, num_leaves):
        return _leaf_percentile(self.label - score, row_leaf, num_leaves,
                                self.alpha, self.weight)


class Mape(ObjectiveFunction):
    name = "mape"
    is_constant_hessian = True
    need_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(self.label))
        self.label_weight = lw if self.weight is None else lw * self.weight

    def globalize_rows(self, globalize, allgather):
        lw = np.asarray(self.label_weight, np.float32)
        super().globalize_rows(globalize, allgather)
        self.label_weight = globalize(lw)       # per-row state realigns

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff) * self.label_weight
        hess = jnp.ones_like(score) * (
            self.label_weight if self.weight is None else self.weight)
        return grad, hess

    def renew_tree_output(self, score, row_leaf, num_leaves):
        return _leaf_percentile(self.label - score, row_leaf, num_leaves,
                                0.5, self.label_weight)


class Gamma(Poisson):
    name = "gamma"

    def get_gradients(self, score):
        ems = jnp.exp(-score)
        grad = 1.0 - self.label * ems
        hess = self.label * ems
        return _apply_weight(grad, hess, self.weight)


class Tweedie(Poisson):
    name = "tweedie"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -self.label * e1 + e2
        hess = (-self.label * (1.0 - self.rho) * e1
                + (2.0 - self.rho) * e2)
        return _apply_weight(grad, hess, self.weight)


# ---------------------------------------------------------------------------
# Binary (reference binary_objective.hpp:13-157)
# ---------------------------------------------------------------------------
class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        self.label_weights = (1.0, 1.0)

    def _check_label(self):
        u = np.unique(self._label_np)
        if not np.all(np.isin(u, [0.0, 1.0])):
            raise ValueError("binary objective requires labels in {0, 1}")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        cnt_pos = float((self._label_np > 0).sum())
        cnt_neg = float(num_data - cnt_pos)
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            # weight the smaller class up (binary_objective.hpp Init)
            if cnt_pos > cnt_neg:
                self.label_weights = (1.0, cnt_pos / cnt_neg)
            else:
                self.label_weights = (cnt_neg / cnt_pos, 1.0)
        else:
            self.label_weights = (1.0, self.scale_pos_weight)
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg

    def globalize_rows(self, globalize, allgather):
        super().globalize_rows(globalize, allgather)
        if self.is_unbalance:
            # class counts are a GLOBAL statistic: per-shard counts
            # would bake different scalars into the same SPMD program
            counts = allgather([self._cnt_pos, self._cnt_neg])
            cnt_pos = sum(c[0] for c in counts)
            cnt_neg = sum(c[1] for c in counts)
            self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg
            if cnt_pos > 0 and cnt_neg > 0:
                self.label_weights = ((1.0, cnt_pos / cnt_neg)
                                      if cnt_pos > cnt_neg
                                      else (cnt_neg / cnt_pos, 1.0))

    def get_gradients(self, score):
        y = self.label
        p = jax.nn.sigmoid(self.sigmoid * score)
        w_cls = jnp.where(y > 0, self.label_weights[1], self.label_weights[0])
        grad = self.sigmoid * (p - y) * w_cls
        hess = self.sigmoid * self.sigmoid * p * (1.0 - p) * w_cls
        return _apply_weight(grad, hess, self.weight)

    def boost_from_score(self):
        # avg label -> logit / sigmoid (binary_objective.hpp BoostFromScore)
        pavg = min(max(self._host_label_mean(), 1e-15), 1.0 - 1e-15)
        return np.log(pavg / (1.0 - pavg)) / self.sigmoid

    def convert_output(self, score):
        return jax.nn.sigmoid(self.sigmoid * score)

    def to_string(self):
        return f"binary sigmoid:{self.sigmoid}"


# ---------------------------------------------------------------------------
# Multiclass (reference multiclass_objective.hpp:16-225)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class

    def _check_label(self):
        lab = self._label_np
        if lab.min() < 0 or lab.max() >= self.num_class:
            raise ValueError(
                f"multiclass labels must be in [0, {self.num_class})")

    def get_gradients(self, score):
        """score: [n, K] raw scores -> grad/hess [n, K]."""
        p = jax.nn.softmax(score, axis=-1)
        y = jax.nn.one_hot(self.label.astype(jnp.int32), self.num_class)
        grad = p - y
        hess = 2.0 * p * (1.0 - p)      # factor-2 upper bound, like reference
        if self.weight is not None:
            grad = grad * self.weight[:, None]
            hess = hess * self.weight[:, None]
        return grad, hess

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=-1)

    def to_string(self):
        return f"multiclass num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class
        self.sigmoid = float(config.sigmoid)

    def get_gradients(self, score):
        y = jax.nn.one_hot(self.label.astype(jnp.int32), self.num_class)
        p = jax.nn.sigmoid(self.sigmoid * score)
        grad = self.sigmoid * (p - y)
        hess = self.sigmoid * self.sigmoid * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[:, None]
            hess = hess * self.weight[:, None]
        return grad, hess

    def convert_output(self, score):
        return jax.nn.sigmoid(self.sigmoid * score)

    def to_string(self):
        return f"multiclassova num_class:{self.num_class} sigmoid:{self.sigmoid}"


# ---------------------------------------------------------------------------
# Cross-entropy (reference xentropy_objective.hpp:39-270)
# ---------------------------------------------------------------------------
class CrossEntropy(ObjectiveFunction):
    name = "xentropy"

    def _check_label(self):
        lab = self._label_np
        if lab.min() < 0 or lab.max() > 1:
            raise ValueError("xentropy labels must be in [0, 1]")

    def get_gradients(self, score):
        p = jax.nn.sigmoid(score)
        grad = p - self.label
        hess = p * (1.0 - p)
        return _apply_weight(grad, hess, self.weight)

    def boost_from_score(self):
        pavg = min(max(self._host_label_mean(), 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, score):
        return jax.nn.sigmoid(score)


class CrossEntropyLambda(ObjectiveFunction):
    name = "xentlambda"
    boost_mean_weighted = False   # boost_from_score uses the plain mean

    def get_gradients(self, score):
        # intensity parameterization: p = 1 - exp(-w*exp(score))
        # (xentropy_objective.hpp:142-238)
        w = self.weight if self.weight is not None else 1.0
        es = jnp.exp(score)
        z = w * es
        emz = jnp.exp(-z)
        p = 1.0 - emz
        p = jnp.clip(p, 1e-15, 1 - 1e-15)
        grad = z * (1.0 - self.label / p * emz)
        hess = z * (1.0 - self.label / p * emz * (1.0 - z * (1 - p) / p))
        hess = jnp.maximum(hess, 1e-15)
        return grad, hess

    def boost_from_score(self):
        pavg = float(self._label_np.mean())
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return float(np.log(-np.log1p(-pavg)))

    def convert_output(self, score):
        return 1.0 - jnp.exp(-jnp.exp(score))


# ---------------------------------------------------------------------------
# LambdaRank (reference rank_objective.hpp:19-245)
# ---------------------------------------------------------------------------
class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"

    def __init__(self, config, metadata=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.max_position = int(config.max_position)
        gains = config.label_gain
        if not gains:
            gains = tuple(float((1 << i) - 1) for i in range(31))
        self.label_gain = np.asarray(gains, np.float64)

    def globalize_rows(self, globalize, allgather):
        raise NotImplementedError(
            "lambdarank is not supported with MULTI-PROCESS training "
            "(documented descope): its per-query pair structures "
            "address rows by position, which the cross-process "
            "row-block layout breaks.  Single-process distributed "
            "training IS supported — tree_learner=data/voting on a "
            "multi-device mesh shards the histogram work while the "
            "objective sees the full row axis "
            "(tests/test_lambdarank.py::test_lambdarank_data_parallel_mesh).")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            raise ValueError("lambdarank requires query data")
        qb = self.query_boundaries
        sizes = (qb[1:] - qb[:-1]).astype(np.int64)
        self.max_query = int(sizes.max())
        labels = self._label_np

        # Queries BUCKETED by ceil-pow2 size: padding every query to the
        # global max wastes ~10x at MSLR shape (mean ~120 docs, max
        # ~1.2k), and the r4 [nq, M, M] full pair grid was out of
        # memory by orders of magnitude there (VERDICT r5 #2).  Within
        # a bucket the pair grid is [T, M]: rows = the top-T
        # score-sorted positions (T = truncation), cols = all sorted
        # positions, pairs r < c — exactly the reference's loop
        # structure (rank_objective.hpp:75-81: `for i < truncation_level;
        # for j = i+1`), so pair count is O(T * docs), not O(docs^2).
        by_size: dict = {}
        for q, s in enumerate(sizes):
            Mb = 1 << max(4, int(s - 1).bit_length())
            by_size.setdefault(Mb, []).append(q)
        self.discounts = jnp.asarray(
            1.0 / np.log2(np.arange(max(by_size) + 1) + 2.0), jnp.float32)
        self.buckets = []
        for Mb in sorted(by_size):
            qs = np.asarray(by_size[Mb], np.int64)
            T = min(self.max_position, Mb)
            idx = qb[:-1][qs, None] + np.arange(Mb)[None, :]
            valid = np.arange(Mb)[None, :] < sizes[qs, None]
            idx = np.where(valid, idx, 0)
            lab = np.where(valid, labels[idx.astype(np.int64)], -1)
            gain = np.where(valid,
                            self.label_gain[lab.astype(int) * (lab >= 0)],
                            0.0)
            # inverse max DCG at truncation (rank_objective.hpp:46-73),
            # bucket-vectorized (a per-query python loop took minutes
            # at 30k queries)
            disc = 1.0 / np.log2(np.arange(T) + 2.0)
            top = -np.sort(-np.where(valid, lab, -1), axis=1)[:, :T]
            ideal = np.where(top >= 0,
                             self.label_gain[top.astype(int) * (top >= 0)],
                             0.0)
            dcg = (ideal * disc[None, :]).sum(axis=1)
            imd = np.where(dcg > 0, 1.0 / np.maximum(dcg, 1e-300), 0.0)
            self.buckets.append({
                "M": Mb, "T": T,
                "idx": jnp.asarray(idx, jnp.int32),
                "valid": jnp.asarray(valid),
                "label": jnp.asarray(np.where(valid, lab, -1), jnp.float32),
                "gain": jnp.asarray(gain, jnp.float32),
                "imd": jnp.asarray(imd, jnp.float32),
            })

    def get_gradients(self, score):
        """Pairwise NDCG-delta-weighted lambdas over the bucketed
        [T, M] sorted-position pair grids (see ``init``).  Traceable —
        runs inside the fused training block.

        Each bucket dispatch is wrapped in an ``obj.rank_grad.<M>``
        telemetry span (ISSUE 9 satellite): on the eager/debug paths
        the spans attribute per-bucket wall-clock (which query-size
        class of the MSLR mix dominates the 0.27x ranking leg); inside
        a traced block they record trace-time and bucket counts.  The
        ``rank_grad`` bench table measures the same mix end-to-end."""
        from .. import obs
        grad = jnp.zeros_like(score)
        hess = jnp.zeros_like(score)
        # pair-grid entries per dispatched chunk: bounds the [C, T, M]
        # intermediates (~10 live f32 arrays) to a few hundred MB of HBM
        budget = int(os.environ.get("LGBM_TPU_RANK_CHUNK_PAIRS", 8_000_000))
        for bk in self.buckets:
            Mb, T = bk["M"], bk["T"]
            nq = bk["idx"].shape[0]
            C = max(1, min(nq, budget // max(1, T * Mb)))
            with obs.span(f"obj.rank_grad.{Mb}", queries=nq, pair_rows=T):
                g, h = _lambdarank_bucket_grads(
                    score[bk["idx"]], bk["valid"], bk["label"], bk["gain"],
                    bk["imd"], self.discounts[:Mb],
                    jnp.float32(self.sigmoid), T=T, C=C)
                grad = grad.at[bk["idx"].ravel()].add(
                    jnp.where(bk["valid"], g, 0.0).ravel())
                hess = hess.at[bk["idx"].ravel()].add(
                    jnp.where(bk["valid"], h, 0.0).ravel())
        return grad, hess

    def to_string(self):
        return "lambdarank"


def _fold_pair_grid(signed, hh, T, M):
    """Fold one query's [T, M] pair grids to per-doc grad/hess rows.

    Partition-independent by construction: rows of one query are never
    split across shards (ranking descopes row-blocked streaming), so
    the fold order is fixed by the in-query sort alone — registered as
    a sanctioned numcheck context
    (tools/numcheck/reduction_registry.py)."""
    g_sorted = (jnp.pad(jnp.sum(signed, axis=1), (0, M - T))
                - jnp.sum(signed, axis=0))
    h_sorted = (jnp.pad(jnp.sum(hh, axis=1), (0, M - T))
                + jnp.sum(hh, axis=0))
    return g_sorted, h_sorted


@functools.partial(jax.jit, static_argnames=("T", "C"))
def _lambdarank_bucket_grads(s, valid, label, gain, imd, disc, sigma,
                             *, T: int, C: int):
    """(grad, hess) per padded doc slot for one query-size bucket.

    Per query (vmapped, ``lax.map``-chunked by ``C`` queries): sort docs
    by score desc, then the pair grid is ``[T, M]`` over SORTED
    positions — rows the top-T positions, cols all positions, a pair
    live when ``col > row``, both valid, labels differ.  Since row <
    col, "min position < truncation" (the reference's pair condition,
    rank_objective.hpp:75-81) is exactly "row < T".  Each unordered
    pair appears once; the better-labeled side receives ``lam``, the
    worse ``-lam``, both receive ``+hess`` — summed along grid axes and
    scattered back through the sort permutation.
    """
    nq, M = s.shape

    def per_query(args):
        s, valid, label, gain, imd = args
        sm = jnp.where(valid, s, -jnp.inf)
        order = jnp.argsort(-sm)
        s_s = sm[order]
        lab_s = label[order]
        gain_s = gain[order]
        val_s = valid[order]
        dl = lab_s[:T, None] - lab_s[None, :]
        pv = ((jnp.arange(M)[None, :] > jnp.arange(T)[:, None])
              & val_s[None, :] & val_s[:T, None] & (dl != 0))
        delta = jnp.abs((gain_s[:T, None] - gain_s[None, :])
                        * (disc[:T, None] - disc[None, :])) * imd
        better_row = dl > 0
        sd = s_s[:T, None] - s_s[None, :]
        sig = jax.nn.sigmoid(-sigma * jnp.where(better_row, sd, -sd))
        lam = jnp.where(pv, -sigma * sig * delta, 0.0)
        hh = jnp.where(pv, sigma * sigma * sig * (1.0 - sig) * delta, 0.0)
        row_sign = jnp.where(better_row, 1.0, -1.0)
        signed = lam * row_sign
        # accumulate in SORTED coordinates, then one inverse-permutation
        # gather back — the equivalent per-original-index scatter-adds
        # (4 of them) are the slow path on TPU
        g_sorted, h_sorted = _fold_pair_grid(signed, hh, T, M)
        inv = jnp.argsort(order)
        return g_sorted[inv], h_sorted[inv]

    if C >= nq:
        return jax.vmap(per_query)((s, valid, label, gain, imd))
    # chunk the query axis: [ceil(nq/C), C, ...] with dummy (all-invalid)
    # pad queries, sequenced by lax.map so only one [C, T, M] grid set
    # is live at a time
    NC = -(-nq // C)
    pad = NC * C - nq

    def padq(a, fill):
        if pad == 0:
            return a.reshape((NC, C) + a.shape[1:])
        return jnp.concatenate(
            [a, jnp.full((pad,) + a.shape[1:], fill, a.dtype)]
        ).reshape((NC, C) + a.shape[1:])

    g, h = jax.lax.map(
        jax.vmap(per_query),
        (padq(s, 0.0), padq(valid, False), padq(label, -1.0),
         padq(gain, 0.0), padq(imd, 0.0)))
    return (g.reshape(NC * C, M)[:nq], h.reshape(NC * C, M)[:nq])


class CustomObjective(ObjectiveFunction):
    """Wraps a user fobj(score, dataset) -> (grad, hess) (the reference's
    Python custom-objective path, engine.py fobj)."""
    name = "none"

    def __init__(self, config, fobj=None):
        super().__init__(config)
        self.fobj = fobj

    def get_gradients(self, score):
        raise RuntimeError("custom objective gradients are supplied externally")


OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": Mape,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "xentropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (reference objective_function.cpp:10-47)."""
    if config.objective == "none":
        fobj = config.extra.get("fobj")
        return CustomObjective(config, fobj) if fobj else None
    cls = OBJECTIVES.get(config.objective)
    if cls is None:
        raise ValueError(f"unknown objective {config.objective!r}")
    return cls(config)


def _leaf_percentile(values: jnp.ndarray, row_leaf: jnp.ndarray,
                     num_leaves: int, alpha: float,
                     weight: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Per-leaf (weighted) percentile of ``values`` — RenewTreeOutput's
    kernel (`regression_objective.hpp` PercentileFun/WeightedPercentileFun).

    Sort-based: rows sorted by (leaf, value); per-leaf quantile read at the
    interpolated offset.  Weighted variant uses the cumulative-weight
    crossing rule like the reference.
    """
    leaf = row_leaf.astype(jnp.int32)
    order = jnp.lexsort((values, leaf))
    sv = values[order]
    sl = leaf[order]
    n = values.shape[0]
    lid = jnp.arange(num_leaves)
    start = jnp.searchsorted(sl, lid, side="left")
    end = jnp.searchsorted(sl, lid, side="right")
    cnt = end - start

    if weight is None:
        pos = alpha * (cnt - 1).astype(jnp.float32)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.ceil(pos).astype(jnp.int32)
        frac = pos - lo
        vlo = sv[jnp.clip(start + lo, 0, n - 1)]
        vhi = sv[jnp.clip(start + hi, 0, n - 1)]
        out = vlo * (1 - frac) + vhi * frac
    else:
        sw = weight[order]
        cum_w = jnp.cumsum(sw)
        base = jnp.where(start > 0, cum_w[jnp.maximum(start - 1, 0)], 0.0)
        total = jnp.where(end > 0, cum_w[jnp.maximum(end - 1, 0)], 0.0) - base
        # first position where cumulative leaf weight >= alpha * total
        target = base + alpha * total
        pos = jnp.searchsorted(cum_w, target, side="left")
        pos = jnp.clip(pos, start, jnp.maximum(end - 1, start))
        out = sv[jnp.clip(pos, 0, n - 1)]
    return jnp.where(cnt > 0, out, 0.0)
