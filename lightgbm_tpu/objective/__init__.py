from .objectives import OBJECTIVES, ObjectiveFunction, create_objective
