"""Distributed ingest: feature-sharded bin finding + host collectives.

Counterpart of the reference's distributed loading branch
(`/root/reference/src/io/dataset_loader.cpp:744-993`): with rows sharded
across machines, no rank sees the full value distribution, so

1. the usable feature count is synced to the minimum across ranks
   (`GlobalSyncUpByMin`, `dataset_loader.cpp:821`),
2. each rank computes quantile bin mappers for ITS feature slice from its
   local rows (`:816-858`),
3. the serialized mappers are allgathered so every rank holds the
   identical full mapper list (`:860-880`).

The collective is injectable — mirroring the reference's pluggable
external collectives (`LGBM_NetworkInitWithFunctions`, `c_api.h:760`):

* :class:`ThreadedAllgather` — in-process world for tests and
  single-host multi-worker simulation,
* :func:`jax_process_allgather` — multi-host production seam over JAX's
  ``multihost_utils`` (DCN), used after ``jax.distributed.initialize``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..config import Config
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper

# allgather: (obj) -> list of every rank's obj, rank-ordered
AllgatherFn = Callable[[object], List[object]]


class RankLostError(RuntimeError):
    """A host collective blew its deadline: some rank stopped
    participating (dead, or wedged past ``LGBM_TPU_COLLECTIVE_DEADLINE_S``).
    Typed so the elastic recovery loop (``parallel/elastic.py``) can
    re-rendezvous instead of the whole job blocking forever — the
    failure mode both the reference and PR 1-13 still had.  NOT
    transient for the retry layer: retrying into the same dead world
    just burns another deadline."""

    def __init__(self, site: str, deadline_s: float, detail: str = ""):
        self.site = site
        self.deadline_s = float(deadline_s)
        msg = (f"collective {site!r} exceeded its {deadline_s:g}s "
               f"deadline; a rank is lost or wedged")
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


def collective_deadline_s() -> Optional[float]:
    """The host-collective deadline from ``LGBM_TPU_COLLECTIVE_DEADLINE_S``
    (seconds; unset/non-positive = block forever, the pre-elastic
    behavior)."""
    raw = os.environ.get("LGBM_TPU_COLLECTIVE_DEADLINE_S", "")
    if not raw:
        return None
    try:
        s = float(raw)
    except ValueError:
        return None
    return s if s > 0 else None


def deadline_call(fn: Callable, site: str,
                  deadline: Optional[float] = None):
    """Run ``fn()`` under the collective deadline: the call executes in
    a worker thread and a result must land within ``deadline`` seconds
    or a typed :class:`RankLostError` is raised (the blocked thread is
    daemonic and abandoned — a wedged DCN op cannot be cancelled from
    Python, but the caller gets control back to re-rendezvous).

    The ``collective.hang`` fault point fires here as a *silent* sleep
    past the deadline (``utils/faults.fault_flag``) — it exercises
    detection (the deadline path), unlike ``collective.allgather`` which
    raises and exercises retry.  With no deadline configured the call
    runs inline, zero overhead."""
    from ..utils.faults import fault_flag
    if deadline is None:
        deadline = collective_deadline_s()
    hang = fault_flag("collective.hang")
    if deadline is None:
        if hang:
            time.sleep(0.05)        # armed but undeadlined: token stall
        return fn()
    done = threading.Event()
    box: dict = {}

    def run():
        if hang:
            # sleep PAST the deadline, then still complete: the caller
            # must already have raised — detection, not data loss
            time.sleep(deadline * 1.5 + 0.05)
        try:
            box["value"] = fn()
        # tpulint: disable=TPL006 -- not swallowed: the caller re-raises
        # box["error"] after done.wait() (unless the deadline already
        # fired, in which case RankLostError preempted this result)
        except BaseException as exc:    # noqa: BLE001
            box["error"] = exc
        finally:
            done.set()

    t = threading.Thread(target=run, name=f"lgbm-tpu-collective-{site}",
                         daemon=True)
    t.start()
    if not done.wait(deadline):
        from ..obs import counter_add, event
        counter_add("collective.deadline_exceeded")
        event("elastic", "rank_lost", site=site, deadline_s=deadline)
        raise RankLostError(site, deadline)
    # success path: `done` is set so the worker is past its useful
    # life — reap it (bounded-shutdown contract; only the deadline
    # path above abandons the daemonized thread, by design)
    t.join(timeout=1.0)
    if "error" in box:
        raise box["error"]
    return box["value"]


class ThreadedAllgather:
    """Barrier-synchronized in-process allgather for a thread-per-rank
    world (the test harness's stand-in for DCN collectives)."""

    def __init__(self, world: int):
        self.world = world
        self._barrier = threading.Barrier(world)
        self._buf: List[object] = [None] * world

    def for_rank(self, rank: int) -> AllgatherFn:
        def allgather(obj):
            self._buf[rank] = obj
            self._barrier.wait()
            out = list(self._buf)
            self._barrier.wait()
            return out
        return allgather


def jax_process_allgather(obj) -> List[object]:
    """Multi-host allgather of a JSON-serializable object over DCN
    (requires ``jax.distributed.initialize``; one entry per process).

    Retried with exponential backoff on RPC-transient failures (a DCN
    blip during a week-long run must not kill it); the
    ``collective.allgather`` fault point sits in front for the
    robustness tests."""
    from ..obs import enabled as obs_enabled
    from ..obs import fleet, span
    from ..obs.flight_recorder import record as fr_record
    from ..utils.faults import fault_point
    from ..utils.retry import retry_call

    site = "io.distributed.jax_process_allgather"
    info: dict = {}

    def _gather():
        fault_point("collective.allgather")
        import jax
        from jax.experimental import multihost_utils
        payload = json.dumps(obj).encode()
        n = np.frombuffer(payload, np.uint8)
        # the size row doubles as the arrival stamp — [nbytes, entry_us]
        # rides the int64 gather every rank already issues, so the
        # collective schedule is unchanged and max(entry) - mine is this
        # rank's arrival skew (raw wall clocks; the fleet report applies
        # clk_off_s when it folds ranks onto one timeline)
        # detcheck: disable=DET006 -- arrival stamp is observability metadata; it rides the gather but never feeds a traced computation
        entry_us = int(time.time() * 1e6)
        sizes = multihost_utils.process_allgather(
            np.array([len(n), entry_us], np.int64))
        sz = np.asarray(sizes).reshape(-1, 2)
        cap = int(sz[:, 0].max())
        padded = np.zeros(cap, np.uint8)
        padded[:len(n)] = n
        gathered = multihost_utils.process_allgather(padded)
        g = np.asarray(gathered).reshape(len(sz), cap)
        info["bytes"] = int(len(n))
        info["entry_us"] = [int(v) for v in sz[:, 1]]
        info["my_us"] = entry_us
        # tpulint: disable=TPL001 -- process_index() is a host-side int, not a traced array
        info["rank"] = int(jax.process_index())
        return [json.loads(bytes(g[r, :int(sz[r, 0])]).decode())
                for r in range(len(sz))]

    # one fingerprint per LOGICAL collective (outside the retry loop: a
    # retried rank joins the same collective late, it does not issue a
    # new one); payload sizes legitimately differ per rank, so only the
    # site+op enter the fingerprint
    fr_record(site, "process_allgather")
    # (site, seq) is the cross-rank join key: per-site counters advance
    # in lockstep because every rank runs the same collective schedule
    seq = fleet.next_seq(site)
    # span around the WHOLE retried call: collective wall-clock in the
    # run summary includes retries + backoff (what the run actually paid)
    # — under the deadline (RankLostError is not transient, so it cuts
    # through the retry policy instead of burning deadline x attempts)
    with span("collective.allgather", site=site, seq=seq) as sp:
        t0 = time.perf_counter()
        out = deadline_call(
            lambda: retry_call(_gather, what="collective.allgather"),
            site)
        dur = time.perf_counter() - t0
        ents = info.get("entry_us")
        if ents:
            last = max(ents)
            wait = max((last - info["my_us"]) / 1e6, 0.0)
            straggler = ents.index(last)
            sp["bytes"] = info["bytes"]
            sp["wait_s"] = round(wait, 6)
            sp["xfer_s"] = round(max(dur - wait, 0.0), 6)
            sp["arrive_ts"] = info["my_us"] / 1e6
            sp["straggler_rank"] = straggler
            if obs_enabled():
                fleet.note_collective(site, -1, seq, wait,
                                      max(dur - wait, 0.0), info["bytes"],
                                      straggler == info["rank"])
    return out


class ExternalCollectives:
    """C-function-pointer collective backend — the direct analog of
    ``LGBM_NetworkInitWithFunctions`` (c_api.h:760, `network.h:96`):
    a host app embeds the framework and supplies its OWN reduce-scatter
    and allgather implementations.

    Function signatures match the reference's ``ReduceScatterFunction`` /
    ``AllgatherFunction`` (`include/LightGBM/meta.h:48-56`)::

        void allgather(char* input, int input_size, const int* block_start,
                       const int* block_len, int num_block, char* output,
                       int output_size);
        void reduce_scatter(char* input, int input_size, int type_size,
                            const int* block_start, const int* block_len,
                            int num_block, char* output, int output_size,
                            const ReduceFunction reducer);

    The wrapped allgather is exposed in the :data:`AllgatherFn` shape used
    by :func:`find_bins_distributed`, so an embedded host can drive
    distributed ingest through its own transport."""

    def __init__(self, num_machines: int, rank: int,
                 reduce_scatter_addr: int, allgather_addr: int):
        import ctypes
        self.num_machines = int(num_machines)
        self.rank = int(rank)
        proto_ag = ctypes.CFUNCTYPE(
            None, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int)
        self._c_allgather = (proto_ag(int(allgather_addr))
                             if allgather_addr else None)
        self._reduce_scatter_addr = int(reduce_scatter_addr)

    def allgather(self, obj) -> List[object]:
        """JSON-object allgather over the injected C function.  Blocks are
        padded to a synced max size (the reference's fixed-size mapper
        allgather does the same, `dataset_loader.cpp:858-880`)."""
        import ctypes
        payload = json.dumps(obj).encode()
        # round 1: sync sizes (8-byte blocks)
        sizes = self._raw_allgather(
            len(payload).to_bytes(8, "little"), 8)
        lens = [int.from_bytes(sizes[r * 8:(r + 1) * 8], "little")
                for r in range(self.num_machines)]
        cap = max(lens)
        # round 2: the padded payloads
        out = self._raw_allgather(payload.ljust(cap, b"\0"), cap)
        return [json.loads(out[r * cap:r * cap + lens[r]].decode())
                for r in range(self.num_machines)]

    def _raw_allgather(self, block: bytes, block_size: int) -> bytes:
        import ctypes
        if self._c_allgather is None:
            raise RuntimeError("no allgather function installed")
        world = self.num_machines
        inp = ctypes.create_string_buffer(block, block_size)
        outp = ctypes.create_string_buffer(block_size * world)
        starts = (ctypes.c_int * world)(
            *[r * block_size for r in range(world)])
        lens = (ctypes.c_int * world)(*([block_size] * world))
        self._c_allgather(ctypes.cast(inp, ctypes.c_char_p), block_size,
                          starts, lens, world,
                          ctypes.cast(outp, ctypes.c_char_p),
                          block_size * world)
        return outp.raw


_external: List[Optional[ExternalCollectives]] = [None]


def install_external_collectives(num_machines: int, rank: int,
                                 reduce_scatter_addr: int,
                                 allgather_addr: int) -> None:
    _external[0] = ExternalCollectives(num_machines, rank,
                                       reduce_scatter_addr, allgather_addr)


def external_collectives() -> Optional[ExternalCollectives]:
    return _external[0]


def find_bins_distributed(X_local: np.ndarray,
                          config: Config,
                          rank: int,
                          num_machines: int,
                          allgather: AllgatherFn,
                          categorical_features: Sequence[int] = ()
                          ) -> List[BinMapper]:
    """Feature-sharded distributed bin finding -> full mapper list,
    identical on every rank (`dataset_loader.cpp:816-880`).

    Whatever collective backend the caller injects is wrapped in the
    shared retry policy, with the ``collective.allgather`` fault point
    in front — the seam the fault-injection tests drive.  The fault
    fires BEFORE the backend touches any rank-synchronization state, so
    a retried rank simply joins the collective late (the
    ThreadedAllgather barrier and the reference's blocking sockets both
    tolerate that)."""
    from ..obs import enabled as obs_enabled
    from ..obs import fleet, span
    from ..obs.flight_recorder import record as fr_record
    from ..utils.faults import fault_point
    from ..utils.retry import retrying
    inner = allgather
    site = "io.distributed.binfind_allgather"

    def _ag(obj):
        fault_point("collective.allgather")
        return inner(obj)

    _retry_ag = retrying(_ag, what="collective.allgather")

    # distinct span name: with the jax backend injected the transport
    # op times itself under "collective.allgather"; this one must not
    # double-count into the same bucket.  The payload rides wrapped as
    # {"_fleet_us": <entry wall-clock>, "o": obj} — every backend
    # (threaded / external-C / jax) passes dicts through unchanged, so
    # each rank learns the full arrival spread from the gather itself
    def allgather(obj):
        fr_record(site, "allgather")
        seq = fleet.next_seq(site)
        # detcheck: disable=DET006 -- arrival stamp is observability metadata; it rides the gather but never feeds a traced computation
        entry_us = int(time.time() * 1e6)
        with span("collective.binfind", site=site, seq=seq) as sp:
            # detcheck: disable=DET006 -- host-side span timing for the wait/xfer split; pure observability
            t0 = time.perf_counter()
            parts = deadline_call(
                lambda: _retry_ag({"_fleet_us": entry_us, "o": obj}),
                site)
            # detcheck: disable=DET006 -- host-side span timing for the wait/xfer split; pure observability
            dur = time.perf_counter() - t0
            try:
                ents = [int(p["_fleet_us"]) for p in parts]
                objs = [p["o"] for p in parts]
            except (TypeError, KeyError, ValueError):
                return parts    # a backend that rewrites payloads
            last = max(ents)
            wait = max((last - entry_us) / 1e6, 0.0)
            straggler = ents.index(last)
            sp["wait_s"] = round(wait, 6)
            sp["xfer_s"] = round(max(dur - wait, 0.0), 6)
            sp["arrive_ts"] = entry_us / 1e6
            sp["straggler_rank"] = straggler
            if obs_enabled():
                try:
                    nbytes = len(json.dumps(obj).encode())
                except (TypeError, ValueError):
                    nbytes = -1
                sp["bytes"] = nbytes
                fleet.note_collective(site, -1, seq, wait,
                                      max(dur - wait, 0.0), nbytes,
                                      straggler == rank)
        return objs
    cat_set = set(int(c) for c in categorical_features)
    # 1. sync feature count to the min across ranks (:821)
    counts = allgather(int(X_local.shape[1]))
    F = min(int(c) for c in counts)

    # 2. local bin finding for this rank's feature slice (:816-858)
    f_per = -(-F // num_machines)
    start = min(rank * f_per, F)
    end = min(start + f_per, F)
    sample_cnt = min(len(X_local), config.bin_construct_sample_cnt)
    rng = np.random.RandomState(config.data_random_seed + rank)
    idx = (np.arange(len(X_local)) if sample_cnt >= len(X_local)
           else np.sort(rng.choice(len(X_local), sample_cnt, replace=False)))
    local = []
    for f in range(start, end):
        m = BinMapper()
        col = X_local[idx, f].astype(np.float64)
        if f in cat_set:
            m.find_bin(col[~np.isnan(col)], len(col), config.max_bin,
                       config.min_data_in_bin, bin_type=BIN_CATEGORICAL,
                       use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing)
        else:
            nz = col[(col != 0.0) | np.isnan(col)]
            m.find_bin(nz, len(col), config.max_bin, config.min_data_in_bin,
                       bin_type=BIN_NUMERICAL, use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing)
        local.append((f, m.to_dict()))

    # 3. allgather serialized mappers; every rank rebuilds the full list
    #    (:860-880 — the reference ships fixed-size byte blocks; we ship
    #    (feature, dict) pairs through the injected collective)
    parts = allgather(local)
    full: List[Optional[BinMapper]] = [None] * F
    for part in parts:
        for f, d in part:
            full[int(f)] = BinMapper.from_dict(d)
    missing = [f for f, m in enumerate(full) if m is None]
    if missing:
        raise RuntimeError(f"distributed bin finding left features "
                           f"{missing} unmapped")
    return full
