"""Out-of-core ingest — the binned shard store (ROADMAP item 4).

The fork's signature delta over upstream LightGBM is per-rank sharded
data fetch from a distributed FS (``DownloadData``, reference
`application.cpp:168-237`), and the reference's ``.bin`` dataset cache
is what makes training beyond RAM practical.  This module is both,
done on our seams:

* **per-rank file-list sharding** over the ``utils/file_io.py`` scheme
  registry — rank ``r`` of ``S`` owns ``sources[r::S]``, each shard
  file ``localize()``-d (remote schemes download to a temp path) under
  the shared retry policy with the ``ingest.shard_fetch`` fault seam;
* **multi-file sampled bin finding** — the two-round loader's
  global-sample-index discipline (`io/loader.py load_file_two_round`)
  extended to a file LIST: row counts come from the same raw scan
  (``raw_data_row_count``), the sample is drawn over the concatenated
  global row space with the same ``data_random_seed`` RNG, and the
  mappers come from the same ``find_mappers_from_sample`` — so they
  are byte-identical to the in-memory path loading the concatenation
  (pinned by tests/test_outofcore.py);
* **an mmap-able binned shard cache** — the reference ``.bin`` analog:
  chunked parse → binned uint8/int32 row blocks appended to
  ``shard-<k>.bins`` (written tmp+rename, with the
  ``ingest.cache_write`` fault seam between chunks), a per-shard JSON
  sidecar published only after the blob, and a sha256'd ``manifest``
  written LAST via ``atomic_write`` — so a SIGKILL at any instant
  leaves either a valid complete cache or an obviously-incomplete one
  whose finished shards are reused on the next run (resumable
  mid-ingest) and whose torn shards are re-ingested, never trained on.

The cache is keyed on **source bytes + BinMapper-relevant config**
(``cache_key``): a changed source file or a changed binning knob
produces a different key, and ``load_store`` refuses a stale cache
instead of silently training on the wrong bins.

Training against the store is the streaming block trainer
(``boosting/streaming.py``): rows stay in this mmap cache and stream
through the device block-by-block (``LGBM_TPU_STREAM_ROWS``).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.faults import fault_point
from ..utils.file_io import atomic_write, localize, release
from ..utils.log import log_info, log_warning
from ..utils.retry import retry_call
from .dataset import BinnedDataset, Metadata, find_mappers_from_sample

STORE_VERSION = 1
MANIFEST = "manifest.json"

# binning-relevant config knobs the cache key covers: any change here
# changes the mappers, so it must invalidate the cache
_KEY_KNOBS = ("max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
              "data_random_seed", "use_missing", "zero_as_missing",
              "categorical_column", "label_column", "weight_column",
              "ignore_column", "has_header", "two_round_chunk_bytes")


def _sha256_bytes(*parts: bytes) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p)
    return h.hexdigest()


def mapper_digest(mappers) -> str:
    """Canonical sha256 over the BinMapper set — the bin-boundary
    identity the manifest records and ``load_store`` re-checks, so a
    cache binned under different boundaries can never silently train."""
    payload = json.dumps([m.to_dict() for m in mappers], sort_keys=True,
                         default=float).encode()
    return _sha256_bytes(payload)


def _config_key(config: Config) -> Dict:
    return {k: getattr(config, k, None) for k in _KEY_KNOBS}


def _source_fingerprint(path: str) -> Dict:
    """Cheap per-source identity: name + byte size (a full content
    sha256 is recorded per SHARD during ingest, where the bytes stream
    through anyway)."""
    return {"path": os.path.basename(path),
            "bytes": os.path.getsize(path) if os.path.exists(path) else -1}


def cache_key(sources: List[str], config: Config) -> str:
    """The store identity: source fingerprints + binning knobs."""
    payload = json.dumps({
        "version": STORE_VERSION,
        "sources": [_source_fingerprint(localize_probe(s)) for s in sources],
        "config": _config_key(config),
    }, sort_keys=True, default=str).encode()
    return _sha256_bytes(payload)


def localize_probe(path: str) -> str:
    """Local path for fingerprinting: identity for local files; remote
    schemes fingerprint by path only (size -1), so their staleness is
    caught by the per-shard source sha recorded at ingest."""
    return path if "://" not in path else path


def shard_sources(sources: List[str], rank: int, num_ranks: int
                  ) -> List[str]:
    """Per-rank file-list sharding (the ``DownloadData`` ownership rule:
    rank ``r`` fetches and ingests ``sources[r::S]``)."""
    return list(sources)[rank::max(1, num_ranks)]


# ---------------------------------------------------------------------------
# multi-file chunk streaming (shared parse discipline with io/loader.py)
# ---------------------------------------------------------------------------
def _file_plan(path: str, config: Config):
    """-> (fmt, sep, skip, header_names, chunk_stream_fn, n_rows)."""
    from .loader import detect_format, raw_data_row_count
    from .. import native
    fmt = detect_format(path, config.has_header)
    skip = 1 if config.has_header else 0
    header_names = None
    chunk_bytes = 4 << 20
    if fmt == "libsvm":
        scanned = native.scan_libsvm(path, skip) if native.available() else None
        if scanned is None:
            raise ValueError(
                "out-of-core ingest needs the native parser for libsvm "
                f"sources ({path!r})")
        n, fcols = scanned

        def stream(fc=fcols):
            return native.parse_libsvm_chunks(path, skip, fc,
                                              chunk_bytes=chunk_bytes)
        return fmt, " ", skip, None, stream, int(n), int(fcols) + 1
    sep = {"csv": ",", "tsv": "\t"}[fmt]
    if config.has_header:
        with open(path) as f:
            header_names = f.readline().rstrip("\n").split(sep)
    n = raw_data_row_count(path, skip)

    def stream():
        from .. import native as nat
        if nat.available():
            yield from nat.parse_delimited_chunks(path, sep, skip,
                                                  chunk_bytes=chunk_bytes)
            return
        # pure-python fallback: bounded line batches (tier-1 must not
        # depend on the native .so being buildable)
        import io as _io
        with open(path) as f:
            for _ in range(skip):
                f.readline()
            while True:
                lines = f.readlines(chunk_bytes)
                if not lines:
                    break
                body = "".join(ln for ln in lines if ln.strip())
                if not body:
                    continue
                arr = np.genfromtxt(_io.StringIO(body), delimiter=sep,
                                    dtype=np.float64)
                yield arr.reshape(-1, arr.shape[-1]) if arr.ndim else \
                    arr.reshape(1, -1)
    return fmt, sep, skip, header_names, stream, int(n), None


def find_mappers_multi(files: List[str], config: Config
                       ) -> Tuple[list, List[int], List[str], int,
                                  List[int], tuple]:
    """Round 1 of the two-round scheme over a file LIST: draw the bin-
    finding sample over the CONCATENATED global row space with the same
    RNG draw as the in-memory path, stream every file keeping only
    sampled rows, and find mappers from the sample.

    -> (mappers, used_features, feature_names, num_total_features,
        per_file_rows, column_plan)

    Byte-identity contract: the mappers equal ``BinnedDataset.from_raw``
    over the concatenation of the files (same ``data_random_seed``
    draw over the same global indices — tests/test_outofcore.py pins a
    3-file list against the single concatenated file)."""
    from .loader import _column_plan
    plans = [_file_plan(p, config) for p in files]
    rows = [pl[5] for pl in plans]
    n = int(sum(rows))
    if n <= 0:
        raise ValueError(f"no data rows in shard list {files!r}")
    sample_cnt = min(n, config.bin_construct_sample_cnt)
    rng = np.random.RandomState(config.data_random_seed)
    sample_gidx = (np.arange(n) if sample_cnt >= n
                   else np.sort(rng.choice(n, sample_cnt, replace=False)))

    sample_rows = []
    plan = None
    base = 0
    for (fmt, sep, skip, header_names, stream, n_f, ncol), path in zip(
            plans, files):
        seen = 0
        for chunk in stream():
            if plan is None:
                plan = _column_plan(chunk.shape[1], config, header_names)
            lo = np.searchsorted(sample_gidx, base + seen)
            hi = np.searchsorted(sample_gidx, base + seen + len(chunk))
            if hi > lo:
                sample_rows.append(
                    np.array(chunk[sample_gidx[lo:hi] - base - seen]))
            seen += len(chunk)
        if seen != n_f:
            raise ValueError(
                f"chunked parse of {path!r} saw {seen} rows, raw scan "
                f"counted {n_f}")
        base += n_f
    label_idx, weight_idx, query_idx, keep, names, cat_cols = plan
    if query_idx is not None:
        raise ValueError(
            "out-of-core ingest does not support ranking group columns "
            "(streamed row blocks would split queries; see README "
            "\"Out-of-core training\")")
    sample = np.concatenate(sample_rows)[:, keep]
    mappers = find_mappers_from_sample(sample, config, set(cat_cols))
    used = [f for f in range(len(keep)) if not mappers[f].is_trivial]
    return mappers, used, names, len(keep), rows, plan


# ---------------------------------------------------------------------------
# the shard store
# ---------------------------------------------------------------------------
class ShardStore:
    """An opened (complete, key-validated) binned shard cache.

    Row blocks are served as numpy views of the per-shard memmaps —
    host RSS holds only the touched (evictable) pages, never the whole
    binned matrix — which is what lets the streaming trainer's memory
    scale with ``LGBM_TPU_STREAM_ROWS`` instead of dataset rows."""

    def __init__(self, cache_dir: str, manifest: Dict):
        self.cache_dir = cache_dir
        self.manifest = manifest
        from .binning import BinMapper
        self.mappers = [BinMapper.from_dict(d) for d in manifest["mappers"]]
        self.used_features = list(manifest["used_features"])
        self.feature_names = list(manifest["feature_names"])
        self.num_total_features = int(manifest["num_total_features"])
        self.dtype = np.dtype(manifest["dtype"])
        self.feature_info = BinnedDataset._build_feature_info(
            [self.mappers[f] for f in self.used_features])
        self._shards = manifest["shards"]
        self._rows = [int(s["rows"]) for s in self._shards]
        self._offsets = np.concatenate(
            [[0], np.cumsum(self._rows)]).astype(np.int64)
        self.n = int(self._offsets[-1])
        self._bins: List[Optional[np.memmap]] = [None] * len(self._shards)
        self._label: List[Optional[np.memmap]] = [None] * len(self._shards)
        self._weight: List[Optional[np.memmap]] = [None] * len(self._shards)
        self.has_weight = any(s.get("has_weight") for s in self._shards)

    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def _mm(self, cache, k: int, suffix: str, shape, dtype):
        if cache[k] is None:
            if shape[0] == 0:
                cache[k] = np.zeros(shape, dtype)
            else:
                path = os.path.join(self.cache_dir,
                                    self._shards[k]["name"] + suffix)
                cache[k] = np.memmap(path, dtype=dtype, mode="r",
                                     shape=shape)
        return cache[k]

    def _shard_bins(self, k: int) -> np.ndarray:
        return self._mm(self._bins, k, ".bins",
                        (self._rows[k], self.num_features), self.dtype)

    def _shard_label(self, k: int) -> np.ndarray:
        return self._mm(self._label, k, ".label", (self._rows[k],),
                        np.float32)

    def _shard_weight(self, k: int) -> Optional[np.ndarray]:
        if not self._shards[k].get("has_weight"):
            return None
        return self._mm(self._weight, k, ".weight", (self._rows[k],),
                        np.float32)

    def _gather(self, start: int, stop: int, per_shard) -> np.ndarray:
        """Concatenate ``[start, stop)`` of the global row space from
        per-shard arrays (views when the range stays inside one shard)."""
        lo = int(np.searchsorted(self._offsets, start, side="right") - 1)
        parts = []
        pos = start
        k = lo
        while pos < stop:
            s0, s1 = self._offsets[k], self._offsets[k + 1]
            a, b = pos - s0, min(stop, s1) - s0
            parts.append(per_shard(k)[a:b])
            pos += b - a
            k += 1
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def read_rows(self, start: int, stop: int
                  ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """-> (bins [m, G], label [m], weight [m] or None)."""
        bins = self._gather(start, stop, self._shard_bins)
        label = self._gather(start, stop, self._shard_label)
        weight = (self._gather(start, stop, self._shard_weight)
                  if self.has_weight else None)
        return bins, label, weight

    def labels_array(self) -> np.ndarray:
        """The full label vector (concatenated memmap views) — used for
        the host-side boost-from-average statistic at fittable sizes."""
        return self._gather(0, self.n, self._shard_label)

    def weights_array(self) -> Optional[np.ndarray]:
        if not self.has_weight:
            return None
        return self._gather(0, self.n, self._shard_weight)

    def to_binned_dataset(self, config: Config) -> BinnedDataset:
        """Materialize a RESIDENT BinnedDataset (the fittable-size
        parity anchor; obviously not for out-of-core shapes)."""
        packed = np.array(self._gather(0, self.n, self._shard_bins))
        md = Metadata()
        md.set_field("label", np.array(self.labels_array()))
        w = self.weights_array()
        if w is not None:
            md.set_field("weight", np.array(w))
        ds = BinnedDataset()
        ds.config = config
        ds.num_total_features = self.num_total_features
        ds.feature_names = list(self.feature_names)
        ds.mappers = self.mappers
        ds.used_features = list(self.used_features)
        cols = [packed[:, j] for j in range(self.num_features)]
        return BinnedDataset._finish_from_mappers(
            ds, np.zeros((self.n, 0)), config, md, self.n,
            self.num_total_features, cols=cols, packed=packed,
            allow_bundle=False)


# ---------------------------------------------------------------------------
# ingest
# ---------------------------------------------------------------------------
def _shard_paths(cache_dir: str, k: int) -> Dict[str, str]:
    name = f"shard-{k:04d}"
    base = os.path.join(cache_dir, name)
    return {"name": name, "bins": base + ".bins", "label": base + ".label",
            "weight": base + ".weight", "sidecar": base + ".json"}


def _sidecar_valid(cache_dir: str, k: int, key: str, source: Dict,
                   itemsize_x_cols: int) -> Optional[Dict]:
    """A shard is reusable iff its sidecar parses, matches this store
    key + source fingerprint, and the published blob sizes agree with
    the recorded row count — a torn or foreign blob is re-ingested."""
    p = _shard_paths(cache_dir, k)
    try:
        with open(p["sidecar"]) as f:
            sc = json.load(f)
    except (OSError, ValueError):
        return None
    if sc.get("key") != key or sc.get("source") != source:
        return None
    rows = int(sc.get("rows", -1))
    if rows < 0:
        return None
    want = rows * itemsize_x_cols
    try:
        if rows and os.path.getsize(p["bins"]) != want:
            return None
        if rows and os.path.getsize(p["label"]) != rows * 4:
            return None
        if sc.get("has_weight") and rows and \
                os.path.getsize(p["weight"]) != rows * 4:
            return None
    except OSError:
        return None
    return sc


def _ingest_one_shard(k: int, path: str, config: Config, cache_dir: str,
                      mappers, used, plan, key: str, dtype) -> Dict:
    """Parse one shard file chunk-by-chunk into the cache.  Crash-safe:
    blobs build under ``.tmp`` names, are published with ``os.replace``,
    and the sidecar (the validity marker) goes last."""
    from ..obs import counter_add, span
    label_idx, weight_idx, query_idx, keep, names, cat_cols = plan
    p = _shard_paths(cache_dir, k)

    def _fetch(src):
        # the DownloadData analog: a flaky remote FS read is a
        # transient, not a lost ingest
        fault_point("ingest.shard_fetch")
        return localize(src)

    local = retry_call(_fetch, path, what="ingest.shard_fetch")
    fmt, sep, skip, header_names, stream, n_f, _ = _file_plan(local, config)
    source = _source_fingerprint(local)
    source["path"] = os.path.basename(path)

    with span("ingest.shard", shard=k, rows=n_f):
        sha = hashlib.sha256()
        rows = 0
        has_weight = weight_idx is not None
        fb = open(p["bins"] + ".tmp", "wb")
        fl = open(p["label"] + ".tmp", "wb")
        fw = open(p["weight"] + ".tmp", "wb") if has_weight else None
        try:
            for chunk in stream():
                binned = np.empty((len(chunk), len(used)), dtype)
                for j, f in enumerate(used):
                    binned[:, j] = mappers[f].value_to_bin(
                        chunk[:, keep[f]])
                payload = np.ascontiguousarray(binned).tobytes()
                sha.update(payload)
                fb.write(payload)
                fl.write(np.ascontiguousarray(
                    chunk[:, label_idx].astype(np.float32)).tobytes())
                if fw is not None:
                    fw.write(np.ascontiguousarray(
                        chunk[:, weight_idx].astype(np.float32)).tobytes())
                rows += len(chunk)
                # mid-shard crash seam: a fault (or SIGKILL) here
                # leaves only .tmp garbage — the shard is re-ingested
                fault_point("ingest.cache_write")
            for f in (fb, fl) + ((fw,) if fw else ()):
                f.flush()
                os.fsync(f.fileno())
        finally:
            fb.close()
            fl.close()
            if fw is not None:
                fw.close()
        if rows != n_f:
            raise ValueError(
                f"shard {path!r}: chunked parse yielded {rows} rows, "
                f"raw scan counted {n_f}")
        os.replace(p["bins"] + ".tmp", p["bins"])
        os.replace(p["label"] + ".tmp", p["label"])
        if has_weight:
            os.replace(p["weight"] + ".tmp", p["weight"])
        sc = {"key": key, "rows": rows, "sha256": sha.hexdigest(),
              "source": source, "has_weight": has_weight, "name": p["name"]}
        # sidecar LAST: its existence is the shard's validity marker
        atomic_write(p["sidecar"], json.dumps(sc, indent=1))
    counter_add("ingest.shards")
    counter_add("ingest.rows", rows)
    if local != path:
        release(local)
    return sc


def load_store(cache_dir: str, sources: List[str], config: Config,
               rank: int = 0, num_ranks: int = 1) -> Optional[ShardStore]:
    """Open an existing cache iff its manifest matches this (sources,
    config) key — a stale cache (changed bytes, changed binning knobs,
    hence a different mapper set) is REJECTED, never silently trained."""
    path = os.path.join(cache_dir, MANIFEST)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    files = shard_sources(sources, rank, num_ranks)
    if manifest.get("key") != cache_key(files, config):
        log_warning(f"shard cache at {cache_dir!r} is stale (key "
                    "mismatch: source bytes or binning config changed); "
                    "re-ingesting")
        return None
    store = ShardStore(cache_dir, manifest)
    # cheap structural re-validation: every shard blob still matches its
    # sidecar size (a truncated blob must never be trained on)
    itemsize = store.dtype.itemsize * store.num_features
    for k in range(len(files)):
        if _sidecar_valid(cache_dir, k, manifest["key"],
                          manifest["shards"][k]["source"], itemsize) is None:
            log_warning(f"shard cache at {cache_dir!r}: shard {k} is "
                        "torn; re-ingesting")
            return None
    return store


def ingest(sources: List[str], config: Config, cache_dir: str,
           rank: int = 0, num_ranks: int = 1) -> ShardStore:
    """Build (or resume, or cache-hit) the binned shard store for this
    rank's file-list shard.  Idempotent and SIGKILL-resumable: finished
    shards (valid sidecars) are reused, torn shards re-ingested, and
    the manifest is only ever written after every shard is valid."""
    files = shard_sources(sources, rank, num_ranks)
    if not files:
        raise ValueError(f"rank {rank}/{num_ranks} owns no source files")
    os.makedirs(cache_dir, exist_ok=True)
    hit = load_store(cache_dir, sources, config, rank, num_ranks)
    if hit is not None:
        log_info(f"shard cache hit at {cache_dir!r} "
                 f"({hit.n} rows, {len(files)} shards)")
        return hit
    key = cache_key(files, config)

    from ..obs import span
    with span("ingest.find_bins", files=len(files)):
        mappers, used, names, num_total, rows, plan = \
            find_mappers_multi(files, config)
    max_nb = max((mappers[f].num_bin for f in used), default=2)
    dtype = np.dtype(np.uint8 if max_nb <= 256 else np.int32)
    itemsize = dtype.itemsize * len(used)

    shards = []
    reused = 0
    for k, path in enumerate(files):
        src = _source_fingerprint(path)
        sc = _sidecar_valid(cache_dir, k, key, src, itemsize)
        if sc is not None:
            reused += 1
        else:
            # retried as a unit: a transient mid-shard fault (flaky FS,
            # injected ingest.cache_write) re-ingests THIS shard only
            sc = retry_call(_ingest_one_shard, k, path, config, cache_dir,
                            mappers, used, plan, key, dtype,
                            what="ingest.cache_write")
        shards.append(sc)
    if reused:
        log_info(f"resumed ingest: reused {reused}/{len(files)} "
                 "already-valid shards")

    manifest = {
        "version": STORE_VERSION,
        "key": key,
        "mapper_digest": mapper_digest(mappers),
        "mappers": [m.to_dict() for m in mappers],
        "used_features": list(map(int, used)),
        "feature_names": list(names),
        "num_total_features": int(num_total),
        "dtype": dtype.name,
        "config": _config_key(config),
        "shards": shards,
        "total_rows": int(sum(s["rows"] for s in shards)),
    }
    # manifest-last commit: tmp+rename via the same atomic discipline as
    # snapshots — readers either see a complete store or none at all
    atomic_write(os.path.join(cache_dir, MANIFEST),
                 json.dumps(manifest, indent=1))
    log_info(f"ingested {manifest['total_rows']} rows into "
             f"{len(shards)} shard(s) at {cache_dir!r}")
    return ShardStore(cache_dir, manifest)


def default_cache_dir(sources: List[str]) -> str:
    """``LGBM_TPU_STREAM_CACHE`` override, else a ``.lgbm_shards``
    directory next to the first source."""
    override = os.environ.get("LGBM_TPU_STREAM_CACHE")
    if override:
        return override
    first = sources[0]
    base = os.path.dirname(first) if "://" not in first else "."
    return os.path.join(base or ".", ".lgbm_shards")


# synthetic-store writer: the bench's >=100M-row leg writes binned
# blocks straight into the store format (text parse throughput is
# covered at toy scale; the 100M leg measures streamed TRAINING)
def ingest_synthetic(cache_dir: str, rows: int, features: int,
                     config: Config, seed: int = 0,
                     shard_rows: int = 1 << 22) -> ShardStore:
    """Write a synthetic pre-binned store: HIGGS-shaped uniform bins +
    a separable label, emitted shard-by-shard so peak host memory is
    one shard.  Shares the manifest/sidecar discipline with
    :func:`ingest` (same resumability), keyed on (rows, features,
    seed, max_bin)."""
    os.makedirs(cache_dir, exist_ok=True)
    from .binning import BIN_NUMERICAL, BinMapper
    rng = np.random.RandomState(seed)
    mappers = []
    for f in range(features):
        m = BinMapper()
        m.find_bin(rng.uniform(size=256), 256, config.max_bin, 1,
                   bin_type=BIN_NUMERICAL, use_missing=False,
                   zero_as_missing=False)
        mappers.append(m)
    used = list(range(features))
    key = _sha256_bytes(json.dumps(
        {"synthetic": [rows, features, seed, int(config.max_bin)]},
        sort_keys=True).encode())
    max_nb = max(m.num_bin for m in mappers)
    dtype = np.dtype(np.uint8 if max_nb <= 256 else np.int32)
    n_shards = -(-rows // shard_rows)
    shards = []
    for k in range(n_shards):
        m_rows = min(shard_rows, rows - k * shard_rows)
        src = {"path": f"synthetic-{k}", "bytes": m_rows}
        sc = _sidecar_valid(cache_dir, k, key, src,
                            dtype.itemsize * features)
        if sc is None:
            p = _shard_paths(cache_dir, k)
            r = np.random.RandomState(seed + 1 + k)
            bins = r.randint(0, max(2, max_nb - 1),
                             size=(m_rows, features)).astype(dtype)
            label = (bins[:, 0].astype(np.float32)
                     + 0.5 * bins[:, 1] > 0.75 * (max_nb - 2)
                     ).astype(np.float32)
            sha = hashlib.sha256(bins.tobytes())
            with open(p["bins"] + ".tmp", "wb") as f:
                f.write(bins.tobytes())
                f.flush()
                os.fsync(f.fileno())
            with open(p["label"] + ".tmp", "wb") as f:
                f.write(label.tobytes())
                f.flush()
                os.fsync(f.fileno())
            os.replace(p["bins"] + ".tmp", p["bins"])
            os.replace(p["label"] + ".tmp", p["label"])
            sc = {"key": key, "rows": int(m_rows), "sha256": sha.hexdigest(),
                  "source": src, "has_weight": False, "name": p["name"]}
            atomic_write(p["sidecar"], json.dumps(sc))
        shards.append(sc)
    manifest = {
        "version": STORE_VERSION, "key": key,
        "mapper_digest": mapper_digest(mappers),
        "mappers": [m.to_dict() for m in mappers],
        "used_features": used,
        "feature_names": [f"Column_{i}" for i in range(features)],
        "num_total_features": features, "dtype": dtype.name,
        "config": _config_key(config), "shards": shards,
        "total_rows": int(rows),
    }
    atomic_write(os.path.join(cache_dir, MANIFEST),
                 json.dumps(manifest))
    return ShardStore(cache_dir, manifest)
