"""Device-resident dataset: the HBM binned matrix + static feature metadata.

The TPU analog of the reference's in-memory ``Dataset`` handed to tree
learners (`/root/reference/include/LightGBM/dataset.h:280-578`): one dense
``[n, F]`` integer array plus flat per-feature metadata arrays, all ready
to be sharded over a ``jax.sharding.Mesh`` data axis by the distributed
learners.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .dataset import BinnedDataset


@jax.tree_util.register_pytree_node_class
class DeviceData(NamedTuple):
    """Static-shape training data pytree (device arrays + static ints).

    Registered as a custom pytree so the static metadata (`total_bins`,
    `max_bins`, `has_categorical`) stays Python-side across ``jax.jit``
    boundaries (they parameterize shapes) while the arrays are traced.
    """
    bins: jnp.ndarray           # [n, F] uint8/int32
    bin_offsets: jnp.ndarray    # [F] int32 offsets into flat bin space
    num_bins: jnp.ndarray       # [F] int32 (includes NaN bin)
    default_bins: jnp.ndarray   # [F] int32 (bin of value 0.0)
    missing_types: jnp.ndarray  # [F] int32
    is_categorical: jnp.ndarray  # [F] bool
    nan_bins: jnp.ndarray       # [F] int32 (num_bins-1 where NaN else -1)
    total_bins: int             # static
    max_bins: int               # static
    has_categorical: bool = True   # static: lets the split scan drop cat work

    def tree_flatten(self):
        children = (self.bins, self.bin_offsets, self.num_bins,
                    self.default_bins, self.missing_types,
                    self.is_categorical, self.nan_bins)
        aux = (self.total_bins, self.max_bins, self.has_categorical)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def num_data(self) -> int:
        return self.bins.shape[0]

    @property
    def num_features(self) -> int:
        return self.bins.shape[1]


def to_device(ds: BinnedDataset) -> DeviceData:
    info = ds.feature_info
    from .binning import MISSING_NAN
    nan_bins = np.where(info.missing_types == MISSING_NAN,
                        info.num_bins - 1, -1).astype(np.int32)
    return DeviceData(
        bins=jnp.asarray(ds.bins),
        bin_offsets=jnp.asarray(info.bin_offsets[:-1], jnp.int32),
        num_bins=jnp.asarray(info.num_bins, jnp.int32),
        default_bins=jnp.asarray(info.default_bins, jnp.int32),
        missing_types=jnp.asarray(info.missing_types, jnp.int32),
        is_categorical=jnp.asarray(info.is_categorical),
        nan_bins=jnp.asarray(nan_bins),
        total_bins=int(info.total_bins),
        max_bins=int(info.max_num_bins),
        has_categorical=bool(info.is_categorical.any()),
    )
