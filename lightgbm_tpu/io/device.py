"""Device-resident dataset: the HBM binned matrix + static feature metadata.

The TPU analog of the reference's in-memory ``Dataset`` handed to tree
learners (`/root/reference/include/LightGBM/dataset.h:280-578`): one dense
``[n, G]`` integer array (G = EFB group columns; G == F when nothing
bundles) plus flat per-feature metadata arrays, all ready to be sharded
over a ``jax.sharding.Mesh`` data axis by the distributed learners.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .dataset import BinnedDataset


@jax.tree_util.register_pytree_node_class
class DeviceData(NamedTuple):
    """Static-shape training data pytree (device arrays + static ints).

    Registered as a custom pytree so the static metadata (`total_bins`,
    `max_bins`, `has_categorical`, ...) stays Python-side across
    ``jax.jit`` boundaries (they parameterize shapes) while the arrays are
    traced.

    Feature-indexed arrays describe the F *logical* features; ``bins``
    holds the G stored group columns (EFB, `dataset.cpp:138-210` analog);
    ``feat_group``/``feat_offset`` map logical features into group
    columns (`io/dataset.py` BundleInfo encoding).
    """
    bins: jnp.ndarray           # [n, G] uint8/int32 group columns
    bin_offsets: jnp.ndarray    # [F] int32 offsets into flat bin space
    num_bins: jnp.ndarray       # [F] int32 (includes NaN bin)
    default_bins: jnp.ndarray   # [F] int32 (bin of value 0.0)
    missing_types: jnp.ndarray  # [F] int32
    is_categorical: jnp.ndarray  # [F] bool
    nan_bins: jnp.ndarray       # [F] int32 (num_bins-1 where NaN else -1)
    feat_group: jnp.ndarray     # [F] int32 group column per feature
    feat_offset: jnp.ndarray    # [F] int32 offset in group (-1: identity)
    total_bins: int             # static
    max_bins: int               # static: max per-FEATURE bins
    has_categorical: bool = True   # static: lets the split scan drop cat work
    max_group_bins: int = 0     # static: max per-GROUP bins (0 -> max_bins)
    is_bundled: bool = False    # static: any multi-feature group present
    has_missing: bool = True    # static: any feature with a missing type

    def tree_flatten(self):
        children = (self.bins, self.bin_offsets, self.num_bins,
                    self.default_bins, self.missing_types,
                    self.is_categorical, self.nan_bins,
                    self.feat_group, self.feat_offset)
        aux = (self.total_bins, self.max_bins, self.has_categorical,
               self.max_group_bins, self.is_bundled, self.has_missing)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def num_data(self) -> int:
        return self.bins.shape[0]

    @property
    def num_features(self) -> int:
        return self.num_bins.shape[0]

    @property
    def num_groups(self) -> int:
        return self.bins.shape[1]

    @property
    def group_max_bins(self) -> int:
        return self.max_group_bins or self.max_bins


def feature_meta_np(ds: BinnedDataset) -> dict:
    """The per-feature metadata of :func:`to_device` as HOST numpy plus
    the static fields — shared by the single-device converter and the
    multi-process path (which replicates these and builds the bins rows
    as a global sharded array WITHOUT a throwaway local bins upload)."""
    info = ds.feature_info
    from .binning import MISSING_NAN
    nan_bins = np.where(info.missing_types == MISSING_NAN,
                        info.num_bins - 1, -1).astype(np.int32)
    F = len(info.num_bins)
    if ds.bundle is not None:
        feat_group = ds.bundle.feat_group
        feat_offset = ds.bundle.feat_offset
        max_group_bins = int(ds.bundle.group_num_bins.max())
        is_bundled = bool(ds.bundle.is_bundled)
    else:
        feat_group = np.arange(F, dtype=np.int32)
        feat_offset = np.full(F, -1, np.int32)
        max_group_bins = int(info.max_num_bins)
        is_bundled = False
    return dict(
        bin_offsets=np.asarray(info.bin_offsets[:-1], np.int32),
        num_bins=np.asarray(info.num_bins, np.int32),
        default_bins=np.asarray(info.default_bins, np.int32),
        missing_types=np.asarray(info.missing_types, np.int32),
        is_categorical=np.asarray(info.is_categorical),
        nan_bins=nan_bins,
        feat_group=np.asarray(feat_group, np.int32),
        feat_offset=np.asarray(feat_offset, np.int32),
        total_bins=int(info.total_bins),
        max_bins=int(info.max_num_bins),
        has_categorical=bool(info.is_categorical.any()),
        max_group_bins=max_group_bins,
        is_bundled=is_bundled,
        has_missing=bool((info.missing_types != 0).any()),
    )


def to_device(ds: BinnedDataset) -> DeviceData:
    meta = feature_meta_np(ds)
    arrays = {k: jnp.asarray(meta[k]) for k in (
        "bin_offsets", "num_bins", "default_bins", "missing_types",
        "is_categorical", "nan_bins", "feat_group", "feat_offset")}
    return DeviceData(
        bins=jnp.asarray(ds.bins),
        total_bins=meta["total_bins"],
        max_bins=meta["max_bins"],
        has_categorical=meta["has_categorical"],
        max_group_bins=meta["max_group_bins"],
        is_bundled=meta["is_bundled"],
        has_missing=meta["has_missing"],
        **arrays,
    )
